"""Multi-host distributed backend on the virtual 8-device CPU mesh:
single-process fallbacks + portable hybrid-mesh shardings."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel import distributed as D


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert D.initialize() is False


def test_initialize_refuses_partial_config(monkeypatch):
    import pytest
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    with pytest.raises(ValueError):
        D.initialize()
    # and the other direction: a process count with nowhere to rendezvous
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    with pytest.raises(ValueError):
        D.initialize()


def test_hybrid_mesh_single_host_shape():
    mesh = D.make_hybrid_mesh()
    assert mesh.axis_names == ("hosts", "data")
    assert mesh.devices.shape == (1, len(jax.devices()))


def test_row_sharding_and_ingest_roundtrip():
    mesh = D.make_hybrid_mesh()
    n = 16 * len(jax.devices())
    rows = np.arange(n, dtype=np.float32).reshape(n // 2, 2)
    arr = D.from_process_local(rows, mesh)
    np.testing.assert_allclose(np.asarray(arr), rows)
    # a sharded reduction over the hybrid mesh produces the global sum
    total = jax.jit(lambda x: x.sum(),
                    out_shardings=D.replicated(mesh))(arr)
    assert float(total) == rows.sum()


def test_histogram_reduction_over_hybrid_mesh():
    """The framework's core pattern — row-sharded histogram all-reduced to a
    replicated table — compiles and is exact over the (hosts, data) mesh."""
    from avenir_tpu.ops.histogram import class_bin_histogram
    mesh = D.make_hybrid_mesh()
    n = 32 * len(jax.devices())
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 2, n).astype(np.int32)
    bins = rng.integers(0, 5, (n, 3)).astype(np.int32)
    row = D.row_sharding(mesh)
    rep = D.replicated(mesh)
    fn = jax.jit(lambda c, b: class_bin_histogram(c, b, 2, 5),
                 in_shardings=(row, row), out_shardings=rep)
    out = np.asarray(fn(jax.device_put(cls, row), jax.device_put(bins, row)))
    assert out.sum() == n * 3
    expect = np.zeros((2, 3, 5))
    for i in range(n):
        for f in range(3):
            expect[cls[i], f, bins[i, f]] += 1
    np.testing.assert_allclose(out, expect)


def test_cli_distributed_mode_installs_hybrid_context(tmp_path, monkeypatch):
    """-Ddistributed.mode=1 routes the job through a hybrid-mesh runtime
    context, and the model + counters match a default (1-D mesh) run."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.parallel import mesh as M

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    train = tmp_path / "train.csv"
    train.write_text("\n".join(telecom_churn_gen.generate(512, 3)))

    from avenir_tpu.cli import jobs as J
    captured = {}
    orig = J.JOBS["org.avenir.bayesian.BayesianDistribution"]

    def spy(cfg, i, o):
        captured["ctx"] = M.runtime_context()
        return orig(cfg, i, o)

    monkeypatch.setitem(J.JOBS, "org.avenir.bayesian.BayesianDistribution",
                        spy)

    def run(extra, out):
        rc = cli_run.main([
            "org.avenir.bayesian.BayesianDistribution",
            f"-Dconf.path={res}/churn.properties",
            f"-Dbad.feature.schema.file.path={res}/churn.json",
            *extra, str(train), str(tmp_path / out)])
        assert rc == 0
        return (tmp_path / out / "part-r-00000").read_text()

    default_model = run([], "m_default")
    dist_model = run(["-Ddistributed.mode=1"], "m_dist")
    # the job ran over the (hosts, data) hybrid mesh...
    ctx = captured["ctx"]
    assert ctx.mesh.axis_names == ("hosts", "data")
    assert ctx.n_devices == len(jax.devices())
    assert dist_model == default_model
    # ...and main() reset the context afterwards (no leak into later runs)
    assert M.runtime_context().mesh.axis_names != ("hosts", "data")


def test_all_reduce_counters_single_process_identity():
    from avenir_tpu.core.metrics import Counters
    c = Counters()
    c.increment("G", "a", 3)
    out = D.all_reduce_counters(c)
    assert out is c


def _spawn_two_workers(tmp_path, res, shard_names):
    """Spawn the 2-process worker pair on an ephemeral coordinator port,
    returning [(returncode, stdout, stderr)] — workers are killed on
    timeout so a hung coordinator can't leak into the rest of the run."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
                        "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), port,
         str(tmp_path / shard_names[i]), str(tmp_path / f"out{i}"), res],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=180)
            results.append((p.returncode, stdout, stderr))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return results


def test_true_two_process_nb_train(tmp_path):
    """REAL multi-process validation (not the virtual mesh): two coordinated
    jax processes, each loading its own equal-size CSV shard, run the NB
    train job through the CLI distributed mode.  Both processes must produce
    the model of the CONCATENATED data (bit-identical to a single-process
    run), and the all-reduced counters render on process 0 only."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(600, 8)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))
    (tmp_path / "full.csv").write_text("\n".join(rows))

    outs = []
    for rc_w, stdout, stderr in _spawn_two_workers(
            tmp_path, res, ["shard0.csv", "shard1.csv"]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout
        outs.append(stdout)

    # single-process reference on the concatenated file
    rc = cli_run.main([
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        str(tmp_path / "full.csv"), str(tmp_path / "out_single")])
    assert rc == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    m0 = (tmp_path / "out0" / "part-r-00000").read_text()
    m1 = (tmp_path / "out1" / "part-r-00000").read_text()
    assert m0 == single, "proc 0 model != single-process global model"
    assert m1 == single, "proc 1 model != single-process global model"
    # counters: all-reduced and rendered on process 0 only
    c0 = outs[0].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    c1 = outs[1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert c0.strip(), "process 0 rendered no counters"
    assert not c1.strip(), "process 1 must not render counters"


def test_true_two_process_unequal_shards_fail_loudly(tmp_path):
    """Unequal per-process shards must raise (from_process_local's guard):
    jax builds a different global shape per process and reductions silently
    corrupt otherwise (verified on hardware... well, on a real 2-process
    run)."""
    import os
    import sys

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(500, 9)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))   # 300 rows
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))   # 200 rows

    results = _spawn_two_workers(tmp_path, res,
                                 ["shard0.csv", "shard1.csv"])
    assert any(rc != 0 for rc, _, _ in results), "unequal shards must fail"
    combined_err = "".join(err for _, _, err in results)
    assert "local shapes differ" in combined_err


def test_write_text_output_per_process_parts(tmp_path, monkeypatch):
    """Map-only (shard-local) outputs get per-process part numbers under
    multi-process; reducer-style global artifacts keep part 0."""
    from avenir_tpu.core import artifacts
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    p = artifacts.write_text_output(str(tmp_path / "pred"), ["a"], role="m")
    assert p.endswith("part-m-00001")
    p = artifacts.write_text_output(str(tmp_path / "model"), ["b"], role="r")
    assert p.endswith("part-r-00000")
    # explicit override wins either way
    p = artifacts.write_text_output(str(tmp_path / "x"), ["c"], role="r",
                                    local_shard=True)
    assert p.endswith("part-r-00001")
