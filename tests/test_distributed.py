"""Multi-host distributed backend on the virtual 8-device CPU mesh:
single-process fallbacks + portable hybrid-mesh shardings."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel import distributed as D


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert D.initialize() is False


def test_initialize_refuses_partial_config(monkeypatch):
    import pytest
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    with pytest.raises(ValueError):
        D.initialize()
    # and the other direction: a process count with nowhere to rendezvous
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    with pytest.raises(ValueError):
        D.initialize()


def test_hybrid_mesh_single_host_shape():
    mesh = D.make_hybrid_mesh()
    assert mesh.axis_names == ("hosts", "data")
    assert mesh.devices.shape == (1, len(jax.devices()))


def test_row_sharding_and_ingest_roundtrip():
    mesh = D.make_hybrid_mesh()
    n = 16 * len(jax.devices())
    rows = np.arange(n, dtype=np.float32).reshape(n // 2, 2)
    arr = D.from_process_local(rows, mesh)
    np.testing.assert_allclose(np.asarray(arr), rows)
    # a sharded reduction over the hybrid mesh produces the global sum
    total = jax.jit(lambda x: x.sum(),
                    out_shardings=D.replicated(mesh))(arr)
    assert float(total) == rows.sum()


def test_histogram_reduction_over_hybrid_mesh():
    """The framework's core pattern — row-sharded histogram all-reduced to a
    replicated table — compiles and is exact over the (hosts, data) mesh."""
    from avenir_tpu.ops.histogram import class_bin_histogram
    mesh = D.make_hybrid_mesh()
    n = 32 * len(jax.devices())
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 2, n).astype(np.int32)
    bins = rng.integers(0, 5, (n, 3)).astype(np.int32)
    row = D.row_sharding(mesh)
    rep = D.replicated(mesh)
    fn = jax.jit(lambda c, b: class_bin_histogram(c, b, 2, 5),
                 in_shardings=(row, row), out_shardings=rep)
    out = np.asarray(fn(jax.device_put(cls, row), jax.device_put(bins, row)))
    assert out.sum() == n * 3
    expect = np.zeros((2, 3, 5))
    for i in range(n):
        for f in range(3):
            expect[cls[i], f, bins[i, f]] += 1
    np.testing.assert_allclose(out, expect)


def test_cli_distributed_mode_installs_hybrid_context(tmp_path, monkeypatch):
    """-Ddistributed.mode=1 routes the job through a hybrid-mesh runtime
    context, and the model + counters match a default (1-D mesh) run."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.parallel import mesh as M

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    train = tmp_path / "train.csv"
    train.write_text("\n".join(telecom_churn_gen.generate(512, 3)))

    from avenir_tpu.cli import jobs as J
    captured = {}
    orig = J.JOBS["org.avenir.bayesian.BayesianDistribution"]

    def spy(cfg, i, o):
        captured["ctx"] = M.runtime_context()
        return orig(cfg, i, o)

    monkeypatch.setitem(J.JOBS, "org.avenir.bayesian.BayesianDistribution",
                        spy)

    def run(extra, out):
        rc = cli_run.main([
            "org.avenir.bayesian.BayesianDistribution",
            f"-Dconf.path={res}/churn.properties",
            f"-Dbad.feature.schema.file.path={res}/churn.json",
            *extra, str(train), str(tmp_path / out)])
        assert rc == 0
        return (tmp_path / out / "part-r-00000").read_text()

    default_model = run([], "m_default")
    dist_model = run(["-Ddistributed.mode=1"], "m_dist")
    # the job ran over the (hosts, data) hybrid mesh...
    ctx = captured["ctx"]
    assert ctx.mesh.axis_names == ("hosts", "data")
    assert ctx.n_devices == len(jax.devices())
    assert dist_model == default_model
    # ...and main() reset the context afterwards (no leak into later runs)
    assert M.runtime_context().mesh.axis_names != ("hosts", "data")


def test_all_reduce_counters_single_process_identity():
    from avenir_tpu.core.metrics import Counters
    c = Counters()
    c.increment("G", "a", 3)
    out = D.all_reduce_counters(c)
    assert out is c


def _spawn_two_workers_spec(tmp_path, specs):
    """Spawn the 2-process worker pair on an ephemeral coordinator port;
    ``specs[i]`` is process i's {"runs": [[argv...], ...]} spec.  Returns
    [(returncode, stdout, stderr)] — workers are killed on timeout so a
    hung coordinator can't leak into the rest of the run."""
    import json
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
                        "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    spec_paths = []
    for i, spec in enumerate(specs):
        p = tmp_path / f"spec{i}.json"
        p.write_text(json.dumps(spec))
        spec_paths.append(str(p))
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), port, spec_paths[i]],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300)
            results.append((p.returncode, stdout, stderr))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return results


def _nb_train_spec(res, shard, out):
    return {"runs": [[
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        "-Ddistributed.mode=1", shard, out]]}


def _spawn_two_workers(tmp_path, res, shard_names):
    return _spawn_two_workers_spec(tmp_path, [
        _nb_train_spec(res, str(tmp_path / shard_names[i]),
                       str(tmp_path / f"out{i}"))
        for i in range(2)])


def test_true_two_process_nb_train(tmp_path):
    """REAL multi-process validation (not the virtual mesh): two coordinated
    jax processes, each loading its own equal-size CSV shard, run the NB
    train job through the CLI distributed mode.  Both processes must produce
    the model of the CONCATENATED data (bit-identical to a single-process
    run), and the all-reduced counters render on process 0 only."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(600, 8)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))
    (tmp_path / "full.csv").write_text("\n".join(rows))

    outs = []
    for rc_w, stdout, stderr in _spawn_two_workers(
            tmp_path, res, ["shard0.csv", "shard1.csv"]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout
        outs.append(stdout)

    # single-process reference on the concatenated file
    rc = cli_run.main([
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        str(tmp_path / "full.csv"), str(tmp_path / "out_single")])
    assert rc == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    m0 = (tmp_path / "out0" / "part-r-00000").read_text()
    m1 = (tmp_path / "out1" / "part-r-00000").read_text()
    assert m0 == single, "proc 0 model != single-process global model"
    assert m1 == single, "proc 1 model != single-process global model"
    # counters: all-reduced and rendered on process 0 only
    c0 = outs[0].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    c1 = outs[1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert c0.strip(), "process 0 rendered no counters"
    assert not c1.strip(), "process 1 must not render counters"


def test_true_two_process_unequal_shards_correct(tmp_path):
    """Unequal per-process shards: NB train's pod-agreed chunk schedule
    pads the shorter shard with masked-out rows, so the run SUCCEEDS and
    both processes produce the exact global model of the concatenated
    data.  (Jobs that ship whole unequal arrays through from_process_local
    still fail its equal-shape guard — that contract is pinned by
    test_row_sharding unit tests.)"""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(500, 9)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))   # 300 rows
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))   # 200 rows
    (tmp_path / "full.csv").write_text("\n".join(rows))

    for rc_w, stdout, stderr in _spawn_two_workers(
            tmp_path, res, ["shard0.csv", "shard1.csv"]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    rc = cli_run.main([
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        str(tmp_path / "full.csv"), str(tmp_path / "out_single")])
    assert rc == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    assert (tmp_path / "out0" / "part-r-00000").read_text() == single
    assert (tmp_path / "out1" / "part-r-00000").read_text() == single


def test_write_text_output_per_process_parts(tmp_path, monkeypatch):
    """Map-only (shard-local) outputs get per-process part numbers under
    multi-process; reducer-style global artifacts keep part 0."""
    from avenir_tpu.core import artifacts
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    p = artifacts.write_text_output(str(tmp_path / "pred"), ["a"], role="m")
    assert p.endswith("part-m-00001")
    p = artifacts.write_text_output(str(tmp_path / "model"), ["b"], role="r")
    assert p.endswith("part-r-00000")
    # explicit override wins either way
    p = artifacts.write_text_output(str(tmp_path / "x"), ["c"], role="r",
                                    local_shard=True)
    assert p.endswith("part-r-00001")


# ---------------------------------------------------------------------------
# round-4: multi-process correct-or-loud for host-side jobs
# ---------------------------------------------------------------------------

TRANS_LINES = [
    "t01,milk,bread,butter", "t02,milk,bread", "t03,bread,butter",
    "t04,milk,butter", "t05,milk,bread,butter,jam", "t06,bread,jam",
    "t07,milk,bread", "t08,coffee,milk", "t09,milk,bread,butter",
    "t10,bread,butter,jam", "t11,milk,jam", "t12,bread,milk,butter",
]


def _apriori_props(tmp_path, total):
    props = tmp_path / "fit.properties"
    props.write_text(
        "field.delim.regex=,\nfield.delim.out=,\n"
        "fia.item.set.length=1\nfia.tans.id.ord=0\n"
        "fia.skip.field.count=1\nfia.support.threshold=0.25\n"
        f"fia.total.tans.count={total}\n"
        "fia.trans.id.output=false\n"
        "arm.conf.threshold=0.5\narm.output.confidence=true\n")
    return str(props)


def _apriori_runs(props, shard, lvl1, lvl2, comb, rules):
    """Level-1 -> level-2 -> rule mining, chained in one worker process
    (re-enters distributed mode per run).  ``comb`` is the rule miner's
    input dir — the parent pre-creates it with symlinks to both level
    outputs (the reference feeds the miner every level's itemset file)."""
    return [
        ["org.avenir.association.FrequentItemsApriori",
         f"-Dconf.path={props}", "-Ddistributed.mode=1", shard, lvl1],
        ["org.avenir.association.FrequentItemsApriori",
         f"-Dconf.path={props}", "-Dfia.item.set.length=2",
         f"-Dfia.item.set.file.path={lvl1}",
         "-Ddistributed.mode=1", shard, lvl2],
        ["org.avenir.association.AssociationRuleMiner",
         f"-Dconf.path={props}", "-Ddistributed.mode=1", comb, rules],
    ]


def _link_levels(comb, lvl_paths):
    import os
    os.makedirs(comb, exist_ok=True)
    for j, lvl in enumerate(lvl_paths):
        os.symlink(os.path.join(lvl, "part-r-00000"),
                   os.path.join(comb, f"part-lvl{j}"))


def test_true_two_process_apriori_and_rules(tmp_path):
    """Sharded Apriori (vocab/candidate union + count all-reduce) and the
    gather-mode rule miner must produce the IDENTICAL global output on both
    processes as a single-process run over the full transaction file —
    the reference got this from the shuffle (FrequentItemsApriori.java:
    89-306); shard-local results are the silent failure this guards.

    The rule-mining stage also pins the gather contract: the union of the
    per-process inputs is the dataset, so a replicated upstream artifact
    (every process holds the identical global itemset files) is fed on
    process 0 only — process 1 reads an empty shard and still emits the
    full global rule set."""
    import os

    from avenir_tpu.cli import run as cli_run

    (tmp_path / "shard0.csv").write_text("\n".join(TRANS_LINES[:6]))
    (tmp_path / "shard1.csv").write_text("\n".join(TRANS_LINES[6:]))
    (tmp_path / "full.csv").write_text("\n".join(TRANS_LINES))
    props = _apriori_props(tmp_path, len(TRANS_LINES))

    # process 0's rule input: both level outputs; process 1: empty shard
    _link_levels(str(tmp_path / "comb_0"),
                 [str(tmp_path / "lvl1_0"), str(tmp_path / "lvl2_0")])
    os.makedirs(tmp_path / "comb_1")
    (tmp_path / "comb_1" / "part-empty").write_text("")

    specs = []
    for i in range(2):
        specs.append({"runs": _apriori_runs(
            props, str(tmp_path / f"shard{i}.csv"),
            str(tmp_path / f"lvl1_{i}"), str(tmp_path / f"lvl2_{i}"),
            str(tmp_path / f"comb_{i}"), str(tmp_path / f"rules_{i}"))})
    outs = []
    for rc, stdout, stderr in _spawn_two_workers_spec(tmp_path, specs):
        assert rc == 0, f"worker failed:\n{stderr[-3000:]}"
        assert "WORKER_OK" in stdout, stdout
        outs.append(stdout)
    # counter semantics: transactions are per-shard and all-reduced (6+6),
    # the global-identical tallies are NOT inflated by the process count —
    # frequentItemSets counted on process 0 only, and the gather-mode rule
    # miner's counters skip the all-reduce entirely
    c0 = outs[0].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert "transactions=12" in c0, c0
    assert "frequentItemSets=4" in c0, c0      # lvl1: bread,butter,jam,milk
    assert "rules=6" in c0, c0

    # single-process reference over the concatenated transactions
    _link_levels(str(tmp_path / "comb_s"),
                 [str(tmp_path / "lvl1_s"), str(tmp_path / "lvl2_s")])
    for argv in _apriori_runs(props, str(tmp_path / "full.csv"),
                              str(tmp_path / "lvl1_s"),
                              str(tmp_path / "lvl2_s"),
                              str(tmp_path / "comb_s"),
                              str(tmp_path / "rules_s")):
        assert cli_run.main([a for a in argv
                             if a != "-Ddistributed.mode=1"]) == 0

    for stage in ("lvl1", "lvl2", "rules"):
        single = sorted((tmp_path / f"{stage}_s").glob("part-*"))[0].read_text()
        assert single.strip(), f"single-process {stage} output empty"
        for i in range(2):
            got = sorted((tmp_path / f"{stage}_{i}").glob("part-*"))[0].read_text()
            assert got == single, (
                f"process {i} {stage} output != single-process global output")


def test_every_job_has_dist_mode():
    """The correct-or-loud contract: every registered job carries an
    explicit multi-process class, so nothing can silently default."""
    from avenir_tpu.cli import run as cli_run  # registers all packs # noqa
    from avenir_tpu.cli.jobs import JOBS, JOB_DIST, _DIST_MODES
    for name, fn in JOBS.items():
        assert fn in JOB_DIST, f"{name} has no dist mode"
        assert JOB_DIST[fn] in _DIST_MODES


def test_dist_mode_guard_refuses_unclassified(monkeypatch, tmp_path):
    """An unclassified (or refuse-marked) job must be rejected under
    multi-process instead of emitting shard-local results."""
    import pytest
    from avenir_tpu.cli import run as cli_run

    def fake_job(cfg, in_path, out_path):
        return None

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="not multi-process safe"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(tmp_path / "in"))


def test_dist_mode_gather_spools_full_input(monkeypatch, tmp_path):
    """gather-mode jobs see the allgathered input through a spool DIR that
    preserves per-file basenames (prefix-keyed jobs depend on them), and
    an input-presence mismatch across processes raises instead of
    deadlocking half the pod in a collective."""
    import os
    import pytest
    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.cli import jobs as J
    from avenir_tpu.parallel import distributed as D

    def fake_job(cfg, in_path, out_path):
        return None

    indir = tmp_path / "in"
    indir.mkdir()
    (indir / "tr-part").write_text("a\nb")
    (indir / "part-r-00000").write_text("c")
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setitem(J.JOB_DIST, fake_job, "gather")

    # simulate a peer process holding a DIFFERENT shard: digest meta phase
    # ((bool, digest) tuple) then the content phase ((err, files) tuple
    # carrying BYTES — non-UTF-8 input must not decode mid-collective)
    def peer_differs(obj):
        if isinstance(obj, tuple) and isinstance(obj[1], str):
            return [obj, (True, "peer-digest")]
        return [obj, (None, [("tr-part", b"x\ny")])]

    monkeypatch.setattr(D, "allgather_object", peer_differs)
    spool, cleanup = cli_run._apply_dist_mode(fake_job, "FakeJob",
                                              str(indir))
    assert spool == cleanup and os.path.isdir(spool)
    names = sorted(os.listdir(spool))
    assert names == ["part-r-00000.p0", "tr-part.p0", "tr-part.p1"]
    assert open(os.path.join(spool, "tr-part.p1")).read() == "x\ny"
    # the train-prefix key survives spooling
    assert sum(n.startswith("tr") for n in names) == 2

    # shared-filesystem launch (identical digests everywhere): the input
    # is used as-is — no spool, no P-fold double-count of the union
    monkeypatch.setattr(D, "allgather_object", lambda obj: [obj, obj])
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)

    # processes disagreeing on input presence must raise, not deadlock
    monkeypatch.setattr(
        D, "allgather_object", lambda obj: [obj, (False, "")])
    with pytest.raises(RuntimeError, match="disagree"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(indir))

    # sharded jobs with DISTINCT per-process shards pass through untouched
    monkeypatch.setitem(J.JOB_DIST, fake_job, "sharded")
    monkeypatch.setattr(D, "allgather_object",
                        lambda obj: [obj, (True, "peer-digest")])
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)

    # ...but an identical input everywhere (shared-fs same-argv launch)
    # would silently P-fold inflate sharded/map results: refuse loudly
    monkeypatch.setattr(D, "allgather_object", lambda obj: [obj, obj])
    with pytest.raises(RuntimeError, match="IDENTICAL input"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(indir))
    monkeypatch.setenv("AVENIR_TPU_ALLOW_IDENTICAL_SHARDS", "1")
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)


def test_dist_mode_gather_peer_error_raises_everywhere(monkeypatch,
                                                       tmp_path):
    """A peer that fails to READ its shard during the content phase
    reports the error through the collective, so this process raises too
    instead of spooling a partial view (or hanging the pod)."""
    import pytest
    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.cli import jobs as J
    from avenir_tpu.parallel import distributed as D

    def fake_job(cfg, in_path, out_path):
        return None

    shard = tmp_path / "shard.csv"
    shard.write_text("a\n")
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setitem(J.JOB_DIST, fake_job, "gather")

    def peer_errors(obj):
        if isinstance(obj, tuple) and isinstance(obj[1], str):
            return [obj, (True, "peer-digest")]
        return [obj, ("process 1: OSError: file vanished", [])]

    monkeypatch.setattr(D, "allgather_object", peer_errors)
    with pytest.raises(RuntimeError, match="file vanished"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(shard))


def test_allgather_helpers_single_process_identity():
    from avenir_tpu.parallel import distributed as D
    assert D.allgather_object({"k": [1, 2]}) == [{"k": [1, 2]}]
    np.testing.assert_array_equal(
        D.all_reduce_host_array(np.array([3, 4])), np.array([3, 4]))


# ---------------------------------------------------------------------------
# round-5 promotions: partition / sharded modes for the former gather jobs
# ---------------------------------------------------------------------------

def _res_dir():
    import os
    return os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))


def test_true_two_process_partition_sa_ga(tmp_path):
    """SA + GA under dist=partition: identical config on both processes;
    each process runs its work_slice of the chains/islands (6 chains -> 3+3,
    4 islands -> 2+2), results are allgathered, and BOTH processes write the
    identical merged output with every chain/island present.  Set-style
    counters (GA bestCost) survive the cross-process sum because only the
    slice owning item 0 emits them."""
    import json
    import os
    import sys

    res = _res_dir()
    sys.path.insert(0, res)
    import importlib
    task_sched_gen = importlib.import_module("gen.task_sched_gen")

    domain = tmp_path / "taskSched.json"
    domain.write_text(json.dumps(task_sched_gen.generate(8, 5, 4)))
    conf = tmp_path / "opt.conf"
    src = open(os.path.join(res, "opt.conf")).read()
    conf.write_text(src.replace('"taskSched.json"', f'"{domain}"')
                    .replace("num.optimizers = 16", "num.optimizers = 6")
                    .replace("max.num.iterations = 2000",
                             "max.num.iterations = 120")
                    .replace("num.generations = 120", "num.generations = 40"))

    def spec(i):
        return {"runs": [
            ["simulatedAnnealing", "-Ddistributed.mode=1",
             str(tmp_path / f"sa_out{i}"), str(conf)],
            ["geneticAlgorithm", "-Ddistributed.mode=1",
             str(tmp_path / f"ga_out{i}"), str(conf)],
        ]}

    results = _spawn_two_workers_spec(tmp_path, [spec(0), spec(1)])
    for rc_w, stdout, stderr in results:
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    sa0 = (tmp_path / "sa_out0" / "part-r-00000").read_text()
    sa1 = (tmp_path / "sa_out1" / "part-r-00000").read_text()
    assert sa0 == sa1, "processes disagree on the merged SA output"
    sa_lines = sa0.strip().splitlines()
    assert len(sa_lines) == 6  # every chain accounted for
    costs = [float(l.rsplit(",", 1)[1]) for l in sa_lines]
    assert costs == sorted(costs)

    ga0 = (tmp_path / "ga_out0" / "part-r-00000").read_text()
    ga1 = (tmp_path / "ga_out1" / "part-r-00000").read_text()
    assert ga0 == ga1, "processes disagree on the merged GA output"
    ga_lines = ga0.strip().splitlines()
    assert len(ga_lines) == 4  # every island accounted for
    ga_costs = [float(l.rsplit(",", 1)[1]) for l in ga_lines]
    assert ga_costs == sorted(ga_costs)
    # counters: process 0 renders the all-reduced sums; the set-once GA
    # bestCost survives the sum and equals the merged minimum
    c0 = results[0][1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    c1 = results[1][1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert "betterSolnCount" in c0 and "bestCost" in c0
    assert not c1.strip(), "process 1 must not render counters"
    best_line = [l for l in c0.splitlines() if "bestCost" in l][0]
    assert int(best_line.split("=")[-1]) == int(min(ga_costs))


def test_true_two_process_partition_knn_pipeline(tmp_path):
    """knnPipeline under dist=partition: identical input dir on both
    processes; each classifies its work_slice of the test axis (distinct
    halves), writes its own part file, and the union equals the
    single-process prediction set with all-reduced validation counters."""
    import json
    import numpy as np

    rng = np.random.default_rng(7)

    def rows(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for i in range(n):
            a = r.random() < 0.5
            x = r.normal(2 if a else 8, 1.0)
            y = r.normal(2 if a else 8, 1.0)
            out.append([f"s{seed}_{i:03d}", f"{x:.3f}", f"{y:.3f}",
                        "A" if a else "B"])
        return out

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    (data_dir / "tr_train.csv").write_text(
        "\n".join(",".join(r) for r in rows(80, 21)))
    (data_dir / "test.csv").write_text(
        "\n".join(",".join(r) for r in rows(30, 22)))
    schema_path = tmp_path / "s.json"
    schema_path.write_text(json.dumps({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "x", "ordinal": 1, "dataType": "double", "feature": True,
         "min": 0, "max": 10},
        {"name": "y", "ordinal": 2, "dataType": "double", "feature": True,
         "min": 0, "max": 10},
        {"name": "label", "ordinal": 3, "dataType": "categorical",
         "cardinality": ["A", "B"]}]}))
    props = tmp_path / "knn.properties"
    props.write_text(
        "field.delim.regex=,\nfield.delim.out=,\n"
        f"sts.same.schema.file.path={schema_path}\n"
        "sts.base.set.split.prefix=tr\n"
        "nen.top.match.count=5\n"
        "nen.kernel.function=none\n"
        "nen.validation.mode=true\n")

    def spec(i):
        return {"runs": [["knnPipeline", f"-Dconf.path={props}",
                          "-Ddistributed.mode=1", str(data_dir),
                          str(tmp_path / "out_dist")]]}

    results = _spawn_two_workers_spec(tmp_path, [spec(0), spec(1)])
    for rc_w, stdout, stderr in results:
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    from avenir_tpu.cli import run as cli_run
    assert cli_run.main(["knnPipeline", f"-Dconf.path={props}",
                         str(data_dir), str(tmp_path / "out_single")]) == 0
    single = sorted((tmp_path / "out_single" / "part-r-00000")
                    .read_text().strip().splitlines())
    p0 = (tmp_path / "out_dist" / "part-r-00000").read_text() \
        .strip().splitlines()
    p1 = (tmp_path / "out_dist" / "part-r-00001").read_text() \
        .strip().splitlines()
    assert len(p0) == 15 and len(p1) == 15  # distinct halves of 30
    assert sorted(p0 + p1) == single
    assert not (set(p0) & set(p1))
    # validation counters were all-reduced: process 0 renders the GLOBAL
    # confusion counts (sum over both slices)
    c0 = results[0][1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert "Test records=30" in c0.replace(" ", "").replace('"', "") \
        or "Test records" in c0


def test_true_two_process_sharded_kmeans(tmp_path):
    """kmeansCluster under dist=sharded: each process loads its OWN shard;
    assignment partials are all-reduced so both processes converge to the
    identical centroid file, matching a single-process run on the
    concatenated data (within f32 partial-sum tolerance)."""
    import numpy as np

    r = np.random.default_rng(5)
    rows = []
    for i in range(240):
        cx, cy = [(1.5, 1.5), (8.5, 8.5), (1.5, 8.5)][i % 3]
        rows.append(f"p{i:03d},{r.normal(cx, 0.4):.3f},{r.normal(cy, 0.4):.3f}")
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:120]) + "\n")
    (tmp_path / "shard1.csv").write_text("\n".join(rows[120:]) + "\n")
    (tmp_path / "full.csv").write_text("\n".join(rows) + "\n")
    import json
    schema_path = tmp_path / "schema.json"
    schema_path.write_text(json.dumps({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "x", "ordinal": 1, "dataType": "double", "feature": True,
         "min": 0, "max": 10},
        {"name": "y", "ordinal": 2, "dataType": "double", "feature": True,
         "min": 0, "max": 10}]}))
    clf = tmp_path / "clusters.csv"
    clf.write_text("g1,null,1.0,1.0,inf,active\n"
                   "g1,null,9.0,9.0,inf,active\n"
                   "g1,null,1.0,9.0,inf,active\n")
    props = tmp_path / "km.properties"
    props.write_text("\n".join([
        f"kmc.schema.file.path={schema_path}",
        "kmc.attr.odinals=1,2",
        "kmc.movement.threshold=0.0001",
        f"kmc.cluster.file.path={clf}",
        "kmc.num.iterations=30"]) + "\n")

    def spec(i):
        return {"runs": [["kmeansCluster", f"-Dconf.path={props}",
                          "-Ddistributed.mode=1",
                          str(tmp_path / f"shard{i}.csv"),
                          str(tmp_path / f"out{i}")]]}

    for rc_w, stdout, stderr in _spawn_two_workers_spec(
            tmp_path, [spec(0), spec(1)]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    m0 = (tmp_path / "out0" / "part-r-00000").read_text()
    m1 = (tmp_path / "out1" / "part-r-00000").read_text()
    assert m0 == m1, "processes disagree on the global centroids"

    from avenir_tpu.cli import run as cli_run
    assert cli_run.main(["kmeansCluster", f"-Dconf.path={props}",
                         str(tmp_path / "full.csv"),
                         str(tmp_path / "out_single")]) == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()

    def centroids(text):
        out = []
        for line in text.strip().splitlines():
            f = line.split(",")
            out.append((float(f[2]), float(f[3])))
        return sorted(out)

    got, want = centroids(m0), centroids(single)
    assert np.allclose(got, want, atol=2e-3), (got, want)


def test_true_two_process_sharded_logistic_regression(tmp_path):
    """logisticRegression under dist=sharded: per-iteration gradient sums
    all-reduced; both processes walk the identical coefficient history and
    the model matches a single-process run on the concatenated data."""
    import json
    import numpy as np

    r = np.random.default_rng(9)
    rows = []
    for i in range(300):
        pos = r.random() < 0.5
        x1 = r.normal(1.2 if pos else -1.2, 1.0)
        x2 = r.normal(0.8 if pos else -0.8, 1.0)
        rows.append(f"r{i:03d},{x1:.4f},{x2:.4f},{'pos' if pos else 'neg'}")
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:150]) + "\n")
    (tmp_path / "shard1.csv").write_text("\n".join(rows[150:]) + "\n")
    (tmp_path / "full.csv").write_text("\n".join(rows) + "\n")
    schema_path = tmp_path / "schema.json"
    schema_path.write_text(json.dumps({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "x1", "ordinal": 1, "dataType": "double", "feature": True,
         "min": -5, "max": 5},
        {"name": "x2", "ordinal": 2, "dataType": "double", "feature": True,
         "min": -5, "max": 5},
        {"name": "label", "ordinal": 3, "dataType": "categorical",
         "cardinality": ["neg", "pos"]}]}))

    def props(i):
        p = tmp_path / f"lr{i}.properties"
        p.write_text("\n".join([
            f"feature.schema.file.path={schema_path}",
            f"coeff.file.path={tmp_path / f'coeff{i}.csv'}",
            "positive.class.value=pos",
            "learning.rate=1.0",
            "convergence.criteria=iterLimit",
            "iteration.limit=12"]) + "\n")
        return p

    def spec(i):
        return {"runs": [["logisticRegression",
                          f"-Dconf.path={props(i)}",
                          "-Ddistributed.mode=1",
                          str(tmp_path / f"shard{i}.csv"),
                          str(tmp_path / f"out{i}")]]}

    for rc_w, stdout, stderr in _spawn_two_workers_spec(
            tmp_path, [spec(0), spec(1)]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    w0 = (tmp_path / "out0" / "part-r-00000").read_text()
    w1 = (tmp_path / "out1" / "part-r-00000").read_text()
    assert w0 == w1, "processes disagree on the coefficients"
    assert (tmp_path / "coeff0.csv").read_text() \
        == (tmp_path / "coeff1.csv").read_text()

    p_single = tmp_path / "lr_single.properties"
    p_single.write_text("\n".join([
        f"feature.schema.file.path={schema_path}",
        f"coeff.file.path={tmp_path / 'coeff_single.csv'}",
        "positive.class.value=pos",
        "learning.rate=1.0",
        "convergence.criteria=iterLimit",
        "iteration.limit=12"]) + "\n")
    from avenir_tpu.cli import run as cli_run
    assert cli_run.main(["logisticRegression", f"-Dconf.path={p_single}",
                         str(tmp_path / "full.csv"),
                         str(tmp_path / "out_single")]) == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    got = np.array([float(v) for v in w0.strip().split(",")])
    want = np.array([float(v) for v in single.strip().split(",")])
    assert np.allclose(got, want, rtol=1e-3, atol=1e-4), (got, want)


def test_cheap_digest_distinguishes_mid_file_differences(tmp_path):
    """The sharded/map identical-input check uses a cheap digest (size +
    head + tail + strided interior samples).  Shards that agree in head,
    tail, and size but differ mid-file — fixed-width records — must get
    DISTINCT digests (round-4 advisor: they were falsely refused as
    identical when only head/tail/size were hashed)."""
    from avenir_tpu.cli.run import file_sha
    blob = bytearray(b"r" * (1 << 18))        # 256 KiB, > head+tail window
    a = tmp_path / "shard_a.dat"
    b = tmp_path / "shard_b.dat"
    a.write_bytes(bytes(blob))
    mid = len(blob) // 2
    blob[mid:mid + 8] = b"DIFFERS!"           # only an interior run differs
    b.write_bytes(bytes(blob))
    assert file_sha(str(a), full=False) != file_sha(str(b), full=False)
    # identical files still agree, and the cheap form is stable
    assert file_sha(str(a), full=False) == file_sha(str(a), full=False)
    # full form sees the difference too (sanity)
    assert file_sha(str(a), full=True) != file_sha(str(b), full=True)
