"""Multi-host distributed backend on the virtual 8-device CPU mesh:
single-process fallbacks + portable hybrid-mesh shardings."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel import distributed as D


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert D.initialize() is False


def test_initialize_refuses_partial_config(monkeypatch):
    import pytest
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    with pytest.raises(ValueError):
        D.initialize()
    # and the other direction: a process count with nowhere to rendezvous
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    with pytest.raises(ValueError):
        D.initialize()


def test_hybrid_mesh_single_host_shape():
    mesh = D.make_hybrid_mesh()
    assert mesh.axis_names == ("hosts", "data")
    assert mesh.devices.shape == (1, len(jax.devices()))


def test_row_sharding_and_ingest_roundtrip():
    mesh = D.make_hybrid_mesh()
    n = 16 * len(jax.devices())
    rows = np.arange(n, dtype=np.float32).reshape(n // 2, 2)
    arr = D.from_process_local(rows, mesh)
    np.testing.assert_allclose(np.asarray(arr), rows)
    # a sharded reduction over the hybrid mesh produces the global sum
    total = jax.jit(lambda x: x.sum(),
                    out_shardings=D.replicated(mesh))(arr)
    assert float(total) == rows.sum()


def test_histogram_reduction_over_hybrid_mesh():
    """The framework's core pattern — row-sharded histogram all-reduced to a
    replicated table — compiles and is exact over the (hosts, data) mesh."""
    from avenir_tpu.ops.histogram import class_bin_histogram
    mesh = D.make_hybrid_mesh()
    n = 32 * len(jax.devices())
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 2, n).astype(np.int32)
    bins = rng.integers(0, 5, (n, 3)).astype(np.int32)
    row = D.row_sharding(mesh)
    rep = D.replicated(mesh)
    fn = jax.jit(lambda c, b: class_bin_histogram(c, b, 2, 5),
                 in_shardings=(row, row), out_shardings=rep)
    out = np.asarray(fn(jax.device_put(cls, row), jax.device_put(bins, row)))
    assert out.sum() == n * 3
    expect = np.zeros((2, 3, 5))
    for i in range(n):
        for f in range(3):
            expect[cls[i], f, bins[i, f]] += 1
    np.testing.assert_allclose(out, expect)


def test_cli_distributed_mode_installs_hybrid_context(tmp_path, monkeypatch):
    """-Ddistributed.mode=1 routes the job through a hybrid-mesh runtime
    context, and the model + counters match a default (1-D mesh) run."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.parallel import mesh as M

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    train = tmp_path / "train.csv"
    train.write_text("\n".join(telecom_churn_gen.generate(512, 3)))

    from avenir_tpu.cli import jobs as J
    captured = {}
    orig = J.JOBS["org.avenir.bayesian.BayesianDistribution"]

    def spy(cfg, i, o):
        captured["ctx"] = M.runtime_context()
        return orig(cfg, i, o)

    monkeypatch.setitem(J.JOBS, "org.avenir.bayesian.BayesianDistribution",
                        spy)

    def run(extra, out):
        rc = cli_run.main([
            "org.avenir.bayesian.BayesianDistribution",
            f"-Dconf.path={res}/churn.properties",
            f"-Dbad.feature.schema.file.path={res}/churn.json",
            *extra, str(train), str(tmp_path / out)])
        assert rc == 0
        return (tmp_path / out / "part-r-00000").read_text()

    default_model = run([], "m_default")
    dist_model = run(["-Ddistributed.mode=1"], "m_dist")
    # the job ran over the (hosts, data) hybrid mesh...
    ctx = captured["ctx"]
    assert ctx.mesh.axis_names == ("hosts", "data")
    assert ctx.n_devices == len(jax.devices())
    assert dist_model == default_model
    # ...and main() reset the context afterwards (no leak into later runs)
    assert M.runtime_context().mesh.axis_names != ("hosts", "data")


def test_all_reduce_counters_single_process_identity():
    from avenir_tpu.core.metrics import Counters
    c = Counters()
    c.increment("G", "a", 3)
    out = D.all_reduce_counters(c)
    assert out is c


def _spawn_two_workers_spec(tmp_path, specs):
    """Spawn the 2-process worker pair on an ephemeral coordinator port;
    ``specs[i]`` is process i's {"runs": [[argv...], ...]} spec.  Returns
    [(returncode, stdout, stderr)] — workers are killed on timeout so a
    hung coordinator can't leak into the rest of the run."""
    import json
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    worker = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_COORDINATOR_ADDRESS",
                        "JAX_NUM_PROCESSES", "JAX_PROCESS_ID")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    spec_paths = []
    for i, spec in enumerate(specs):
        p = tmp_path / f"spec{i}.json"
        p.write_text(json.dumps(spec))
        spec_paths.append(str(p))
    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), port, spec_paths[i]],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=300)
            results.append((p.returncode, stdout, stderr))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return results


def _nb_train_spec(res, shard, out):
    return {"runs": [[
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        "-Ddistributed.mode=1", shard, out]]}


def _spawn_two_workers(tmp_path, res, shard_names):
    return _spawn_two_workers_spec(tmp_path, [
        _nb_train_spec(res, str(tmp_path / shard_names[i]),
                       str(tmp_path / f"out{i}"))
        for i in range(2)])


def test_true_two_process_nb_train(tmp_path):
    """REAL multi-process validation (not the virtual mesh): two coordinated
    jax processes, each loading its own equal-size CSV shard, run the NB
    train job through the CLI distributed mode.  Both processes must produce
    the model of the CONCATENATED data (bit-identical to a single-process
    run), and the all-reduced counters render on process 0 only."""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(600, 8)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))
    (tmp_path / "full.csv").write_text("\n".join(rows))

    outs = []
    for rc_w, stdout, stderr in _spawn_two_workers(
            tmp_path, res, ["shard0.csv", "shard1.csv"]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout
        outs.append(stdout)

    # single-process reference on the concatenated file
    rc = cli_run.main([
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        str(tmp_path / "full.csv"), str(tmp_path / "out_single")])
    assert rc == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    m0 = (tmp_path / "out0" / "part-r-00000").read_text()
    m1 = (tmp_path / "out1" / "part-r-00000").read_text()
    assert m0 == single, "proc 0 model != single-process global model"
    assert m1 == single, "proc 1 model != single-process global model"
    # counters: all-reduced and rendered on process 0 only
    c0 = outs[0].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    c1 = outs[1].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert c0.strip(), "process 0 rendered no counters"
    assert not c1.strip(), "process 1 must not render counters"


def test_true_two_process_unequal_shards_correct(tmp_path):
    """Unequal per-process shards: NB train's pod-agreed chunk schedule
    pads the shorter shard with masked-out rows, so the run SUCCEEDS and
    both processes produce the exact global model of the concatenated
    data.  (Jobs that ship whole unequal arrays through from_process_local
    still fail its equal-shape guard — that contract is pinned by
    test_row_sharding unit tests.)"""
    import os
    import sys

    from avenir_tpu.cli import run as cli_run

    res = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "resource"))
    sys.path.insert(0, res)
    from gen import telecom_churn_gen

    rows = telecom_churn_gen.generate(500, 9)
    (tmp_path / "shard0.csv").write_text("\n".join(rows[:300]))   # 300 rows
    (tmp_path / "shard1.csv").write_text("\n".join(rows[300:]))   # 200 rows
    (tmp_path / "full.csv").write_text("\n".join(rows))

    for rc_w, stdout, stderr in _spawn_two_workers(
            tmp_path, res, ["shard0.csv", "shard1.csv"]):
        assert rc_w == 0, f"worker failed:\n{stderr[-2000:]}"
        assert "WORKER_OK" in stdout, stdout

    rc = cli_run.main([
        "org.avenir.bayesian.BayesianDistribution",
        f"-Dconf.path={res}/churn.properties",
        f"-Dbad.feature.schema.file.path={res}/churn.json",
        str(tmp_path / "full.csv"), str(tmp_path / "out_single")])
    assert rc == 0
    single = (tmp_path / "out_single" / "part-r-00000").read_text()
    assert (tmp_path / "out0" / "part-r-00000").read_text() == single
    assert (tmp_path / "out1" / "part-r-00000").read_text() == single


def test_write_text_output_per_process_parts(tmp_path, monkeypatch):
    """Map-only (shard-local) outputs get per-process part numbers under
    multi-process; reducer-style global artifacts keep part 0."""
    from avenir_tpu.core import artifacts
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    p = artifacts.write_text_output(str(tmp_path / "pred"), ["a"], role="m")
    assert p.endswith("part-m-00001")
    p = artifacts.write_text_output(str(tmp_path / "model"), ["b"], role="r")
    assert p.endswith("part-r-00000")
    # explicit override wins either way
    p = artifacts.write_text_output(str(tmp_path / "x"), ["c"], role="r",
                                    local_shard=True)
    assert p.endswith("part-r-00001")


# ---------------------------------------------------------------------------
# round-4: multi-process correct-or-loud for host-side jobs
# ---------------------------------------------------------------------------

TRANS_LINES = [
    "t01,milk,bread,butter", "t02,milk,bread", "t03,bread,butter",
    "t04,milk,butter", "t05,milk,bread,butter,jam", "t06,bread,jam",
    "t07,milk,bread", "t08,coffee,milk", "t09,milk,bread,butter",
    "t10,bread,butter,jam", "t11,milk,jam", "t12,bread,milk,butter",
]


def _apriori_props(tmp_path, total):
    props = tmp_path / "fit.properties"
    props.write_text(
        "field.delim.regex=,\nfield.delim.out=,\n"
        "fia.item.set.length=1\nfia.tans.id.ord=0\n"
        "fia.skip.field.count=1\nfia.support.threshold=0.25\n"
        f"fia.total.tans.count={total}\n"
        "fia.trans.id.output=false\n"
        "arm.conf.threshold=0.5\narm.output.confidence=true\n")
    return str(props)


def _apriori_runs(props, shard, lvl1, lvl2, comb, rules):
    """Level-1 -> level-2 -> rule mining, chained in one worker process
    (re-enters distributed mode per run).  ``comb`` is the rule miner's
    input dir — the parent pre-creates it with symlinks to both level
    outputs (the reference feeds the miner every level's itemset file)."""
    return [
        ["org.avenir.association.FrequentItemsApriori",
         f"-Dconf.path={props}", "-Ddistributed.mode=1", shard, lvl1],
        ["org.avenir.association.FrequentItemsApriori",
         f"-Dconf.path={props}", "-Dfia.item.set.length=2",
         f"-Dfia.item.set.file.path={lvl1}",
         "-Ddistributed.mode=1", shard, lvl2],
        ["org.avenir.association.AssociationRuleMiner",
         f"-Dconf.path={props}", "-Ddistributed.mode=1", comb, rules],
    ]


def _link_levels(comb, lvl_paths):
    import os
    os.makedirs(comb, exist_ok=True)
    for j, lvl in enumerate(lvl_paths):
        os.symlink(os.path.join(lvl, "part-r-00000"),
                   os.path.join(comb, f"part-lvl{j}"))


def test_true_two_process_apriori_and_rules(tmp_path):
    """Sharded Apriori (vocab/candidate union + count all-reduce) and the
    gather-mode rule miner must produce the IDENTICAL global output on both
    processes as a single-process run over the full transaction file —
    the reference got this from the shuffle (FrequentItemsApriori.java:
    89-306); shard-local results are the silent failure this guards.

    The rule-mining stage also pins the gather contract: the union of the
    per-process inputs is the dataset, so a replicated upstream artifact
    (every process holds the identical global itemset files) is fed on
    process 0 only — process 1 reads an empty shard and still emits the
    full global rule set."""
    import os

    from avenir_tpu.cli import run as cli_run

    (tmp_path / "shard0.csv").write_text("\n".join(TRANS_LINES[:6]))
    (tmp_path / "shard1.csv").write_text("\n".join(TRANS_LINES[6:]))
    (tmp_path / "full.csv").write_text("\n".join(TRANS_LINES))
    props = _apriori_props(tmp_path, len(TRANS_LINES))

    # process 0's rule input: both level outputs; process 1: empty shard
    _link_levels(str(tmp_path / "comb_0"),
                 [str(tmp_path / "lvl1_0"), str(tmp_path / "lvl2_0")])
    os.makedirs(tmp_path / "comb_1")
    (tmp_path / "comb_1" / "part-empty").write_text("")

    specs = []
    for i in range(2):
        specs.append({"runs": _apriori_runs(
            props, str(tmp_path / f"shard{i}.csv"),
            str(tmp_path / f"lvl1_{i}"), str(tmp_path / f"lvl2_{i}"),
            str(tmp_path / f"comb_{i}"), str(tmp_path / f"rules_{i}"))})
    outs = []
    for rc, stdout, stderr in _spawn_two_workers_spec(tmp_path, specs):
        assert rc == 0, f"worker failed:\n{stderr[-3000:]}"
        assert "WORKER_OK" in stdout, stdout
        outs.append(stdout)
    # counter semantics: transactions are per-shard and all-reduced (6+6),
    # the global-identical tallies are NOT inflated by the process count —
    # frequentItemSets counted on process 0 only, and the gather-mode rule
    # miner's counters skip the all-reduce entirely
    c0 = outs[0].split("COUNTERS_BEGIN\n")[1].split("COUNTERS_END")[0]
    assert "transactions=12" in c0, c0
    assert "frequentItemSets=4" in c0, c0      # lvl1: bread,butter,jam,milk
    assert "rules=6" in c0, c0

    # single-process reference over the concatenated transactions
    _link_levels(str(tmp_path / "comb_s"),
                 [str(tmp_path / "lvl1_s"), str(tmp_path / "lvl2_s")])
    for argv in _apriori_runs(props, str(tmp_path / "full.csv"),
                              str(tmp_path / "lvl1_s"),
                              str(tmp_path / "lvl2_s"),
                              str(tmp_path / "comb_s"),
                              str(tmp_path / "rules_s")):
        assert cli_run.main([a for a in argv
                             if a != "-Ddistributed.mode=1"]) == 0

    for stage in ("lvl1", "lvl2", "rules"):
        single = sorted((tmp_path / f"{stage}_s").glob("part-*"))[0].read_text()
        assert single.strip(), f"single-process {stage} output empty"
        for i in range(2):
            got = sorted((tmp_path / f"{stage}_{i}").glob("part-*"))[0].read_text()
            assert got == single, (
                f"process {i} {stage} output != single-process global output")


def test_every_job_has_dist_mode():
    """The correct-or-loud contract: every registered job carries an
    explicit multi-process class, so nothing can silently default."""
    from avenir_tpu.cli import run as cli_run  # registers all packs # noqa
    from avenir_tpu.cli.jobs import JOBS, JOB_DIST, _DIST_MODES
    for name, fn in JOBS.items():
        assert fn in JOB_DIST, f"{name} has no dist mode"
        assert JOB_DIST[fn] in _DIST_MODES


def test_dist_mode_guard_refuses_unclassified(monkeypatch, tmp_path):
    """An unclassified (or refuse-marked) job must be rejected under
    multi-process instead of emitting shard-local results."""
    import pytest
    from avenir_tpu.cli import run as cli_run

    def fake_job(cfg, in_path, out_path):
        return None

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="not multi-process safe"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(tmp_path / "in"))


def test_dist_mode_gather_spools_full_input(monkeypatch, tmp_path):
    """gather-mode jobs see the allgathered input through a spool DIR that
    preserves per-file basenames (prefix-keyed jobs depend on them), and
    an input-presence mismatch across processes raises instead of
    deadlocking half the pod in a collective."""
    import os
    import pytest
    from avenir_tpu.cli import run as cli_run
    from avenir_tpu.cli import jobs as J
    from avenir_tpu.parallel import distributed as D

    def fake_job(cfg, in_path, out_path):
        return None

    indir = tmp_path / "in"
    indir.mkdir()
    (indir / "tr-part").write_text("a\nb")
    (indir / "part-r-00000").write_text("c")
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setitem(J.JOB_DIST, fake_job, "gather")

    # simulate a peer process holding a DIFFERENT shard: digest meta phase
    # (tuple arg) then the content phase (list arg)
    def peer_differs(obj):
        if isinstance(obj, tuple):
            return [obj, (True, "peer-digest")]
        return [obj, [("tr-part", "x\ny")]]

    monkeypatch.setattr(D, "allgather_object", peer_differs)
    spool, cleanup = cli_run._apply_dist_mode(fake_job, "FakeJob",
                                              str(indir))
    assert spool == cleanup and os.path.isdir(spool)
    names = sorted(os.listdir(spool))
    assert names == ["part-r-00000.p0", "tr-part.p0", "tr-part.p1"]
    assert open(os.path.join(spool, "tr-part.p1")).read() == "x\ny"
    # the train-prefix key survives spooling
    assert sum(n.startswith("tr") for n in names) == 2

    # shared-filesystem launch (identical digests everywhere): the input
    # is used as-is — no spool, no P-fold double-count of the union
    monkeypatch.setattr(D, "allgather_object", lambda obj: [obj, obj])
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)

    # processes disagreeing on input presence must raise, not deadlock
    monkeypatch.setattr(
        D, "allgather_object", lambda obj: [obj, (False, "")])
    with pytest.raises(RuntimeError, match="disagree"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(indir))

    # sharded jobs with DISTINCT per-process shards pass through untouched
    monkeypatch.setitem(J.JOB_DIST, fake_job, "sharded")
    monkeypatch.setattr(D, "allgather_object",
                        lambda obj: [obj, (True, "peer-digest")])
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)

    # ...but an identical input everywhere (shared-fs same-argv launch)
    # would silently P-fold inflate sharded/map results: refuse loudly
    monkeypatch.setattr(D, "allgather_object", lambda obj: [obj, obj])
    with pytest.raises(RuntimeError, match="IDENTICAL input"):
        cli_run._apply_dist_mode(fake_job, "FakeJob", str(indir))
    monkeypatch.setenv("AVENIR_TPU_ALLOW_IDENTICAL_SHARDS", "1")
    assert cli_run._apply_dist_mode(
        fake_job, "FakeJob", str(indir)) == (str(indir), None)


def test_allgather_helpers_single_process_identity():
    from avenir_tpu.parallel import distributed as D
    assert D.allgather_object({"k": [1, 2]}) == [{"k": [1, 2]}]
    np.testing.assert_array_equal(
        D.all_reduce_host_array(np.array([3, 4])), np.array([3, 4]))
