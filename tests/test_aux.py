"""Aux subsystems (SURVEY.md §5): step timing/tracing, checkpoint/resume."""

import json
import time

import numpy as np
import pytest

from avenir_tpu.core.checkpoint import CheckpointManager
from avenir_tpu.core.metrics import Counters
from avenir_tpu.utils.tracing import StepTimer, get_logger, trace


def test_step_timer_accumulates_and_exports():
    t = StepTimer()
    for _ in range(3):
        with t.step("work"):
            time.sleep(0.01)
    with t.step("other"):
        pass
    assert t.calls["work"] == 3
    assert t.totals["work"] >= 0.03
    assert t.mean_ms("work") >= 10.0
    c = Counters()
    t.export(c)
    assert c.get("Profiling", "work.calls") == 3
    assert c.get("Profiling", "work.timeMs") >= 30
    assert "work" in t.summary()


def test_step_timer_percentiles_from_recorded_samples():
    t = StepTimer(keep_samples=1000)
    for ms in range(1, 101):               # 1..100 ms
        t.record("req", ms / 1000.0)
    assert t.calls["req"] == 100
    # numpy linear-interpolation percentiles over the sample window
    assert t.percentile_ms("req", 50) == pytest.approx(50.5)
    assert t.percentile_ms("req", 95) == pytest.approx(95.05)
    assert t.percentile_ms("req", 99) == pytest.approx(99.01)
    assert t.percentiles_ms("req") == {
        50.0: pytest.approx(50.5), 95.0: pytest.approx(95.05),
        99.0: pytest.approx(99.01)}
    c = Counters()
    t.export(c)
    # exported as integer MICROseconds so sub-ms tails survive
    assert c.get("Profiling", "req.p50Us") == 50500
    assert c.get("Profiling", "req.p95Us") == 95050
    assert c.get("Profiling", "req.p99Us") == 99010
    # p50 and the mean tell different stories under a skewed tail
    t.record("req", 10.0)
    assert t.mean_ms("req") > t.percentile_ms("req", 50)


def test_step_timer_sample_window_is_bounded():
    t = StepTimer(keep_samples=10)
    for ms in range(1, 101):
        t.record("req", ms / 1000.0)
    # only the most recent 10 samples (91..100 ms) back the percentiles
    assert len(t.samples["req"]) == 10
    assert t.percentile_ms("req", 50) == pytest.approx(95.5)
    # totals/calls still account every call
    assert t.calls["req"] == 100


def test_step_timer_step_context_records_samples():
    t = StepTimer(keep_samples=16)
    with t.step("work"):
        time.sleep(0.005)
    assert len(t.samples["work"]) == 1
    assert t.percentile_ms("work", 50) >= 5.0


def test_step_timer_without_samples_keeps_legacy_export():
    t = StepTimer()                        # keep_samples=0: no window
    with t.step("work"):
        pass
    assert t.percentile_ms("work", 99) == 0.0
    c = Counters()
    t.export(c)
    assert "work.p99Us" not in c.as_dict().get("Profiling", {})
    assert c.get("Profiling", "work.calls") == 1


def test_trace_noop_without_dir():
    with trace(None) as active:
        assert active is False


def test_logger_debug_gate(capsys):
    lg = get_logger("avenir_tpu.test", debug_on=False)
    assert not lg.isEnabledFor(10)  # DEBUG off
    lg = get_logger("avenir_tpu.test", debug_on=True)
    assert lg.isEnabledFor(10)


def test_checkpoint_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest_step() is None
    with pytest.raises(FileNotFoundError):
        mgr.restore()
    mgr.save(1, {"w": np.arange(4.0)}, {"note": "first"})
    mgr.save(5, {"w": np.arange(4.0) * 2})
    step, arrays, meta = mgr.restore()
    assert step == 5
    np.testing.assert_allclose(arrays["w"], np.arange(4.0) * 2)
    step, arrays, meta = mgr.restore(1)
    assert meta == {"note": "first"}


def test_checkpoint_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, {"x": np.zeros(1)})
    assert mgr.steps() == [3, 4]


def test_nn_trainer_checkpoint_resume(tmp_path):
    """Checkpointed chunked training resumes exactly where it stopped."""
    from avenir_tpu.cli import run as cli_run
    from tests.test_nn_jobs import SCHEMA, gen_csv
    schema = tmp_path / "nn.json"
    schema.write_text(json.dumps(SCHEMA))
    train_csv = tmp_path / "train.csv"
    gen_csv(str(train_csv), n=150)
    ck = tmp_path / "ck"
    props = tmp_path / "nn.properties"
    props.write_text(f"""
field.delim.regex=,
feature.schema.file.path={schema}
nn.hidden.units=4
nn.iteration.count=200
nn.learning.rate=0.01
nn.checkpoint.dir.path={ck}
nn.checkpoint.interval=80
""")
    rc = cli_run.main(["neuralNetwork", f"-Dconf.path={props}",
                       str(train_csv), str(tmp_path / "out1")])
    assert rc == 0
    mgr = CheckpointManager(str(ck))
    # interval 80 aligns down to the validation grid (50): 4 chunks of 50
    assert mgr.latest_step() == 200
    # rerun: resumes at 200, trains nothing, still succeeds
    rc = cli_run.main(["neuralNetwork", f"-Dconf.path={props}",
                       str(train_csv), str(tmp_path / "out2")])
    assert rc == 0
    assert mgr.latest_step() == 200
    # changing the architecture against the same checkpoint dir must fail
    props.write_text(props.read_text().replace("nn.hidden.units=4",
                                               "nn.hidden.units=9"))
    with pytest.raises(ValueError, match="checkpoint"):
        cli_run.main(["neuralNetwork", f"-Dconf.path={props}",
                      str(train_csv), str(tmp_path / "out3")])


def test_java_time_format_translation():
    """utils/timefmt: the SimpleDateFormat subset reference configs use."""
    from avenir_tpu.utils.timefmt import java_time_format
    import datetime as dt
    assert java_time_format("yyyy-MM-dd HH:mm:ss") == "%Y-%m-%d %H:%M:%S"
    assert java_time_format("MM-dd-yyyy") == "%m-%d-%Y"
    # round-trip: parse a formatted timestamp with the translated pattern
    fmt = java_time_format("yyyy-MM-dd HH:mm:ss")
    t = dt.datetime.strptime("2026-07-30 13:45:10", fmt)
    assert (t.year, t.minute) == (2026, 45)


def test_force_platform_no_request_is_noop(monkeypatch):
    """core/platform: with nothing requested the escape hatch must not
    touch jax config (the conftest already pinned cpu for this process)."""
    from avenir_tpu.core.platform import force_platform
    monkeypatch.delenv("AVENIR_TPU_PLATFORM", raising=False)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert force_platform() is None


def test_force_platform_applies_requested():
    """The apply path must run in a FRESH interpreter (this process's
    conftest already pinned cpu, which would make the in-process guard a
    no-op and the assertion vacuous): sitecustomize pre-imports jax on
    the default backend, then the escape hatch flips it to cpu."""
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-c",
         "from avenir_tpu.core.platform import force_platform\n"
         "import jax\n"
         "applied = force_platform()\n"
         "print(applied, jax.config.jax_platforms)"],
        capture_output=True, text=True, timeout=300,
        env={**__import__('os').environ, "AVENIR_TPU_PLATFORM": "cpu"})
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip().split()[-2:] == ["cpu", "cpu"]
