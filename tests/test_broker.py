"""Sharded RESP broker tier (ISSUE 13): the consistent-hash ring, the
fan-out client, and the horizontal fleet on top of it.

Contracts under test:

  * ring stability — adding/removing one of M shards remaps only ~1/M of
    the id space, and every surviving assignment stays put (the property
    that makes a shard death a local event, not a fleet-wide reshuffle);
  * reply reassembly — the same requests through a 2-shard ring and
    through one broker produce byte-identical (id, label) sets;
  * degraded-ring semantics — a killed shard degrades the client to the
    survivors with a warning + ``Broker/BrokerShardDown`` counter;
    values from a failed push re-route, and the unanswered-id re-offer
    closes the loop: NO accepted request ends the run unanswered (busy
    replies allowed, drops are not);
  * the multi-process lane — two ``fleet_host`` OS processes over two
    broker shards answer a shared load exactly once, each under its own
    host label.
"""

import json
import os
import subprocess
import sys
import time
import warnings

import pytest

from avenir_tpu.core.metrics import Counters
from avenir_tpu.core.table import encode_rows
from avenir_tpu.io.respq import (HashRing, RespClient, RespServer,
                                 ShardedRespClient, make_queue_client)
from avenir_tpu.serving import BatchPolicy, ModelRegistry, ServingFleet
from tests.test_fleet import drain_replies, make_fleet_registry
from tests.test_serving import forest_batch_predict, raw_rows_of
from tests.test_tree import SCHEMA

pytestmark = pytest.mark.broker


# --------------------------------------------------------------------------
# the ring
# --------------------------------------------------------------------------

def test_hash_ring_remap_bound_on_remove_and_add():
    """Consistent hashing's defining property, pinned: dropping one of
    M=4 shards moves EXACTLY the dead shard's keys (~1/M, bounded at
    1.6/M for vnode imbalance) and no surviving key moves; adding a 5th
    moves at most ~1.6/5."""
    ids = [str(i) for i in range(20_000)]
    eps4 = [f"shard{i}:1" for i in range(4)]
    r4 = HashRing(eps4)
    r3 = r4.without("shard2:1")
    before = {i: r4.lookup(i) for i in ids}
    after3 = {i: r3.lookup(i) for i in ids}
    moved = [i for i in ids if before[i] != after3[i]]
    # everything that moved WAS on the removed shard; nothing else moved
    assert all(before[i] == "shard2:1" for i in moved)
    assert len(moved) == sum(1 for i in ids if before[i] == "shard2:1")
    assert len(moved) / len(ids) <= 1.6 / 4, \
        f"remove remapped {len(moved) / len(ids):.3f} of the id space"
    r5 = HashRing(eps4 + ["shard4:1"])
    after5 = {i: r5.lookup(i) for i in ids}
    moved5 = [i for i in ids if before[i] != after5[i]]
    # adding only STEALS keys for the new shard — no lateral moves
    assert all(after5[i] == "shard4:1" for i in moved5)
    assert len(moved5) / len(ids) <= 1.6 / 5, \
        f"add remapped {len(moved5) / len(ids):.3f} of the id space"


def test_hash_ring_stable_across_constructions():
    """Placement is md5-derived, not builtin hash(): two independently
    built rings (what two fleet hosts do) agree on every id."""
    eps = ["h1:1", "h2:1", "h3:1"]
    a, b = HashRing(eps), HashRing(list(eps))
    assert all(a.lookup(str(i)) == b.lookup(str(i)) for i in range(2000))
    with pytest.raises(ValueError, match="duplicate"):
        HashRing(["h1:1", "h1:1"])


def test_sharded_client_routes_request_and_reply_together():
    """predict,<id>,... and its reply <id>,<label> hash to the same
    shard, and the distribution across M=3 is roughly balanced."""
    servers = [RespServer().start() for _ in range(3)]
    try:
        eps = [f"127.0.0.1:{s.port}" for s in servers]
        sc = ShardedRespClient(eps)
        counts = {e: 0 for e in eps}
        for i in range(3000):
            ep = sc.shard_of(sc.id_of(f"predict,{i},a,b"))
            assert ep == sc.shard_of(sc.id_of(f"{i},label"))
            counts[ep] += 1
        for ep, n in counts.items():
            assert 0.15 <= n / 3000 <= 0.55, f"{ep} got {n}/3000"
        sc.close()
    finally:
        for s in servers:
            s.stop()


# --------------------------------------------------------------------------
# reassembly parity vs a single broker
# --------------------------------------------------------------------------

def _collect(cli, queue, expect_n, timeout_s=60.0, stall_s=None):
    """Pop first-reply-per-id until ``expect_n`` collected, the timeout
    lapses, or (``stall_s``) no NEW reply arrived for that long — the
    killed-shard drill's 'the rest died with the shard' detector."""
    got = {}
    deadline = time.monotonic() + timeout_s
    last_progress = time.monotonic()
    while len(got) < expect_n and time.monotonic() < deadline:
        vs = cli.rpop_many(queue, 256)
        if not vs:
            if stall_s is not None \
                    and time.monotonic() - last_progress > stall_s:
                break
            time.sleep(0.002)
            continue
        last_progress = time.monotonic()
        for v in vs:
            rid, label = v.split(",", 1)
            got.setdefault(rid, label)
    return got


def test_sharded_replies_match_single_broker_oracle(tmp_path, mesh_ctx):
    """The SAME 120 requests through a 2-shard ring (2-worker fleet) and
    through one broker (the oracle) reassemble to byte-identical
    ``<id>,<label>`` lines."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 40)
    msgs = [",".join(["predict", str(i)] + rows[i % 40])
            for i in range(120)]

    def run(endpoints):
        fleet = ServingFleet(
            reg, "churn", buckets=(8, 64),
            policy=BatchPolicy(max_batch=16, max_wait_ms=2.0),
            n_workers=2,
            config={"redis.server.endpoints": endpoints})
        fleet.start()
        feeder = make_queue_client({"redis.server.endpoints": endpoints})
        try:
            feeder.lpush_many("requestQueue", msgs)
            got = _collect(feeder, "predictionQueue", len(msgs))
            feeder.lpush("requestQueue", "stop")
            assert fleet.wait(30.0)
        finally:
            fleet.stop()
            feeder.close()
        return ["%s,%s" % (rid, got[rid]) for rid in
                sorted(got, key=int)]

    servers = [RespServer().start() for _ in range(3)]
    try:
        sharded = run([f"127.0.0.1:{servers[0].port}",
                       f"127.0.0.1:{servers[1].port}"])
        single = run([f"127.0.0.1:{servers[2].port}"])
    finally:
        for s in servers:
            s.stop()
    assert len(sharded) == 120
    assert "\n".join(sharded).encode() == "\n".join(single).encode(), \
        "sharded reassembly diverged from the single-broker oracle"


# --------------------------------------------------------------------------
# degraded ring: killed shard, nothing accepted is lost
# --------------------------------------------------------------------------

def test_dead_shard_degrades_client_with_counter():
    servers = [RespServer().start() for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    cnt = Counters()
    sc = ShardedRespClient(eps, counters=cnt)
    try:
        msgs = [f"predict,{i},x" for i in range(50)]
        sc.lpush_many("q", msgs)
        servers[1].kill()
        with pytest.warns(RuntimeWarning, match="degrading to the "
                                               "surviving ring"):
            sc.lpush_many("q", msgs)          # re-routes the dead group
        assert cnt.get("Broker", "BrokerShardDown") == 1
        assert sc.down_endpoints == [eps[1]]
        assert sc.live_endpoints == [eps[0]]
        # the re-routed batch is fully poppable from the survivor
        got = sc.rpop_many("q", 500)
        assert len(got) >= len(msgs)
        # depth observability over the degraded ring keeps working
        assert eps[0] in sc.depths("q")
        # killing the LAST shard raises — nowhere to degrade to
        servers[0].kill()
        with pytest.raises((ConnectionError, OSError)):
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sc.lpush_many("q", msgs)
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_killed_shard_mid_run_no_accepted_request_lost(tmp_path,
                                                       mesh_ctx):
    """The acceptance drill: 2-shard ring, 2-worker fleet, one shard
    KILLED mid-load.  The producer re-offers ids still unanswered after
    the kill (the documented client-side recovery for messages that
    died inside the shard's memory), and the run ends with EVERY id
    answered a real prediction — busy replies would be allowed, drops
    are not.  The fleet's merged counters carry the BrokerShardDown
    evidence."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 40)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    servers = [RespServer().start() for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    fleet = ServingFleet(
        reg, "churn", buckets=(8, 64),
        policy=BatchPolicy(max_batch=16, max_wait_ms=1.0),
        n_workers=2, config={"redis.server.endpoints": eps})
    n = 240
    ids = [str(i) for i in range(n)]
    msgs = {i: ",".join(["predict", i] + rows[int(i) % 40]) for i in ids}
    got = {}
    feeder = None
    try:
        with warnings.catch_warnings():
            # shard-down warnings from worker threads and the feeder are
            # the EXPECTED evidence here; pytest.warns can't see the
            # worker threads' anyway
            warnings.simplefilter("ignore", RuntimeWarning)
            fleet.start()
            feeder = ShardedRespClient(eps)
            feeder.lpush_many("requestQueue",
                              [msgs[i] for i in ids[:n // 2]])
            # let the fleet get properly into the first half…
            deadline = time.monotonic() + 30
            while len(got) < n // 4 and time.monotonic() < deadline:
                got.update(_collect(feeder, "predictionQueue", n // 4,
                                    timeout_s=0.2))
            # …kill shard B mid-run, keep offering the second half: the
            # feeder re-routes onto the survivor
            servers[1].kill()
            feeder.lpush_many("requestQueue",
                              [msgs[i] for i in ids[n // 2:]])
            got.update(_collect(feeder, "predictionQueue", n,
                                timeout_s=30.0, stall_s=3.0))
            # requests that died inside the killed shard's memory are
            # the producer's re-offer window: send the unanswered ids
            # again through the surviving ring
            missing = [i for i in ids if i not in got]
            resent = len(missing)
            if missing:
                feeder.lpush_many("requestQueue",
                                  [msgs[i] for i in missing])
                got.update(_collect(feeder, "predictionQueue", n,
                                    timeout_s=30.0))
        assert sorted(got, key=int) == ids, \
            f"{n - len(got)} accepted requests lost after shard kill " \
            f"({resent} re-offered)"
        for i in ids:
            assert got[i] == expect[int(i) % 40]
        merged = fleet.merged_counters()
        assert merged.get("Broker", "BrokerShardDown") >= 1 \
            or feeder.down_endpoints, \
            "nothing recorded the dead shard"
    finally:
        fleet.stop()
        if feeder is not None:
            feeder.close()
        for s in servers:
            s.stop()


def test_addressed_reload_reaches_its_host_only(tmp_path, mesh_ctx):
    """Multi-host hot-swap convergence: 'reload,<host_label>' applies
    only on the addressed fleet; a copy popped by the WRONG host is
    re-pushed until the addressee drains it (a bare broadcast cannot
    converge N hosts — one host's workers can pop every copy).  The
    unaddressed 'reload' single-fleet path stays pinned by
    test_fleet_hot_swap_no_loss_no_dup."""
    import warnings as _w
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    server = RespServer().start()

    def make(host):
        return ServingFleet(
            reg, "churn", buckets=(8,),
            policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
            n_workers=1, host_label=host,
            config={"redis.server.port": server.port})

    fa, fb = make("hA").start(), make("hB").start()
    feeder = RespClient(port=server.port)
    try:
        reg.publish("churn", models, schema=SCHEMA)   # v2
        # addressed to hB: hA workers must re-push, hB must converge
        feeder.lpush("requestQueue", "reload,hB")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                set(fb.stats()["model_versions"].values()) != {2}:
            time.sleep(0.05)
        assert set(fb.stats()["model_versions"].values()) == {2}
        assert set(fa.stats()["model_versions"].values()) == {1}, \
            "a reload addressed to hB swapped hA"
    finally:
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            fa.stop()
            fb.stop()
        feeder.close()
        server.stop()


def test_stop_on_one_shard_never_strands_requests_on_another(tmp_path,
                                                             mesh_ctx):
    """The drain-then-stop invariant, made deterministic: the wire
    'stop' and a batch of requests are pushed to DIFFERENT shards
    BEFORE the fleet starts, so a worker can meet the stop first.  The
    single-queue FIFO argument ('everything before the stop was already
    popped') does not hold across a ring — the post-stop sweep must
    still answer every request."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 20)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    servers = [RespServer().start() for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    feeder = ShardedRespClient(eps)
    stop_shard = feeder.shard_of(feeder.id_of("stop"))
    # ids routed to the shard the stop does NOT land on
    ids = [str(i) for i in range(400)
           if feeder.shard_of(str(i)) != stop_shard][:60]
    assert len(ids) == 60
    fleet = ServingFleet(
        reg, "churn", buckets=(8, 64),
        policy=BatchPolicy(max_batch=16, max_wait_ms=1.0),
        n_workers=2, config={"redis.server.endpoints": eps})
    try:
        # everything queued BEFORE the fleet exists: the stop sits
        # alone on its shard, the requests on the other
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", i] + rows[int(i) % 20])
                           for i in ids])
        feeder.lpush("requestQueue", "stop")
        fleet.start()
        assert fleet.wait(60.0), "fleet never stopped"
        got = _collect(feeder, "predictionQueue", len(ids),
                       timeout_s=30.0, stall_s=3.0)
        missing = sorted(set(ids) - set(got), key=int)
        assert not missing, \
            f"stop stranded {len(missing)} accepted requests on the " \
            f"other shard: {missing[:5]}..."
        for i in ids:
            assert got[i] == expect[int(i) % 20]
    finally:
        fleet.stop()
        feeder.close()
        for s in servers:
            s.stop()


# --------------------------------------------------------------------------
# multi-process: two fleet_host processes, two broker shards
# --------------------------------------------------------------------------

def test_two_fleet_hosts_two_shards_exactly_once(tmp_path, mesh_ctx):
    """The horizontal topology as OS processes: 2 broker shards in this
    process, 2 ``fleet_host`` children draining them against the shared
    registry.  Every request answered exactly once, BOTH hosts served a
    share, and each child reports its own host label."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 40)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    servers = [RespServer().start() for _ in range(2)]
    eps = ",".join(f"127.0.0.1:{s.port}" for s in servers)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AVENIR_TPU_PLATFORM="cpu")
    children = [
        subprocess.Popen(
            [sys.executable, "-m", "avenir_tpu.serving.fleet_host",
             "--registry", str(tmp_path / "registry"),
             "--model", "churn", "--endpoints", eps,
             "--workers", "2", "--host-label", label,
             "--buckets", "8,64", "--max-batch", "16",
             "--max-idle-s", "45",
             "--ready-file", str(tmp_path / f"ready-{label}")],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for label in ("hostA", "hostB")]
    feeder = ShardedRespClient(eps.split(","))
    n = 200
    try:
        # wait for BOTH hosts to be draining before offering load: a
        # slow-starting child (jax import) must not be measured absent
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not all(
                (tmp_path / f"ready-{lab}").exists()
                for lab in ("hostA", "hostB")):
            assert all(c.poll() is None for c in children), \
                "a fleet_host child died during startup"
            time.sleep(0.05)
        # paced bursts (not one burst) so both hosts demonstrably pull
        for i in range(0, n, 20):
            feeder.lpush_many(
                "requestQueue",
                [",".join(["predict", str(j)] + rows[j % 40])
                 for j in range(i, min(i + 20, n))])
            time.sleep(0.02)
        got = drain_replies(feeder, "predictionQueue", n, timeout_s=120.0)
        assert sorted(got, key=int) == [str(i) for i in range(n)]
        assert all(len(v) == 1 for v in got.values()), "duplicated reply"
        for i in range(n):
            assert got[str(i)] == [expect[i % 40]]
        # one stop per child process, SERIALIZED (push, wait for a child
        # to exit, push the next) so one fast host cannot eat both
        stats = []
        remaining = list(children)
        while remaining:
            feeder.lpush("requestQueue", "stop")
            deadline = time.monotonic() + 90
            exited = None
            while exited is None and time.monotonic() < deadline:
                exited = next((c for c in remaining
                               if c.poll() is not None), None)
                time.sleep(0.05)
            assert exited is not None, "no fleet_host exited on stop"
            remaining.remove(exited)
            out, err = exited.communicate(timeout=30)
            assert exited.returncode == 0, err
            stats.append(json.loads(out.strip().splitlines()[-1]))
        assert {s["host"] for s in stats} == {"hostA", "hostB"}
        assert sum(s["served"] for s in stats) == n
        assert all(s["served"] > 0 for s in stats), \
            f"one host served nothing: {[s['served'] for s in stats]}"
    finally:
        for c in children:
            if c.poll() is None:
                c.kill()
        feeder.close()
        for s in servers:
            s.stop()


# --------------------------------------------------------------------------
# CLI: ps.broker.shards
# --------------------------------------------------------------------------

def test_cli_job_broker_shards(tmp_path, mesh_ctx):
    """predictionService with ps.workers=2 ps.broker.shards=2 answers
    byte-identically to the single-broker replay and stamps the shard
    count into the dump."""
    from avenir_tpu.core.config import Config
    from avenir_tpu.cli import serving_jobs  # noqa: F401
    from avenir_tpu.cli.jobs import resolve
    from tests.test_serving import _train_forest_via_cli
    from tests.test_tree import make_table
    reg_dir = tmp_path / "registry"
    schema_path, trees = _train_forest_via_cli(tmp_path, reg_dir)
    req_rows = raw_rows_of(make_table(40, seed=33), 40)
    expect = forest_batch_predict(trees, encode_rows(req_rows, SCHEMA))
    req_path = tmp_path / "requests.csv"
    req_path.write_text("\n".join(",".join(r) for r in req_rows) + "\n")
    job = resolve("predictionService")
    out_dir = tmp_path / "out_sharded"
    cfg = Config({
        "field.delim.regex": ",", "field.delim.out": ",",
        "ps.model.registry.dir": str(reg_dir),
        "ps.model.name": "churn",
        "ps.feature.schema.file.path": str(schema_path),
        "ps.batch.max.size": "16", "ps.bucket.sizes": "8,64",
        "ps.transport": "resp", "ps.workers": "2",
        "ps.broker.shards": "2",
    })
    counters = job(cfg, str(req_path), str(out_dir))
    with open(out_dir / "part-m-00000") as fh:
        lines = fh.read().splitlines()
    assert [ln.split(",", 1)[1] for ln in lines] == expect
    assert counters.get("Broker", "Shards") == 2
    assert counters.get("Serving", "Requests") == 40
    # shards without the wire refuse
    from avenir_tpu.core.config import Config as C2
    bad = C2({
        "field.delim.regex": ",", "field.delim.out": ",",
        "ps.model.registry.dir": str(reg_dir),
        "ps.model.name": "churn",
        "ps.feature.schema.file.path": str(schema_path),
        "ps.broker.shards": "2",
    })
    with pytest.raises(ValueError, match="resp"):
        job(bad, str(req_path), str(tmp_path / "out_bad"))
