"""Byte-format pinning (VERDICT r2 #4): every golden-flow artifact must be
BYTE-identical to its committed fixture — a delimiter, column-order, float
-format, or JSON-layout drift fails here.  Regenerate deliberately with
tests/golden/regen.py and commit the diff alongside the format change."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "golden"))

import flows

FIXTURES = os.path.join(os.path.dirname(__file__), "golden", "fixtures")


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    return flows.run_all(str(tmp_path_factory.mktemp("golden")))


@pytest.mark.parametrize("flow_idx", range(len(flows.FLOWS)),
                         ids=[f.__name__ for f in flows.FLOWS])
def test_flow_bytes_match_fixtures(artifacts, flow_idx):
    prefix = flows.FLOWS[flow_idx].__name__.split("_")[0]
    rels = [r for r in artifacts if r.startswith(prefix + "/")]
    assert rels, f"flow produced no artifacts under {prefix}/"
    for rel in rels:
        fixture = os.path.join(FIXTURES, rel)
        assert os.path.exists(fixture), (
            f"missing fixture {rel}; run tests/golden/regen.py and commit")
        with open(fixture) as fh:
            expect = fh.read()
        assert artifacts[rel] == expect, (
            f"{rel} differs from its committed fixture — byte format "
            f"drifted; if intentional, regenerate via tests/golden/regen.py")


def test_no_orphan_fixtures(artifacts):
    on_disk = set()
    for root, _, files in os.walk(FIXTURES):
        for f in files:
            on_disk.add(os.path.relpath(os.path.join(root, f), FIXTURES))
    assert on_disk == set(artifacts), (
        "fixtures/ and flow outputs disagree; run tests/golden/regen.py")
