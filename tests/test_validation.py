"""Validation/model-selection layer (reference python/supv/svm.py k-fold /
random-fold / bagging) over framework trainers."""

import numpy as np

from avenir_tpu.models import validation as V


def _blobs(n=300, seed=0):
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < 0.5).astype(np.int32)
    X = rng.normal(0, 1, (n, 2)).astype(np.float32) + np.where(
        y[:, None] == 1, 1.8, -1.8)
    return X, y


def _centroid_train(X, y):
    return {c: X[y == c].mean(axis=0) for c in np.unique(y)}


def _centroid_predict(model, X):
    classes = sorted(model)
    d = np.stack([np.linalg.norm(X - model[c], axis=1) for c in classes])
    return np.asarray(classes)[np.argmin(d, axis=0)]


def test_kfold_validation():
    X, y = _blobs()
    res = V.kfold_validation(X, y, 5, _centroid_train, _centroid_predict)
    assert len(res.scores) == 5
    assert res.mean > 0.9
    assert res.std < 0.1


def test_random_fold_validation():
    X, y = _blobs(seed=1)
    res = V.random_fold_validation(X, y, n_folds=5, n_iter=7,
                                   train_fn=_centroid_train,
                                   predict_fn=_centroid_predict)
    assert len(res.scores) == 7
    assert res.mean > 0.9


def test_bagging_and_vote():
    X, y = _blobs(seed=2)
    models = V.bagging_train(X, y, 5, _centroid_train)
    assert len(models) == 5
    pred = V.majority_vote(models, X, _centroid_predict)
    assert (pred == y).mean() > 0.9


def test_kfold_vmapped_matches_loop():
    """Masked nearest-centroid trainer under vmap: one XLA program, k folds."""
    import jax.numpy as jnp
    X, y = _blobs(seed=3)

    def train_fold(Xj, yj, train_mask):
        w = train_mask.astype(jnp.float32)
        sums = jnp.stack([
            (Xj * (w * (yj == c))[:, None]).sum(0)
            / jnp.maximum((w * (yj == c)).sum(), 1.0) for c in (0, 1)])
        d = jnp.linalg.norm(Xj[:, None, :] - sums[None], axis=2)  # (n, 2)
        pred = jnp.argmin(d, axis=1)
        val = 1.0 - w
        return ((pred == yj) * val).sum() / jnp.maximum(val.sum(), 1.0)

    res = V.kfold_validation_vmapped(X, y, 5, train_fold)
    assert len(res.scores) == 5
    assert res.mean > 0.9
    loop = V.kfold_validation(X, y, 5, _centroid_train, _centroid_predict)
    assert abs(res.mean - loop.mean) < 0.05


def test_kfold_with_mlp():
    """The validation layer composes with the NN pack trainer."""
    from avenir_tpu.nn import mlp
    X, y = _blobs(seed=4, n=200)
    cfg = mlp.MLPConfig(hidden_dim=4, iterations=150, learning_rate=0.02)

    res = V.kfold_validation(
        X, y, 4,
        train_fn=lambda Xt, yt: mlp.train(Xt, yt, cfg)[0],
        predict_fn=lambda m, Xv: mlp.predict(m, Xv))
    assert res.mean > 0.9
