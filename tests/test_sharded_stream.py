"""Multi-host data-parallel streaming training (ISSUE 7): row-range shard
arithmetic, sharded ingest (parse + cache paths), the one-collective-per-
level tree/forest build, lock-step KNN top-k merge, sharded SMO groups,
kill/resume under sharding, the concurrent-cache-writer guard, and a true
two-subprocess CLI smoke over the jax.distributed-free file transport.

Thread-simulated shards pin a 1-device runtime mesh first: concurrent
multi-device XLA programs from different threads interleave their
per-device collective rendezvous and deadlock (production multi-host runs
one thread per process, so the hazard is harness-only)."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from avenir_tpu.core.metrics import Counters
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.core.table import (BadRecordPolicy, ColumnarTable,
                                   count_source_rows, iter_csv_chunks,
                                   load_csv)
from avenir_tpu.parallel.collectives import AllReducer
from avenir_tpu.parallel.distributed import ShardSpec, shard_rows, shard_spec
from avenir_tpu.parallel.mesh import MeshContext, make_mesh, \
    set_runtime_context
from avenir_tpu.utils.tracing import transfer_ledger

pytestmark = pytest.mark.sharded

SCHEMA = {"fields": [
    {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
    {"name": "c1", "ordinal": 1, "dataType": "categorical", "feature": True,
     "maxSplit": 2, "cardinality": ["a", "b", "c"]},
    {"name": "n1", "ordinal": 2, "dataType": "int", "feature": True,
     "min": 0, "max": 600, "splitScanInterval": 150},
    {"name": "cls", "ordinal": 3, "dataType": "categorical",
     "cardinality": ["T", "F"]},
]}


def _schema():
    return FeatureSchema.from_dict(SCHEMA)


def _write_csv(path, n=499, seed=3, bad_rows=()):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        if i in bad_rows:
            lines.append(f"r{i},a,NOT_A_NUMBER,T")
            continue
        c = ["a", "b", "c"][rng.integers(0, 3)]
        v = int(rng.integers(0, 600))
        cls = "T" if (v > 300) ^ (c == "c") else "F"
        lines.append(f"r{i},{c},{v},{cls}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return str(path)


@pytest.fixture()
def one_device_ctx():
    """Thread-simulated shards need single-device programs (see module
    docstring); restores the default context afterwards."""
    set_runtime_context(MeshContext(make_mesh(1)))
    yield
    set_runtime_context(None)


# --------------------------------------------------------------------------
# split-point arithmetic (parallel/distributed.shard_rows)
# --------------------------------------------------------------------------

def test_shard_rows_partition_properties():
    for n, count, chunk in [(997, 2, 100), (997, 3, 100), (10, 5, 8),
                            (0, 3, 4), (7, 7, 1), (100, 1, 32),
                            (1000, 4, 1)]:
        ranges = [shard_rows(n, i, count, chunk) for i in range(count)]
        # disjoint, ordered, complete
        assert ranges[0][0] == 0
        assert ranges[-1][1] == n
        for (lo_a, hi_a), (lo_b, hi_b) in zip(ranges, ranges[1:]):
            assert hi_a == lo_b
            assert lo_a <= hi_a and lo_b <= hi_b
        # split points on the chunk grid (except the file end)
        for lo, hi in ranges:
            for p in (lo, hi):
                assert p == n or p % chunk == 0


def test_shard_rows_empty_shards_and_remainder():
    # more shards than blocks: extras are empty, the last shard still owns
    # the tail remainder block
    parts = [shard_rows(10, i, 5, 8) for i in range(5)]
    assert sum(h - l for l, h in parts) == 10
    assert parts[-1][1] == 10 and parts[-1][0] == 8  # remainder block
    assert any(l == h for l, h in parts)             # some shard is empty


def test_shard_rows_validation():
    with pytest.raises(ValueError):
        shard_rows(10, 2, 2)
    with pytest.raises(ValueError):
        shard_rows(10, -1, 2)
    with pytest.raises(ValueError):
        shard_rows(10, 0, 0)
    with pytest.raises(ValueError):
        shard_rows(-1, 0, 1)
    with pytest.raises(ValueError):
        shard_rows(10, 0, 2, chunk_rows=0)


def test_shard_spec_env_override(monkeypatch):
    monkeypatch.setenv("AVENIR_TPU_SHARD", "1/3")
    assert shard_spec() == ShardSpec(1, 3)
    monkeypatch.setenv("AVENIR_TPU_SHARD", "junk")
    with pytest.raises(ValueError):
        shard_spec()
    monkeypatch.delenv("AVENIR_TPU_SHARD")
    assert shard_spec() == ShardSpec(0, 1)
    assert not shard_spec().active


# --------------------------------------------------------------------------
# sharded ingest: parse paths
# --------------------------------------------------------------------------

def _union(csv, schema, count, chunk_rows, **kw):
    chunks = []
    for i in range(count):
        chunks.extend(iter_csv_chunks(csv, schema, ",",
                                      chunk_rows=chunk_rows,
                                      shard=(i, count), **kw))
    return ColumnarTable.from_chunks(chunks)


@pytest.mark.parametrize("use_native", [True, False])
def test_shard_union_equals_full_stream(tmp_path, use_native):
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=499)
    full = ColumnarTable.from_chunks(list(iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, use_native=use_native)))
    for count in (2, 3, 7):
        t = _union(csv, schema, count, 64, use_native=use_native)
        assert t.n_rows == full.n_rows == 499
        for o in full.columns:
            np.testing.assert_array_equal(t.columns[o], full.columns[o])


def test_shard_source_row_accounting(tmp_path):
    """Every shard's chunks report absolute source_row_end on the shared
    axis, and consecutive shards hand over exactly at the split point."""
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=300)
    ends = {}
    for i in range(3):
        ends[i] = [c.source_row_end
                   for c in iter_csv_chunks(csv, schema, ",",
                                            chunk_rows=64, shard=(i, 3))]
    bounds = [shard_rows(300, i, 3, 64) for i in range(3)]
    for i, (lo, hi) in enumerate(bounds):
        if ends[i]:
            assert ends[i][-1] == hi
            assert all(lo < e <= hi for e in ends[i])


def test_shard_bad_rows_on_boundary_counters_sum(tmp_path):
    """Bad records landing exactly on (and around) shard split points are
    reported by exactly one shard: per-shard quarantine tallies sum to the
    single-host totals and the quarantined bytes union exactly."""
    schema = _schema()
    # chunk 64, 3 shards over 300 rows -> split points at 128, 192 (grid)
    bad = {0, 63, 64, 127, 128, 191, 192, 299}
    csv = _write_csv(tmp_path / "d.csv", n=300, bad_rows=bad)

    def run(shard, tag):
        counters = Counters()
        pol = BadRecordPolicy("quarantine", str(tmp_path / f"q_{tag}"),
                              counters)
        rows = sum(c.n_rows for c in iter_csv_chunks(
            csv, schema, ",", chunk_rows=64, bad_records=pol, shard=shard))
        return rows, counters, tmp_path / f"q_{tag}" / "part-q-00000"

    rows_full, c_full, q_full = run(None, "full")
    assert c_full.get("BadRecords", "Malformed") == len(bad)
    tot_rows, tot_bad, q_lines = 0, 0, []
    for i in range(3):
        r, c, q = run((i, 3), f"s{i}")
        tot_rows += r
        tot_bad += c.get("BadRecords", "Malformed")
        if q.exists():
            q_lines.extend(q.read_text().splitlines())
    assert tot_rows == rows_full == 300 - len(bad)
    assert tot_bad == len(bad)
    assert sorted(q_lines) == sorted(q_full.read_text().splitlines())


def test_shard_composes_with_start_row(tmp_path):
    """Resume inside a shard: start_row cuts only within the shard's own
    range (the satellite-2 shard-relative restart contract)."""
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=300)
    lo, hi = shard_rows(300, 1, 2, 64)
    whole = ColumnarTable.from_chunks(list(iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, shard=(1, 2))))
    cut = lo + 70  # mid-chunk, inside the shard
    resumed = ColumnarTable.from_chunks(list(iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, shard=(1, 2), start_row=cut)))
    assert resumed.n_rows == hi - cut
    for o in whole.columns:
        np.testing.assert_array_equal(resumed.columns[o],
                                      whole.columns[o][cut - lo:])
    # start_row past the shard's end: empty stream, not an error
    assert list(iter_csv_chunks(csv, schema, ",", chunk_rows=64,
                                shard=(1, 2), start_row=hi + 5)) == []


def test_shard_and_stop_row_are_exclusive(tmp_path):
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=50)
    with pytest.raises(ValueError, match="not both"):
        list(iter_csv_chunks(csv, schema, ",", shard=(0, 2), stop_row=10))


def test_count_source_rows(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text("a,b\n\n  \nc,d\ne,f")
    assert count_source_rows(str(p)) == 3


# --------------------------------------------------------------------------
# sharded ingest: columnar-cache paths
# --------------------------------------------------------------------------

def _build_cache(csv, schema, chunk_rows=64, bad_records=None):
    from avenir_tpu.io.colcache import CachePolicy
    list(iter_csv_chunks(csv, schema, ",", chunk_rows=chunk_rows,
                         bad_records=bad_records,
                         cache=CachePolicy("build")))
    assert os.path.isdir(csv + ".avtc")


def test_shard_union_from_cache_hit(tmp_path):
    """A warm (sidecar) sharded read unions to the same table and the same
    bad-record tallies as the cold parse — even when the replay requests a
    DIFFERENT chunk grid than the cache was built with (mid-chunk cuts by
    source-row arithmetic)."""
    from avenir_tpu.io.colcache import CachePolicy
    schema = _schema()
    bad = {10, 100, 250}
    csv = _write_csv(tmp_path / "d.csv", n=300, bad_rows=bad)
    pol0 = BadRecordPolicy("skip", None, Counters())
    full = ColumnarTable.from_chunks(list(iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, bad_records=pol0)))
    _build_cache(csv, schema, chunk_rows=64,
                 bad_records=BadRecordPolicy("skip", None, Counters()))
    for replay_chunk in (64, 50):   # same grid, and a mismatched one
        chunks, tot_bad = [], 0
        for i in range(3):
            counters = Counters()
            pol = BadRecordPolicy("skip", None, counters)
            got = list(iter_csv_chunks(
                csv, schema, ",", chunk_rows=replay_chunk,
                bad_records=pol, shard=(i, 3),
                cache=CachePolicy("require")))
            chunks.extend(got)
            tot_bad += counters.get("BadRecords", "Malformed")
        t = ColumnarTable.from_chunks(chunks)
        assert t.n_rows == full.n_rows
        for o in full.columns:
            np.testing.assert_array_equal(t.columns[o], full.columns[o])
        assert tot_bad == len(bad)


def test_sharded_pass_never_builds_cache(tmp_path):
    """Satellite 1: a row-range shard must not commit itself as the full
    sidecar; policy=build under sharding degrades to parse-only with a
    visible BuildSkipped tally."""
    from avenir_tpu.io.colcache import CachePolicy
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=200)
    pol = CachePolicy("build")
    rows = sum(c.n_rows for c in iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, shard=(0, 2), cache=pol))
    assert rows == shard_rows(200, 0, 2, 64)[1]
    assert not os.path.isdir(csv + ".avtc")
    assert pol.tallies.get("BuildSkipped") == 1
    assert pol.tallies.get("Built") is None


def test_nonowner_process_never_builds_cache(tmp_path, monkeypatch):
    """Satellite 1, multi-process form: only process/shard 0 may build;
    a non-owner with policy=build parses without racing the commit."""
    from avenir_tpu.io.colcache import CachePolicy
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=100)
    monkeypatch.setenv("AVENIR_TPU_SHARD", "1/2")
    pol = CachePolicy("build")
    rows = sum(c.n_rows for c in iter_csv_chunks(
        csv, schema, ",", chunk_rows=64, cache=pol))
    assert rows == 100 and not os.path.isdir(csv + ".avtc")
    assert pol.tallies.get("BuildSkipped") == 1
    # ...and the owner does build
    monkeypatch.setenv("AVENIR_TPU_SHARD", "0/2")
    list(iter_csv_chunks(csv, schema, ",", chunk_rows=64,
                         cache=CachePolicy("build")))
    assert os.path.isdir(csv + ".avtc")


def test_two_concurrent_cache_writers_last_commit_wins(tmp_path):
    """Satellite 1 regression: two writers racing the same sidecar never
    interleave chunks from two builds — each builds privately, the last
    commit replaces the whole directory, and the survivor verifies
    clean."""
    from avenir_tpu.io.colcache import (CacheWriter, probe, verify_cache)
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=120)
    chunks = list(iter_csv_chunks(csv, schema, ",", chunk_rows=40))
    cdir = csv + ".avtc"
    w1 = CacheWriter(cdir, schema, csv, ",", 40)
    w2 = CacheWriter(cdir, schema, csv, ",", 40)
    # interleaved appends: private build dirs keep them apart
    for c in chunks:
        w1.append(c, [], [])
        w2.append(c, [], [])
    w1.finalize()
    w2.finalize()
    status, header = probe(csv, schema, ",")
    assert status == "hit"
    assert header["build_id"] == w2.build_id  # last commit, whole
    assert verify_cache(cdir, schema, csv, ",") == []


# --------------------------------------------------------------------------
# sharded forest build: bit-identity + one collective per level
# --------------------------------------------------------------------------

def _forest_params(trees=3, depth=3, seed=7):
    from avenir_tpu.models.forest import ForestParams
    p = ForestParams(num_trees=trees, seed=seed)
    p.tree.max_depth = depth
    p.tree.stopping_strategy = "maxDepth"
    return p


def _reference_forest(csv, schema, params):
    from avenir_tpu.models.forest import build_forest
    return [m.to_json() for m in build_forest(load_csv(csv, schema, ","),
                                              params)]


def test_single_shard_build_bit_identical_one_collective_per_level(
        tmp_path, one_device_ctx):
    """The Collectives pin: a sharded build pays exactly ONE all-reduce
    per tree level (root + each fused level) plus the single post-ingest
    row-count allgather — and at shard count 1 it is still bit-identical
    to the monolithic build."""
    from avenir_tpu.models.forest import build_forest_from_stream
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=400)
    params = _forest_params(trees=3, depth=3)
    ref = _reference_forest(csv, schema, params)
    red = AllReducer(spec=ShardSpec(0, 1), name="rf")
    with transfer_ledger() as led:
        models = build_forest_from_stream(
            iter_csv_chunks(csv, schema, ",", chunk_rows=128, shard=(0, 1)),
            schema, params, ctx=MeshContext(make_mesh(1)), reducer=red)
    assert [m.to_json() for m in models] == ref
    snap = led.snapshot()
    # depth-3 forest: root histogram + fused levels 1..2 = 3 per-level
    # all-reduces, + 1 ingest row-count allgather.  Exact, so a change
    # that sneaks in a second collective per level fails loudly.
    assert snap["allreduces"] == 4, snap
    assert snap["allreduce_bytes"] > 0


def test_two_shard_threads_bit_identical(tmp_path, one_device_ctx):
    from avenir_tpu.models.forest import build_forest_from_stream
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=401)  # odd: remainder block
    params = _forest_params(trees=3, depth=3)
    ref = _reference_forest(csv, schema, params)
    rdir = str(tmp_path / "reduce")
    out = {}

    def worker(i):
        red = AllReducer(spec=ShardSpec(i, 2), name="rf2",
                         transport_dir=rdir, timeout_s=120)
        models = build_forest_from_stream(
            iter_csv_chunks(csv, schema, ",", chunk_rows=64, shard=(i, 2)),
            schema, params, ctx=MeshContext(make_mesh(1)), reducer=red)
        out[i] = [m.to_json() for m in models]

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    assert out.get(0) == out.get(1) == ref, \
        "sharded forest differs from the single-host build"


def test_empty_shard_participates(tmp_path, one_device_ctx):
    """More processes than ingest blocks: the row-less shard still joins
    every collective and returns the identical model."""
    from avenir_tpu.models.forest import build_forest_from_stream
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=90)   # 2 blocks of 64
    params = _forest_params(trees=2, depth=2)
    ref = _reference_forest(csv, schema, params)
    rdir = str(tmp_path / "reduce")
    out = {}

    def worker(i):
        red = AllReducer(spec=ShardSpec(i, 3), name="rf3",
                         transport_dir=rdir, timeout_s=120)
        models = build_forest_from_stream(
            iter_csv_chunks(csv, schema, ",", chunk_rows=64, shard=(i, 3)),
            schema, params, ctx=MeshContext(make_mesh(1)), reducer=red)
        out[i] = [m.to_json() for m in models]

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    # shard 0 owns block 0? (3 shards over 2 blocks: one shard is empty)
    assert any(shard_rows(90, i, 3, 64)[0] == shard_rows(90, i, 3, 64)[1]
               for i in range(3))
    assert out.get(0) == out.get(1) == out.get(2) == ref


def test_sharded_kill_resume_restarts_shard_relative(tmp_path,
                                                     one_device_ctx):
    """Satellite 2: kill one shard mid-ingest; resuming restarts each
    process at its OWN shard-relative row and the finished model is
    bit-identical; a resume under a different process count refuses."""
    from avenir_tpu.core.checkpoint import CheckpointManager
    from avenir_tpu.models.forest import build_forest_from_stream
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=400)
    params = _forest_params(trees=2, depth=2)
    ref = _reference_forest(csv, schema, params)
    mgrs = {i: CheckpointManager(str(tmp_path / f"ck{i}")) for i in range(2)}

    class Boom(RuntimeError):
        pass

    def killed_blocks(i):
        # shard 1 dies after its first block
        for bi, c in enumerate(iter_csv_chunks(
                csv, schema, ",", chunk_rows=64, shard=(i, 2))):
            if i == 1 and bi == 1:
                raise Boom("injected shard crash")
            yield c

    errs = {}

    def crash_worker(i):
        red = AllReducer(spec=ShardSpec(i, 2), name="rfc",
                         transport_dir=str(tmp_path / "r1"), timeout_s=8)
        try:
            build_forest_from_stream(
                killed_blocks(i), schema, params,
                ctx=MeshContext(make_mesh(1)), reducer=red,
                checkpoint=mgrs[i], checkpoint_every=1)
        except Exception as exc:
            errs[i] = exc

    ts = [threading.Thread(target=crash_worker, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    # shard 1 crashed; shard 0 timed out waiting at the collective
    assert isinstance(errs.get(1), Boom)
    assert isinstance(errs.get(0), RuntimeError)
    # both left intact checkpoints carrying their shard spec; the killed
    # shard's ingest is incomplete (shard 0 finished its own blocks and
    # died later, at the post-ingest collective)
    for i in range(2):
        _, _, meta = mgrs[i].restore()
        assert meta["shard"] == {"index": i, "count": 2}
    assert not mgrs[1].restore()[2]["ingest_complete"]

    # refuse resume under a different process count
    _, arrays, meta = (lambda t: t)(mgrs[0].restore())
    with pytest.raises(ValueError, match="SAME process count"):
        from avenir_tpu.models.tree import TreeBuilder, TreeParams
        red = AllReducer(spec=ShardSpec(0, 3),
                         transport_dir=str(tmp_path / "r_bad"))
        TreeBuilder.from_stream(iter([]), schema, TreeParams(seed=7),
                                ctx=MeshContext(make_mesh(1)),
                                reducer=red, resume_state=(arrays, meta))

    # resume both shards at their own source_rows_done
    out = {}

    def resume_worker(i):
        step, arrays, meta = mgrs[i].restore()
        start = int(meta.get("source_rows_done") or 0)
        lo, hi = shard_rows(400, i, 2, 64)
        assert lo <= start <= hi
        red = AllReducer(spec=ShardSpec(i, 2), name="rfr",
                         transport_dir=str(tmp_path / "r2"), timeout_s=120)
        models = build_forest_from_stream(
            iter_csv_chunks(csv, schema, ",", chunk_rows=64, shard=(i, 2),
                            start_row=start),
            schema, params, ctx=MeshContext(make_mesh(1)), reducer=red,
            checkpoint=mgrs[i], checkpoint_every=1,
            resume_state=(arrays, meta))
        out[i] = [m.to_json() for m in models]

    ts = [threading.Thread(target=resume_worker, args=(i,))
          for i in range(2)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    assert out.get(0) == out.get(1) == ref, \
        "resumed sharded forest differs from the single-host build"
    for i in range(2):
        assert mgrs[i].restore()[2]["ingest_complete"] is True


# --------------------------------------------------------------------------
# lock-step KNN top-k merge
# --------------------------------------------------------------------------

def _knn_tables():
    schema = FeatureSchema.from_dict({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "x", "ordinal": 1, "dataType": "double", "feature": True,
         "min": 0, "max": 10},
        {"name": "c", "ordinal": 2, "dataType": "categorical",
         "feature": True, "cardinality": ["p", "q"]},
        {"name": "cls", "ordinal": 3, "dataType": "categorical",
         "cardinality": ["A", "B"]}]})

    def tbl(n, seed):
        rng = np.random.default_rng(seed)
        return ColumnarTable(schema=schema, n_rows=n, columns={
            1: rng.integers(0, 10, n).astype(np.float64),  # many ties
            2: rng.integers(0, 2, n).astype(np.int32),
            3: rng.integers(0, 2, n).astype(np.int32)},
            str_columns={0: [f"r{i}" for i in range(n)]})
    return schema, tbl(173, 1), tbl(37, 2)


def test_knn_sharded_topk_bit_identical(tmp_path, one_device_ctx):
    from avenir_tpu.ops.distance import DistanceComputer
    schema, train, test = _knn_tables()
    k = 9
    ref_d, ref_i = DistanceComputer(schema).pairwise_topk(
        test, train, k, test_chunk=16)
    out = {}

    def worker(i, P):
        red = AllReducer(spec=ShardSpec(i, P), name="knn",
                         transport_dir=str(tmp_path / "knn"), timeout_s=120)
        lo, hi = shard_rows(train.n_rows, i, P)
        out[i] = DistanceComputer(schema).pairwise_topk(
            test, train.take_rows(lo, hi), k, test_chunk=16,
            shard_reducer=red, shard_base=lo)

    P = 3
    ts = [threading.Thread(target=worker, args=(i, P)) for i in range(P)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    for i in range(P):
        d, idx = out[i]
        np.testing.assert_array_equal(d, ref_d)
        np.testing.assert_array_equal(idx, ref_i)


def test_knn_single_shard_one_collective_per_chunk(one_device_ctx):
    """The per-chunk collective pin: ceil(n_test / test_chunk) merges,
    results identical to the unsharded scan."""
    from avenir_tpu.ops.distance import DistanceComputer
    schema, train, test = _knn_tables()
    k = 9
    ref_d, ref_i = DistanceComputer(schema).pairwise_topk(
        test, train, k, test_chunk=16)
    red = AllReducer(spec=ShardSpec(0, 1), name="knn1")
    with transfer_ledger() as led:
        d, idx = DistanceComputer(schema).pairwise_topk(
            test, train, k, test_chunk=16, shard_reducer=red, shard_base=0)
    np.testing.assert_array_equal(d, ref_d)
    np.testing.assert_array_equal(idx, ref_i)
    assert led.snapshot()["allreduces"] == 3   # ceil(37 / 16)


# --------------------------------------------------------------------------
# sharded SMO groups
# --------------------------------------------------------------------------

def test_smo_sharded_groups_identical_across_shards(tmp_path,
                                                    one_device_ctx):
    from avenir_tpu.discriminant import smo as S
    rng = np.random.default_rng(3)
    groups = {}
    for g in range(5):
        n = 24 + 4 * g
        yv = np.where(np.arange(n) % 2 == 0, 1.0, -1.0)
        groups[f"g{g}"] = (rng.normal(0, 0.7, (n, 3)) + 1.1 * yv[:, None],
                          yv)
    p = S.SMOParams(penalty_factor=1.0)
    ref = S.train_groups_batched(groups, p)
    out = {}

    def worker(i):
        red = AllReducer(spec=ShardSpec(i, 2), name="smo",
                         transport_dir=str(tmp_path / "smo"), timeout_s=120)
        out[i] = S.train_groups_sharded(groups, p, red)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join(240) for t in ts]
    assert set(out[0]) == set(out[1]) == set(ref)
    X = np.vstack([groups[g][0] for g in groups])
    for g in ref:
        np.testing.assert_array_equal(out[0][g].weights, out[1][g].weights)
        assert out[0][g].threshold == out[1][g].threshold
        # same optimum as the unsharded batched trainer (the batch-width
        # padding may retile f32 math, so optimization-tolerance close)
        np.testing.assert_allclose(out[0][g].weights, ref[g].weights,
                                   rtol=1e-4, atol=1e-5)
        # and identical PREDICTIONS on the pooled data
        np.testing.assert_array_equal(S.predict(out[0][g], X),
                                      S.predict(ref[g], X))


# --------------------------------------------------------------------------
# CLI: the two-subprocess jax.distributed-free smoke lane
# --------------------------------------------------------------------------

def _cli_env(extra):
    env = {k: v for k, v in os.environ.items()
           if k not in ("AVENIR_TPU_SHARD", "AVENIR_TPU_ALLREDUCE_DIR")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
    env.update(extra)
    return env


def test_cli_two_process_sharded_rf_smoke(tmp_path):
    """The CI smoke lane (satellite 5): two plain subprocesses (no
    jax.distributed coordinator) run the sharded streaming RF build on a
    tiny CSV through the real CLI; both must write models bit-identical
    to a single-host run, and process 0's counter dump must pin the
    Collectives group."""
    from avenir_tpu.cli import run as cli_run
    schema_path = tmp_path / "s.json"
    schema_path.write_text(json.dumps(SCHEMA))
    csv = _write_csv(tmp_path / "d.csv", n=400)
    props = tmp_path / "rf.properties"
    props.write_text(
        "field.delim.regex=,\nfield.delim.out=,\n"
        f"dtb.feature.schema.file.path={schema_path}\n"
        "dtb.num.trees=3\ndtb.random.seed=7\n"
        "dtb.max.depth.limit=3\ndtb.path.stopping.strategy=maxDepth\n"
        "dtb.streaming.ingest=true\ndtb.streaming.block.rows=100\n")

    # single-host reference, in-process
    assert cli_run.main(["randomForestBuilder", f"-Dconf.path={props}",
                         str(csv), str(tmp_path / "out_single")]) == 0
    ref = [(tmp_path / "out_single" / f"tree_{i}.json").read_text()
           for i in range(3)]

    rdir = str(tmp_path / "reduce")
    procs = []
    for i in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "avenir_tpu.cli.run",
             "randomForestBuilder", f"-Dconf.path={props}",
             "-Ddtb.streaming.shard=on",
             str(csv), str(tmp_path / f"out_shard{i}")],
            env=_cli_env({"AVENIR_TPU_SHARD": f"{i}/2",
                          "AVENIR_TPU_ALLREDUCE_DIR": rdir}),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            so, se = p.communicate(timeout=280)
            assert p.returncode == 0, se[-3000:]
            outs.append(so)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for i in range(2):
        got = [(tmp_path / f"out_shard{i}" / f"tree_{t}.json").read_text()
               for t in range(3)]
        assert got == ref, f"shard {i} models != single-host"
    # Collectives pinned through the job counter dump: 3 per-level
    # all-reduces (root + 2 fused levels) + 1 row-count allgather
    for so in outs:
        assert "AllReduces=4" in so, so
    # shard identity is emitted by shard 0 only (the cross-process
    # counter sum must not inflate it)
    assert "Count=2" in outs[0]
    assert "Count=2" not in outs[1]


def test_cli_knn_train_shard_single_process(tmp_path, one_device_ctx):
    """nen.train.shard=true through the knnPipeline job: at shard count 1
    the lock-step merge is the identity, predictions and counters match
    the default path byte for byte, and the output lands as a global
    part-r file."""
    from avenir_tpu.cli import run as cli_run
    rng = np.random.default_rng(7)
    data_dir = tmp_path / "data"
    data_dir.mkdir()

    def rows(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for i in range(n):
            a = r.random() < 0.5
            out.append(f"s{seed}_{i:03d},{r.normal(2 if a else 8, 1.0):.3f},"
                       f"{'A' if a else 'B'}")
        return out

    (data_dir / "tr_train.csv").write_text("\n".join(rows(60, 21)))
    (data_dir / "test.csv").write_text("\n".join(rows(20, 22)))
    schema_path = tmp_path / "ks.json"
    schema_path.write_text(json.dumps({"fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "x", "ordinal": 1, "dataType": "double", "feature": True,
         "min": 0, "max": 10},
        {"name": "label", "ordinal": 2, "dataType": "categorical",
         "cardinality": ["A", "B"]}]}))
    props = tmp_path / "knn.properties"
    props.write_text(
        "field.delim.regex=,\nfield.delim.out=,\n"
        f"sts.same.schema.file.path={schema_path}\n"
        "sts.base.set.split.prefix=tr\nnen.top.match.count=5\n"
        "nen.kernel.function=none\nnen.validation.mode=true\n")
    assert cli_run.main(["knnPipeline", f"-Dconf.path={props}",
                         str(data_dir), str(tmp_path / "out_plain")]) == 0
    assert cli_run.main(["knnPipeline", f"-Dconf.path={props}",
                         "-Dnen.train.shard=true",
                         str(data_dir), str(tmp_path / "out_shard")]) == 0
    assert (tmp_path / "out_shard" / "part-r-00000").read_text() == \
        (tmp_path / "out_plain" / "part-r-00000").read_text()


def test_cli_shard_on_requires_multi_shard(tmp_path):
    """dtb.streaming.shard=on outside a multi-shard run refuses instead of
    silently training single-host."""
    from avenir_tpu.cli.jobs import random_forest_builder
    from avenir_tpu.core.config import Config
    schema_path = tmp_path / "s.json"
    schema_path.write_text(json.dumps(SCHEMA))
    csv = _write_csv(tmp_path / "d.csv", n=50)
    cfg = Config({"dtb.feature.schema.file.path": str(schema_path),
                  "dtb.streaming.ingest": "true",
                  "dtb.streaming.shard": "on"})
    with pytest.raises(ValueError, match="multi-shard"):
        random_forest_builder(cfg, csv, str(tmp_path / "out"))


def test_cli_shard_on_without_streaming_ingest_refuses(tmp_path):
    """dtb.streaming.shard=on without dtb.streaming.ingest must refuse
    (the monolithic load_csv build cannot row-range shard), and a junk
    knob value is rejected whether or not streaming is on."""
    from avenir_tpu.cli.jobs import random_forest_builder
    from avenir_tpu.core.config import Config
    schema_path = tmp_path / "s.json"
    schema_path.write_text(json.dumps(SCHEMA))
    csv = _write_csv(tmp_path / "d.csv", n=50)
    base = {"dtb.feature.schema.file.path": str(schema_path),
            "dtb.num.trees": "1"}
    with pytest.raises(ValueError, match="streaming.ingest"):
        random_forest_builder(
            Config(dict(base, **{"dtb.streaming.shard": "on"})),
            csv, str(tmp_path / "out"))
    with pytest.raises(ValueError, match="auto|on|off"):
        random_forest_builder(
            Config(dict(base, **{"dtb.streaming.shard": "yes"})),
            csv, str(tmp_path / "out"))


def test_reused_transport_dir_ignores_stale_payloads(tmp_path):
    """Regression: a transport dir reused across sequential runs must not
    serve run 1's leftover step files as run 2's partials.  Run 1 is a
    single-exchange pair (the rolling reap keeps its step-0 files); run 2
    reuses the dir with one participant delayed past the point where an
    unguarded reader would have accepted the stale payload."""
    import time as _time
    rdir = str(tmp_path / "reduce")

    def run(tag, values, delay_shard1=0.0):
        out, errs = {}, {}

        def worker(i):
            try:
                if i == 1 and delay_shard1:
                    _time.sleep(delay_shard1)
                red = AllReducer(spec=ShardSpec(i, 2), name="reuse",
                                 transport_dir=rdir, timeout_s=60)
                out[i] = red.sum(np.array(values[i], dtype=np.int64))
            except Exception as exc:  # surface thread failures in the test
                errs[i] = exc
        ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        [t.start() for t in ts]
        [t.join(120) for t in ts]
        assert not errs, errs
        return out

    first = run("r1", {0: [1, 2], 1: [10, 20]})
    np.testing.assert_array_equal(first[0], [11, 22])
    # run 1's single step leaves its step-0 payloads behind
    leftovers = os.listdir(rdir)
    assert any("-000000.1." in f for f in leftovers), leftovers
    second = run("r2", {0: [3, 4], 1: [30, 40]}, delay_shard1=1.5)
    for i in range(2):
        np.testing.assert_array_equal(second[i], [33, 44])


def test_dist_mode_distinct_inputs_with_row_range_shard_refuse(
        tmp_path, monkeypatch):
    """Under jax.distributed, dtb.streaming.shard assumes ONE shared
    input: distinct per-process shard files must refuse (each process
    would row-range split its OWN file and silently drop rows), while
    identical inputs stand the identical-shard refusal down."""
    from avenir_tpu.cli import jobs, run as cli_run
    from avenir_tpu.core.config import Config
    import avenir_tpu.parallel.distributed as dist
    csv = _write_csv(tmp_path / "d.csv", n=20)
    cfg = Config({"dtb.streaming.ingest": "true"})
    fn = jobs.resolve("randomForestBuilder")
    monkeypatch.setattr(dist, "is_multiprocess", lambda: True)
    monkeypatch.setattr(dist, "allgather_object",
                        lambda obj: [obj, (obj[0], "other-digest")])
    with pytest.raises(RuntimeError, match="DISTINCT"):
        cli_run._apply_dist_mode(fn, "randomForestBuilder", csv, cfg)
    # identical digests: the sanctioned shared-input layout passes through
    monkeypatch.setattr(dist, "allgather_object", lambda obj: [obj, obj])
    assert cli_run._apply_dist_mode(
        fn, "randomForestBuilder", csv, cfg) == (csv, None)
    # and with sharding off, distinct inputs are the per-process-shard
    # layout and pass through unchanged
    monkeypatch.setattr(dist, "allgather_object",
                        lambda obj: [obj, (obj[0], "other-digest")])
    cfg_off = Config({"dtb.streaming.ingest": "true",
                      "dtb.streaming.shard": "off"})
    assert cli_run._apply_dist_mode(
        fn, "randomForestBuilder", csv, cfg_off) == (csv, None)
