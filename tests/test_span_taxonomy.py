"""Span-taxonomy drift guard (ISSUE 15 satellite): every ``span(``/
``instant(``/``flow(`` name literal in the source tree must appear in
the TPU_NOTES §27 taxonomy table, and every table row must still exist
in code — docs and instrumentation can no longer diverge silently.

Runs in the fast tier-1 lane (``obs`` marker)."""

import os
import re

import pytest

pytestmark = pytest.mark.obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NOTES = os.path.join(_REPO, "docs", "TPU_NOTES.md")
_SCAN_DIRS = ("avenir_tpu", "tools")

# first string-literal argument of a span()/instant()/flow() call — the
# taxonomy is literal names by design (a computed name would be
# un-greppable for operators too)
_CALL_RE = re.compile(
    r"\b(?:span|instant|flow)\(\s*[\"']([a-z0-9_.]+)[\"']")
_TABLE_ROW_RE = re.compile(r"^\s*\|\s*`([a-z0-9_.]+)`\s*\|")

# call sites whose first string argument is deliberately NOT a taxonomy
# name (empty: every literal is governed)
_IGNORED = set()


def _source_names():
    names = {}
    for d in _SCAN_DIRS:
        for root, _, files in os.walk(os.path.join(_REPO, d)):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                with open(path) as fh:
                    text = fh.read()
                for m in _CALL_RE.finditer(text):
                    names.setdefault(m.group(1), []).append(
                        os.path.relpath(path, _REPO))
    # reqtrace emits its flow legs through the FLOW_NAME constant; pick
    # it up so the flow family is governed by the same table
    from avenir_tpu.telemetry import reqtrace
    names.setdefault(reqtrace.FLOW_NAME, []).append(
        "avenir_tpu/telemetry/reqtrace.py")
    return names


def _taxonomy_names():
    with open(_NOTES) as fh:
        text = fh.read()
    m = re.search(r"<!-- span-taxonomy:begin -->(.*?)"
                  r"<!-- span-taxonomy:end -->", text, re.DOTALL)
    assert m, "TPU_NOTES.md lost its span-taxonomy table markers"
    names = set()
    for line in m.group(1).splitlines():
        row = _TABLE_ROW_RE.match(line)
        if row and row.group(1) != "name":
            names.add(row.group(1))
    assert names, "span-taxonomy table parsed empty"
    return names


def test_every_source_literal_is_in_the_taxonomy_table():
    src = _source_names()
    table = _taxonomy_names()
    missing = {n: files for n, files in src.items()
               if n not in table and n not in _IGNORED}
    assert not missing, (
        f"span/instant/flow names used in code but absent from the "
        f"TPU_NOTES §27 taxonomy table: {missing} — add them to the "
        f"table (between the span-taxonomy markers)")


def test_every_taxonomy_row_still_exists_in_source():
    src = _source_names()
    table = _taxonomy_names()
    stale = sorted(table - set(src))
    assert not stale, (
        f"taxonomy table rows with no remaining span/instant/flow call "
        f"site: {stale} — remove the rows or restore the "
        f"instrumentation")
