"""Pipeline-compiler tests (ISSUE 9): fused per-chunk XLA programs with
device-resident intermediates, the ProgramCache control plane, and the
three rewired flows.

Pins, in the style of tests/test_transfers.py:

  * bit-identity — fused pipeline output == unfused per-stage output for
    the streamed RF build (+ monolithic oracle), the baseline publish
    tee, and the combined predictDriftScore job vs the two-job flow,
    including the 2-shard file-transport lane and checkpoint/resume
    mid-stream;
  * dispatch counts — the fused path launches STRICTLY fewer XLA
    programs per chunk than the unfused path (ledger per-site
    breakdown: ``pipeline.chunk`` vs ``ingest.encode`` +
    ``baseline.absorb`` / ``monitor.absorb`` + ``serve.predict``);
  * ProgramCache — schema-fingerprint, chunk-shape, and mesh-spec
    changes each MISS; an identical re-run HITS with zero retraces
    (compile counts via the cache's own tallies).
"""

import json
import os
import threading
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from avenir_tpu.core.metrics import Counters
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.core.table import iter_csv_chunks, load_csv, prefetch_chunks
from avenir_tpu.parallel.mesh import MeshContext, make_mesh, \
    set_runtime_context
from avenir_tpu.pipeline import (ChunkPipeline, ProgramCache, Stage,
                                 program_cache, schema_fingerprint)
from avenir_tpu.utils.tracing import TransferLedger, transfer_ledger

pytestmark = pytest.mark.pipeline


SCHEMA = {"fields": [
    {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
    {"name": "c1", "ordinal": 1, "dataType": "categorical", "feature": True,
     "maxSplit": 2, "cardinality": ["a", "b", "c"]},
    {"name": "n1", "ordinal": 2, "dataType": "int", "feature": True,
     "min": 0, "max": 600, "splitScanInterval": 150},
    {"name": "cls", "ordinal": 3, "dataType": "categorical",
     "cardinality": ["T", "F"]},
]}


def _schema():
    return FeatureSchema.from_dict(SCHEMA)


def _write_csv(path, n=400, seed=3, shift=0, noise=0.0):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        c = ["a", "b", "c"][rng.integers(0, 3)]
        v = int(rng.integers(shift, 600))
        cls = "T" if (v > 300) ^ (c == "c") else "F"
        if noise and rng.random() < noise:
            # flipped delayed labels: the model must mispredict some
            # rows or the accuracy-alert path (inverted threshold) is
            # unreachable — the split grid contains the true boundary
            cls = "F" if cls == "T" else "T"
        lines.append(f"r{i},{c},{v},{cls}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return str(path)


def _forest_params(trees=3, depth=3, seed=7):
    from avenir_tpu.models.forest import ForestParams
    p = ForestParams(num_trees=trees, seed=seed)
    p.tree.max_depth = depth
    p.tree.stopping_strategy = "maxDepth"
    return p


# --------------------------------------------------------------------------
# ProgramCache mechanics
# --------------------------------------------------------------------------

def test_program_cache_hit_miss_and_eviction():
    cache = ProgramCache(maxsize=2)

    def build():
        return jax.jit(lambda x: x + 1)

    x = jnp.arange(4.0)
    c1 = cache.get_or_compile(("k1",), build, (x,))
    assert np.allclose(np.asarray(c1(x)), np.arange(4.0) + 1)
    assert cache.stats()["retraces"] == 1
    # identical key: hit, no recompile
    cache.get_or_compile(("k1",), build, (x,))
    s = cache.stats()
    assert s["hits"] == 1 and s["retraces"] == 1
    # two more keys overflow maxsize=2 -> k1 evicted (LRU)
    cache.get_or_compile(("k2",), build, (x,))
    cache.get_or_compile(("k3",), build, (x,))
    assert cache.stats()["entries"] == 2
    cache.get_or_compile(("k1",), build, (x,))
    assert cache.stats()["retraces"] == 4  # k1 had to recompile


def _toy_stage():
    def kernel(carry, consts, inputs, upstream):
        return carry, {"y": inputs["x"] * consts["scale"]}
    return Stage(name="toy", kernel=kernel, version="1",
                 consts={"scale": jnp.float32(2.0)}, returns=("y",))


def _run_toy(cache, schema_fp="s", mesh_fp="m", n=8):
    pl = ChunkPipeline([_toy_stage()], ctx=MeshContext(make_mesh(1)),
                       schema_fp=schema_fp, mesh_fp=mesh_fp, cache=cache)
    out = pl.run_chunk({"x": jnp.arange(float(n))})
    assert np.allclose(np.asarray(out["toy.y"]), np.arange(n) * 2.0)
    return pl


def test_program_cache_key_invalidation_axes():
    """Schema fingerprint, chunk shape, and mesh spec each MISS; an
    identical re-run HITS with zero retraces."""
    cache = ProgramCache()
    _run_toy(cache, "s", "m", n=8)
    assert cache.stats() == dict(hits=0, misses=1, retraces=1,
                                 disk_hits=0, disk_stores=0, entries=1)
    # identical re-run (fresh pipeline instance, same everything): HIT
    pl = _run_toy(cache, "s", "m", n=8)
    assert cache.stats()["retraces"] == 1
    assert pl.run_stats() == {"chunks": 1, "hits": 1, "misses": 0,
                              "retraces": 0}
    # schema fingerprint change: MISS
    _run_toy(cache, "s2", "m", n=8)
    assert cache.stats()["retraces"] == 2
    # chunk shape change: MISS
    _run_toy(cache, "s", "m", n=16)
    assert cache.stats()["retraces"] == 3
    # mesh spec change: MISS
    _run_toy(cache, "s", "m2", n=8)
    assert cache.stats()["retraces"] == 4


def test_pipeline_donated_carry_accumulates():
    """A stage carry is device-resident and threads chunk to chunk."""
    def kernel(carry, consts, inputs, upstream):
        return carry + inputs["x"].sum(), {}
    st = Stage(name="acc", kernel=kernel,
               carry_init=lambda: jnp.float32(0.0))
    pl = ChunkPipeline([st], ctx=MeshContext(make_mesh(1)),
                       cache=ProgramCache())
    total = 0.0
    for k in range(3):
        x = jnp.full((4,), float(k + 1))
        pl.run_chunk({"x": x})
        total += 4.0 * (k + 1)
    got = {}
    st.finish = lambda c: got.setdefault("v", float(np.asarray(c)))
    pl.finalize()
    assert got["v"] == total


def test_pipeline_duplicate_stage_names_refused():
    with pytest.raises(ValueError, match="duplicate"):
        ChunkPipeline([_toy_stage(), _toy_stage()],
                      ctx=MeshContext(make_mesh(1)), cache=ProgramCache())


def test_pipeline_export_counters():
    cache = ProgramCache()
    pl = _run_toy(cache)
    c = Counters()
    pl.export(c)
    assert c.group("ProgramCache") == {"Chunks": 1, "Hits": 0,
                                       "Misses": 1, "Retraces": 1}


# --------------------------------------------------------------------------
# satellite: ledger per-site dispatch breakdown
# --------------------------------------------------------------------------

def test_ledger_site_breakdown_exports():
    led = TransferLedger()
    led.record_dispatch(2, site="pipeline.chunk")
    led.record_dispatch(1, site="forest.level")
    led.record_dispatch(1)             # untagged: total only
    assert led.snapshot()["dispatches"] == 4
    assert led.site_snapshot() == {"pipeline.chunk": 2, "forest.level": 1}
    c = Counters()
    led.export(c)
    assert c.get("Transfers", "Dispatches") == 4
    assert c.group("Dispatches") == {"pipeline.chunk": 2,
                                     "forest.level": 1}


def test_ledger_no_sites_no_dispatches_group():
    led = TransferLedger()
    led.record_dispatch(3)
    c = Counters()
    led.export(c)
    assert c.group("Dispatches") == {}


# --------------------------------------------------------------------------
# satellite: producer exception type surfaces in the stats dict
# --------------------------------------------------------------------------

def test_prefetch_surfaces_producer_exception_in_stats():
    def crashing():
        yield 1
        raise ValueError("bad parse at row 7")

    stats = {}
    it = prefetch_chunks(crashing(), stats=stats)
    assert next(it) == 1
    with pytest.raises(ValueError, match="bad parse"):
        list(it)
    # the crash is identifiable FROM THE STATS DICT, not only via the
    # re-raise: a crashed producer no longer looks like a slow one
    assert stats["producer_error"] == "ValueError: bad parse at row 7"
    assert stats["producer_error_thread"] == "avenir-ingest-prefetch"


def test_prefetch_no_error_leaves_stats_clean():
    stats = {}
    assert list(prefetch_chunks(iter([1, 2]), stats=stats)) == [1, 2]
    assert "producer_error" not in stats


# --------------------------------------------------------------------------
# bit-identity: streamed RF build, fused vs unfused vs monolithic
# --------------------------------------------------------------------------

def _stream_forest(csv, schema, params, fuse, baseline=None,
                   chunk_rows=128, **kw):
    from avenir_tpu.models.forest import build_forest_from_stream
    stats = {}
    with transfer_ledger() as led:
        models = build_forest_from_stream(
            iter_csv_chunks(csv, schema, ",", chunk_rows=chunk_rows),
            schema, params, stats=stats, fuse=fuse, baseline=baseline,
            **kw)
    return [m.to_json() for m in models], led, stats


def test_rf_stream_fused_bit_identical_and_fewer_dispatches(tmp_path):
    from avenir_tpu.models.forest import build_forest
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=401)   # odd: remainder chunk
    params = _forest_params()
    ref = [m.to_json() for m in
           build_forest(load_csv(csv, schema, ","), params)]
    fused, led_f, stats_f = _stream_forest(csv, schema, params, fuse=True)
    unfused, led_u, _ = _stream_forest(csv, schema, params, fuse=False)
    assert fused == ref and unfused == ref
    chunks = stats_f["pipeline"]["chunks"]
    assert chunks == 4
    # the acceptance pin: RF encode <= 1 dispatch per chunk fused
    assert led_f.site_snapshot()["pipeline.chunk"] == chunks
    assert led_u.site_snapshot()["ingest.encode"] == chunks


def test_rf_stream_fused_baseline_strictly_fewer_dispatches(tmp_path):
    """With the baseline riding along, fused = 1 launch/chunk vs the
    unfused encode + tee'd absorb = 2 launches/chunk — and the finalized
    baselines are byte-identical."""
    from avenir_tpu.monitor.baseline import BaselineBuilder
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=400)
    params = _forest_params()
    bf = BaselineBuilder(schema, n_bins=8)
    bu = BaselineBuilder(schema, n_bins=8)
    fused, led_f, stats_f = _stream_forest(csv, schema, params,
                                           fuse=True, baseline=bf)
    unfused, led_u, _ = _stream_forest(csv, schema, params,
                                       fuse=False, baseline=bu)
    assert fused == unfused
    chunks = stats_f["pipeline"]["chunks"]
    sf, su = led_f.site_snapshot(), led_u.site_snapshot()
    fused_per_chunk = sf["pipeline.chunk"]
    unfused_per_chunk = su["ingest.encode"] + su["baseline.absorb"]
    assert fused_per_chunk == chunks
    assert unfused_per_chunk == 2 * chunks
    assert fused_per_chunk < unfused_per_chunk     # STRICTLY fewer
    # baseline bit-identity: counts, row count, quantiles
    fb, ub = bf.finalize(), bu.finalize()
    assert np.array_equal(fb.counts, ub.counts)
    assert fb.n_rows == ub.n_rows
    assert np.array_equal(fb.quantiles, ub.quantiles, equal_nan=True)
    assert fb.to_sidecar() == ub.to_sidecar()


def test_rf_stream_warm_rerun_zero_retraces(tmp_path):
    """Identical re-run: every chunk key HITS the process-global cache;
    zero retraces (the Execution Templates acceptance)."""
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=400)
    params = _forest_params()
    _, _, s1 = _stream_forest(csv, schema, params, fuse=True)
    _, _, s2 = _stream_forest(csv, schema, params, fuse=True)
    assert s2["pipeline"]["retraces"] == 0
    assert s2["pipeline"]["misses"] == 0
    assert s2["pipeline"]["hits"] == s2["pipeline"]["chunks"]


def test_rf_stream_fused_checkpoint_resume_bit_identical(tmp_path):
    """Crash mid-stream under the fused pipeline; resume finishes the
    bit-identical model (checkpoint/resume composes with fusion)."""
    from avenir_tpu.core.checkpoint import CheckpointManager
    from avenir_tpu.models.forest import build_forest_from_stream
    schema = _schema()
    csv = _write_csv(tmp_path / "d.csv", n=400)
    params = _forest_params()
    ref, _, _ = _stream_forest(csv, schema, params, fuse=True,
                               chunk_rows=64)
    mgr = CheckpointManager(str(tmp_path / "ck"))

    def crash_after(blocks, k):
        for i, b in enumerate(blocks):
            if i == k:
                raise RuntimeError("injected crash")
            yield b

    with pytest.raises(RuntimeError, match="injected crash"):
        build_forest_from_stream(
            crash_after(iter_csv_chunks(csv, schema, ",", chunk_rows=64),
                        3),
            schema, params, checkpoint=mgr, checkpoint_every=1, fuse=True)
    step, arrays, meta = mgr.restore()
    assert not meta["ingest_complete"] and meta["source_rows_done"] > 0
    models = build_forest_from_stream(
        iter_csv_chunks(csv, schema, ",", chunk_rows=64,
                        start_row=meta["source_rows_done"]),
        schema, params, checkpoint=mgr, checkpoint_every=1,
        resume_state=(arrays, meta), fuse=True)
    assert [m.to_json() for m in models] == ref


def test_rf_stream_fused_two_shard_file_transport(tmp_path):
    """The 2-shard file-transport lane: fused shards train the
    bit-identical forest of the single-host build (thread-simulated
    shards share the process-global ProgramCache — also a thread-safety
    exercise)."""
    from avenir_tpu.models.forest import build_forest, \
        build_forest_from_stream
    from avenir_tpu.parallel.collectives import AllReducer
    from avenir_tpu.parallel.distributed import ShardSpec
    set_runtime_context(MeshContext(make_mesh(1)))
    try:
        schema = _schema()
        csv = _write_csv(tmp_path / "d.csv", n=401)
        params = _forest_params()
        ref = [m.to_json() for m in
               build_forest(load_csv(csv, schema, ","), params,
                            MeshContext(make_mesh(1)))]
        rdir = str(tmp_path / "reduce")
        out = {}

        def worker(i):
            red = AllReducer(spec=ShardSpec(i, 2), name="rf-pl",
                             transport_dir=rdir, timeout_s=120)
            models = build_forest_from_stream(
                iter_csv_chunks(csv, schema, ",", chunk_rows=64,
                                shard=(i, 2)),
                schema, params, ctx=MeshContext(make_mesh(1)),
                reducer=red, fuse=True)
            out[i] = [m.to_json() for m in models]

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        [t.start() for t in ts]
        [t.join(240) for t in ts]
        assert out.get(0) == out.get(1) == ref, \
            "fused sharded forest differs from the single-host build"
    finally:
        set_runtime_context(None)


# --------------------------------------------------------------------------
# the combined predictDriftScore flow vs the two-job baseline
# --------------------------------------------------------------------------

def _train_and_publish(tmp_path, schema_path):
    """randomForestBuilder with streaming ingest + baseline publish."""
    from avenir_tpu.cli import jobs
    from avenir_tpu.core.config import Config
    cfg = Config({"dtb.feature.schema.file.path": schema_path,
                  "dtb.num.trees": "3", "dtb.random.seed": "7",
                  "dtb.max.depth.limit": "3",
                  "dtb.path.stopping.strategy": "maxDepth",
                  "dtb.streaming.ingest": "true",
                  "dtb.streaming.block.rows": "128",
                  "dtb.baseline.publish": "true",
                  "dtb.model.registry.dir": str(tmp_path / "reg"),
                  "dtb.baseline.bins": "8"})
    counters = jobs.random_forest_builder(
        cfg, str(tmp_path / "train.csv"), str(tmp_path / "out_rf"))
    return counters


@pytest.fixture()
def published(tmp_path):
    schema_path = str(tmp_path / "schema.json")
    with open(schema_path, "w") as fh:
        json.dump(SCHEMA, fh)
    _write_csv(tmp_path / "train.csv", n=500, seed=3)
    # drifted scoring stream (value range shifted up, labels noisy so
    # accuracy alerts fire ALONGSIDE drift alerts — the alerts.jsonl
    # byte-diff therefore pins their relative order inside a window)
    _write_csv(tmp_path / "score.csv", n=300, seed=11, shift=200,
               noise=0.1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _train_and_publish(tmp_path, schema_path)
    return schema_path


def _dm_cfg(tmp_path, extra=None):
    from avenir_tpu.core.config import Config
    # accuracy thresholds ON: an accuracy alert and a drift alert firing
    # in the SAME window pins the alert ordering inside alerts.jsonl,
    # not just the set of alerts
    keys = {"dm.model.registry.dir": str(tmp_path / "reg"),
            "dm.model.name": "forest", "dm.window.rows": "100",
            "dm.consecutive.windows": "1",
            "dm.accuracy.warn": "100", "dm.accuracy.alert": "100",
            "dm.score.predictions": "true"}
    keys.update(extra or {})
    return Config(keys)


def test_predict_drift_score_bit_identical_to_two_jobs(tmp_path,
                                                       published):
    """The combined one-pass job's BOTH artifacts == the two-job flow's:
    prediction lines byte-equal modelPredictor, drift rows + alerts
    byte-equal driftMonitor(dm.score.predictions) — at strictly fewer
    launches per window."""
    from avenir_tpu.cli import jobs, monitor_jobs
    from avenir_tpu.core.config import Config
    score = str(tmp_path / "score.csv")
    jobs.model_predictor_job(
        Config({"mop.feature.schema.file.path": published,
                "mop.model.dir.path": str(tmp_path / "out_rf")}),
        score, str(tmp_path / "out_pred"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with transfer_ledger() as led_dm:
            monitor_jobs.drift_monitor(_dm_cfg(tmp_path), score,
                                       str(tmp_path / "out_dm"))
        with transfer_ledger() as led_pds:
            c = monitor_jobs.predict_drift_score(
                _dm_cfg(tmp_path), score, str(tmp_path / "out_pds"))

    def rd(*p):
        return open(os.path.join(str(tmp_path), *p)).read()

    assert rd("out_pds", "predictions", "part-m-00000") \
        == rd("out_pred", "part-m-00000")
    assert rd("out_pds", "part-r-00000") == rd("out_dm", "part-r-00000")
    assert rd("out_pds", "alerts.jsonl") == rd("out_dm", "alerts.jsonl")
    # every window fused, ONE launch per window; the unfused pair pays
    # predict + absorb launches per window
    windows = 3
    assert c.get("PredictDrift", "FusedWindows") == windows
    assert c.get("PredictDrift", "UnfusedWindows") == 0
    sf, su = led_pds.site_snapshot(), led_dm.site_snapshot()
    assert sf["pipeline.chunk"] == windows
    unfused = su["monitor.absorb"] + su.get("serve.predict", 0)
    assert sf["pipeline.chunk"] < unfused
    # drift scoring itself is shared (same launches either way)
    assert sf["drift.score"] == su["drift.score"]


def test_predict_drift_score_unfused_knob_identical(tmp_path, published):
    """dm.pipeline.fuse=false: same single-pass job, eager per-stage
    launches — artifacts identical to the fused run."""
    from avenir_tpu.cli import monitor_jobs
    score = str(tmp_path / "score.csv")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        cf = monitor_jobs.predict_drift_score(
            _dm_cfg(tmp_path), score, str(tmp_path / "out_f"))
        cu = monitor_jobs.predict_drift_score(
            _dm_cfg(tmp_path, {"dm.pipeline.fuse": "false"}), score,
            str(tmp_path / "out_u"))

    def rd(*p):
        return open(os.path.join(str(tmp_path), *p)).read()

    assert rd("out_f", "predictions", "part-m-00000") \
        == rd("out_u", "predictions", "part-m-00000")
    assert rd("out_f", "part-r-00000") == rd("out_u", "part-r-00000")
    assert cf.get("PredictDrift", "FusedWindows") > 0
    assert cu.get("PredictDrift", "FusedWindows") == 0
    assert cu.get("PredictDrift", "UnfusedWindows") > 0


def test_rf_job_warm_rerun_reports_zero_retraces(tmp_path, published):
    """The CLI-level warm-re-run acceptance: an identical second
    randomForestBuilder run reports ProgramCache Retraces=0 (every
    chunk program served from the process-global cache)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c2 = _train_and_publish(tmp_path, published)
    assert c2.group("ProgramCache")["Retraces"] == 0
    assert c2.group("ProgramCache")["Misses"] == 0
    assert c2.group("ProgramCache")["Hits"] \
        == c2.group("ProgramCache")["Chunks"]


def test_predict_drift_score_refuses_even_unweighted_forest(tmp_path):
    """modelPredictor refuses an even unweighted ensemble; the combined
    job must too (both fused and unfused) — silently tie-broken
    predictions would violate the byte-identity contract."""
    from avenir_tpu.cli import jobs, monitor_jobs
    from avenir_tpu.core.config import Config
    schema_path = str(tmp_path / "schema.json")
    with open(schema_path, "w") as fh:
        json.dump(SCHEMA, fh)
    _write_csv(tmp_path / "train.csv", n=400, seed=3)
    _write_csv(tmp_path / "score.csv", n=120, seed=11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        jobs.random_forest_builder(
            Config({"dtb.feature.schema.file.path": schema_path,
                    "dtb.num.trees": "4", "dtb.random.seed": "7",
                    "dtb.max.depth.limit": "3",
                    "dtb.path.stopping.strategy": "maxDepth",
                    "dtb.baseline.publish": "true",
                    "dtb.model.registry.dir": str(tmp_path / "reg"),
                    "dtb.baseline.bins": "8"}),
            str(tmp_path / "train.csv"), str(tmp_path / "out_rf"))
    for extra in (None, {"dm.pipeline.fuse": "false"}):
        with pytest.raises(ValueError, match="odd number"):
            monitor_jobs.predict_drift_score(
                _dm_cfg(tmp_path, extra), str(tmp_path / "score.csv"),
                str(tmp_path / "out_even"))


def test_stream_monitor_close_counts_matches_close_window():
    """close_counts (the fused entry) and close_window (the internal
    accumulator) score/decay/debounce identically for the same window
    counts."""
    from avenir_tpu.monitor.accumulator import StreamDriftMonitor
    from avenir_tpu.monitor.baseline import compute_baseline, \
        encode_monitor_codes
    from avenir_tpu.core.table import encode_rows
    schema = _schema()
    rng = np.random.default_rng(5)
    rows = [["r%d" % i, "abc"[rng.integers(3)],
             str(int(rng.integers(0, 600))), "TF"[rng.integers(2)]]
            for i in range(200)]
    base_tbl = encode_rows(rows, schema)
    baseline = compute_baseline(base_tbl, n_bins=8)
    win = [["w%d" % i, "abc"[rng.integers(3)],
            str(int(rng.integers(300, 600))), "T"] for i in range(64)]
    tbl = encode_rows(win, schema)
    m1 = StreamDriftMonitor(baseline, window_rows=64)
    m1.observe_table(tbl)
    r1 = m1.reports
    # external counts: the same window counted in one contraction
    import jax.numpy as jnp
    from avenir_tpu.ops.histogram import feature_bin_counts
    codes = encode_monitor_codes(tbl, baseline.specs)
    counts = np.asarray(feature_bin_counts(
        jnp.asarray(codes), baseline.n_bins_max), dtype=np.float64)
    m2 = StreamDriftMonitor(baseline, window_rows=64)
    m2.close_counts(counts, tbl.n_rows)
    r2 = m2.reports
    assert len(r1) == len(r2) == 2      # window + longterm
    for a, b in zip(r1, r2):
        assert a.kind == b.kind and a.n_rows == b.n_rows
        for ra, rb in zip(a.rows, b.rows):
            assert ra.stats == rb.stats

    # interleaving guard: pending internal rows refuse the external path
    m2.acc.absorb_codes(codes[:8])
    with pytest.raises(ValueError, match="absorb path"):
        m2.close_counts(counts, 64)


def test_schema_fingerprint_stable_and_sensitive():
    s1 = schema_fingerprint(_schema())
    assert s1 == schema_fingerprint(_schema())
    changed = {"fields": [dict(f) for f in SCHEMA["fields"]]}
    changed["fields"][2]["max"] = 700
    assert schema_fingerprint(FeatureSchema.from_dict(changed)) != s1
