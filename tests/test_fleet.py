"""Traffic-shaped serving fleet (ISSUE 10): multi-worker RESP draining,
coordinated hot-swap, admission-control backpressure, degraded-worker
parking with per-worker /healthz.

The contracts under test: every request popped off the one request queue
is answered EXACTLY once (prediction, 'error', or 'busy' — never dropped,
never duplicated) across N concurrent workers; a 'reload' seen by any
worker converges every worker onto the newest intact registry version; a
degraded worker stops pulling (503 on its own /healthz/<name>) while its
peers keep serving."""

import threading
import time
import urllib.error
import urllib.request

import pytest

from avenir_tpu.core.table import encode_rows
from avenir_tpu.io.respq import RespClient, RespServer
from avenir_tpu.serving import (BatchPolicy, ModelRegistry, ServingFleet)
from avenir_tpu.serving.predictor import ForestPredictor
from tests.test_serving import (forest_batch_predict, raw_rows_of,
                                small_forest)
from tests.test_tree import SCHEMA, make_table

pytestmark = pytest.mark.fleet


def drain_replies(cli, queue, expect_n, timeout_s=60.0):
    """Pop replies until ``expect_n`` collected (or timeout); returns
    {rid: [labels...]} so duplicates are visible, not masked."""
    got = {}
    deadline = time.monotonic() + timeout_s
    n = 0
    while n < expect_n and time.monotonic() < deadline:
        vs = cli.rpop_many(queue, 256)
        if not vs:
            time.sleep(0.002)
            continue
        for v in vs:
            rid, label = v.split(",", 1)
            got.setdefault(rid, []).append(label)
            n += 1
    return got


@pytest.fixture()
def resp_server():
    server = RespServer().start()
    yield server
    server.stop()


def make_fleet_registry(tmp_path, mesh_ctx, trees=3, depth=2, seed=3):
    table, models = small_forest(mesh_ctx, n=300, trees=trees, depth=depth,
                                 seed=seed)
    reg = ModelRegistry(str(tmp_path / "registry"))
    reg.publish("churn", models, schema=SCHEMA)
    return reg, table, models


def test_fleet_serves_and_matches_offline(tmp_path, mesh_ctx, resp_server):
    """2 workers draining one queue: every reply identical to the offline
    batch predict, every id answered exactly once."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 60)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    fleet = ServingFleet(reg, "churn", buckets=(8, 64),
                         policy=BatchPolicy(max_batch=16, max_wait_ms=2.0),
                         n_workers=2,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    try:
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i % 60])
                           for i in range(150)])
        got = drain_replies(feeder, "predictionQueue", 150)
        assert sorted(got, key=int) == [str(i) for i in range(150)]
        assert all(len(v) == 1 for v in got.values()), "duplicated reply"
        for i in range(150):
            assert got[str(i)] == [expect[i % 60]]
        st = fleet.stats()
        assert st["served"] == 150 and st["errors"] == 0
        # both workers actually pulled (the queue is shared, not sharded)
        per = st["per_worker"]
        assert len(per) == 2
        assert all(s["model_version"] == 1 for s in per.values())
        # a wire 'stop' ends every worker after pending replies flush
        feeder.lpush("requestQueue", "stop")
        assert fleet.wait(30.0)
    finally:
        fleet.stop()
        feeder.close()


def test_fleet_hot_swap_no_loss_no_dup(tmp_path, mesh_ctx, resp_server):
    """The fleet-scope no-loss/no-dup guarantee under a concurrent
    coordinated hot-swap: requests keep flowing while 'reload' lands,
    every request is answered exactly once with a prediction from v1 OR
    v2 (in-flight batches finish on the model they started on), and both
    workers' model_version converges to the new version."""
    reg, table, m1 = make_fleet_registry(tmp_path, mesh_ctx)
    _, m2 = small_forest(mesh_ctx, n=300, trees=3, depth=2, seed=11)
    rows = raw_rows_of(table, 60)
    enc = encode_rows(rows, SCHEMA)
    valid = {str(i): {forest_batch_predict(m1, enc)[i % 60],
                      forest_batch_predict(m2, enc)[i % 60]}
             for i in range(300)}
    fleet = ServingFleet(reg, "churn", buckets=(8,),
                         policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
                         n_workers=2,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    try:
        for i in range(300):
            feeder.lpush("requestQueue",
                         ",".join(["predict", str(i)] + rows[i % 60]))
            if i == 120:
                # publish v2 and drop the reload into the SAME queue the
                # requests ride — whichever worker pops it triggers the
                # fleet-wide swap
                reg.publish("churn", m2, schema=SCHEMA)
                feeder.lpush("requestQueue", "reload")
            time.sleep(0.0005)
        got = drain_replies(feeder, "predictionQueue", 300)
        assert sorted(got, key=int) == [str(i) for i in range(300)]
        assert all(len(v) == 1 for v in got.values()), "duplicated reply"
        for rid, labels in got.items():
            assert labels[0] in valid[rid], \
                f"request {rid} answered {labels[0]!r}, not a v1/v2 label"
        # every worker converged onto v2 (coordinated, not just the one
        # that saw the message)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            versions = set(fleet.stats()["model_versions"].values())
            if versions == {2}:
                break
            time.sleep(0.05)
        assert set(fleet.stats()["model_versions"].values()) == {2}
        assert fleet.stats()["reload_generation"] >= 1
    finally:
        fleet.stop()
        feeder.close()


class _SlowPredictor:
    """Forest predictor with a deliberate per-batch delay so the bounded
    queue actually fills under a burst (backpressure test)."""

    def __init__(self, inner, delay_s):
        self.inner = inner
        self.delay_s = delay_s

    def warm(self):
        self.inner.warm()
        return self

    def predict_rows(self, rows):
        time.sleep(self.delay_s)
        return self.inner.predict_rows(rows)


def test_fleet_backpressure_busy_never_dropped(mesh_ctx, resp_server):
    """Over-offered load against a bounded queue: the overflow is
    answered '<id>,busy' (admission control), everything else gets a real
    prediction, and EVERY request is answered exactly once — backpressure
    sheds load, it never drops an accepted request."""
    table, models = small_forest(mesh_ctx, n=200, trees=3, depth=2)
    rows = raw_rows_of(table, 40)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    factory = lambda: _SlowPredictor(  # noqa: E731
        ForestPredictor(models, SCHEMA, buckets=(8,)), 0.05)
    fleet = ServingFleet(
        predictor_factory=factory,
        policy=BatchPolicy(max_batch=8, max_wait_ms=1.0,
                           max_queue_depth=4),
        n_workers=1,
        config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    try:
        n = 120
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i % 40])
                           for i in range(n)])
        got = drain_replies(feeder, "predictionQueue", n)
        assert sorted(got, key=int) == [str(i) for i in range(n)]
        assert all(len(v) == 1 for v in got.values()), "duplicated reply"
        n_busy = sum(1 for v in got.values() if v == ["busy"])
        assert n_busy > 0, "over-offered burst produced no busy replies"
        assert n_busy < n, "nothing was actually served"
        for rid, labels in got.items():
            if labels != ["busy"]:
                assert labels == [expect[int(rid) % 40]]
        st = fleet.stats()
        assert st["rejected"] == n_busy
        assert st["served"] == n - n_busy
    finally:
        fleet.stop()
        feeder.close()


def test_fleet_degraded_worker_healthz_peers_serve(tmp_path, mesh_ctx,
                                                   resp_server):
    """mark_degraded on one worker: its own /healthz/<name> flips 503 and
    it stops pulling (ParkedPolls), while its peer keeps answering; a
    hot-swap to a fresh version clears the flag and it rejoins."""
    from avenir_tpu.telemetry import MetricsRegistry, MetricsServer
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 40)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    mreg = MetricsRegistry()
    fleet = ServingFleet(reg, "churn", buckets=(8,),
                         policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
                         n_workers=2, metrics=mreg,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    msrv = MetricsServer(mreg, port=0).start()
    feeder = RespClient(port=resp_server.port)

    def healthz(name):
        try:
            return urllib.request.urlopen(
                f"{msrv.url}/healthz/{name}", timeout=10).status
        except urllib.error.HTTPError as exc:
            return exc.code

    try:
        assert healthz("churn-w0") == 200
        assert healthz("churn-w1") == 200
        assert healthz("no-such-worker") == 404
        w0 = fleet.workers[0].service
        w0.mark_degraded("drift: psi over threshold")
        # the degraded worker's own endpoint flips; its peer's does not
        assert healthz("churn-w0") == 503
        assert healthz("churn-w1") == 200
        # it parks (stops pulling) ...
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                w0.counters.get("Serving", "ParkedPolls") == 0:
            time.sleep(0.01)
        assert w0.counters.get("Serving", "ParkedPolls") > 0
        polls_before = w0.counters.get("Serving", "Polls")
        # ... while the peer keeps answering everything
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i % 40])
                           for i in range(60)])
        got = drain_replies(feeder, "predictionQueue", 60)
        assert sorted(got, key=int) == [str(i) for i in range(60)]
        for i in range(60):
            assert got[str(i)] == [expect[i % 40]]
        assert w0.counters.get("Serving", "Polls") == polls_before, \
            "a degraded worker kept pulling from the queue"
        assert w0.counters.get("Serving", "Requests") == 0
        # a fresh published version + coordinated reload clears the flag
        # and the worker rejoins the fleet
        reg.publish("churn", models, schema=SCHEMA)   # v2
        fleet.request_reload()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and w0.degraded is not None:
            time.sleep(0.05)
        assert w0.degraded is None
        assert healthz("churn-w0") == 200
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                w0.counters.get("Serving", "Polls") == polls_before:
            time.sleep(0.01)
        assert w0.counters.get("Serving", "Polls") > polls_before
    finally:
        msrv.stop()
        fleet.stop()
        feeder.close()


def test_fleet_all_degraded_last_worker_keeps_serving(tmp_path, mesh_ctx,
                                                      resp_server):
    """When EVERY worker is degraded (here: a fleet of one), the last
    one keeps pulling — otherwise nobody could ever pop the wire
    'reload' that is the documented recovery path, and the queue would
    wedge unanswered forever."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    rows = raw_rows_of(table, 8)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    fleet = ServingFleet(reg, "churn", buckets=(8,),
                         policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
                         n_workers=1,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    try:
        w0 = fleet.workers[0].service
        w0.mark_degraded("drift: psi over threshold")
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i])
                           for i in range(8)])
        got = drain_replies(feeder, "predictionQueue", 8)
        assert sorted(got, key=int) == [str(i) for i in range(8)]
        for i in range(8):
            assert got[str(i)] == [expect[i]]
        # and the wire 'reload' recovery path actually recovers it
        reg.publish("churn", models, schema=SCHEMA)   # v2
        feeder.lpush("requestQueue", "reload")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and w0.degraded is not None:
            time.sleep(0.02)
        assert w0.degraded is None and w0.version == 2
    finally:
        fleet.stop()
        feeder.close()


def test_fleet_cli_job_workers(tmp_path, mesh_ctx):
    """predictionService with ps.workers=2: the replay answers every
    request byte-identically to the single-worker job, and the counter
    dump carries the fleet aggregate (Workers, Polls, per-worker-summed
    Requests)."""
    from avenir_tpu.core.config import Config
    from avenir_tpu.cli import serving_jobs  # noqa: F401
    from avenir_tpu.cli.jobs import resolve
    from tests.test_serving import _train_forest_via_cli
    reg_dir = tmp_path / "registry"
    schema_path, trees = _train_forest_via_cli(tmp_path, reg_dir)
    req_rows = raw_rows_of(make_table(40, seed=33), 40)
    expect = forest_batch_predict(trees, encode_rows(req_rows, SCHEMA))
    req_path = tmp_path / "requests.csv"
    req_path.write_text("\n".join(",".join(r) for r in req_rows) + "\n")
    job = resolve("predictionService")
    out_dir = tmp_path / "out_fleet"
    cfg = Config({
        "field.delim.regex": ",", "field.delim.out": ",",
        "ps.model.registry.dir": str(reg_dir),
        "ps.model.name": "churn",
        "ps.feature.schema.file.path": str(schema_path),
        "ps.batch.max.size": "16", "ps.batch.max.wait.ms": "2",
        "ps.bucket.sizes": "8,64",
        "ps.transport": "resp",
        "ps.workers": "2",
    })
    counters = job(cfg, str(req_path), str(out_dir))
    with open(out_dir / "part-m-00000") as fh:
        lines = fh.read().splitlines()
    assert [ln.split(",", 1)[1] for ln in lines] == expect
    assert counters.get("Serving", "Requests") == 40
    assert counters.get("Serving", "Workers") == 2
    assert counters.get("Serving", "Polls") > 0
    assert counters.get("Serving", "ModelVersion") == 1
    assert counters.get("Serving", "serve.request.p99Us") > 0
    # fleet size needs the wire: inprocess transport refuses
    bad = Config({
        "field.delim.regex": ",", "field.delim.out": ",",
        "ps.model.registry.dir": str(reg_dir),
        "ps.model.name": "churn",
        "ps.feature.schema.file.path": str(schema_path),
        "ps.workers": "2",
    })
    with pytest.raises(ValueError, match="resp"):
        job(bad, str(req_path), str(tmp_path / "out_bad"))


def test_two_fleets_one_registry_host_label_disjoint(tmp_path, mesh_ctx,
                                                     resp_server):
    """The multi-host scrape shape: two fleets (two 'hosts') serving the
    SAME model name bound to ONE MetricsRegistry write DISJOINT
    host-labeled series — same fix shape as the PR 8 service label, one
    level up.  Worker names collide across hosts on purpose; the host
    label (and host-qualified health keys) keep them apart, and
    stopping one fleet drops only ITS series."""
    from avenir_tpu.telemetry import MetricsRegistry
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx)
    mreg = MetricsRegistry()

    def make(host):
        return ServingFleet(
            reg, "churn", buckets=(8,),
            policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
            n_workers=1, metrics=mreg, host_label=host,
            config={"redis.server.port": resp_server.port,
                    "redis.request.queue": f"rq-{host}",
                    "redis.prediction.queue": f"pq-{host}"})

    fa, fb = make("hostA").start(), make("hostB").start()
    try:
        assert fa.stats()["host"] == "hostA"
        assert fb.stats()["host"] == "hostB"
        text = mreg.render()
        a = 'avenir_serving{host="hostA",service="churn-w0",model="churn",'
        b = 'avenir_serving{host="hostB",service="churn-w0",model="churn",'
        assert a + 'key="queue_depth"}' in text
        assert b + 'key="queue_depth"}' in text
        # NO rename happened: both kept the bare worker identity, the
        # host label is what separates the series
        assert "churn-w0-1" not in text
        # health providers are host-qualified and both reachable
        ok_a = mreg.health_one("hostA:churn-w0")
        ok_b = mreg.health_one("hostB:churn-w0")
        assert ok_a is not None and ok_b is not None
        # the bare-name probe still resolves (first match — the single-
        # host shape load balancers use)
        assert mreg.health_one("churn-w0") is not None
        # one fleet degrading flips ONLY its own provider
        fb.workers[0].service.mark_degraded("drift")
        assert mreg.health_one("hostA:churn-w0")[0] is True
        assert mreg.health_one("hostB:churn-w0")[0] is False
        # stopping hostB drops ITS series and provider; hostA's survive
        fb.stop()
        text = mreg.render()
        assert a + 'key="queue_depth"}' in text
        assert b + 'key="queue_depth"}' not in text
        assert mreg.health_one("hostB:churn-w0") is None
        assert mreg.health_one("hostA:churn-w0") is not None
    finally:
        fa.stop()
        fb.stop()


@pytest.mark.slow
def test_fleet_soak_sustained_multiworker(tmp_path, mesh_ctx, resp_server):
    """Sustained load through 2 workers: thousands of requests, every
    answer correct, exactly once."""
    reg, table, models = make_fleet_registry(tmp_path, mesh_ctx, trees=5,
                                             depth=3)
    rows = raw_rows_of(table, 128)
    expect = forest_batch_predict(models, encode_rows(rows, SCHEMA))
    fleet = ServingFleet(reg, "churn", buckets=(8, 64),
                         policy=BatchPolicy(max_batch=64, max_wait_ms=2.0),
                         n_workers=2,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    try:
        n = 4000
        for i in range(0, n, 256):
            feeder.lpush_many(
                "requestQueue",
                [",".join(["predict", str(j)] + rows[j % 128])
                 for j in range(i, min(i + 256, n))])
        got = drain_replies(feeder, "predictionQueue", n, timeout_s=120.0)
        assert sorted(got, key=int) == [str(i) for i in range(n)]
        assert all(len(v) == 1 for v in got.values())
        for i in range(n):
            assert got[str(i)] == [expect[i % 128]]
    finally:
        fleet.stop()
        feeder.close()


# --------------------------------------------------------------------------
# drift-policy guardrail actions at FLEET scope (ISSUE 14 satellite):
# refresh_action/degrade_action were written against a single
# PredictionService — pin that wired to a ServingFleet the refresh
# converges ALL workers and a degrade parks only per the PR 12 rules
# --------------------------------------------------------------------------

def _fake_alert(value=0.7):
    from avenir_tpu.monitor.policy import AlertRecord
    return AlertRecord(window_index=1, window_kind="window",
                       scope="holdTime", stat="psi", value=value,
                       threshold=0.25, level="alert", streak=2,
                       n_rows=256)


def test_refresh_action_converges_whole_fleet(tmp_path, mesh_ctx,
                                              resp_server):
    """A fleet-addressed refresh_action (the 'a retrain already landed'
    guardrail) converges every worker onto the newly published version —
    not just one service."""
    from avenir_tpu.core.metrics import Counters
    from avenir_tpu.monitor.policy import refresh_action
    reg, table, m1 = make_fleet_registry(tmp_path, mesh_ctx)
    fleet = ServingFleet(reg, "churn", buckets=(8,),
                         policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
                         n_workers=2,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    counters = Counters()
    act = refresh_action(fleet, counters)
    try:
        assert fleet.converged_version() == 1
        # no newer version yet: the probe counts, but NOT a swap —
        # fleet.refresh() reports will-it-swap like a service's does
        act(_fake_alert())
        assert counters.get("DriftMonitor", "RefreshSwaps") == 0
        _, m2 = small_forest(mesh_ctx, n=300, trees=3, depth=2, seed=11)
        reg.publish("churn", m2, schema=SCHEMA)
        act(_fake_alert())
        assert counters.get("DriftMonitor", "RefreshSwaps") == 1
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 2 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 2
        st = fleet.stats()
        assert set(st["model_versions"].values()) == {2}
        assert counters.get("DriftMonitor", "RefreshProbes") == 2
        # the fleet still answers after the converged swap
        rows = raw_rows_of(table, 16)
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i])
                           for i in range(16)])
        got = drain_replies(feeder, "predictionQueue", 16)
        assert len(got) == 16
    finally:
        fleet.stop()
        feeder.close()


def test_degrade_action_fleet_parks_per_pr12_rules(tmp_path, mesh_ctx,
                                                   resp_server):
    """degrade_action at fleet scope flags EVERY worker; the PR 12
    parking rules then hold: the fleet keeps answering (the last active
    worker serves flagged rather than parking — nobody-pulling is the
    wedge the rules exist to prevent), and a hot-swap to a fresh version
    clears the flags and un-parks everyone."""
    from avenir_tpu.core.metrics import Counters
    from avenir_tpu.monitor.policy import degrade_action
    reg, table, m1 = make_fleet_registry(tmp_path, mesh_ctx)
    fleet = ServingFleet(reg, "churn", buckets=(8,),
                         policy=BatchPolicy(max_batch=8, max_wait_ms=1.0),
                         n_workers=2,
                         config={"redis.server.port": resp_server.port})
    fleet.start()
    feeder = RespClient(port=resp_server.port)
    counters = Counters()
    try:
        degrade_action(fleet, counters)(_fake_alert())
        assert counters.get("DriftMonitor", "Degradations") == 1
        st = fleet.stats()
        assert all(s["degraded"] for s in st["per_worker"].values())
        # an all-degraded fleet still answers (last-active-keeps-serving)
        rows = raw_rows_of(table, 16)
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", str(i)] + rows[i])
                           for i in range(16)])
        got = drain_replies(feeder, "predictionQueue", 16)
        assert len(got) == 16 and all(len(v) == 1 for v in got.values())
        # publish a fix + fleet refresh: flags clear, both workers serve
        _, m2 = small_forest(mesh_ctx, n=300, trees=3, depth=2, seed=11)
        reg.publish("churn", m2, schema=SCHEMA)
        fleet.refresh()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            st = fleet.stats()
            if set(st["model_versions"].values()) == {2} and \
                    not any(s["degraded"]
                            for s in st["per_worker"].values()):
                break
            time.sleep(0.01)
        st = fleet.stats()
        assert set(st["model_versions"].values()) == {2}
        assert not any(s["degraded"] for s in st["per_worker"].values())
        feeder.lpush_many("requestQueue",
                          [",".join(["predict", f"b{i}"] + rows[i])
                           for i in range(16)])
        assert len(drain_replies(feeder, "predictionQueue", 16)) == 16
    finally:
        fleet.stop()
        feeder.close()
