"""Bandit tests: every learner converges on an easy problem, state round
trips, grouped batch flow, vectorized device path, serving loop."""

import numpy as np
import pytest

from avenir_tpu.reinforce.learners import LEARNERS, create_learner
from avenir_tpu.reinforce.batch import GroupedBandits, VectorBandits
from avenir_tpu.reinforce.serving import ReinforcementLearnerService

ACTIONS = ["a", "b", "c"]
TRUE_MEANS = {"a": 0.2, "b": 0.5, "c": 0.8}


def run_learner(algorithm, rounds=800, seed=3):
    rng = np.random.default_rng(seed)
    learner = create_learner(algorithm, ACTIONS,
                             {"random.seed": seed, "min.trial": 3})
    picks = []
    for _ in range(rounds):
        a = learner.next_action()
        picks.append(a)
        r = float(np.clip(rng.normal(TRUE_MEANS[a], 0.1), 0, 1))
        learner.set_reward(a, r)
    return learner, picks


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_learner_converges(algorithm):
    learner, picks = run_learner(algorithm)
    late = picks[-200:]
    frac_best = late.count("c") / len(late)
    assert frac_best > 0.5, f"{algorithm}: best-arm rate {frac_best}"


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_state_roundtrip(algorithm):
    learner, _ = run_learner(algorithm, rounds=100)
    lines = learner.get_model()
    fresh = create_learner(algorithm, ACTIONS, {"random.seed": 1})
    fresh.build_model(lines)
    for a in ACTIONS:
        assert fresh.stats[a].count == learner.stats[a].count
        assert abs(fresh.stats[a].mean - learner.stats[a].mean) < 1e-9
    # extra state (weights/prefs/epochs) preserved
    assert fresh.get_model() == lines


def test_unknown_algorithm():
    with pytest.raises(ValueError):
        create_learner("bogus", ACTIONS)


def test_auer_greedy_variant():
    rng = np.random.default_rng(8)
    learner = create_learner("randomGreedy", ACTIONS,
                             {"random.seed": 8, "min.trial": 2,
                              "prob.reduction.algorithm": "auerGreedy",
                              "auer.greedy.constant": 0.3})
    picks = []
    for _ in range(600):
        a = learner.next_action()
        picks.append(a)
        learner.set_reward(a, float(np.clip(rng.normal(TRUE_MEANS[a], 0.1),
                                            0, 1)))
    assert picks[-150:].count("c") / 150 > 0.5


def test_group_seeding_deterministic_across_rounds():
    """Recreated learners must not replay identical random draws each round
    (regression for the salted-hash / replayed-stream bug)."""
    from avenir_tpu.reinforce.batch import GroupedBandits
    draws = []
    state = None
    for round_no in range(3):
        gb = GroupedBandits("randomGreedy", ACTIONS,
                            {"random.seed": 11, "random.selection.prob": 1.0})
        if state:
            gb.load_state(state)
        else:
            gb.learner("g")
        acts = gb.next_actions(["g"])
        draws.append(acts[0])
        for a in acts[0].split(",")[1:]:
            gb.apply_rewards([f"g,{a},0.5"])
        state = gb.save_state()
    # with epsilon=1 every pick is random; streams must differ across rounds
    assert len(set(draws)) > 1


def test_grouped_bandits_flow():
    gb = GroupedBandits("randomGreedy", ACTIONS,
                        {"random.seed": 5, "random.selection.prob": 0.2})
    rng = np.random.default_rng(0)
    # simulate 2 groups with different best arms
    best = {"g1": "c", "g2": "a"}
    for _ in range(300):
        for line in gb.next_actions(["g1", "g2"]):
            parts = line.split(",")
            g, acts = parts[0], parts[1:]
            for a in acts:
                r = 0.9 if a == best[g] else 0.1
                gb.apply_rewards([f"{g},{a},{r + rng.normal(0, 0.05):.4f}"])
    state = gb.save_state()
    assert any(l.startswith("g1,") for l in state)
    # reload into a fresh instance and check the learned best arms
    gb2 = GroupedBandits("randomGreedy", ACTIONS, {"random.seed": 6,
                                                   "random.selection.prob": 0.0})
    gb2.load_state(state)
    assert gb2.learner("g1")._greedy() == "c"
    assert gb2.learner("g2")._greedy() == "a"


def test_vector_bandits_device_path(mesh_ctx):
    G, A = 64, 4
    vb = VectorBandits("ucb1", G, A, seed=2)
    rng = np.random.default_rng(2)
    best = rng.integers(0, A, G)
    for _ in range(150):
        acts = vb.next_actions()
        rewards = np.where(acts == best, 0.9, 0.1) + rng.normal(0, 0.02, G)
        vb.set_rewards(np.arange(G), acts, rewards.astype(np.float32))
    final = vb.next_actions()
    assert (final == best).mean() > 0.9


@pytest.mark.parametrize("algo", ["randomGreedy", "softMax", "sampsonSampler",
                                  "intervalEstimator"])
def test_vector_bandits_algorithms(algo, mesh_ctx):
    vb = VectorBandits(algo, 16, 3, seed=1)
    rng = np.random.default_rng(1)
    for _ in range(200):
        acts = vb.next_actions()
        rewards = np.where(acts == 2, 0.8, 0.2) + rng.normal(0, 0.05, 16)
        vb.set_rewards(np.arange(16), acts, rewards.astype(np.float32))
    mean = vb.sums / np.maximum(vb.counts, 1)
    assert (vb.counts.argmax(axis=1) == 2).mean() > 0.6


def test_serving_loop():
    svc = ReinforcementLearnerService("randomGreedy", ACTIONS,
                                      {"random.seed": 7,
                                       "decision.batch.size": 2})
    out = svc.process("round,1")
    parts = out.split(",")
    assert parts[0] == "1" and len(parts) == 3
    svc.process(f"reward,{parts[1]},0.9")
    assert svc.learner.stats[parts[1]].count == 1
    # async loop
    svc.start()
    svc.event_queue.put("round,2")
    got = svc.action_queue.get(timeout=2)
    assert got.split(",")[0] in ("1", "2")
    svc.stop()
    with pytest.raises(ValueError):
        svc.process("bogus,1")
