"""Bandit tests: every learner converges on an easy problem, state round
trips, grouped batch flow, vectorized device path, serving loop."""

import numpy as np
import pytest

from avenir_tpu.reinforce.learners import LEARNERS, create_learner
from avenir_tpu.reinforce.batch import GroupedBandits, VectorBandits
from avenir_tpu.reinforce.serving import ReinforcementLearnerService

ACTIONS = ["a", "b", "c"]
TRUE_MEANS = {"a": 0.2, "b": 0.5, "c": 0.8}


def run_learner(algorithm, rounds=800, seed=3):
    rng = np.random.default_rng(seed)
    learner = create_learner(algorithm, ACTIONS,
                             {"random.seed": seed, "min.trial": 3})
    picks = []
    for _ in range(rounds):
        a = learner.next_action()
        picks.append(a)
        r = float(np.clip(rng.normal(TRUE_MEANS[a], 0.1), 0, 1))
        learner.set_reward(a, r)
    return learner, picks


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_learner_converges(algorithm):
    learner, picks = run_learner(algorithm)
    late = picks[-200:]
    frac_best = late.count("c") / len(late)
    assert frac_best > 0.5, f"{algorithm}: best-arm rate {frac_best}"


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_state_roundtrip(algorithm):
    learner, _ = run_learner(algorithm, rounds=100)
    lines = learner.get_model()
    fresh = create_learner(algorithm, ACTIONS, {"random.seed": 1})
    fresh.build_model(lines)
    for a in ACTIONS:
        assert fresh.stats[a].count == learner.stats[a].count
        assert abs(fresh.stats[a].mean - learner.stats[a].mean) < 1e-9
    # extra state (weights/prefs/epochs) preserved
    assert fresh.get_model() == lines


def test_unknown_algorithm():
    with pytest.raises(ValueError):
        create_learner("bogus", ACTIONS)


def test_auer_greedy_variant():
    rng = np.random.default_rng(8)
    learner = create_learner("randomGreedy", ACTIONS,
                             {"random.seed": 8, "min.trial": 2,
                              "prob.reduction.algorithm": "auerGreedy",
                              "auer.greedy.constant": 0.3})
    picks = []
    for _ in range(600):
        a = learner.next_action()
        picks.append(a)
        learner.set_reward(a, float(np.clip(rng.normal(TRUE_MEANS[a], 0.1),
                                            0, 1)))
    assert picks[-150:].count("c") / 150 > 0.5


def test_group_seeding_deterministic_across_rounds():
    """Recreated learners must not replay identical random draws each round
    (regression for the salted-hash / replayed-stream bug)."""
    from avenir_tpu.reinforce.batch import GroupedBandits
    draws = []
    state = None
    for round_no in range(3):
        gb = GroupedBandits("randomGreedy", ACTIONS,
                            {"random.seed": 11, "random.selection.prob": 1.0})
        if state:
            gb.load_state(state)
        else:
            gb.learner("g")
        acts = gb.next_actions(["g"])
        draws.append(acts[0])
        for a in acts[0].split(",")[1:]:
            gb.apply_rewards([f"g,{a},0.5"])
        state = gb.save_state()
    # with epsilon=1 every pick is random; streams must differ across rounds
    assert len(set(draws)) > 1


def test_grouped_bandits_flow():
    gb = GroupedBandits("randomGreedy", ACTIONS,
                        {"random.seed": 5, "random.selection.prob": 0.2})
    rng = np.random.default_rng(0)
    # simulate 2 groups with different best arms
    best = {"g1": "c", "g2": "a"}
    for _ in range(300):
        for line in gb.next_actions(["g1", "g2"]):
            parts = line.split(",")
            g, acts = parts[0], parts[1:]
            for a in acts:
                r = 0.9 if a == best[g] else 0.1
                gb.apply_rewards([f"{g},{a},{r + rng.normal(0, 0.05):.4f}"])
    state = gb.save_state()
    assert any(l.startswith("g1,") for l in state)
    # reload into a fresh instance and check the learned best arms
    gb2 = GroupedBandits("randomGreedy", ACTIONS, {"random.seed": 6,
                                                   "random.selection.prob": 0.0})
    gb2.load_state(state)
    assert gb2.learner("g1")._greedy() == "c"
    assert gb2.learner("g2")._greedy() == "a"


def test_vector_bandits_device_path(mesh_ctx):
    G, A = 64, 4
    vb = VectorBandits("ucb1", G, A, seed=2)
    rng = np.random.default_rng(2)
    best = rng.integers(0, A, G)
    for _ in range(150):
        acts = vb.next_actions()
        rewards = np.where(acts == best, 0.9, 0.1) + rng.normal(0, 0.02, G)
        vb.set_rewards(np.arange(G), acts, rewards.astype(np.float32))
    final = vb.next_actions()
    assert (final == best).mean() > 0.9


@pytest.mark.parametrize("algo", ["randomGreedy", "softMax", "sampsonSampler",
                                  "intervalEstimator", "ucb2",
                                  "optimisticSampsonSampler", "actionPursuit",
                                  "rewardComparison", "exponentialWeight",
                                  "exponentialWeightExpert"])
def test_vector_bandits_algorithms(algo, mesh_ctx):
    vb = VectorBandits(algo, 16, 3, seed=1)
    rng = np.random.default_rng(1)
    for _ in range(200):
        acts = vb.next_actions()
        rewards = np.where(acts == 2, 0.8, 0.2) + rng.normal(0, 0.05, 16)
        vb.set_rewards(np.arange(16), acts, rewards.astype(np.float32))
    mean = vb.sums / np.maximum(vb.counts, 1)
    assert (vb.counts.argmax(axis=1) == 2).mean() > 0.6


def test_serving_loop():
    svc = ReinforcementLearnerService("randomGreedy", ACTIONS,
                                      {"random.seed": 7,
                                       "decision.batch.size": 2})
    out = svc.process("round,1")
    parts = out.split(",")
    assert parts[0] == "1" and len(parts) == 3
    svc.process(f"reward,{parts[1]},0.9")
    assert svc.learner.stats[parts[1]].count == 1
    # async loop
    svc.start()
    svc.event_queue.put("round,2")
    got = svc.action_queue.get(timeout=2)
    assert got.split(",")[0] in ("1", "2")
    svc.stop()
    with pytest.raises(ValueError):
        svc.process("bogus,1")


def test_vector_bandits_cover_all_factory_algorithms():
    """VERDICT r2 #6: the device path supports every algorithm the factory
    creates (MultiArmBanditLearnerFactory.java:30-41)."""
    from avenir_tpu.reinforce.learners import LEARNERS
    from avenir_tpu.reinforce.batch import VectorBandits
    assert set(VectorBandits.ALGORITHMS) == set(LEARNERS)


def test_vector_ucb2_epoch_commitment(mesh_ctx):
    """ucb2 commits to an arm for tau(r+1)-tau(r)-1 rounds after choosing."""
    vb = VectorBandits("ucb2", 4, 3, {"alpha": 2.0}, seed=3)
    rng = np.random.default_rng(3)
    # warm all arms so the inf-untried phase passes
    for a in range(3):
        acts = np.full(4, a)
        vb.set_rewards(np.arange(4), acts, rng.random(4).astype(np.float32))
    first = vb.next_actions()
    # with alpha=2: after the first committed pick, tau jumps 1 -> 3, so the
    # next 1+ rounds replay the same arm per group
    second = vb.next_actions()
    assert (first == second).all()


def test_vector_exp3_weights_move_toward_best(mesh_ctx):
    vb = VectorBandits("exponentialWeight", 8, 3,
                       {"distr.constant": 0.2}, seed=4)
    rng = np.random.default_rng(4)
    for _ in range(300):
        acts = vb.next_actions()
        rewards = np.where(acts == 1, 1.0, 0.0)
        vb.set_rewards(np.arange(8), acts, rewards.astype(np.float32))
    assert (vb.weights.argmax(axis=1) == 1).mean() > 0.8


def test_vector_reward_comparison_reference_moves(mesh_ctx):
    vb = VectorBandits("rewardComparison", 2, 2,
                       {"preference.step": 0.5,
                        "reference.reward.step": 0.5}, seed=5)
    vb.set_rewards(np.array([0, 0]), np.array([0, 1]),
                   np.array([1.0, 1.0], dtype=np.float32))
    # first event: pref[0,0] += .5*(1-0)=.5, ref->.5;
    # second: pref[0,1] += .5*(1-.5)=.25, ref->.75 (order-sensitive)
    assert abs(vb.prefs[0, 0] - 0.5) < 1e-6
    assert abs(vb.prefs[0, 1] - 0.25) < 1e-6
    assert abs(vb.ref_reward[0] - 0.75) < 1e-6
    assert vb.ref_reward[1] == 0.0


def test_vector_serving_loop(mesh_ctx):
    from avenir_tpu.reinforce.serving import VectorLearnerService
    svc = VectorLearnerService("randomGreedy", ["a", "b", "c"], 4,
                               {"random.selection.prob": 0.0}, seed=9)
    # teach every group that 'b' pays
    for g in range(4):
        for act in ("a", "b", "c"):
            svc.process(f"reward,{g},{act},{0.9 if act == 'b' else 0.1}")
    out = svc.process("round,7")
    lines = out.splitlines()
    assert len(lines) == 4
    for g, line in enumerate(lines):
        rnd, grp, act = line.split(",")
        assert (rnd, grp, act) == ("7", str(g), "b")
    assert svc.action_queue.qsize() == 4


def test_vector_exp3_no_overflow_long_run(mesh_ctx):
    """f32 EXP3 weights must survive thousands of rewarded rounds (they are
    renormalized per update; unnormalized they hit inf at ~2.5k)."""
    vb = VectorBandits("exponentialWeight", 2, 3, seed=6)
    g = np.array([0, 1])
    for _ in range(3000):
        acts = vb.next_actions()
        vb.set_rewards(g, acts, np.ones(2, dtype=np.float32))
    assert np.isfinite(vb.weights).all()
    probs = vb.last_probs
    assert np.isfinite(probs).all() and (probs > 0).all()


def test_vector_ucb2_survives_delayed_rewards(mesh_ctx):
    """ucb2 selection must stay finite when rounds outpace rewards (the
    serving pattern): epochs advance per pick but N tracks trials, so the
    bonus can never go NaN and later rewards still steer the arm."""
    vb = VectorBandits("ucb2", 1, 2, seed=7)
    vb.set_rewards(np.zeros(2, int), np.array([0, 1]),
                   np.array([0.5, 0.5], dtype=np.float32))
    for _ in range(80):  # many unrewarded selections
        acts = vb.next_actions()
    assert np.isfinite(vb.epochs).all()
    # arm 1 becomes clearly better; the learner must switch to it
    for _ in range(60):
        acts = vb.next_actions()
        vb.set_rewards(np.zeros(1, int), acts,
                       np.where(acts == 1, 1.0, 0.0).astype(np.float32))
    picks = [int(vb.next_actions()[0]) for _ in range(10)]
    assert 1 in picks


def test_exploration_counter_reference_semantics():
    """ExplorationCounter.java:52-98: windowed forced exploration with
    wrap-around, inactive once the budget is spent."""
    from avenir_tpu.reinforce.learners import ExplorationCounter
    ec = ExplorationCounter("g", count=5, exploration_count=12, batch_size=4)
    ec.select_next_round(1)   # remaining 12 -> beg 12%5=2, end 5 -> wraps
    assert ec.is_in_exploration()
    assert ec.should_explore(2) and ec.should_explore(4)
    assert ec.should_explore(0)  # wrapped segment 0..0
    assert not ec.should_explore(1)
    ec.select_next_round(3)   # remaining 12-8=4 -> beg 4, end 7 -> wraps
    assert ec.should_explore(4) and ec.should_explore(2)
    ec.select_next_round(4)   # remaining 0 -> exploration over
    assert not ec.is_in_exploration()
    assert not ec.should_explore(0)
