"""Bandit tests: every learner converges on an easy problem, state round
trips, grouped batch flow, vectorized device path, serving loop."""

import numpy as np
import pytest

from avenir_tpu.reinforce.learners import LEARNERS, create_learner
from avenir_tpu.reinforce.batch import GroupedBandits, VectorBandits
from avenir_tpu.reinforce.serving import ReinforcementLearnerService

ACTIONS = ["a", "b", "c"]
TRUE_MEANS = {"a": 0.2, "b": 0.5, "c": 0.8}


def run_learner(algorithm, rounds=800, seed=3):
    rng = np.random.default_rng(seed)
    learner = create_learner(algorithm, ACTIONS,
                             {"random.seed": seed, "min.trial": 3})
    picks = []
    for _ in range(rounds):
        a = learner.next_action()
        picks.append(a)
        r = float(np.clip(rng.normal(TRUE_MEANS[a], 0.1), 0, 1))
        learner.set_reward(a, r)
    return learner, picks


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_learner_converges(algorithm):
    learner, picks = run_learner(algorithm)
    late = picks[-200:]
    frac_best = late.count("c") / len(late)
    assert frac_best > 0.5, f"{algorithm}: best-arm rate {frac_best}"


@pytest.mark.parametrize("algorithm", sorted(LEARNERS))
def test_state_roundtrip(algorithm):
    learner, _ = run_learner(algorithm, rounds=100)
    lines = learner.get_model()
    fresh = create_learner(algorithm, ACTIONS, {"random.seed": 1})
    fresh.build_model(lines)
    for a in ACTIONS:
        assert fresh.stats[a].count == learner.stats[a].count
        assert abs(fresh.stats[a].mean - learner.stats[a].mean) < 1e-9
    # extra state (weights/prefs/epochs) preserved
    assert fresh.get_model() == lines


def test_unknown_algorithm():
    with pytest.raises(ValueError):
        create_learner("bogus", ACTIONS)


def test_auer_greedy_variant():
    rng = np.random.default_rng(8)
    learner = create_learner("randomGreedy", ACTIONS,
                             {"random.seed": 8, "min.trial": 2,
                              "prob.reduction.algorithm": "auerGreedy",
                              "auer.greedy.constant": 0.3})
    picks = []
    for _ in range(600):
        a = learner.next_action()
        picks.append(a)
        learner.set_reward(a, float(np.clip(rng.normal(TRUE_MEANS[a], 0.1),
                                            0, 1)))
    assert picks[-150:].count("c") / 150 > 0.5


def test_group_seeding_deterministic_across_rounds():
    """Recreated learners must not replay identical random draws each round
    (regression for the salted-hash / replayed-stream bug)."""
    from avenir_tpu.reinforce.batch import GroupedBandits
    draws = []
    state = None
    for round_no in range(3):
        gb = GroupedBandits("randomGreedy", ACTIONS,
                            {"random.seed": 11, "random.selection.prob": 1.0})
        if state:
            gb.load_state(state)
        else:
            gb.learner("g")
        acts = gb.next_actions(["g"])
        draws.append(acts[0])
        for a in acts[0].split(",")[1:]:
            gb.apply_rewards([f"g,{a},0.5"])
        state = gb.save_state()
    # with epsilon=1 every pick is random; streams must differ across rounds
    assert len(set(draws)) > 1


def test_grouped_bandits_flow():
    gb = GroupedBandits("randomGreedy", ACTIONS,
                        {"random.seed": 5, "random.selection.prob": 0.2})
    rng = np.random.default_rng(0)
    # simulate 2 groups with different best arms
    best = {"g1": "c", "g2": "a"}
    for _ in range(300):
        for line in gb.next_actions(["g1", "g2"]):
            parts = line.split(",")
            g, acts = parts[0], parts[1:]
            for a in acts:
                r = 0.9 if a == best[g] else 0.1
                gb.apply_rewards([f"{g},{a},{r + rng.normal(0, 0.05):.4f}"])
    state = gb.save_state()
    assert any(l.startswith("g1,") for l in state)
    # reload into a fresh instance and check the learned best arms
    gb2 = GroupedBandits("randomGreedy", ACTIONS, {"random.seed": 6,
                                                   "random.selection.prob": 0.0})
    gb2.load_state(state)
    assert gb2.learner("g1")._greedy() == "c"
    assert gb2.learner("g2")._greedy() == "a"


def test_vector_bandits_device_path(mesh_ctx):
    G, A = 64, 4
    vb = VectorBandits("ucb1", G, A, seed=2)
    rng = np.random.default_rng(2)
    best = rng.integers(0, A, G)
    for _ in range(150):
        acts = vb.next_actions()
        rewards = np.where(acts == best, 0.9, 0.1) + rng.normal(0, 0.02, G)
        vb.set_rewards(np.arange(G), acts, rewards.astype(np.float32))
    final = vb.next_actions()
    assert (final == best).mean() > 0.9


@pytest.mark.parametrize("algo", ["randomGreedy", "softMax", "sampsonSampler",
                                  "intervalEstimator", "ucb2",
                                  "optimisticSampsonSampler", "actionPursuit",
                                  "rewardComparison", "exponentialWeight",
                                  "exponentialWeightExpert"])
def test_vector_bandits_algorithms(algo, mesh_ctx):
    vb = VectorBandits(algo, 16, 3, seed=1)
    rng = np.random.default_rng(1)
    for _ in range(200):
        acts = vb.next_actions()
        rewards = np.where(acts == 2, 0.8, 0.2) + rng.normal(0, 0.05, 16)
        vb.set_rewards(np.arange(16), acts, rewards.astype(np.float32))
    mean = vb.sums / np.maximum(vb.counts, 1)
    assert (vb.counts.argmax(axis=1) == 2).mean() > 0.6


def test_serving_loop():
    svc = ReinforcementLearnerService("randomGreedy", ACTIONS,
                                      {"random.seed": 7,
                                       "decision.batch.size": 2})
    out = svc.process("round,1")
    parts = out.split(",")
    assert parts[0] == "1" and len(parts) == 3
    svc.process(f"reward,{parts[1]},0.9")
    assert svc.learner.stats[parts[1]].count == 1
    # async loop
    svc.start()
    svc.event_queue.put("round,2")
    got = svc.action_queue.get(timeout=2)
    assert got.split(",")[0] in ("1", "2")
    svc.stop()
    with pytest.raises(ValueError):
        svc.process("bogus,1")


def test_vector_bandits_cover_all_factory_algorithms():
    """VERDICT r2 #6: the device path supports every algorithm the factory
    creates (MultiArmBanditLearnerFactory.java:30-41)."""
    from avenir_tpu.reinforce.learners import LEARNERS
    from avenir_tpu.reinforce.batch import VectorBandits
    assert set(VectorBandits.ALGORITHMS) == set(LEARNERS)


def test_vector_ucb2_epoch_commitment(mesh_ctx):
    """ucb2 commits to an arm for tau(r+1)-tau(r)-1 rounds after choosing."""
    vb = VectorBandits("ucb2", 4, 3, {"alpha": 2.0}, seed=3)
    rng = np.random.default_rng(3)
    # warm all arms so the inf-untried phase passes
    for a in range(3):
        acts = np.full(4, a)
        vb.set_rewards(np.arange(4), acts, rng.random(4).astype(np.float32))
    first = vb.next_actions()
    # with alpha=2: after the first committed pick, tau jumps 1 -> 3, so the
    # next 1+ rounds replay the same arm per group
    second = vb.next_actions()
    assert (first == second).all()


def test_vector_exp3_weights_move_toward_best(mesh_ctx):
    vb = VectorBandits("exponentialWeight", 8, 3,
                       {"distr.constant": 0.2}, seed=4)
    rng = np.random.default_rng(4)
    for _ in range(300):
        acts = vb.next_actions()
        rewards = np.where(acts == 1, 1.0, 0.0)
        vb.set_rewards(np.arange(8), acts, rewards.astype(np.float32))
    assert (vb.weights.argmax(axis=1) == 1).mean() > 0.8


def test_vector_reward_comparison_reference_moves(mesh_ctx):
    vb = VectorBandits("rewardComparison", 2, 2,
                       {"preference.step": 0.5,
                        "reference.reward.step": 0.5}, seed=5)
    vb.set_rewards(np.array([0, 0]), np.array([0, 1]),
                   np.array([1.0, 1.0], dtype=np.float32))
    # first event: pref[0,0] += .5*(1-0)=.5, ref->.5;
    # second: pref[0,1] += .5*(1-.5)=.25, ref->.75 (order-sensitive)
    assert abs(vb.prefs[0, 0] - 0.5) < 1e-6
    assert abs(vb.prefs[0, 1] - 0.25) < 1e-6
    assert abs(vb.ref_reward[0] - 0.75) < 1e-6
    assert vb.ref_reward[1] == 0.0


def test_vector_serving_loop(mesh_ctx):
    from avenir_tpu.reinforce.serving import VectorLearnerService
    svc = VectorLearnerService("randomGreedy", ["a", "b", "c"], 4,
                               {"random.selection.prob": 0.0}, seed=9)
    # teach every group that 'b' pays
    for g in range(4):
        for act in ("a", "b", "c"):
            svc.process(f"reward,{g},{act},{0.9 if act == 'b' else 0.1}")
    out = svc.process("round,7")
    lines = out.splitlines()
    assert len(lines) == 4
    for g, line in enumerate(lines):
        rnd, grp, act = line.split(",")
        assert (rnd, grp, act) == ("7", str(g), "b")
    assert svc.action_queue.qsize() == 4


def test_vector_exp3_no_overflow_long_run(mesh_ctx):
    """f32 EXP3 weights must survive thousands of rewarded rounds (they are
    renormalized per update; unnormalized they hit inf at ~2.5k)."""
    vb = VectorBandits("exponentialWeight", 2, 3, seed=6)
    g = np.array([0, 1])
    for _ in range(3000):
        acts = vb.next_actions()
        vb.set_rewards(g, acts, np.ones(2, dtype=np.float32))
    assert np.isfinite(vb.weights).all()
    probs = vb.last_probs
    assert np.isfinite(probs).all() and (probs > 0).all()


def test_vector_ucb2_survives_delayed_rewards(mesh_ctx):
    """ucb2 selection must stay finite when rounds outpace rewards (the
    serving pattern): epochs advance per pick but N tracks trials, so the
    bonus can never go NaN and later rewards still steer the arm."""
    vb = VectorBandits("ucb2", 1, 2, seed=7)
    vb.set_rewards(np.zeros(2, int), np.array([0, 1]),
                   np.array([0.5, 0.5], dtype=np.float32))
    for _ in range(80):  # many unrewarded selections
        acts = vb.next_actions()
    assert np.isfinite(vb.epochs).all()
    # arm 1 becomes clearly better; the learner must switch to it
    for _ in range(60):
        acts = vb.next_actions()
        vb.set_rewards(np.zeros(1, int), acts,
                       np.where(acts == 1, 1.0, 0.0).astype(np.float32))
    picks = [int(vb.next_actions()[0]) for _ in range(10)]
    assert 1 in picks


def test_exploration_counter_reference_semantics():
    """ExplorationCounter.java:52-98: windowed forced exploration with
    wrap-around, inactive once the budget is spent."""
    from avenir_tpu.reinforce.learners import ExplorationCounter
    ec = ExplorationCounter("g", count=5, exploration_count=12, batch_size=4)
    ec.select_next_round(1)   # remaining 12 -> beg 12%5=2, end 5 -> wraps
    assert ec.is_in_exploration()
    assert ec.should_explore(2) and ec.should_explore(4)
    assert ec.should_explore(0)  # wrapped segment 0..0
    assert not ec.should_explore(1)
    ec.select_next_round(3)   # remaining 12-8=4 -> beg 4, end 7 -> wraps
    assert ec.should_explore(4) and ec.should_explore(2)
    ec.select_next_round(4)   # remaining 0 -> exploration over
    assert not ec.is_in_exploration()
    assert not ec.should_explore(0)


def test_exploration_counter_non_wrapping_window():
    """A batch that fits inside the item set selects one contiguous
    window; items outside it are not forced."""
    from avenir_tpu.reinforce.learners import ExplorationCounter
    ec = ExplorationCounter("g", count=10, exploration_count=6, batch_size=3)
    ec.select_next_round(1)   # remaining 6 -> beg 6, end 8: no wrap
    assert ec.selections == [(6, 8)]
    assert all(ec.should_explore(i) for i in (6, 7, 8))
    assert not any(ec.should_explore(i) for i in (0, 5, 9))
    ec.select_next_round(2)   # remaining 3 -> beg 3, end 5
    assert ec.selections == [(3, 5)]
    ec.select_next_round(3)   # remaining 0: budget spent exactly
    assert not ec.is_in_exploration()


def test_exploration_counter_batch_spanning_whole_set():
    """batch_size == count sweeps every item each round until the
    budget runs out."""
    from avenir_tpu.reinforce.learners import ExplorationCounter
    ec = ExplorationCounter("g", count=4, exploration_count=8, batch_size=4)
    ec.select_next_round(1)   # remaining 8 -> beg 0, end 3
    assert all(ec.should_explore(i) for i in range(4))
    ec.select_next_round(2)   # remaining 4 -> beg 0, end 3
    assert all(ec.should_explore(i) for i in range(4))
    ec.select_next_round(3)
    assert not ec.is_in_exploration()


def test_min_trial_forces_round_robin_first():
    """Every arm must reach min.trial pulls before the policy scores
    (selectActionBasedOnMinTrial)."""
    learner = create_learner("ucb1", ACTIONS, {"min.trial": 2})
    picks = []
    for _ in range(6):
        a = learner.next_action()
        picks.append(a)
        learner.set_reward(a, 0.0 if a != "a" else 1.0)
    assert picks == ["a", "a", "b", "b", "c", "c"]
    # budget spent: scoring takes over (all-zero rewards except "a")
    assert learner.next_action() == "a"


def test_ucb1_decide_is_the_shared_scoring_body():
    """next_action == argmax of ucb1_upper_bound over the same stats —
    the formula the device twin jit-compiles."""
    from avenir_tpu.reinforce.learners import ucb1_upper_bound
    learner = create_learner("ucb1", ACTIONS)
    counts = {"a": 8, "b": 3, "c": 5}
    means = {"a": 0.40, "b": 0.55, "c": 0.50}
    for act in ACTIONS:
        learner.set_reward_stats(act, counts[act], means[act], 0.05)
    N = learner.total_trial_count + 1          # the pull being decided
    expect = max(ACTIONS,
                 key=lambda act: ucb1_upper_bound(means[act], counts[act],
                                                  max(N, 1)))
    assert learner.next_action() == expect


def test_ucb1_untried_arm_scores_infinite():
    learner = create_learner("ucb1", ACTIONS)
    learner.set_reward_stats("a", 50, 0.99, 0.0)
    learner.set_reward_stats("c", 50, 0.98, 0.0)
    assert learner.next_action() == "b"        # count 0 outranks any mean


def test_softmax_decide_is_the_shared_weight_body():
    """Replay the seeded RNG against softmax_weight: the learner's draw
    must land exactly where the shared body's distribution says."""
    import random as _random
    from avenir_tpu.reinforce.learners import softmax_weight
    learner = create_learner("softMax", ACTIONS,
                             {"random.seed": 7, "temp.constant": 0.1})
    means = {"a": 0.2, "b": 0.6, "c": 0.4}
    for act in ACTIONS:
        learner.set_reward_stats(act, 5, means[act], 0.0)
    twin = _random.Random(7)
    for _ in range(20):
        probs = {act: softmax_weight(means[act], 0.1) for act in ACTIONS}
        total = sum(probs.values())
        r = twin.random() * total
        acc, expect = 0.0, ACTIONS[-1]
        for act in ACTIONS:
            acc += probs[act]
            if r <= acc:
                expect = act
                break
        assert learner.next_action() == expect


def test_sampson_decide_is_the_shared_sample_body():
    """Same replay for Thompson sampling: rng.gauss draws fed through
    sampson_sample pick the identical arm."""
    import math as _math
    import random as _random
    from avenir_tpu.reinforce.learners import sampson_sample
    learner = create_learner("sampsonSampler", ACTIONS, {"random.seed": 11})
    for act, mean in (("a", 0.3), ("b", 0.5), ("c", 0.4)):
        learner.set_reward_stats(act, 9, mean, 0.2)
    twin = _random.Random(11)
    for _ in range(20):
        best, best_v = None, -float("inf")
        for act in ACTIONS:
            s = learner.stats[act]
            v = sampson_sample(s.mean, s.std_dev or 1.0, s.count,
                               twin.gauss(0.0, 1.0))
            if v > best_v:
                best, best_v = act, v
        assert learner.next_action() == best


def test_set_reward_accounting_matches_simple_stat():
    """count / total / total_sq accumulate exactly; mean and std_dev
    derive the sample statistics."""
    learner = create_learner("ucb1", ACTIONS)
    rewards = [0.5, 1.0, 0.25, 0.75]
    for r in rewards:
        learner.set_reward("b", r)
    s = learner.stats["b"]
    assert s.count == len(rewards)
    assert s.total == sum(rewards)
    assert s.total_sq == sum(r * r for r in rewards)
    assert abs(s.mean - np.mean(rewards)) < 1e-12
    assert abs(s.std_dev - np.std(rewards, ddof=1)) < 1e-12
    assert learner.rewarded


def test_set_reward_stats_reconstructs_mean_and_std():
    learner = create_learner("ucb1", ACTIONS)
    learner.set_reward_stats("a", 10, 0.6, 0.15)
    s = learner.stats["a"]
    assert s.count == 10
    assert abs(s.mean - 0.6) < 1e-12
    assert abs(s.std_dev - 0.15) < 1e-9


def test_next_actions_honors_decision_batch_size():
    learner = create_learner("softMax", ACTIONS,
                             {"random.seed": 1, "decision.batch.size": 5})
    batch = learner.next_actions()
    assert len(batch) == 5
    assert set(batch) <= set(ACTIONS)
    assert learner.total_trial_count == 5
