"""Closed-loop retrain controller (ISSUE 14): drift alert -> retrain ->
validate -> publish -> swap -> probation, with automatic rollback and a
crash journal.

The acceptance contracts under test:

  * chaos drills — the controller killed (injected RuntimeError) at each
    of its five named fault points (``retrain_build``,
    ``candidate_validate``, ``registry_publish``, ``fleet_swap``,
    ``rollback``) while a LIVE 2-worker ServingFleet drains traffic: the
    fleet keeps answering through the crash, never sees a torn or
    duplicated version, and a NEW controller resumed on the same state
    dir converges the fleet onto exactly one model version — with
    exactly one new registry version (no double-publish, pinned by sha
    dedup);
  * a worse candidate is REFUSED at validation (champion untouched);
  * a candidate that underperforms live probation AUTO-ROLLS-BACK to the
    prior registry version;
  * the controller never sits on the data path: its only side effects
    are registry writes and a reload nudge.
"""

import json
import os
import shutil
import time

import numpy as np
import pytest

from avenir_tpu.control import (CycleJournal, PROBATION, PUBLISHED,
                                REFUSED, RETRAIN_BUILD, ROLLED_BACK,
                                RetrainController, RetrainPolicy,
                                alerts_from_jsonl)
from avenir_tpu.core import faults
from avenir_tpu.core.table import load_csv
from avenir_tpu.models.forest import ForestParams, build_forest
from avenir_tpu.monitor.baseline import compute_baseline, publish_baseline
from avenir_tpu.monitor.policy import (AlertRecord, DriftPolicy,
                                       retrain_action)
from avenir_tpu.serving import BatchPolicy, ModelRegistry, ServingFleet
from tests.test_tree import SCHEMA

pytestmark = pytest.mark.controller

MODEL = "churn"


# --------------------------------------------------------------------------
# data: a clean regime the champion learns, and a drifted regime (shifted
# feature distributions AND a different label rule) the candidate learns
# --------------------------------------------------------------------------

def gen_rows(n, seed, drifted=False, shuffle_labels=False):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        ct = rng.choice(["business", "residence"])
        if drifted:
            issue = rng.choice(["internet", "cable", "billing", "other"],
                               p=[0.05, 0.05, 0.55, 0.35])
            ht = int(rng.integers(0, 240))
            hung = issue in ("billing", "other")
        else:
            issue = rng.choice(["internet", "cable", "billing", "other"])
            ht = int(rng.integers(0, 600))
            hung = (issue in ("internet", "cable") and ht > 240) or \
                   (ct == "business" and ht > 480)
        if rng.random() < 0.03:
            hung = not hung
        rows.append([f"r{i}", ct, issue, str(ht), "T" if hung else "F"])
    if shuffle_labels:
        labs = [r[4] for r in rows]
        rng.shuffle(labs)
        for r, lab in zip(rows, labs):
            r[4] = lab
    return rows


def write_csv(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(",".join(r) + "\n")


def forest_params(trees=3, depth=2, seed=3):
    p = ForestParams(num_trees=trees, seed=seed)
    p.tree.max_depth = depth
    return p


def build_champion(tmp_path, mesh_ctx, params=None):
    """Registry holding v1 (clean-regime forest + baseline sidecar) plus
    the clean/fresh CSV pair on disk."""
    params = params or forest_params()
    clean = str(tmp_path / "clean.csv")
    fresh = str(tmp_path / "fresh.csv")
    write_csv(clean, gen_rows(600, seed=1))
    write_csv(fresh, gen_rows(600, seed=2, drifted=True))
    table = load_csv(clean, SCHEMA, ",")
    models = build_forest(table, params, mesh_ctx)
    reg = ModelRegistry(str(tmp_path / "registry"))
    v = reg.publish(MODEL, models, schema=SCHEMA)
    publish_baseline(reg, MODEL, v, compute_baseline(table))
    return reg, params, clean, fresh


def make_controller(reg, params, tmp_path, train_source, fleet=None,
                    **policy_kw):
    kw = dict(chunk_rows=128, checkpoint_blocks=1, swap_ack_timeout_s=20.0)
    kw.update(policy_kw)
    return RetrainController(
        reg, MODEL, SCHEMA, state_dir=str(tmp_path / "state"),
        train_source=train_source, forest_params=params, fleet=fleet,
        policy=RetrainPolicy(**kw))


def drift_alert(n_rows=600):
    return AlertRecord(window_index=3, window_kind="window",
                       scope="holdTime", stat="psi", value=0.7,
                       threshold=0.25, level="alert", streak=2,
                       n_rows=n_rows)


# --------------------------------------------------------------------------
# journal
# --------------------------------------------------------------------------

def test_journal_atomic_roundtrip_and_torn_tolerance(tmp_path):
    state = str(tmp_path / "state")
    jr = CycleJournal(state)
    assert jr.stage == "idle" and not jr.pending
    jr.open_cycle({"scope": "x"}, "incremental", champion_version=1)
    jr.advance("candidate_validate", candidate_sha="abc")
    # a fresh instance reads the exact persisted state
    jr2 = CycleJournal(state)
    assert jr2.stage == "candidate_validate" and jr2.pending
    assert jr2["candidate_sha"] == "abc" and jr2.cycle == 1
    # an abandoned pre-rename tmp never shadows the real file
    with open(jr2.path + ".tmp.999", "w") as fh:
        fh.write("{ torn")
    assert CycleJournal(state).stage == "candidate_validate"
    # a damaged final journal degrades to idle with a warning, it does
    # not wedge the controller forever
    with open(jr2.path, "w") as fh:
        fh.write("{ not json")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        jr3 = CycleJournal(state)
    assert jr3.stage == "idle"


def test_journal_refuses_overlapping_cycles(tmp_path):
    jr = CycleJournal(str(tmp_path / "state"))
    jr.open_cycle(None, "incremental", 1)
    with pytest.raises(RuntimeError, match="still at stage"):
        jr.open_cycle(None, "incremental", 1)
    jr.close_cycle(PUBLISHED)
    assert jr.open_cycle(None, "full", 2) == 2
    assert [h["cycle"] for h in jr.history] == [1]


# --------------------------------------------------------------------------
# the happy cycle
# --------------------------------------------------------------------------

def test_cycle_retrains_validates_publishes_swaps(tmp_path, mesh_ctx):
    """Alert -> incremental retrain on the fresh window -> candidate beats
    the champion on the drifted holdout -> published -> pinned -> a
    linked PredictionService hot-swaps to it.  The published candidate is
    bit-identical to a direct build over the same window (streaming
    determinism carries through the controller)."""
    from avenir_tpu.serving import PredictionService
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    svc = PredictionService(registry=reg, model_name=MODEL, warm=False)
    ctl = make_controller(reg, params, tmp_path, fresh, fleet=svc)
    assert ctl.submit_alert(drift_alert())
    summary = ctl.run_pending()
    assert summary["outcome"] == PUBLISHED
    assert summary["candidate_version"] == 2
    # candidate really is better on the drifted holdout
    assert summary["candidate_accuracy"] > summary["champion_accuracy"]
    # registry: exactly one new version, pinned, sha-stamped, baseline on
    assert reg.versions(MODEL) == [1, 2]
    assert reg.pinned_version(MODEL) == 2
    assert reg.serving_version(MODEL) == 2
    loaded = reg.load(MODEL, 2)
    assert loaded.params["candidate_sha"]
    assert loaded.params["retrain_mode"] == "incremental"
    from avenir_tpu.monitor.baseline import load_baseline
    assert load_baseline(reg, MODEL, 2).n_rows == 600
    # the linked service swapped (and the ack saw it)
    assert svc.version == 2
    # bit-identity vs a direct monolithic build over the same window
    ref = build_forest(load_csv(fresh, SCHEMA, ","), params, mesh_ctx)
    assert [m.to_json() for m in loaded.model] == \
        [m.to_json() for m in ref]
    c = ctl.counters.as_dict()["Controller"]
    assert c["Cycles"] == 1 and c["Published"] == 1 and c["Swaps"] == 1
    assert ctl.journal.stage == "complete" and not ctl.journal.pending
    # the cycle working set was swept; the journal survives
    assert os.listdir(ctl.journal.state_dir) == ["controller.json"]


def test_worse_candidate_refused_champion_untouched(tmp_path, mesh_ctx):
    """A candidate trained on label noise scores below the champion on
    the holdout: REFUSED — nothing published, nothing pinned, serving
    still the champion."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    garbage = str(tmp_path / "garbage.csv")
    write_csv(garbage, gen_rows(600, seed=9, shuffle_labels=True))
    ctl = RetrainController(
        reg, MODEL, SCHEMA, state_dir=str(tmp_path / "state"),
        train_source=garbage, holdout_source=clean,
        forest_params=params,
        policy=RetrainPolicy(chunk_rows=128))
    ctl.submit_alert(drift_alert())
    with pytest.warns(RuntimeWarning, match="candidate refused"):
        summary = ctl.run_pending()
    assert summary["outcome"] == REFUSED
    assert summary["candidate_accuracy"] < summary["champion_accuracy"]
    assert reg.versions(MODEL) == [1]
    assert reg.pinned_version(MODEL) is None
    assert reg.serving_version(MODEL) == 1
    assert ctl.counters.get("Controller", "Refused") == 1


def test_scheduled_full_rebuild_mode(tmp_path, mesh_ctx):
    """full_rebuild_every=1 makes every cycle a FULL rebuild over the
    full_source instead of the fresh window."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = RetrainController(
        reg, MODEL, SCHEMA, state_dir=str(tmp_path / "state"),
        train_source=fresh, full_source=clean, holdout_source=clean,
        forest_params=params,
        policy=RetrainPolicy(chunk_rows=128, full_rebuild_every=1))
    ctl.submit_alert(drift_alert())
    summary = ctl.run_pending()
    assert summary["outcome"] == PUBLISHED
    loaded = reg.load(MODEL, 2)
    assert loaded.params["retrain_mode"] == "full"
    # trained on the FULL (clean) source: identical to the champion build
    ref = build_forest(load_csv(clean, SCHEMA, ","), params, mesh_ctx)
    assert [m.to_json() for m in loaded.model] == \
        [m.to_json() for m in ref]


def test_alert_intake_coalesce_and_warn_ignored(tmp_path, mesh_ctx):
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh)
    warn = drift_alert()
    warn.level = "warn"
    assert not ctl.submit_alert(warn)
    assert ctl.counters.get("Controller", "AlertsIgnored") == 1
    assert ctl.run_pending() is None       # nothing pending
    assert ctl.submit_alert(drift_alert())
    assert not ctl.submit_alert(drift_alert())   # coalesced
    assert ctl.counters.get("Controller", "AlertsCoalesced") == 1


def test_policy_retrain_action_wires_alerts_to_controller(tmp_path,
                                                          mesh_ctx):
    """The live wiring: a DriftPolicy scoring drifted windows against the
    champion baseline fires through retrain_action into the controller's
    intake — and the handoff is a queue append (no retrain ran inline)."""
    from avenir_tpu.core.metrics import Counters
    from avenir_tpu.monitor.accumulator import StreamDriftMonitor
    from avenir_tpu.monitor.baseline import load_baseline
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh)
    counters = Counters()
    policy = DriftPolicy(consecutive=1, counters=counters,
                         on_alert=retrain_action(ctl, counters))
    monitor = StreamDriftMonitor(load_baseline(reg, MODEL, 1),
                                 policy=policy, window_rows=300)
    monitor.observe_table(load_csv(fresh, SCHEMA, ","))
    monitor.close_window()
    assert counters.get("DriftMonitor", "RetrainRequests") >= 1
    assert ctl.counters.get("Controller", "Alerts") == 1
    assert ctl.journal.stage == "idle"      # nothing ran inline
    summary = ctl.run_pending()
    assert summary["outcome"] == PUBLISHED
    assert reg.serving_version(MODEL) == 2
    # the triggering alert is journaled as the cycle's trigger
    assert ctl.journal["trigger"]["level"] == "alert"


def test_alerts_jsonl_stream_intake(tmp_path, mesh_ctx):
    """The batch intake: a driftMonitor-style alerts.jsonl (including a
    malformed line, which is skipped with a warning) triggers a cycle."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    apath = str(tmp_path / "alerts.jsonl")
    with open(apath, "w") as fh:
        warn = drift_alert()
        warn.level = "warn"
        fh.write(warn.to_json() + "\n")
        fh.write("NOT JSON\n")
        fh.write(drift_alert().to_json() + "\n")
    with pytest.warns(RuntimeWarning, match="unparseable"):
        recs = alerts_from_jsonl(apath)
    assert len(recs) == 2
    ctl = make_controller(reg, params, tmp_path, fresh)
    assert ctl.consume(recs) == 1          # warn ignored, alert queued
    assert ctl.run_pending()["outcome"] == PUBLISHED
    # missing file: empty, no crash
    assert alerts_from_jsonl(str(tmp_path / "nope.jsonl")) == []


# --------------------------------------------------------------------------
# probation: live underperformance auto-rolls-back
# --------------------------------------------------------------------------

def test_probation_rollback_on_live_underperformance(tmp_path, mesh_ctx):
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh,
                          probation_outcomes=40, probation_margin=5)
    ctl.submit_alert(drift_alert())
    waiting = ctl.run_pending()
    assert waiting["stage"] == PROBATION
    assert reg.serving_version(MODEL) == 2       # candidate live
    floor = ctl.journal["probation"]["floor"]
    # every live outcome wrong -> window accuracy 0 < floor -> rollback
    verdict = None
    with pytest.warns(RuntimeWarning, match="rolled back"):
        for _ in range(40):
            verdict = ctl.record_outcome("T", "F")
            if verdict is not None:
                break
    assert verdict["outcome"] == ROLLED_BACK
    assert reg.pinned_version(MODEL) == 1
    assert reg.serving_version(MODEL) == 1       # champion restored
    assert reg.versions(MODEL) == [1, 2]         # candidate retained
    assert ctl.counters.get("Controller", "Rollbacks") == 1
    assert ctl.journal["probation"]["last_accuracy"] < floor
    # a later refresh-driven service loads the CHAMPION despite v2 newer
    from avenir_tpu.serving import PredictionService
    svc = PredictionService(registry=reg, model_name=MODEL, warm=False)
    assert svc.version == 1


def test_probation_survival_completes_published(tmp_path, mesh_ctx):
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh,
                          probation_outcomes=20, probation_windows=2)
    ctl.submit_alert(drift_alert())
    assert ctl.run_pending()["stage"] == PROBATION
    verdict = None
    for _ in range(40):                      # 2 windows of 20, all right
        verdict = ctl.record_outcome("T", "T")
        if verdict is not None:
            break
    assert verdict["outcome"] == PUBLISHED
    assert reg.serving_version(MODEL) == 2
    assert ctl.counters.get("Controller", "ProbationWindows") == 2
    # outside probation the feed is a no-op
    assert ctl.record_outcome("T", "F") is None


# --------------------------------------------------------------------------
# chaos drills: kill the controller at every fault point under live load
# --------------------------------------------------------------------------

@pytest.fixture()
def resp_server():
    from avenir_tpu.io.respq import RespServer
    server = RespServer().start()
    yield server
    server.stop()


def start_fleet(reg, port, n_workers=2):
    fleet = ServingFleet(reg, MODEL, buckets=(8, 64),
                         policy=BatchPolicy(max_batch=16, max_wait_ms=1.0),
                         n_workers=n_workers,
                         config={"redis.server.port": port})
    return fleet.start()


def serve_round(client, rows, base_id, n=20, timeout_s=30.0):
    """Push n requests, pop n replies; returns {rid: label} — the 'fleet
    is still answering' probe used before/during/after each drill."""
    client.lpush_many("requestQueue",
                      [",".join(["predict", f"{base_id}-{i}"]
                                + rows[i % len(rows)])
                       for i in range(n)])
    got = {}
    deadline = time.monotonic() + timeout_s
    while len(got) < n and time.monotonic() < deadline:
        for v in client.rpop_many("predictionQueue", 64):
            rid, label = v.split(",", 1)
            assert rid not in got, f"duplicate reply for {rid}"
            got[rid] = label
        time.sleep(0.002)
    assert len(got) == n, f"fleet stopped answering ({len(got)}/{n})"
    return got


DRILLS = [
    # (spec, what the kill interrupts)
    ("retrain_build@3=raise:RuntimeError", "mid-build, checkpoint saved"),
    ("candidate_validate@0=raise:RuntimeError", "validation entry"),
    ("registry_publish@1=raise:RuntimeError", "mid payload write"),
    ("registry_publish@2=raise:RuntimeError",
     "post-commit pre-journal (the double-publish window)"),
    ("fleet_swap@0=raise:RuntimeError", "before pin+reload"),
]


@pytest.mark.faultinject
@pytest.mark.parametrize("spec,_what", DRILLS,
                         ids=[s.split("=")[0] for s, _ in DRILLS])
def test_chaos_drill_controller_killed_fleet_survives(
        spec, _what, tmp_path, mesh_ctx, resp_server, fault_injector):
    """Kill the controller at each named fault point while a live
    2-worker fleet drains traffic: the fleet answers through the crash
    on exactly one model version, and a NEW controller resumed on the
    same state dir finishes the cycle with exactly ONE new registry
    version (no double-publish) and converges the fleet onto it."""
    from avenir_tpu.io.respq import RespClient
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    rows = gen_rows(30, seed=77, drifted=True)
    fleet = start_fleet(reg, resp_server.port)
    feeder = RespClient(port=resp_server.port)
    try:
        serve_round(feeder, rows, "pre", 20)
        assert fleet.converged_version() == 1
        ctl = make_controller(reg, params, tmp_path, fresh, fleet=fleet)
        ctl.submit_alert(drift_alert())
        fault_injector(spec)
        with pytest.raises(RuntimeError, match="injected fault"):
            ctl.run_pending()
        # the crash journaled a mid-flight stage; serving never noticed:
        # the fleet still answers, on exactly one (un-torn) version
        assert ctl.journal.pending
        serve_round(feeder, rows, "mid", 20)
        assert fleet.converged_version() == 1
        faults.uninstall()
        # a NEW controller (no shared memory with the dead one) resumes
        ctl2 = make_controller(reg, params, tmp_path, fresh, fleet=fleet)
        summary = ctl2.run_pending()
        assert summary["outcome"] == PUBLISHED
        assert ctl2.counters.get("Controller", "Resumes") == 1
        # exactly one new version: the sha dedup closed the
        # double-publish window
        assert reg.versions(MODEL) == [1, 2]
        assert reg.serving_version(MODEL) == 2
        # the fleet converged onto exactly the published version and
        # still answers
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 2
        serve_round(feeder, rows, "post", 20)
        st = fleet.stats()
        assert st["errors"] == 0
        assert set(st["model_versions"].values()) == {2}
    finally:
        fleet.stop()
        feeder.close()


@pytest.mark.faultinject
def test_chaos_drill_killed_mid_rollback_resumes_rollback(
        tmp_path, mesh_ctx, resp_server, fault_injector):
    """The fifth fault point: probation fails, the controller dies INSIDE
    rollback (after journaling the rollback intent, before the pin) —
    the fleet keeps serving the candidate meanwhile, and the resumed
    controller finishes the rollback: pin back to the champion, fleet
    converges back onto v1."""
    from avenir_tpu.io.respq import RespClient
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    rows = gen_rows(30, seed=78, drifted=True)
    fleet = start_fleet(reg, resp_server.port)
    feeder = RespClient(port=resp_server.port)
    try:
        ctl = make_controller(reg, params, tmp_path, fresh, fleet=fleet,
                              probation_outcomes=10)
        ctl.submit_alert(drift_alert())
        assert ctl.run_pending()["stage"] == PROBATION
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 2
        fault_injector("rollback@0=raise:RuntimeError")
        with pytest.raises(RuntimeError, match="injected fault"):
            for _ in range(10):
                ctl.record_outcome("T", "F")
        faults.uninstall()
        # mid-rollback crash: candidate still pinned+serving, fleet fine
        assert ctl.journal.stage == "rollback"
        assert reg.serving_version(MODEL) == 2
        serve_round(feeder, rows, "mid", 20)
        ctl2 = make_controller(reg, params, tmp_path, fresh, fleet=fleet)
        with pytest.warns(RuntimeWarning, match="rolled back"):
            summary = ctl2.run_pending()
        assert summary["outcome"] == ROLLED_BACK
        assert reg.serving_version(MODEL) == 1
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 1
        serve_round(feeder, rows, "post", 20)
        assert fleet.stats()["errors"] == 0
    finally:
        fleet.stop()
        feeder.close()


@pytest.mark.faultinject
def test_resumed_build_is_bit_identical(tmp_path, mesh_ctx,
                                        fault_injector):
    """A build killed between checkpoints resumes from the checkpoint and
    publishes the bit-identical model of an uninterrupted run (the PR 2/7
    resume contract carried through the controller)."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh)
    ctl.submit_alert(drift_alert())
    fault_injector("retrain_build@3=raise:RuntimeError")
    with pytest.raises(RuntimeError):
        ctl.run_pending()
    faults.uninstall()
    ctl2 = make_controller(reg, params, tmp_path, fresh)
    assert ctl2.run_pending()["outcome"] == PUBLISHED
    # the resume really started from the checkpoint, not row 0
    assert ctl2.counters.get("Controller", "BuildResumes") == 1
    ref = build_forest(load_csv(fresh, SCHEMA, ","), params, mesh_ctx)
    assert [m.to_json() for m in reg.load(MODEL, 2).model] == \
        [m.to_json() for m in ref]
    # the published baseline covers the WHOLE window, not just the
    # post-crash tail: the resumed build re-profiles the head the
    # checkpoint already consumed (and the fused absorb stage carries
    # those pre-seeded counts through instead of discarding them)
    from avenir_tpu.monitor.baseline import load_baseline
    bl = load_baseline(reg, MODEL, 2)
    assert bl.n_rows == 600
    ref_bl = compute_baseline(load_csv(fresh, SCHEMA, ","))
    assert np.array_equal(bl.counts, ref_bl.counts)


def test_resume_without_candidate_abandons_safely(tmp_path, mesh_ctx):
    """A journal stuck at candidate_validate whose candidate payload is
    gone (or torn) cannot finish the cycle — resume abandons it with the
    champion untouched instead of wedging or publishing garbage."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    state = str(tmp_path / "state")
    jr = CycleJournal(state)
    jr.open_cycle(None, "incremental", champion_version=1)
    jr.advance("candidate_validate", candidate_sha="deadbeef")
    ctl = RetrainController(reg, MODEL, SCHEMA, state_dir=state,
                            train_source=fresh, forest_params=params)
    with pytest.warns(RuntimeWarning, match="abandoned"):
        summary = ctl.run_pending()
    assert summary["outcome"] == "abandoned"
    assert reg.versions(MODEL) == [1]
    assert reg.serving_version(MODEL) == 1
    assert ctl.counters.get("Controller", "Abandoned") == 1


# --------------------------------------------------------------------------
# registry pin / retire / tool
# --------------------------------------------------------------------------

def small_registry(tmp_path, mesh_ctx, versions=4):
    params = forest_params()
    table = load_csv_rows(tmp_path)
    reg = ModelRegistry(str(tmp_path / "reg"))
    models = build_forest(table, params, mesh_ctx)
    for _ in range(versions):
        reg.publish(MODEL, models, schema=SCHEMA)
    return reg


def load_csv_rows(tmp_path):
    p = str(tmp_path / "rows.csv")
    write_csv(p, gen_rows(200, seed=4))
    return load_csv(p, SCHEMA, ",")


def test_registry_pin_and_serving_resolution(tmp_path, mesh_ctx):
    reg = small_registry(tmp_path, mesh_ctx, versions=3)
    assert reg.serving_version(MODEL) == 3
    reg.pin_version(MODEL, 2)
    assert reg.pinned_version(MODEL) == 2
    assert reg.serving_version(MODEL) == 2
    assert reg.latest_version(MODEL) == 3    # pin does not lie to latest
    # pinning a non-version refuses
    with pytest.raises(ValueError, match="refusing to pin"):
        reg.pin_version(MODEL, 99)
    # a pin whose target tears falls back to newest intact with a warning
    shutil.rmtree(reg.version_dir(MODEL, 2))
    with pytest.warns(RuntimeWarning, match="pinned version 2"):
        assert reg.serving_version(MODEL) == 3
    reg.clear_pin(MODEL)
    reg.clear_pin(MODEL)                     # idempotent
    assert reg.serving_version(MODEL) == 3


def test_registry_retire_keeps_pin_and_newest(tmp_path, mesh_ctx):
    reg = small_registry(tmp_path, mesh_ctx, versions=4)
    reg.pin_version(MODEL, 2)
    # an abandoned tmp publish from a DEAD process is swept; a LIVE
    # publisher's in-flight tmp (this process's pid) must survive a
    # cadenced GC racing it
    dead = 999999
    while os.path.exists(f"/proc/{dead}"):
        dead -= 1
    old_dir = os.path.join(reg.store.path(MODEL),
                           f"v_000099.tmp.{dead}")
    os.makedirs(old_dir)
    # a crashed pin_version leaves a tmp FILE — swept by the same rule
    old_pin = os.path.join(reg.store.path(MODEL),
                           f"serving.json.tmp.{dead}")
    with open(old_pin, "w") as fh:
        fh.write("{}")
    # backdate both past the NFS grace window (a YOUNG dead-pid tmp may
    # be a remote host's live publisher and must survive the sweep)
    stale = time.time() - 7200
    os.utime(old_dir, (stale, stale))
    os.utime(old_pin, (stale, stale))
    fresh_dead = os.path.join(reg.store.path(MODEL),
                              f"v_000097.tmp.{dead}")
    os.makedirs(fresh_dead)
    live = os.path.join(reg.store.path(MODEL),
                        f"v_000098.tmp.{os.getpid()}")
    os.makedirs(live)
    # dry_run reports the same keep rule without touching anything
    assert reg.retire(MODEL, keep_last=1, dry_run=True) == [1, 3]
    assert reg.versions(MODEL) == [1, 2, 3, 4]
    retired = reg.retire(MODEL, keep_last=1)
    assert retired == [1, 3]
    assert reg.versions(MODEL) == [2, 4]     # pinned + newest survive
    assert reg.serving_version(MODEL) == 2
    assert not os.path.exists(old_dir)       # stale dead-pid dir swept
    assert not os.path.exists(old_pin)       # stale pin tmp swept
    assert os.path.isdir(fresh_dead)         # young: maybe remote-live
    assert os.path.isdir(live)               # live publisher untouched
    reg.clear_pin(MODEL)
    assert reg.retire(MODEL, keep_last=1) == [2]
    assert reg.versions(MODEL) == [4]
    with pytest.raises(ValueError):
        reg.retire(MODEL, keep_last=0)


def test_registrytool_list_verify_gc(tmp_path, mesh_ctx, capsys):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "registrytool", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "registrytool.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    # a missing/empty registry (typo'd path) must not read as healthy
    assert tool.main(["verify", str(tmp_path / "nowhere")]) == 1
    capsys.readouterr()
    reg = small_registry(tmp_path, mesh_ctx, versions=3)
    reg.pin_version(MODEL, 2)
    base = reg.base_dir
    assert tool.main(["list", base]) == 0
    out = capsys.readouterr().out
    assert "pinned=2 serving=2" in out and " 3 " in out
    assert tool.main(["verify", base]) == 0
    assert "verified" in capsys.readouterr().out
    # dry-run GC changes nothing
    assert tool.main(["gc", base, "--name", MODEL, "--keep", "1",
                      "--dry-run"]) == 0
    assert reg.versions(MODEL) == [1, 2, 3]
    assert tool.main(["gc", base, "--name", MODEL, "--keep", "1"]) == 0
    assert reg.versions(MODEL) == [2, 3]
    # tear a version -> verify exits 1 and names it
    meta = os.path.join(reg.version_dir(MODEL, 3), "meta.json")
    with open(meta, "w") as fh:
        fh.write("{ torn")
    assert tool.main(["verify", base]) == 1
    assert "TORN" in capsys.readouterr().out


@pytest.mark.multimodel
def test_registrytool_gc_keep_last_applies_per_name(tmp_path, mesh_ctx,
                                                    capsys):
    """Multi-model registries (ISSUE 18): ``gc`` without --name sweeps
    every model, each keeping its OWN newest --keep — and each name's
    pin protects ITS versions only.  ``list`` flags pin and serving
    per name."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "registrytool", os.path.join(os.path.dirname(__file__), "..",
                                     "tools", "registrytool.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    reg = small_registry(tmp_path, mesh_ctx, versions=3)   # churn v1..3
    params = forest_params()
    table = load_csv_rows(tmp_path)
    models = build_forest(table, params, mesh_ctx)
    for _ in range(4):
        reg.publish("fraud", models, schema=SCHEMA)        # fraud v1..4
    reg.pin_version(MODEL, 1)
    reg.pin_version("fraud", 2)
    base = reg.base_dir
    assert tool.main(["list", base]) == 0
    out = capsys.readouterr().out
    # both names' pin/serving resolve independently in one listing
    assert "churn: pinned=1 serving=1" in out
    assert "fraud: pinned=2 serving=2" in out
    assert "*P" in out                       # pin == serving flags both
    # one sweep, keep_last PER NAME: each name keeps its own newest 1
    # plus its own pinned version — churn's pin does not shield fraud
    assert tool.main(["gc", base, "--keep", "1"]) == 0
    assert reg.versions(MODEL) == [1, 3]     # own pin + own newest
    assert reg.versions("fraud") == [2, 4]   # own pin + own newest
    out = capsys.readouterr().out
    assert "churn:" in out and "fraud:" in out


def test_controller_retires_old_versions_in_loop(tmp_path, mesh_ctx):
    """retire_keep_last in the controller policy GCs after each cycle so
    the publish cadence cannot grow the registry unboundedly."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh,
                          retire_keep_last=2)
    for i in range(2):
        ctl.submit_alert(drift_alert())
        assert ctl.run_pending()["outcome"] == PUBLISHED
    # three versions existed (1,2,3); GC kept the newest two (3 pinned)
    assert reg.versions(MODEL) == [2, 3]
    assert reg.serving_version(MODEL) == 3
    assert ctl.counters.get("Controller", "VersionsRetired") >= 1


# --------------------------------------------------------------------------
# CLI job
# --------------------------------------------------------------------------

def test_retrain_controller_cli_job(tmp_path, mesh_ctx):
    """End-to-end through the CLI: alerts.jsonl trigger, incremental
    retrain, publish+pin, decisions artifact; then a second run whose
    probation replay (against labels the candidate gets WRONG) rolls the
    fleet back — all through config keys only."""
    from avenir_tpu.cli import run as cli_run
    reg, params, clean, fresh = build_champion(
        tmp_path, mesh_ctx, params=forest_params(seed=3))
    schema_path = str(tmp_path / "schema.json")
    import json as _json
    with open(schema_path, "w") as fh:
        _json.dump(SCHEMA.to_dict(), fh)
    apath = str(tmp_path / "alerts.jsonl")
    with open(apath, "w") as fh:
        fh.write(drift_alert().to_json() + "\n")
    props = str(tmp_path / "retrain.properties")
    with open(props, "w") as fh:
        fh.write("\n".join([
            f"dtb.model.registry.dir={reg.base_dir}",
            f"dtb.model.name={MODEL}",
            f"dtb.feature.schema.file.path={schema_path}",
            f"dtb.retrain.state.dir={tmp_path / 'cli_state'}",
            f"dtb.retrain.alerts.path={apath}",
            "dtb.retrain.block.rows=128",
            "dtb.num.trees=3",
            "dtb.max.depth.limit=2",
            "dtb.random.seed=3",
        ]) + "\n")
    out = str(tmp_path / "out")
    rc = cli_run.main(["retrainController", f"-Dconf.path={props}",
                       fresh, out])
    assert rc == 0
    assert reg.versions(MODEL) == [1, 2]
    assert reg.serving_version(MODEL) == 2
    lines = open(os.path.join(out, "decisions.jsonl")).read().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert recs[0]["outcome"] == PUBLISHED and recs[0].get("this_run")
    part = open(os.path.join(out, "part-r-00000")).read().split(",")
    assert part[2].strip() == PUBLISHED
    # counters sibling written by cli.run
    ctrs = json.loads(open(out + ".counters.json").read())
    assert ctrs["Controller"]["Published"] == 1


def test_retrain_controller_cli_probation_rollback(tmp_path, mesh_ctx):
    """CLI probation replay: the swapped candidate scores the probation
    CSV; labels engineered so it underperforms the floor -> the job
    auto-rolls-back before exiting."""
    from avenir_tpu.cli import run as cli_run
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    schema_path = str(tmp_path / "schema.json")
    import json as _json
    with open(schema_path, "w") as fh:
        _json.dump(SCHEMA.to_dict(), fh)
    # probation stream: drifted features with INVERTED labels — the
    # candidate (trained on the drifted rule) gets nearly all wrong
    prob = str(tmp_path / "probation.csv")
    rows = gen_rows(200, seed=11, drifted=True)
    for r in rows:
        r[4] = "F" if r[4] == "T" else "T"
    write_csv(prob, rows)
    props = str(tmp_path / "retrain.properties")
    with open(props, "w") as fh:
        fh.write("\n".join([
            f"dtb.model.registry.dir={reg.base_dir}",
            f"dtb.model.name={MODEL}",
            f"dtb.feature.schema.file.path={schema_path}",
            f"dtb.retrain.state.dir={tmp_path / 'cli_state'}",
            "dtb.retrain.trigger=force",
            "dtb.retrain.probation.outcomes=50",
            f"dtb.retrain.probation.input={prob}",
            "dtb.retrain.block.rows=128",
            "dtb.num.trees=3",
            "dtb.max.depth.limit=2",
            "dtb.random.seed=3",
        ]) + "\n")
    out = str(tmp_path / "out")
    rc = cli_run.main(["retrainController", f"-Dconf.path={props}",
                       fresh, out])
    assert rc == 0
    assert reg.versions(MODEL) == [1, 2]
    assert reg.serving_version(MODEL) == 1       # rolled back
    recs = [json.loads(ln) for ln in
            open(os.path.join(out, "decisions.jsonl"))]
    assert any(r.get("outcome") == ROLLED_BACK for r in recs)


# --------------------------------------------------------------------------
# the closed-loop soak: monitor -> policy -> controller thread -> fleet
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_closed_loop_soak_drift_to_swap(tmp_path, mesh_ctx, resp_server):
    """The whole loop live: a fleet serves drifted traffic, the stream
    monitor fires a debounced alert through retrain_action, the
    controller's background thread retrains/validates/publishes/swaps,
    and the fleet converges onto the candidate — no operator in the
    loop."""
    from avenir_tpu.core.metrics import Counters
    from avenir_tpu.io.respq import RespClient
    from avenir_tpu.monitor.accumulator import StreamDriftMonitor
    from avenir_tpu.monitor.baseline import load_baseline
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    fleet = start_fleet(reg, resp_server.port)
    feeder = RespClient(port=resp_server.port)
    ctl = make_controller(reg, params, tmp_path, fresh, fleet=fleet)
    counters = Counters()
    policy = DriftPolicy(consecutive=2, counters=counters,
                         on_alert=retrain_action(ctl, counters))
    monitor = StreamDriftMonitor(load_baseline(reg, MODEL, 1),
                                 policy=policy, window_rows=200)
    ctl.start(poll_s=0.05)
    try:
        drift_rows = gen_rows(500, seed=21, drifted=True)
        # live traffic + the monitor scoring the same stream
        serve_round(feeder, drift_rows, "soak", 40)
        monitor.observe_table(load_csv(fresh, SCHEMA, ","))
        monitor.close_window()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if ctl.journal.stage == "complete" \
                    and ctl.journal["outcome"] == PUBLISHED:
                break
            time.sleep(0.05)
        assert ctl.journal["outcome"] == PUBLISHED
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 2
        serve_round(feeder, drift_rows, "post", 40)
        assert fleet.stats()["errors"] == 0
    finally:
        ctl.stop()
        fleet.stop()
        feeder.close()


def test_rollback_target_retired_abandons_not_wedges(tmp_path, mesh_ctx):
    """An external GC that retired the journaled champion mid-probation
    must not wedge the rollback stage forever: the cycle abandons with a
    loud warning, serving stays on the newest intact version."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh,
                          probation_outcomes=10)
    ctl.submit_alert(drift_alert())
    assert ctl.run_pending()["stage"] == PROBATION
    shutil.rmtree(reg.version_dir(MODEL, 1))     # the GC-killed champion
    with pytest.warns(RuntimeWarning, match="rollback target"):
        verdict = None
        for _ in range(10):
            verdict = ctl.record_outcome("T", "F")
            if verdict is not None:
                break
    assert verdict["outcome"] == "abandoned"
    assert ctl.counters.get("Controller", "RollbackTargetMissing") == 1
    assert reg.pinned_version(MODEL) is None     # un-pinned, not wedged
    assert reg.serving_version(MODEL) == 2
    # the controller is usable again: a new cycle opens cleanly (and
    # enters probation per this controller's policy)
    ctl.submit_alert(drift_alert())
    assert ctl.run_pending()["stage"] == PROBATION
    assert ctl.resolve_probation(keep=True)["outcome"] == PUBLISHED


def test_cached_head_read_honors_stop_row(tmp_path, mesh_ctx):
    """The bounded head read the resumed build uses is served from a
    warm .avtc sidecar: the cached iterator honors stop_row, and a
    bounded read never BUILDS a cache (a head must not masquerade as a
    full sidecar)."""
    from avenir_tpu.core.table import iter_csv_chunks
    from avenir_tpu.io.colcache import CachePolicy
    fresh = str(tmp_path / "rows.csv")
    write_csv(fresh, gen_rows(600, seed=5))

    def head_rows(cache):
        out = 0
        for c in iter_csv_chunks(fresh, SCHEMA, ",", chunk_rows=128,
                                 cache=cache, stop_row=256):
            out += c.n_rows
        return out

    # bounded read under policy=build: parses, does NOT build
    assert head_rows(CachePolicy(policy="build")) == 256
    assert not os.path.exists(fresh + ".avtc")
    # build the sidecar with a full pass, then a bounded cached read
    for _ in iter_csv_chunks(fresh, SCHEMA, ",", chunk_rows=128,
                             cache=CachePolicy(policy="build")):
        pass
    assert os.path.exists(fresh + ".avtc")
    pol = CachePolicy(policy="require")
    assert head_rows(pol) == 256                 # served FROM the cache


def test_probation_timeout_and_operator_resolve(tmp_path, mesh_ctx):
    """A probation whose outcome stream never materializes must not
    wedge the controller: past probation_timeout_s the next tick keeps
    the candidate with a warning; resolve_probation(keep=False) is the
    operator's immediate rollback."""
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    ctl = make_controller(reg, params, tmp_path, fresh,
                          probation_outcomes=10,
                          probation_timeout_s=0.05)
    ctl.submit_alert(drift_alert())
    assert ctl.run_pending()["stage"] == PROBATION
    assert ctl.run_pending() is None         # within the timeout: wait
    time.sleep(0.1)
    with pytest.warns(RuntimeWarning, match="no verdict"):
        summary = ctl.run_pending()
    assert summary["outcome"] == PUBLISHED
    assert ctl.counters.get("Controller", "ProbationTimeouts") == 1
    assert reg.serving_version(MODEL) == 2
    # operator rollback on a second cycle stuck in probation
    ctl2 = make_controller(reg, params, tmp_path / "s2", fresh,
                           probation_outcomes=10)
    ctl2.submit_alert(drift_alert())
    assert ctl2.run_pending()["stage"] == PROBATION
    assert ctl2.force_cycle() is None        # force must NOT reset it
    with pytest.warns(RuntimeWarning, match="rolled back"):
        verdict = ctl2.resolve_probation(keep=False)
    assert verdict["outcome"] == ROLLED_BACK
    assert reg.serving_version(MODEL) == 2   # back on cycle-2's champion
    assert ctl2.resolve_probation() is None  # no-op outside probation


def test_submit_alert_never_blocks_on_a_running_cycle(tmp_path):
    """The monitor/serving thread's handoff contract: submit_alert takes
    only the alert-slot lock, so an alert arriving while run_pending
    holds the cycle lock for a whole retrain returns immediately."""
    import threading
    reg = ModelRegistry(str(tmp_path / "reg"))
    ctl = RetrainController(reg, MODEL, SCHEMA,
                            state_dir=str(tmp_path / "state"),
                            train_source=str(tmp_path / "x.csv"))
    with ctl._lock:                  # a cycle is mid-flight
        done = threading.Event()
        threading.Thread(
            target=lambda: (ctl.submit_alert(drift_alert()), done.set()),
            daemon=True).start()
        assert done.wait(2.0), "submit_alert blocked behind the cycle lock"
    assert ctl.counters.get("Controller", "Alerts") == 1


def test_alerts_from_resp_repushes_stop_keeps_batch(resp_server):
    """The RESP tap: a drained 'stop' sentinel goes BACK on the queue
    (it was aimed at the queue's consumer, not this reader) and alerts
    popped in the same batch are still returned, never dropped."""
    from avenir_tpu.control import alerts_from_resp
    from avenir_tpu.io.respq import RespClient
    cli = RespClient(port=resp_server.port)
    try:
        cli.lpush_many("alertQueue", [drift_alert().to_json(), "stop",
                                      drift_alert(n_rows=7).to_json()])
        recs = alerts_from_resp(cli, "alertQueue")
        assert [r.n_rows for r in recs] == [600, 7]
        assert cli.rpop_many("alertQueue", 10) == ["stop"]
    finally:
        cli.close()


def test_wire_fleet_link_pushes_addressed_reloads(resp_server):
    """The out-of-process swap link speaks the PR 12 multi-host
    convergence protocol: one addressed reload per named host (bare
    'reload' when unnamed) onto the request queue."""
    from avenir_tpu.control import WireFleetLink
    from avenir_tpu.io.respq import RespClient
    cli = RespClient(port=resp_server.port)
    try:
        assert WireFleetLink(cli, hosts=["hostA", "hostB"]).refresh()
        assert set(cli.rpop_many("requestQueue", 10)) == \
            {"reload,hostA", "reload,hostB"}
        assert WireFleetLink(cli).refresh()
        assert cli.rpop_many("requestQueue", 10) == ["reload"]
    finally:
        cli.close()


# --------------------------------------------------------------------------
# canary_validate (ISSUE 18): the journaled live-traffic gate
# --------------------------------------------------------------------------

def start_multimodel_fleet(reg, port, n_workers=2):
    """The drill fleet, canary-capable: models= puts a ModelRouter in
    every worker, so the controller's canary verbs actually route."""
    fleet = ServingFleet(reg, MODEL, buckets=(8, 64),
                         policy=BatchPolicy(max_batch=16, max_wait_ms=1.0),
                         n_workers=n_workers, models=[MODEL],
                         config={"redis.server.port": port})
    return fleet.start()


@pytest.mark.multimodel
@pytest.mark.faultinject
def test_chaos_drill_canary_validate_resumes_and_publishes_once(
        tmp_path, mesh_ctx, resp_server, fault_injector):
    """Kill the controller AT the canary_validate fault point while a
    live multi-model fleet drains traffic, then resume: the new
    controller re-installs the candidate as a live canary (pre-publish —
    the registry is untouched while the split serves), delayed labels
    attributed by the SAME deterministic request-id split decide the
    stage, and the cycle completes with exactly ONE new version."""
    from avenir_tpu.control.journal import CANARY_VALIDATE
    from avenir_tpu.io.respq import RespClient
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    rows = gen_rows(30, seed=77, drifted=True)
    fleet = start_multimodel_fleet(reg, resp_server.port)
    feeder = RespClient(port=resp_server.port)
    try:
        serve_round(feeder, rows, "pre", 20)
        ctl = make_controller(reg, params, tmp_path, fresh, fleet=fleet,
                              canary_outcomes=6, canary_percent=50)
        ctl.submit_alert(drift_alert())
        fault_injector("canary_validate@0=raise:RuntimeError")
        with pytest.raises(RuntimeError, match="injected fault"):
            ctl.run_pending()
        # the crash journaled the stage BEFORE any canary was installed:
        # serving never noticed, the champion answers 100%
        assert ctl.journal.pending
        assert ctl.journal.stage == CANARY_VALIDATE
        serve_round(feeder, rows, "mid", 20)
        assert fleet.converged_version() == 1
        assert fleet.canary_state(MODEL) is None
        faults.uninstall()
        # a NEW controller resumes: reloads the candidate payload and
        # re-installs the live canary, then WAITS on outcomes
        ctl2 = make_controller(reg, params, tmp_path, fresh, fleet=fleet,
                               canary_outcomes=6, canary_percent=50)
        waiting = ctl2.run_pending()
        assert waiting["stage"] == CANARY_VALIDATE
        assert waiting["canary"]["needed"] == 6
        assert ctl2.counters.get("Controller", "Resumes") == 1
        # pre-publish: the candidate serves its split from controller
        # memory, the registry still holds only the champion
        assert reg.versions(MODEL) == [1]
        st = fleet.canary_state(MODEL)
        assert st is not None and st["percent"] == 50
        serve_round(feeder, rows, "can", 30)
        # run_pending during the wait is a no-op, not a re-resume
        assert ctl2.run_pending() is None
        # delayed labels arrive; the 6th candidate-arm outcome decides.
        # predicted == actual -> live accuracy 100 >= the journaled floor
        card = list(SCHEMA.class_attr_field.cardinality)
        summary = None
        for i in range(40):
            summary = ctl2.record_canary_outcome(f"oc-{i}", card[1],
                                                 card[1])
            if summary is not None:
                break
        assert summary is not None and summary["outcome"] == PUBLISHED
        # exactly one new version despite the crash (no double-publish),
        # and the canary journal block records the verdict evidence
        assert reg.versions(MODEL) == [1, 2]
        assert reg.serving_version(MODEL) == 2
        can = ctl2.journal["canary"]
        assert can["candidate_accuracy"] == 100
        assert can["candidate_outcomes"] >= 6
        assert can["floor"] >= 0 and not can["timed_out"]
        # canary torn down: the fleet converges onto the published
        # version and keeps answering
        assert fleet.canary_state(MODEL) is None
        deadline = time.monotonic() + 20.0
        while fleet.converged_version() != 2 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.converged_version() == 2
        serve_round(feeder, rows, "post", 20)
    finally:
        fleet.stop()
        feeder.close()


class _FakeCanaryFleet:
    """The canary verbs alone (duck-typed like the real fleet), with a
    record_canary_outcome that returns None so the controller exercises
    its own deterministic-split fallback."""

    def __init__(self):
        self.installed = None
        self.cleared = False

    def install_canary(self, mname, version=None, percent=10,
                       predictor=None, pos_class=None, neg_class=None,
                       window=32):
        self.installed = dict(mname=mname, percent=percent,
                              predictor=predictor, pos_class=pos_class,
                              neg_class=neg_class, window=window)

    def record_canary_outcome(self, mname, rid, predicted, actual):
        return None

    def clear_canary(self, mname):
        self.cleared = True


@pytest.mark.multimodel
def test_canary_refuses_candidate_below_live_floor(tmp_path, mesh_ctx):
    """Live canary outcomes judge the candidate: all-wrong candidate-arm
    labels put its live accuracy under the journaled champion floor, the
    cycle completes REFUSED, the champion keeps 100% and the registry is
    untouched."""
    from avenir_tpu.control.journal import CANARY_VALIDATE
    from avenir_tpu.serving.router import canary_split
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    fake = _FakeCanaryFleet()
    ctl = make_controller(reg, params, tmp_path, fresh, fleet=fake,
                          canary_outcomes=5, canary_percent=50)
    ctl.submit_alert(drift_alert())
    waiting = ctl.run_pending()
    assert waiting["stage"] == CANARY_VALIDATE
    # the candidate went live pre-publish, classes from the schema card
    assert fake.installed["percent"] == 50
    assert fake.installed["predictor"] is not None
    assert {fake.installed["pos_class"], fake.installed["neg_class"]} \
        == set(SCHEMA.class_attr_field.cardinality)
    card = list(SCHEMA.class_attr_field.cardinality)
    summary = None
    i = 0
    with pytest.warns(RuntimeWarning, match="refused at canary"):
        while summary is None:
            rid = f"lbl-{i}"
            i += 1
            assert i < 100
            if canary_split(rid, 50):   # candidate arm: always WRONG
                summary = ctl.record_canary_outcome(rid, card[0], card[1])
            else:                       # champion arm: always right
                summary = ctl.record_canary_outcome(rid, card[1], card[1])
    assert summary["outcome"] == REFUSED
    assert fake.cleared
    # champion untouched: no new version, pin and serving stay
    assert reg.versions(MODEL) == [1]
    assert reg.serving_version(MODEL) == 1
    can = ctl.journal["canary"]
    assert can["candidate_accuracy"] == 0
    assert can["champion_accuracy"] == 100
    assert can["floor"] > 0
    assert ctl.counters.get("Controller", "Refused") == 1
    # and the next alert opens a fresh cycle (the journal closed clean)
    assert not ctl.journal.pending


@pytest.mark.multimodel
def test_canary_skips_without_capable_fleet(tmp_path, mesh_ctx):
    """canary_outcomes > 0 with a fleet link that does not speak the
    canary verbs (a plain PredictionService): the stage journals WHY it
    skipped and the cycle publishes on holdout validation alone — a
    resume replays the same decision instead of inventing a canary."""
    from avenir_tpu.serving import PredictionService
    reg, params, clean, fresh = build_champion(tmp_path, mesh_ctx)
    svc = PredictionService(registry=reg, model_name=MODEL, warm=False)
    ctl = make_controller(reg, params, tmp_path, fresh, fleet=svc,
                          canary_outcomes=4)
    ctl.submit_alert(drift_alert())
    summary = ctl.run_pending()
    assert summary["outcome"] == PUBLISHED
    assert reg.versions(MODEL) == [1, 2]
    assert ctl.journal["canary"] == {"skipped": True,
                                     "reason": "no canary-capable fleet"}
