"""Naive Bayes vs numpy oracle + model CSV round-trip + end-to-end."""

import numpy as np
import pytest

from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.core.table import load_csv_text, encode_rows
from avenir_tpu.core.metrics import Counters
from avenir_tpu.models import bayes


SCHEMA = FeatureSchema.from_dict({
    "fields": [
        {"name": "id", "ordinal": 0, "id": True, "dataType": "string"},
        {"name": "plan", "ordinal": 1, "dataType": "categorical", "feature": True,
         "cardinality": ["basic", "plus", "pro"]},
        {"name": "usage", "ordinal": 2, "dataType": "int", "feature": True,
         "bucketWidth": 50, "min": 0, "max": 500},
        {"name": "tenure", "ordinal": 3, "dataType": "int", "feature": True},
        {"name": "status", "ordinal": 4, "dataType": "categorical",
         "cardinality": ["open", "closed"]},
    ]
})


def make_rows(rng, n):
    """Separable synthetic churn data: 'closed' skews pro/low-usage/short-tenure."""
    rows = []
    for i in range(n):
        closed = rng.random() < 0.4
        if closed:
            plan = rng.choice(["pro", "plus", "basic"], p=[0.6, 0.3, 0.1])
            usage = int(rng.integers(0, 150))
            tenure = int(rng.normal(12, 4))
        else:
            plan = rng.choice(["pro", "plus", "basic"], p=[0.1, 0.3, 0.6])
            usage = int(rng.integers(150, 500))
            tenure = int(rng.normal(48, 10))
        rows.append([f"u{i}", plan, str(usage), str(max(tenure, 1)),
                     "closed" if closed else "open"])
    return rows


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    return encode_rows(make_rows(rng, 500), SCHEMA)


def test_train_counts_match_numpy(data, mesh_ctx):
    m = bayes.train(data, mesh_ctx)
    cls = data.class_codes()
    plan = data.column(1)
    # oracle: crosstab of (class, plan)
    fi = m.binned_ordinals.index(1)
    for c in range(2):
        for b in range(3):
            assert m.post_counts[c, fi, b] == np.sum((cls == c) & (plan == b))
    np.testing.assert_array_equal(m.class_counts,
                                  [np.sum(cls == 0), np.sum(cls == 1)])
    assert m.total == 500
    # binned usage
    fi_u = m.binned_ordinals.index(2)
    ub = data.binned_codes(2)
    for c in range(2):
        for b in range(11):
            assert m.post_counts[c, fi_u, b] == np.sum((cls == c) & (ub == b))
    # continuous tenure: reference integer mean/std
    ten = np.trunc(data.column(3))
    for c in range(2):
        xs = ten[cls == c]
        mean = np.floor(xs.sum() / len(xs))
        std = np.floor(np.sqrt((np.sum(xs * xs) - len(xs) * mean * mean) / (len(xs) - 1)))
        assert m.cont_post_mean[c, 0] == mean
        assert abs(m.cont_post_std[c, 0] - std) <= 1  # f32 moment accumulation


def test_model_lines_format(data, mesh_ctx):
    m = bayes.train(data, mesh_ctx)
    lines = m.to_lines()
    # posterior binned lines: class,ord,bin,count (4 tokens)
    post = [l for l in lines if not l.startswith(",") and l.split(",")[1] != ""
            and l.split(",")[2] != ""]
    assert post and all(len(l.split(",")) == 4 for l in post)
    # class prior: class,,,count
    priors = [l for l in lines if l.split(",")[1] == "" and l.split(",")[2] == ""
              and not l.startswith(",")]
    assert priors
    # continuous prior at end: ,ord,,mean,std
    assert lines[-1].startswith(",3,,")


def test_model_roundtrip(data, mesh_ctx):
    m = bayes.train(data, mesh_ctx)
    m2 = bayes.NaiveBayesModel.from_lines(m.to_lines(), SCHEMA)
    np.testing.assert_allclose(m2.post_counts, m.post_counts)
    np.testing.assert_allclose(m2.prior_counts, m.prior_counts)
    np.testing.assert_allclose(m2.class_counts, m.class_counts)
    np.testing.assert_allclose(m2.cont_post_mean, m.cont_post_mean)
    np.testing.assert_allclose(m2.cont_prior_std, m.cont_prior_std)
    assert m2.total == m.total


def test_predict_matches_oracle(data, mesh_ctx):
    m = bayes.train(data, mesh_ctx)
    res = bayes.predict(m, data)
    # numpy float64 oracle of the same math
    cls = data.class_codes()
    bin_codes = np.stack([data.binned_codes(1), data.binned_codes(2)], axis=1)
    cont = np.trunc(data.column(3))[:, None]
    post_p = m.post_counts / m.class_counts[:, None, None]
    prior_p = m.prior_counts / m.total
    class_p = m.class_counts / m.total
    n = data.n_rows
    pct_oracle = np.zeros((n, 2), dtype=int)
    for i in range(n):
        px = np.prod([prior_p[f, bin_codes[i, f]] for f in range(2)])
        for c in range(2):
            pxc = np.prod([post_p[c, f, bin_codes[i, f]] for f in range(2)])
            # continuous gaussian
            mu, sd = m.cont_post_mean[c, 0], max(m.cont_post_std[c, 0], 1e-6)
            pxc *= np.exp(-0.5 * ((cont[i, 0] - mu) / sd) ** 2) / (sd * np.sqrt(2 * np.pi))
            mu0, sd0 = m.cont_prior_mean[0], max(m.cont_prior_std[0], 1e-6)
            px_c = px * np.exp(-0.5 * ((cont[i, 0] - mu0) / sd0) ** 2) / (sd0 * np.sqrt(2 * np.pi))
            pct_oracle[i, c] = int((pxc * class_p[c] / px_c) * 100)
    # f32 vs f64: allow off-by-one on the integer percent
    assert np.mean(np.abs(res.class_probs - pct_oracle) <= 1) > 0.98
    # classifications should agree nearly everywhere
    agree = np.mean(np.argmax(res.class_probs, 1) == np.argmax(pct_oracle, 1))
    assert agree > 0.99


def test_end_to_end_accuracy(data, mesh_ctx, tmp_path):
    m = bayes.train(data, mesh_ctx)
    # round-trip through the model file like the reference two-job pipeline
    from avenir_tpu.core import artifacts
    store = artifacts.ArtifactStore(str(tmp_path))
    store.write_lines("model", m.to_lines())
    m2 = bayes.NaiveBayesModel.from_lines(store.read_lines("model"), SCHEMA)
    res = bayes.predict(m2, data)
    counters = Counters()
    cm = bayes.evaluate(m2, data, res, counters=counters)
    assert cm.accuracy() >= 85  # separable synthetic data
    assert counters.get("Validation", "TruePositive") == cm.true_pos


def test_predict_far_out_of_range_value_skips_feature(data, mesh_ctx):
    """A bucketed value >= 255 bins past the alphabet must be SKIPPED like
    any out-of-alphabet bin, not wrapped into a valid bin id by the uint8
    transfer (regression: uint8 wrap of unclamped codes >= 256)."""
    m = bayes.train(data, mesh_ctx)
    rows = make_rows(np.random.default_rng(3), 40)
    far = [r.copy() for r in rows]
    for r in far:
        r[2] = "999999"       # usage bin code ~20000, >= 256
    unk = [r.copy() for r in rows]
    for r in unk:
        r[2] = "250"          # bin 5 of 11 — stays in-alphabet
    res_far = bayes.predict(m, encode_rows(far, SCHEMA))
    # oracle for "skip the usage feature": out-of-alphabet but < 256, the
    # int-path skip the kernel has always applied
    mid = [r.copy() for r in rows]
    for r in mid:
        r[2] = "12000"        # bin 240: out-of-alphabet, fits in uint8
    res_mid = bayes.predict(m, encode_rows(mid, SCHEMA))
    np.testing.assert_array_equal(res_far.class_probs, res_mid.class_probs)
    # sanity: an in-alphabet value actually changes the outputs
    res_unk = bayes.predict(m, encode_rows(unk, SCHEMA))
    assert not np.array_equal(res_far.class_probs, res_unk.class_probs)


def test_train_chunked_equals_single_launch(mesh_ctx):
    """Chunked streaming train (the 100M-row wire form: uint8 codes, tail
    padded to one compiled shape, host f64 accumulation) must produce the
    IDENTICAL model to a single-launch train."""
    rng = np.random.default_rng(9)
    table = encode_rows(make_rows(rng, 4321), SCHEMA)
    full = bayes.train(table, mesh_ctx)
    small = bayes.train(table, mesh_ctx, chunk_rows=512)
    np.testing.assert_array_equal(full.post_counts, small.post_counts)
    np.testing.assert_array_equal(full.class_counts, small.class_counts)
    np.testing.assert_array_equal(full.cont_post_mean, small.cont_post_mean)
    np.testing.assert_array_equal(full.cont_post_std, small.cont_post_std)
    assert full.to_lines() == small.to_lines()


def test_prefix_mask_kernel_matches_explicit_mask():
    """The device-synthesized prefix mask (scalar k upload) must reproduce
    the explicit byte-mask kernel exactly for every prefix length."""
    import jax.numpy as jnp
    import numpy as np
    from avenir_tpu.models.bayes import _train_kernel, _train_kernel_prefix
    rng = np.random.default_rng(4)
    n, F, C, bmax = 512, 3, 2, 12
    cc = rng.integers(0, C, n).astype(np.uint8)
    bc = rng.integers(0, bmax, (n, F)).astype(np.uint8)
    cv = rng.normal(0, 10, (n, 2)).astype(np.float32)
    for k in (0, 1, 255, n):
        m = np.arange(n) < k
        a = _train_kernel(jnp.asarray(cc), jnp.asarray(bc),
                          jnp.asarray(cv), jnp.asarray(m), C, bmax)
        b = _train_kernel_prefix(jnp.asarray(cc), jnp.asarray(bc),
                                 jnp.asarray(cv), jnp.int32(k), C, bmax)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_pack4_wire_form_equals_uint8_wire_form(mesh_ctx, monkeypatch):
    """The 4-bit packed wire form (class + bin codes two-per-byte, half
    the link bytes) must produce the IDENTICAL model to the uint8 form,
    including with chunked streaming and unknown/out-of-range codes."""
    rows = make_rows(np.random.default_rng(11), 700)
    rows[3][1] = "enterprise"   # unknown categorical -> code -1 -> sentinel
    rows[5][2] = "99999"        # out-of-range bin
    table = encode_rows(rows, SCHEMA)
    monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "1")  # auto is off on cpu
    packed = bayes.train(table, mesh_ctx)
    packed_chunked = bayes.train(table, mesh_ctx, chunk_rows=256)
    monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "0")
    wide = bayes.train(table, mesh_ctx)
    assert packed.to_lines() == wide.to_lines()
    assert packed_chunked.to_lines() == wide.to_lines()
    np.testing.assert_array_equal(packed.post_counts, wide.post_counts)
    np.testing.assert_array_equal(packed.class_counts, wide.class_counts)
    np.testing.assert_array_equal(packed.cont_post_mean, wide.cont_post_mean)
    np.testing.assert_array_equal(packed.cont_post_std, wide.cont_post_std)


def test_pack4_kernels_match_unpacked_kernels():
    """Nibble layout oracle: _unpack4(pack(codes)) == codes for odd and
    even column counts, and the packed kernels reproduce the unpacked
    kernels bit-for-bit (explicit mask AND prefix variants)."""
    import jax.numpy as jnp
    from avenir_tpu.models.bayes import (
        _train_kernel, _train_kernel_packed, _train_kernel_prefix,
        _train_kernel_prefix_packed, _unpack4)
    rng = np.random.default_rng(6)
    n, C, bmax = 256, 3, 13
    for Fb in (2, 3):           # F_packed = 3 (odd) and 4 (even)
        F = 1 + Fb
        cc = rng.integers(0, C, n).astype(np.uint8)
        bc = rng.integers(0, bmax, (n, Fb)).astype(np.uint8)
        # sprinkle sentinels (15 = out-of-alphabet in the packed form,
        # equivalent to 255 in the uint8 form)
        cc[::17] = 15
        bc[::13, 0] = 15
        cv = rng.normal(0, 5, (n, 1)).astype(np.float32)
        codes = np.concatenate([cc[:, None], bc], axis=1)
        pk = np.zeros((n, (F + 1) // 2), dtype=np.uint8)
        for j in range(F):
            col = codes[:, j]
            pk[:, j // 2] |= (col << 4) if j % 2 == 0 else col
        np.testing.assert_array_equal(
            np.asarray(_unpack4(jnp.asarray(pk), F)), codes)
        wide_cc = np.where(cc == 15, 255, cc).astype(np.uint8)
        wide_bc = np.where(bc == 15, 255, bc).astype(np.uint8)
        m = np.arange(n) < 200
        a = _train_kernel(jnp.asarray(wide_cc), jnp.asarray(wide_bc),
                          jnp.asarray(cv), jnp.asarray(m), C, bmax)
        b = _train_kernel_packed(jnp.asarray(pk), jnp.asarray(cv),
                                 jnp.asarray(m), C, bmax, F)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        c = _train_kernel_prefix(jnp.asarray(wide_cc), jnp.asarray(wide_bc),
                                 jnp.asarray(cv), jnp.int32(200), C, bmax)
        d = _train_kernel_prefix_packed(jnp.asarray(pk), jnp.asarray(cv),
                                        jnp.int32(200), C, bmax, F)
        for x, y in zip(c, d):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_pack4_predict_equals_uint8_predict(data, mesh_ctx, monkeypatch):
    """The 4-bit packed predict upload must reproduce the uint8 path's
    outputs exactly, including unknown categoricals and out-of-range
    bucketed values (both collapse to the skip sentinel)."""
    m = bayes.train(data, mesh_ctx)
    rows = make_rows(np.random.default_rng(13), 300)
    rows[2][1] = "enterprise"   # unknown categorical
    rows[4][2] = "12000"        # bin 240: out-of-alphabet, uint8-range
    rows[6][2] = "999999"       # bin ~20000: out of uint8 range too
    table = encode_rows(rows, SCHEMA)
    monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "1")  # auto is off on cpu
    rp = bayes.predict(m, table)
    monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "0")
    rw = bayes.predict(m, table)
    assert rp.pred_class == rw.pred_class
    np.testing.assert_array_equal(rp.pred_prob, rw.pred_prob)
    np.testing.assert_array_equal(rp.class_prob_diff, rw.class_prob_diff)
    np.testing.assert_array_equal(np.asarray(rp.class_probs),
                                  np.asarray(rw.class_probs))


def test_pack4_force_flag_warns_when_alphabet_too_big(mesh_ctx, monkeypatch):
    """AVENIR_TPU_WIRE_PACK4=1 on a schema whose alphabets don't fit a
    nibble must warn and fall back, not silently mislabel an A/B run."""
    wide_schema = FeatureSchema.from_dict({
        "fields": [
            {"name": "v", "ordinal": 0, "dataType": "int", "feature": True,
             "bucketWidth": 10, "min": 0, "max": 500},   # 51 bins > 15
            {"name": "y", "ordinal": 1, "dataType": "categorical",
             "cardinality": ["a", "b"]},
        ]
    })
    rows = [[str(i % 500), "a" if i % 3 else "b"] for i in range(64)]
    table = encode_rows(rows, wide_schema)
    monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "1")
    with pytest.warns(UserWarning, match="don't fit a nibble"):
        m = bayes.train(table, mesh_ctx)
    # and the fallback still trains correctly
    assert m.total == 64


def test_mesh_context_device_platform(mesh_ctx):
    """The wire-format auto-gate keys off this: the test mesh is CPU."""
    assert mesh_ctx.device_platform == "cpu"


def test_pack4_fuzz_random_schemas(mesh_ctx, monkeypatch):
    """Randomized packed-vs-uint8 equivalence across schema shapes:
    varying feature counts (odd/even packing), alphabet sizes at the
    nibble boundary, classes, unknown rates, and chunk sizes."""
    rng = np.random.default_rng(17)
    for trial in range(6):
        n_feat = int(rng.integers(1, 6))
        n_bins = int(rng.integers(2, 16))      # <= 15: always packable
        n_cls = int(rng.integers(2, 4))
        n_rows = int(rng.integers(40, 400))
        fields = [{"name": "id", "ordinal": 0, "id": True,
                   "dataType": "string"}]
        for f in range(n_feat):
            fields.append({"name": f"f{f}", "ordinal": 1 + f,
                           "dataType": "int", "feature": True,
                           "bucketWidth": 10, "min": 0,
                           "max": 10 * n_bins - 1})
        fields.append({"name": "y", "ordinal": 1 + n_feat,
                       "dataType": "categorical",
                       "cardinality": [f"c{k}" for k in range(n_cls)]})
        schema = FeatureSchema.from_dict({"fields": fields})
        rows = []
        for i in range(n_rows):
            vals = [str(i)]
            for f in range(n_feat):
                if rng.random() < 0.05:
                    vals.append(str(10 * n_bins * 50))   # out of range
                else:
                    vals.append(str(int(rng.integers(0, 10 * n_bins))))
            vals.append(f"c{int(rng.integers(0, n_cls))}")
            rows.append(vals)
        table = encode_rows(rows, schema)
        chunk = int(rng.choice([64, 128, 1 << 23]))
        monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "1")
        packed = bayes.train(table, mesh_ctx, chunk_rows=chunk)
        monkeypatch.setenv("AVENIR_TPU_WIRE_PACK4", "0")
        wide = bayes.train(table, mesh_ctx, chunk_rows=chunk)
        assert packed.to_lines() == wide.to_lines(), \
            f"trial {trial}: F={n_feat} B={n_bins} C={n_cls} n={n_rows}"
