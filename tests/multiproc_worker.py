"""Worker for the true 2-process distributed tests (spawned by
tests/test_distributed.py): joins the coordinator, then executes a JSON
spec of one or more CLI runs on THIS process's input shard, printing the
captured counter output between markers for the parent to compare.

Spec file layout::

    {"runs": [[argv...], [argv...], ...]}

Placeholders are resolved by the parent before writing the spec.  Chained
runs exercise the idempotent re-entry of distributed mode (level-wise
Apriori, pipeline scripts).
"""

import contextlib
import io
import json
import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    spec_path = sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from avenir_tpu.cli import run as cli_run
    with open(spec_path) as fh:
        spec = json.load(fh)
    cap = io.StringIO()
    for argv in spec["runs"]:
        with contextlib.redirect_stdout(cap):
            rc = cli_run.main(argv)
        assert rc == 0, f"run failed rc={rc}: {argv}"
    sys.stdout.write(f"COUNTERS_BEGIN\n{cap.getvalue()}COUNTERS_END\n")
    print("WORKER_OK")


if __name__ == "__main__":
    main()
