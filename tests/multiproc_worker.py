"""Worker for the true 2-process distributed test (spawned by
tests/test_distributed.py): joins the coordinator, runs the NaiveBayes
train job through the CLI distributed mode on THIS process's input shard,
and prints the model file path + captured counter output for the parent to
compare."""

import contextlib
import io
import os
import sys


def main():
    pid = int(sys.argv[1])
    port = sys.argv[2]
    shard = sys.argv[3]
    out = sys.argv[4]
    res = sys.argv[5]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from avenir_tpu.cli import run as cli_run
    cap = io.StringIO()
    with contextlib.redirect_stdout(cap):
        rc = cli_run.main([
            "org.avenir.bayesian.BayesianDistribution",
            f"-Dconf.path={res}/churn.properties",
            f"-Dbad.feature.schema.file.path={res}/churn.json",
            "-Ddistributed.mode=1", shard, out])
    assert rc == 0
    sys.stdout.write(f"COUNTERS_BEGIN\n{cap.getvalue()}COUNTERS_END\n")
    print("WORKER_OK")


if __name__ == "__main__":
    main()
