"""Model-selection / validation utilities: the reference's python validation
layer (python/supv/svm.py:41-165 — linear k-fold, repeated random-fold, and
bagging training over any trainer) generalized over a (train_fn, predict_fn)
pair, plus a vmapped k-fold fast path for jittable trainers.

Contract: ``train_fn(X, y) -> model``; ``predict_fn(model, X) -> labels``.
Scores are accuracies per fold (the reference prints sklearn cv scores).
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Sequence, Tuple

import numpy as np


class ValidationResult(NamedTuple):
    scores: np.ndarray          # per-fold accuracy
    mean: float
    std: float


def _score(predict_fn, model, X, y) -> float:
    pred = np.asarray(predict_fn(model, X))
    return float((pred == np.asarray(y)).mean())


def kfold_validation(X: np.ndarray, y: np.ndarray, n_folds: int,
                     train_fn: Callable, predict_fn: Callable,
                     shuffle: bool = True, seed: int = 0) -> ValidationResult:
    """Linear k-fold cross validation (svm.py train_kfold_validation_ext
    :53-97: contiguous fold slices, train on the rest, score on the fold)."""
    n = len(y)
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    folds = np.array_split(idx, n_folds)
    scores = []
    for i in range(n_folds):
        val = folds[i]
        tr = np.concatenate([folds[j] for j in range(n_folds) if j != i])
        model = train_fn(X[tr], y[tr])
        scores.append(_score(predict_fn, model, X[val], y[val]))
    s = np.asarray(scores)
    return ValidationResult(s, float(s.mean()), float(s.std()))


def random_fold_validation(X: np.ndarray, y: np.ndarray, n_folds: int,
                           n_iter: int, train_fn: Callable,
                           predict_fn: Callable,
                           seed: int = 0) -> ValidationResult:
    """Repeated random train/test splits with test fraction 1/n_folds
    (svm.py train_rfold_validation :100-116)."""
    n = len(y)
    test_size = max(n // n_folds, 1)
    rng = np.random.default_rng(seed)
    scores = []
    for _ in range(n_iter):
        idx = rng.permutation(n)
        val, tr = idx[:test_size], idx[test_size:]
        model = train_fn(X[tr], y[tr])
        scores.append(_score(predict_fn, model, X[val], y[val]))
    s = np.asarray(scores)
    return ValidationResult(s, float(s.mean()), float(s.std()))


def bagging_train(X: np.ndarray, y: np.ndarray, n_models: int,
                  train_fn: Callable, sample_rate: float = 1.0,
                  seed: int = 0) -> List:
    """Train n models on bootstrap samples (svm.py train_bagging :22-38);
    combine with majority_vote."""
    rng = np.random.default_rng(seed)
    n = len(y)
    m = max(int(n * sample_rate), 1)  # never hand train_fn an empty sample
    models = []
    for _ in range(n_models):
        idx = rng.integers(0, n, m)
        models.append(train_fn(X[idx], y[idx]))
    return models


def majority_vote(models: Sequence, X: np.ndarray,
                  predict_fn: Callable) -> np.ndarray:
    """Per-record modal prediction over a model list."""
    preds = np.stack([np.asarray(predict_fn(m, X)) for m in models])
    out = []
    for col in preds.T:
        vals, counts = np.unique(col, return_counts=True)
        out.append(vals[np.argmax(counts)])
    return np.asarray(out)


def kfold_validation_vmapped(X: np.ndarray, y: np.ndarray, n_folds: int,
                             train_fold_fn: Callable,
                             seed: int = 0) -> ValidationResult:
    """TPU fast path: all folds train simultaneously under one vmap.

    ``train_fold_fn(X, y, mask) -> accuracy`` must be jittable and honor a
    boolean training mask (False rows held out), returning validation
    accuracy over the held-out rows — each fold is then just a different
    mask, and vmap turns k sequential trainings into one batched XLA
    program (n_folds x the memory, 1 x the wall-clock of a single fold)."""
    import jax
    import jax.numpy as jnp

    n = len(y)
    idx = np.arange(n)
    np.random.default_rng(seed).shuffle(idx)
    fold_of = np.empty(n, dtype=np.int32)
    for i, fold in enumerate(np.array_split(idx, n_folds)):
        fold_of[fold] = i
    masks = np.stack([fold_of != i for i in range(n_folds)])  # (k, n) train
    accs = jax.vmap(lambda m: train_fold_fn(jnp.asarray(X), jnp.asarray(y),
                                            m))(jnp.asarray(masks))
    s = np.asarray(accs)
    return ValidationResult(s, float(s.mean()), float(s.std()))
