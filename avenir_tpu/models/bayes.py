"""Naive Bayes: the minimum end-to-end slice of the framework (SURVEY.md §7.3).

Capability parity with org.avenir.bayesian (SURVEY.md §2.2):

  * ``train``   == BayesianDistribution (bayesian/BayesianDistribution.java):
    one pass computing class priors, feature priors and feature posteriors.
    Categorical and bucketed-numeric features count (class, ord, bin) cells;
    unbucketed numeric features accumulate (count, Σx, Σx²) per class and
    overall -> integer mean/σ, exactly as the reference's reducer
    (:263-327, cleanup :240-258).
  * model CSV  == the reference's model file, line for line (format decoded
    from the reducer emits :298-327 and the predictor's parser
    BayesianPredictor.java:186-224):
        class,ord,bin,count        feature posterior (binned)
        class,ord,,mean,stdDev     feature posterior (continuous)
        class,,,count              class prior (one line per posterior cell)
        ,ord,bin,count             feature prior (binned, per class slice)
        ,ord,,mean,stdDev          feature prior (continuous)
  * ``predict`` == BayesianPredictor (:396-419): per class
    P(c|x) = P(x|c)·P(c)/P(x) as integer percent (truncated), default argmax
    or cost-based arbitration, confusion-matrix counters.

TPU design: the whole training pass is two MXU contractions over row-sharded
arrays (ops.histogram.class_bin_histogram / class_moments); XLA inserts the
cross-shard all-reduce.  Prediction selects per-feature log-probs via one-hot
einsums plus a tiny (C,)-vector epilogue per record, all in one jitted pass.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema, FeatureField
from ..core.table import ColumnarTable
from ..core.metrics import ConfusionMatrix, Counters
from ..parallel.mesh import MeshContext, runtime_context
from ..ops.histogram import class_bin_histogram, class_moments


# --------------------------------------------------------------------------
# model container
# --------------------------------------------------------------------------

@dataclass
class NaiveBayesModel:
    schema: FeatureSchema
    class_values: List[str]
    binned_ordinals: List[int]          # feature ordinals with finite bins
    cont_ordinals: List[int]            # unbucketed numeric feature ordinals
    num_bins: List[int]                 # per binned ordinal
    # counts
    post_counts: np.ndarray             # (C, Fb, Bmax) float
    class_counts: np.ndarray            # (C,) float   (true per-class record counts)
    prior_counts: np.ndarray            # (Fb, Bmax) float
    total: float                        # total record count
    # continuous gaussian parameters, reference-rounded to integer longs
    cont_post_mean: np.ndarray          # (C, Fc)
    cont_post_std: np.ndarray           # (C, Fc)
    cont_prior_mean: np.ndarray         # (Fc,)
    cont_prior_std: np.ndarray          # (Fc,)

    # ---- serialization: reference model CSV ----
    def to_lines(self, delim: str = ",") -> List[str]:
        """Emit the model file with the reference reducer's line set and order:
        for each (class, ord, bin) cell in key-sort order a [posterior,
        class-prior, feature-prior] triple, then continuous feature priors
        (the reducer-cleanup lines) at the end."""
        lines: List[str] = []
        C = len(self.class_values)
        # Hadoop shuffle sorts Tuple keys (classVal:str, ord:int, bin:str);
        # bin sorts lexicographically because it is a string in the Tuple.
        cells = []
        for ci, cv in enumerate(self.class_values):
            for fi, o in enumerate(self.binned_ordinals):
                field = self.schema.find_field_by_ordinal(o)
                for b in range(self.num_bins[fi]):
                    cnt = int(round(self.post_counts[ci, fi, b]))
                    if cnt > 0:
                        cells.append((cv, o, field.bin_label(b), ci, fi, b, cnt))
            for fi, o in enumerate(self.cont_ordinals):
                cells.append((cv, o, None, ci, fi, None, None))
        cells.sort(key=lambda t: (t[0], t[1], "" if t[2] is None else t[2]))
        for cv, o, bin_label, ci, fi, b, cnt in cells:
            if bin_label is not None:
                lines.append(delim.join([cv, str(o), bin_label, str(cnt)]))
                lines.append(delim.join([cv, "", "", str(cnt)]))
                lines.append(delim.join(["", str(o), bin_label, str(cnt)]))
            else:
                mean = int(self.cont_post_mean[ci, fi])
                std = int(self.cont_post_std[ci, fi])
                lines.append(delim.join([cv, str(o), "", str(mean), str(std)]))
                ccount = int(round(self.class_counts[ci]))
                lines.append(delim.join([cv, "", "", str(ccount)]))
        for fi, o in enumerate(self.cont_ordinals):
            mean = int(self.cont_prior_mean[fi])
            std = int(self.cont_prior_std[fi])
            lines.append(delim.join(["", str(o), "", str(mean), str(std)]))
        return lines

    @classmethod
    def from_lines(cls, lines: Sequence[str], schema: FeatureSchema,
                   delim: str = ",") -> "NaiveBayesModel":
        """Parse the reference model CSV (BayesianPredictor.loadModel
        semantics: duplicate bin lines accumulate)."""
        class_field = schema.class_attr_field
        class_values = list(class_field.cardinality or [])
        binned = [f for f in schema.feature_fields if f.is_binned]
        cont = [f for f in schema.feature_fields if not f.is_binned]
        b_ords = [f.ordinal for f in binned]
        c_ords = [f.ordinal for f in cont]
        nbins = [f.num_bins for f in binned]
        bmax = max(nbins) if nbins else 1
        C, Fb, Fc = len(class_values), len(b_ords), len(c_ords)
        post = np.zeros((C, Fb, bmax))
        prior = np.zeros((Fb, bmax))
        cls_counts = np.zeros((C,))
        cpm = np.zeros((C, Fc)); cps = np.ones((C, Fc))
        cqm = np.zeros((Fc,)); cqs = np.ones((Fc,))
        b_index = {o: i for i, o in enumerate(b_ords)}
        c_index = {o: i for i, o in enumerate(c_ords)}
        cls_index = {v: i for i, v in enumerate(class_values)}

        def bin_code(field: FeatureField, label: str) -> int:
            if field.is_categorical:
                return field.cat_code(label)
            return int(label) - field.bin_offset

        for line in lines:
            items = line.split(delim)
            ord_s = items[1]
            if items[0] == "":
                if items[2] != "":       # feature prior binned
                    f = schema.find_field_by_ordinal(int(ord_s))
                    prior[b_index[int(ord_s)], bin_code(f, items[2])] += int(items[3])
                else:                     # feature prior continuous
                    ci2 = c_index[int(ord_s)]
                    cqm[ci2] = float(items[3]); cqs[ci2] = float(items[4])
            elif ord_s == "" and items[2] == "":  # class prior
                ci = cls_index[items[0]]
                cls_counts[ci] += int(items[3])
            else:
                ci = cls_index[items[0]]
                f = schema.find_field_by_ordinal(int(ord_s))
                if items[2] != "":        # posterior binned
                    post[ci, b_index[int(ord_s)], bin_code(f, items[2])] += int(items[3])
                else:                     # posterior continuous
                    fi2 = c_index[int(ord_s)]
                    cpm[ci, fi2] = float(items[3]); cps[ci, fi2] = float(items[4])
        # class prior lines are emitted once per (class,ord,bin) cell, each
        # carrying that cell's count; the per-class record count is the sum
        # over ONE feature's bins.  With Fb binned features (+Fc cont), the
        # accumulated value is (Fb+Fc) * classCount; undo the multiplicity.
        mult = max(Fb + Fc, 1)
        cls_counts = cls_counts / mult
        total = cls_counts.sum()
        return cls(schema=schema, class_values=class_values,
                   binned_ordinals=b_ords, cont_ordinals=c_ords, num_bins=nbins,
                   post_counts=post, class_counts=cls_counts, prior_counts=prior,
                   total=float(total), cont_post_mean=cpm, cont_post_std=cps,
                   cont_prior_mean=cqm, cont_prior_std=cqs)


# --------------------------------------------------------------------------
# training
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(4, 5))
def _train_kernel(cc, bc, cv, m, C, bmax):
    """Module-level jit: the per-call closure recompiled on every train.

    ``cc``/``bc`` may arrive uint8 (the narrow wire form — the host->device
    link is the e2e bottleneck at scale); the upcast to int32 happens here
    on device.  Sentinel 255 (unknown/out-of-range, see train()) stays out
    of every one-hot range, contributing zero exactly like the wide form's
    negative codes."""
    return _train_kernel_body(cc, bc, cv, m, C, bmax)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _train_kernel_prefix(cc, bc, cv, k, C, bmax):
    """_train_kernel with the validity mask SYNTHESIZED on device from the
    scalar valid-prefix length ``k`` (the mask of every single-process
    chunk is ``row < k`` by construction: valid_mask is a prefix and
    chunks slice it contiguously).  Saves one byte/row of upload — ~1/7 of
    the uint8 wire form the tunneled link carries at the 100M scale."""
    m = jnp.arange(cc.shape[0], dtype=jnp.int32) < k
    return _train_kernel_body(cc, bc, cv, m, C, bmax)


def _unpack4(pk, F):
    """Split the 4-bit packed wire matrix back into per-column codes on
    device: byte j carries code 2j in its high nibble and code 2j+1 in
    its low nibble; a trailing zero nibble (odd F) is sliced off.  Pure
    elementwise shifts — XLA fuses this into the one-hot consumers, so
    the unpack is free next to the halved link transfer."""
    pk = pk.astype(jnp.int32)
    both = jnp.stack([pk >> 4, pk & 15], axis=2)
    return both.reshape(pk.shape[0], -1)[:, :F]


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _train_kernel_packed(pk, cv, m, C, bmax, F):
    """_train_kernel over the 4-bit packed wire form (class code in
    column 0, bin codes after): HALF the bytes of the uint8 form on the
    host->device link, which bounds the 100M-row e2e train phase (600 MB
    at the tunnel's ~16 MB/s — BASELINE.md round-5 device capture).
    Usable whenever every alphabet fits in a nibble with 15 left as the
    out-of-alphabet sentinel (nbins <= 15 and n_classes <= 15 — true of
    the north-star churn schema and every resource/ use case)."""
    codes = _unpack4(pk, F)
    return _train_kernel_body(codes[:, 0], codes[:, 1:], cv, m, C, bmax)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _train_kernel_prefix_packed(pk, cv, k, C, bmax, F):
    """Packed wire form + device-synthesized prefix mask: the minimal
    single-process transfer — ceil((1+Fb)/2) bytes/row, no mask byte."""
    codes = _unpack4(pk, F)
    m = jnp.arange(pk.shape[0], dtype=jnp.int32) < k
    return _train_kernel_body(codes[:, 0], codes[:, 1:], cv, m, C, bmax)


def _train_kernel_body(cc, bc, cv, m, C, bmax):
    cc = cc.astype(jnp.int32)
    bc = bc.astype(jnp.int32)
    counts = class_bin_histogram(cc, bc, C, bmax, m)
    cls_counts = jax.nn.one_hot(cc, C, dtype=jnp.float32)
    cls_counts = (cls_counts * m.astype(jnp.float32)[:, None]).sum(axis=0)
    moments = class_moments(cc, cv, C, m)
    return counts, cls_counts, moments


def wire_pack4_fits(schema: FeatureSchema) -> bool:
    """True when every alphabet fits a nibble with 15 left as the
    out-of-alphabet sentinel — the pack4 wire-form eligibility gate.
    ONE definition shared by train() and the A/B tool
    (tools/ab_pack4_device.py): a hand-copied gate there could silently
    diverge and mislabel which wire form an A/B actually measured."""
    C = len(schema.class_attr_field.cardinality or [])
    bmax = max((f.num_bins for f in schema.feature_fields if f.is_binned),
               default=1)
    return C <= 15 and bmax <= 15


def train(table: ColumnarTable, ctx: Optional[MeshContext] = None,
          counters: Optional[Counters] = None,
          chunk_rows: int = 1 << 23) -> NaiveBayesModel:
    """One-pass distribution computation (== BayesianDistribution MR job).

    Rows are padded to the mesh size and sharded over the data axis; the
    histogram/moment contractions reduce over rows, so GSPMD emits per-shard
    partials + all-reduce — the exact combiner+shuffle structure of the
    reference job, in one XLA program per chunk.

    Rows stream to the device in ``chunk_rows`` slices (tail padded to the
    one compiled shape, masked out).  This keeps the 100M-row north star
    inside two ceilings the single-launch form breaks: the (n, F, B)
    one-hot intermediate would exceed HBM past ~50M rows, and f32 count
    accumulation loses integer exactness past 2^24 per cell — per-chunk
    counts stay below 2^24 and the cross-chunk accumulation is host
    float64 (exact to 2^53).  Continuous-moment sums remain f32
    tree-reductions within a chunk (the reference accumulates in long;
    divergence is bounded by f32 rounding on ~8M-term sums and erased by
    the floor-to-int model serialization in all tested configs).

    Multi-process: the chunk schedule is agreed across the pod (max local
    row count), so unequal per-process shards are handled CORRECTLY —
    shorter shards pad masked-out rows instead of tripping
    from_process_local's equal-shape guard."""
    if chunk_rows > 1 << 23:
        # the exactness arguments above are proved AT this bound: per-chunk
        # counts < 2^24 (f32-exact) and moment-divergence bounded by ~8M-term
        # f32 sums.  A caller-supplied larger chunk would silently weaken
        # both invariants (round-4 advisor), so refuse it.
        raise ValueError(
            f"chunk_rows={chunk_rows} exceeds 1<<23: per-chunk f32 count "
            f"exactness (2^24) and the documented moment-precision bound "
            f"both assume chunks of at most 8M rows")
    ctx = ctx or runtime_context()
    schema = table.schema
    class_field = schema.class_attr_field
    class_values = list(class_field.cardinality or [])
    C = len(class_values)
    binned = [f for f in schema.feature_fields if f.is_binned]
    cont = [f for f in schema.feature_fields if not f.is_binned]
    nbins = [f.num_bins for f in binned]
    bmax = max(nbins) if nbins else 1

    padded = table.pad_to_multiple(ctx.n_devices)
    n = padded.n_rows

    def narrow(codes, alphabet):
        """uint8 wire form when the alphabet fits: 4x less host->device
        upload (the tunnel link is the 100M-row e2e bottleneck).  Codes
        outside [0, alphabet) — unknown (-1) or out-of-range — map to the
        255 sentinel, which the kernel's one-hots drop exactly like the
        wide form's out-of-range values."""
        codes = np.asarray(codes)
        if alphabet <= 255:
            return np.where((codes >= 0) & (codes < alphabet),
                            codes, 255).astype(np.uint8)
        return codes.astype(np.int32)

    # 4-bit packed wire form when every alphabet fits in a nibble with 15
    # as the out-of-alphabet sentinel: HALF the uint8 form's bytes on the
    # host->device link, which bounds the 100M-row train phase (600 MB at
    # the tunnel's ~16 MB/s).  Column 0 is the class code, bin codes
    # follow; codes 2j / 2j+1 share byte j (high/low nibble).
    # Auto mode packs only on a REAL device: the nibble-OR host pass
    # costs ~0.1 s/10M rows, which the CPU backend (no link to win back)
    # measured as a pure 15-25% train-phase loss — see BASELINE.md.
    # AVENIR_TPU_WIRE_PACK4=1/0 forces either path (tests, A/B runs).
    env_pack4 = os.environ.get("AVENIR_TPU_WIRE_PACK4", "auto")
    fits4 = wire_pack4_fits(schema)
    pack4 = (fits4 and env_pack4 != "0"
             and (env_pack4 == "1" or ctx.device_platform != "cpu"))
    if env_pack4 == "1" and not fits4:
        # an A/B run that THINKS it measured the packed form must not
        # silently record the uint8 path
        import warnings
        warnings.warn(
            f"AVENIR_TPU_WIRE_PACK4=1 ignored: alphabets don't fit a "
            f"nibble (C={C}, bmax={bmax}); using the uint8 wire form")
    F_packed = 1 + len(binned)

    def narrow4(codes, alphabet):
        codes = np.asarray(codes)
        return np.where((codes >= 0) & (codes < alphabet),
                        codes, 15).astype(np.uint8)

    if pack4:
        # nibble-packed column-at-a-time into the preallocated matrix —
        # same single-pass discipline as the uint8 fill below.  No
        # separate cls_host/bin_host in this form: column 0 is the class,
        # bins follow, and the kernels unpack everything from pk_host.
        cols = [(padded.columns[class_field.ordinal], C)]
        cols += [(padded.binned_codes(f.ordinal), bmax) for f in binned]
        pk_host = np.zeros((n, (F_packed + 1) // 2), dtype=np.uint8)
        for j, (codes, alphabet) in enumerate(cols):
            col = narrow4(codes, alphabet)
            pk_host[:, j // 2] |= (col << 4) if j % 2 == 0 else col
        cls_host = bin_host = None
    else:
        cls_host = narrow(padded.columns[class_field.ordinal], C)
        if binned:
            # column-at-a-time into the preallocated wire matrix: a
            # stacked (n, F) int32 intermediate plus a whole-matrix
            # narrow() pass measured ~30 s of the 100M-row train prep
            bin_host = np.empty((n, len(binned)),
                                dtype=np.uint8 if bmax <= 255 else np.int32)
            for j, f in enumerate(binned):
                bin_host[:, j] = narrow(padded.binned_codes(f.ordinal), bmax)
        else:
            bin_host = np.zeros((n, 0), dtype=np.int32)
    if cont:
        # reference parses continuous values as integers (long)
        cont_host = np.empty((n, len(cont)), dtype=np.float32)
        for j, f in enumerate(cont):
            cont_host[:, j] = np.trunc(padded.columns[f.ordinal])
    else:
        cont_host = np.zeros((n, 0), dtype=np.float32)
    mask_host = padded.valid_mask

    # chunk-count agreement: every iteration is a collective, so all
    # processes must run the SAME number of identically-shaped chunks even
    # with unequal local shards — the schedule covers the pod-wide MAX
    # local row count and shorter shards pad (mask False).  This also
    # upgrades unequal per-process shards from an error to a correct
    # masked computation.  Single-process: one launch for small inputs.
    from ..parallel.distributed import allgather_object, is_multiprocess
    n_goal = max(allgather_object(n)) if is_multiprocess() else n
    align = ctx.n_devices
    # max(..., align) keeps chunk > 0 for an empty table (zero iterations
    # -> the zero-count model, matching the old single-launch behavior)
    chunk = max(align,
                min(max(chunk_rows - chunk_rows % align, align),
                    n_goal + (-n_goal) % align))
    Fb, Fc = len(binned), cont_host.shape[1]
    counts = np.zeros((C, Fb, bmax), dtype=np.float64)
    cls_counts = np.zeros((C,), dtype=np.float64)
    moments = np.zeros((C, Fc, 3), dtype=np.float64)
    # single-process, the mask of every chunk is a VALID PREFIX (valid_mask
    # marks the first n_valid rows; chunks slice it contiguously), so the
    # kernel synthesizes it from a scalar instead of shipping a byte/row —
    # ~1/7 of the wire form.  Multi-process keeps the explicit mask: each
    # process's local block has its own prefix inside the global array.
    prefix_ok = not is_multiprocess()
    n_valid = padded.n_valid  # always set: pad_to_multiple is the only
    for s in range(0, n_goal, chunk):  # PaddedTable constructor
        e = min(s + chunk, n)
        lo = min(s, n)
        cv = cont_host[lo:e]
        mm = None if prefix_ok else mask_host[lo:e]
        pad = chunk - (e - lo)
        if pack4:
            pk = pk_host[lo:e]
            if pad:
                # tail (or past-local-end) padded to the ONE compiled
                # chunk shape, masked out.  Zero bytes unpack to code 0,
                # which the mask drops — same as the uint8 path's zeros.
                pk = np.pad(pk, ((0, pad), (0, 0)))
        else:
            cc, bc = cls_host[lo:e], bin_host[lo:e]
            if pad:
                cc = np.pad(cc, (0, pad))
                bc = np.pad(bc, ((0, pad), (0, 0)))
        if pad:
            cv = np.pad(cv, ((0, pad), (0, 0)))
            if mm is not None:
                mm = np.pad(mm, (0, pad))
        if prefix_ok:
            k = int(np.clip(n_valid - lo, 0, chunk))
            if pack4:
                c_, cl_, mo_ = _train_kernel_prefix_packed(
                    ctx.shard_rows(pk), ctx.shard_rows(cv),
                    jnp.int32(k), C, bmax, F_packed)
            else:
                c_, cl_, mo_ = _train_kernel_prefix(
                    ctx.shard_rows(cc), ctx.shard_rows(bc),
                    ctx.shard_rows(cv), jnp.int32(k), C, bmax)
        elif pack4:
            c_, cl_, mo_ = _train_kernel_packed(
                ctx.shard_rows(pk), ctx.shard_rows(cv),
                ctx.shard_rows(mm), C, bmax, F_packed)
        else:
            c_, cl_, mo_ = _train_kernel(
                ctx.shard_rows(cc), ctx.shard_rows(bc),
                ctx.shard_rows(cv), ctx.shard_rows(mm), C, bmax)
        from ..utils.tracing import fetch, note_dispatch
        note_dispatch()
        counts += fetch(c_, dtype=np.float64)
        cls_counts += fetch(cl_, dtype=np.float64)
        moments += fetch(mo_, dtype=np.float64)

    # zero out bins beyond each field's alphabet (padding of Bmax)
    for fi, nb in enumerate(nbins):
        counts[:, fi, nb:] = 0.0
    prior = counts.sum(axis=0)

    # continuous gaussian params with the reference's integer rounding
    # (mean = valSum/count integer division; std = (long)sqrt((Σx²-n·mean²)/(n-1)))
    def gauss(mom):  # mom (..., 3)
        cnt = np.maximum(mom[..., 0], 1.0)
        mean = np.floor(mom[..., 1] / cnt)
        var = (mom[..., 2] - cnt * mean * mean) / np.maximum(cnt - 1.0, 1.0)
        std = np.floor(np.sqrt(np.maximum(var, 0.0)))
        return mean, std

    cpm, cps = gauss(moments)                       # (C, Fc)
    prior_mom = moments.sum(axis=0)                 # (Fc, 3)
    cqm, cqs = gauss(prior_mom)

    if counters is not None:
        counters.increment("Distribution Data", "Feature posterior binned ",
                           int((counts > 0).sum()))
        counters.increment("Distribution Data", "Class prior", C)

    return NaiveBayesModel(
        schema=schema, class_values=class_values,
        binned_ordinals=[f.ordinal for f in binned],
        cont_ordinals=[f.ordinal for f in cont], num_bins=nbins,
        post_counts=counts, class_counts=cls_counts, prior_counts=prior,
        total=float(cls_counts.sum()),
        cont_post_mean=cpm, cont_post_std=cps,
        cont_prior_mean=cqm, cont_prior_std=cqs)


# --------------------------------------------------------------------------
# prediction
# --------------------------------------------------------------------------

class PredictionResult:
    """Per-record prediction outputs.  ``class_probs`` (used by the
    cost-arbitration branch and oracle tests), ``feature_prior_prob``,
    and ``feature_post_prob`` (BayesianPredictor.outputFeatureProb
    :276-286, feature-prob-only mode) are read back from the device
    lazily on first access — the standard predict path then ships three
    (n,) vectors instead of the full tables over the device->host link."""

    def __init__(self, pred_class: List[str], pred_prob: np.ndarray,
                 class_probs=None,
                 class_prob_diff: Optional[np.ndarray] = None,
                 feature_prior_prob=None, feature_post_prob=None,
                 n_rows: Optional[int] = None):
        self.pred_class = pred_class            # per record
        self.pred_prob = pred_prob              # (n,) int percent
        self.class_prob_diff = class_prob_diff
        self._pct = class_probs                 # (n, C) int percent, device?
        self._px = feature_prior_prob           # (n,)   P(x), maybe device
        self._pxc = feature_post_prob           # (n, C) P(x|c), maybe device
        self._n = n_rows if n_rows is not None else len(pred_class)

    def _fetch(self, attr):
        v = getattr(self, attr)
        if v is not None and not isinstance(v, np.ndarray):
            v = np.asarray(v)[:self._n]
            setattr(self, attr, v)
        return v

    @property
    def class_probs(self) -> Optional[np.ndarray]:
        return self._fetch("_pct")

    @property
    def feature_prior_prob(self) -> Optional[np.ndarray]:
        return self._fetch("_px")

    @property
    def feature_post_prob(self) -> Optional[np.ndarray]:
        return self._fetch("_pxc")


def _log(x, eps=1e-30):
    return jnp.log(jnp.clip(x, eps, None))


@jax.jit
def _predict_kernel(bc, cv, nbins_arr, log_post, log_prior, log_class,
                    cpm, cps, cqm, cqs):
    """Module-level jit (a per-call closure recompiled ~1s on EVERY predict).

    Per-feature log-prob lookups are one-hot einsums at HIGHEST precision:
    each output picks exactly ONE table value, bit-identical to the gather
    they replace — which lowered to a scalar loop on TPU and throttled
    predict to ~0.02M rows/sec."""
    # codes arrive as uint8 when every bin id fits (255 = the unknown
    # sentinel) — the ~16 MB/s host->device tunnel makes predict
    # upload-bound, so the transfer ships the narrowest dtype and decodes
    # here (TPU_NOTES.md section 5); int32 is the >=255-bin fallback
    if bc.dtype == jnp.uint8:
        bci = bc.astype(jnp.int32)
        unknown = bci == 255
    else:
        bci = bc
        unknown = bci < 0
    return _predict_body(bci, unknown, cv, nbins_arr, log_post, log_prior,
                         log_class, cpm, cps, cqm, cqs)


@functools.partial(jax.jit, static_argnums=(10,))
def _predict_kernel_packed(pk, cv, nbins_arr, log_post, log_prior,
                           log_class, cpm, cps, cqm, cqs, F):
    """_predict_kernel over the 4-bit packed wire form (bin codes two per
    byte, sentinel 15 = unknown/out-of-range): HALF the upload bytes on
    the link-bound predict path.  Usable when every feature's alphabet
    fits a nibble; a code in [nbins_f, 15) is dropped by the same
    per-field ``nbins_arr`` check as the uint8 form, so outputs are
    bit-identical."""
    bci = _unpack4(pk, F)
    unknown = bci == 15
    return _predict_body(bci, unknown, cv, nbins_arr, log_post, log_prior,
                         log_class, cpm, cps, cqm, cqs)


def _predict_body(bci, unknown, cv, nbins_arr, log_post, log_prior,
                  log_class, cpm, cps, cqm, cqs):
    C = log_post.shape[0]
    bmax = log_post.shape[2]
    Fb = bci.shape[1]
    safe = jnp.clip(bci, 0, bmax - 1)                     # (n, Fb)
    # unknown categorical or out-of-alphabet bin: skip the feature
    # entirely (contribute to neither P(x|c) nor P(x)); the reference's
    # missing-BinCount lookup degenerates to 0/0, so skipping is the
    # well-defined superset behavior.
    known = ~unknown & (bci < nbins_arr[None, :Fb])
    known_f = known.astype(jnp.float32)                   # (n, Fb)
    oh_b = jax.nn.one_hot(safe, bmax, dtype=jnp.float32)  # (n, Fb, B)
    hi_p = jax.lax.Precision.HIGHEST
    lp_post = jnp.einsum("nfb,cfb->ncf", oh_b, log_post,
                         precision=hi_p)                  # (n, C, Fb)
    lp_prior = jnp.einsum("nfb,fb->nf", oh_b, log_prior,
                          precision=hi_p)                 # (n, Fb)
    lp_post = lp_post * known_f[:, None, :]
    lp_prior = lp_prior * known_f

    # continuous gaussian log densities
    def g(x, mu, sd):
        return -0.5 * ((x - mu) / sd) ** 2 - jnp.log(sd * np.sqrt(2 * np.pi))
    lg_post = g(cv[:, None, :], cpm[None], cps[None])     # (n, C, Fc)
    lg_prior = g(cv, cqm[None], cqs[None])                # (n, Fc)
    log_px_c = lp_post.sum(axis=2) + lg_post.sum(axis=2)  # (n, C)
    log_px = lp_prior.sum(axis=1) + lg_prior.sum(axis=1)  # (n,)
    log_ratio = log_px_c + log_class[None] - log_px[:, None]
    probs = jnp.exp(log_ratio)
    pct = jnp.floor(probs * 100.0).astype(jnp.int32)      # (n, C)
    # argmax/prob/diff on device: the standard predict path then reads
    # back three (n,) vectors instead of the full (n, C) table (which
    # stays device-side for the arbitration/feature-prob modes)
    best = jnp.argmax(pct, axis=1).astype(jnp.int32)      # first-max, like np
    pred_prob = jnp.max(pct, axis=1)
    if C > 1:
        top2 = jax.lax.top_k(pct, 2)[0]
        diff = top2[:, 0] - top2[:, 1]
    else:
        diff = jnp.full(pct.shape[:1], 100, dtype=jnp.int32)
    # the three eager per-row outputs leave as ONE (3, n) array: each
    # separate readback costs a full ~62 ms tunnel round trip
    # (TPU_NOTES.md section 5), so fusing them cuts two round trips off
    # every predict call.  Same int32 values, just stacked.
    return (pct, jnp.stack([best, pred_prob, diff]),
            jnp.exp(log_px), jnp.exp(log_px_c))


def _device_model_tables(model: NaiveBayesModel, ctx: MeshContext):
    """Model probability tables resident on device: all eight small arrays
    packed into ONE f32 transfer (each separate upload costs a full
    ~62 ms tunnel round trip — TPU_NOTES.md section 5), unpacked by
    on-device slices, and cached on the model per context so chunked /
    repeated predicts re-ship nothing."""
    cached = getattr(model, "_dev_tables", None)
    if cached is not None and cached[0] is ctx:
        return cached[1]
    # pack the PROBABILITY tables and take the log on device via _log —
    # the same f32 values and XLA log op as the pre-packing path, so
    # outputs stay bit-identical (a host np.log would differ in the last
    # ulp from XLA's)
    post_p = (model.post_counts / np.maximum(
        model.class_counts[:, None, None], 1.0)).astype(np.float32)
    prior_p = (model.prior_counts / max(model.total, 1.0)).astype(np.float32)
    class_p = (model.class_counts / max(model.total, 1.0)).astype(np.float32)
    cpm = np.asarray(model.cont_post_mean, dtype=np.float32)
    cps = np.maximum(model.cont_post_std, 1e-6).astype(np.float32)
    cqm = np.asarray(model.cont_prior_mean, dtype=np.float32)
    cqs = np.maximum(model.cont_prior_std, 1e-6).astype(np.float32)
    nbins = np.asarray(model.num_bins if model.num_bins else [1],
                       dtype=np.float32)   # small ints, exact in f32
    parts = [post_p.ravel(), prior_p.ravel(), class_p.ravel(),
             cpm.ravel(), cps.ravel(), cqm.ravel(), cqs.ravel(), nbins]
    packed_host = np.concatenate(parts)
    packed = ctx.replicate(jnp.asarray(packed_host, dtype=jnp.float32))
    shapes = [post_p.shape, prior_p.shape, class_p.shape,
              cpm.shape, cps.shape, cqm.shape, cqs.shape, nbins.shape]
    arrays = []
    off = 0
    for shp in shapes:
        size = int(np.prod(shp)) if shp else 1
        arrays.append(packed[off:off + size].reshape(shp))
        off += size
    tables = (_log(arrays[0]), _log(arrays[1]), _log(arrays[2]),
              arrays[3], arrays[4], arrays[5], arrays[6],
              jnp.round(arrays[7]).astype(jnp.int32))
    model.__dict__["_dev_tables"] = (ctx, tables)
    return tables


def predict(model: NaiveBayesModel, table: ColumnarTable,
            ctx: Optional[MeshContext] = None) -> PredictionResult:
    """Per-record class posterior integer percents
    (BayesianPredictor.predictClassValue :396-419).

    classPostProb = (int)(P(x|c)·P(c)/P(x) · 100) with
    P(x|c) = Π_f post[c,f,bin_f]/classCount_c (Gaussian density for
    continuous), P(x) = Π_f prior[f,bin_f]/total.
    """
    ctx = ctx or runtime_context()
    schema = model.schema
    binned_fields = [schema.find_field_by_ordinal(o) for o in model.binned_ordinals]
    cont_fields = [schema.find_field_by_ordinal(o) for o in model.cont_ordinals]

    padded = table.pad_to_multiple(ctx.n_devices)
    (log_post, log_prior, log_class,
     cpm, cps, cqm, cqs, nbins_arr) = _device_model_tables(model, ctx)

    # column-at-a-time into preallocated wire matrices (same shape of fix
    # as train(): the stacked (n, F) intermediates measured tens of
    # seconds at 100M rows).  NOTE the sentinel rule here deliberately
    # differs from train's narrow(): uint8 transfer keeps any code in
    # [0, 255) and maps unknown (-1) and >= 255 to the 255 skip sentinel
    # — per-field out-of-alphabet drops happen in the kernel via
    # nbins_arr, and an unclamped bucketed value would otherwise WRAP
    # into a valid bin id under uint8 and poison the lookup.
    max_bins = max(model.num_bins) if model.num_bins else 0
    u8 = max_bins < 255
    # 4-bit packed upload when every alphabet fits a nibble (sentinel 15;
    # same auto-gate + env override as train(): the nibble pass is host
    # cost that only pays for itself across a real device link).  A code
    # in [nbins_f, 15) survives the pack and is dropped by the kernel's
    # per-field nbins check exactly like the uint8 form.
    env_pack4 = os.environ.get("AVENIR_TPU_WIRE_PACK4", "auto")
    pack4 = (max_bins <= 15 and env_pack4 != "0"
             and (env_pack4 == "1" or ctx.device_platform != "cpu"))
    Fb = len(binned_fields)
    if pack4:
        pk_host = np.zeros((padded.n_rows, (Fb + 1) // 2), dtype=np.uint8)
        for j, f in enumerate(binned_fields):
            codes = padded.binned_codes(f.ordinal)
            col = np.where((codes < 0) | (codes >= 15), 15,
                           codes).astype(np.uint8)
            pk_host[:, j // 2] |= (col << 4) if j % 2 == 0 else col
    else:
        bin_codes = np.empty((padded.n_rows, Fb),
                             dtype=np.uint8 if u8 else np.int32)
        for j, f in enumerate(binned_fields):
            codes = padded.binned_codes(f.ordinal)
            if u8:
                codes = np.where((codes < 0) | (codes >= 255), 255, codes)
            bin_codes[:, j] = codes
    cont_vals = np.empty((padded.n_rows, len(cont_fields)),
                         dtype=np.float32)
    for j, f in enumerate(cont_fields):
        # reference parses continuous values as integers (long)
        cont_vals[:, j] = np.trunc(padded.columns[f.ordinal])
    cv = ctx.shard_rows(cont_vals)

    if pack4:
        pct_dev, eager_dev, px_dev, pxc_dev = _predict_kernel_packed(
            ctx.shard_rows(pk_host), cv, nbins_arr, log_post, log_prior,
            log_class, cpm, cps, cqm, cqs, Fb)
    else:
        pct_dev, eager_dev, px_dev, pxc_dev = _predict_kernel(
            ctx.shard_rows(bin_codes), cv, nbins_arr, log_post, log_prior,
            log_class, cpm, cps, cqm, cqs)
    # only the fused (3, n) int32 block crosses the link eagerly (ONE
    # round trip); the full (n, C) percent table and raw feature
    # probabilities stay device-side until the arbitration /
    # feature-prob-only modes ask for them.  The device
    # argmax/max/top-2-diff match np.argmax (first max) and the
    # np.sort-based diff (defaultArbitrate :345-365) exactly on ints
    eager = np.asarray(eager_dev)[:, :table.n_rows]
    best, pred_prob, diff = eager[0], eager[1], eager[2]
    pred_class = [model.class_values[i] for i in best]
    return PredictionResult(pred_class=pred_class, pred_prob=pred_prob,
                            class_probs=pct_dev, class_prob_diff=diff,
                            feature_prior_prob=px_dev,
                            feature_post_prob=pxc_dev,
                            n_rows=table.n_rows)


def evaluate(model: NaiveBayesModel, table: ColumnarTable,
             result: PredictionResult,
             neg_class: Optional[str] = None, pos_class: Optional[str] = None,
             counters: Optional[Counters] = None) -> ConfusionMatrix:
    """Validation-mode confusion matrix export (BayesianPredictor.cleanup
    :170-180)."""
    if neg_class is None or pos_class is None:
        card = model.class_values
        neg_class, pos_class = card[0], card[1]
    cm = ConfusionMatrix(neg_class, pos_class)
    actual_codes = table.class_codes()
    actual = [model.class_values[c] if c >= 0 else "?" for c in actual_codes]
    for p, a in zip(result.pred_class, actual):
        cm.report(p, a)
    if counters is not None:
        cm.export(counters)
    return cm
