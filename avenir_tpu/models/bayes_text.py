"""Text-mode Naive Bayes (the reference's Lucene-analyzed text path of
BayesianDistribution: when no schema file is configured the input is
``text,classLabel`` lines and the single feature is the token stream —
bayesian/BayesianDistribution.java:124-130 setup, :186-195 mapText).

TPU design: tokens become vocabulary codes host-side; counting is the same
device one-hot contraction as the tabular path over the flattened
(doc -> token) arrays, and scoring is a gather of per-token class log-probs
summed per document with a segment reduction — both static-shape programs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..text.wordcount import STANDARD_STOPWORDS, tokenize

TEXT_FEATURE_ORDINAL = 1  # featureAttrOrdinal in text mode (:126)


@dataclass
class TextBayesModel:
    class_values: List[str]
    vocab: List[str]                 # token id -> token
    token_counts: np.ndarray         # (C, V) float
    class_counts: np.ndarray         # (C,) docs per class

    # ---- model CSV (same layout as the tabular model: class, ord, bin, count
    #      with the token string as the bin label) ----
    def to_lines(self, delim: str = ",") -> List[str]:
        lines = []
        for ci, cv in enumerate(self.class_values):
            lines.append(f"{cv}{delim}{delim}{delim}{int(self.class_counts[ci])}")
        for ci, cv in enumerate(self.class_values):
            for ti, tok in enumerate(self.vocab):
                c = int(self.token_counts[ci, ti])
                if c > 0:
                    lines.append(f"{cv}{delim}{TEXT_FEATURE_ORDINAL}{delim}"
                                 f"{tok}{delim}{c}")
        return lines

    @classmethod
    def from_lines(cls, lines: Sequence[str], delim: str = ","
                   ) -> "TextBayesModel":
        class_counts: Dict[str, int] = {}
        token_counts: Dict[Tuple[str, str], int] = {}
        vocab_set = {}
        for line in lines:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            items = line.split(delim)
            if items[1] == "" and items[2] == "":
                class_counts[items[0]] = int(items[3])
            elif items[0] != "":
                tok = items[2]
                token_counts[(items[0], tok)] = int(items[3])
                vocab_set.setdefault(tok, len(vocab_set))
        class_values = sorted(class_counts)
        vocab = sorted(vocab_set, key=vocab_set.get)
        tc = np.zeros((len(class_values), len(vocab)))
        for (cv, tok), n in token_counts.items():
            tc[class_values.index(cv), vocab_set[tok]] = n
        return cls(class_values=class_values, vocab=vocab, token_counts=tc,
                   class_counts=np.array([class_counts[c]
                                          for c in class_values], dtype=float))


def _flatten(docs_tokens: List[List[int]]) -> Tuple[np.ndarray, np.ndarray]:
    """(token_codes, doc_ids) flattened over all documents."""
    codes = np.fromiter((t for doc in docs_tokens for t in doc),
                        dtype=np.int32)
    doc_ids = np.fromiter((i for i, doc in enumerate(docs_tokens)
                           for _ in doc), dtype=np.int32)
    return codes, doc_ids


def train_text(lines: Sequence[str], delim: str = ",",
               stopwords: frozenset = STANDARD_STOPWORDS) -> TextBayesModel:
    """Count (class, token) occurrences over ``text<delim>class`` lines (the
    text-mode mapper/reducer collapsed into one one-hot contraction)."""
    texts, labels = [], []
    for line in lines:
        line = line.rstrip("\n")
        if not line.strip():
            continue
        text, _, label = line.rpartition(delim)
        texts.append(text)
        labels.append(label.strip())
    class_values = sorted(set(labels))
    cls_index = {c: i for i, c in enumerate(class_values)}
    vocab: Dict[str, int] = {}
    docs_tokens: List[List[int]] = []
    for t in texts:
        toks = tokenize(t, stopwords)
        docs_tokens.append([vocab.setdefault(tok, len(vocab)) for tok in toks])
    V, C = max(len(vocab), 1), len(class_values)
    codes, doc_ids = _flatten(docs_tokens)
    tok_cls = np.array([cls_index[labels[d]] for d in doc_ids], dtype=np.int32)
    # same device kernel as the tabular path: counts[c, v]
    combined = jnp.asarray(tok_cls) * V + jnp.asarray(codes)
    counts = jax.jit(
        lambda x: jnp.zeros((C * V,), jnp.float32).at[x].add(1.0)
    )(combined).reshape(C, V)
    class_counts = np.bincount([cls_index[l] for l in labels], minlength=C)
    inv = [None] * len(vocab)
    for tok, i in vocab.items():
        inv[i] = tok
    return TextBayesModel(class_values=class_values, vocab=inv,
                          token_counts=np.asarray(counts),
                          class_counts=class_counts.astype(float))


def classify_text(model: TextBayesModel, texts: Sequence[str],
                  laplace: float = 1.0,
                  stopwords: frozenset = STANDARD_STOPWORDS
                  ) -> Tuple[List[str], np.ndarray]:
    """(predicted labels, (n, C) class log-posteriors): per-token class
    log-probs gathered and segment-summed per document."""
    C, V = model.token_counts.shape
    vocab_index = {t: i for i, t in enumerate(model.vocab)}
    docs_tokens = [[vocab_index[t] for t in tokenize(x, stopwords)
                    if t in vocab_index] for x in texts]
    codes, doc_ids = _flatten(docs_tokens)
    totals = model.token_counts.sum(axis=1, keepdims=True)
    log_post = np.log((model.token_counts + laplace)
                      / (totals + laplace * V))             # (C, V)
    log_prior = np.log(np.maximum(model.class_counts, 1e-12)
                       / max(model.class_counts.sum(), 1.0))
    n = len(texts)
    if len(codes):
        per_token = jnp.asarray(log_post)[:, jnp.asarray(codes)]   # (C, T)
        sums = jax.vmap(lambda row: jax.ops.segment_sum(
            row, jnp.asarray(doc_ids), num_segments=n))(per_token)  # (C, n)
        scores = np.asarray(sums).T + log_prior[None, :]
    else:
        scores = np.tile(log_prior, (n, 1))
    pred = [model.class_values[i] for i in np.argmax(scores, axis=1)]
    return pred, scores
