"""Candidate-split scoring + physical data partitioning.

Parity targets (SURVEY.md §2.1, §2.4):
  * ClassPartitionGenerator (explore/ClassPartitionGenerator.java) — per
    (attribute, candidate split): weighted info stat under 4 criteria
    (util/AttributeSplitStat.java:40-43) and, for entropy/gini, the gain
    ratio vs a supplied parent info (reducer :515-548); at root (no
    cpg.split.attributes) emits the dataset's single info content value.
  * SplitGenerator (tree/SplitGenerator.java:31) — same job with
    tree-pipeline path conventions.
  * DataPartitioner (tree/DataPartitioner.java) — picks the best (or
    random-from-top) candidate split from the splits file (sorted descending
    by score, :157-201) and routes every record to its split segment,
    materializing ``split=<i>/segment=<j>/data/partition.txt`` (:102-128).

Split-key string formats (util/AttributeSplitHandler.java:130-245):
  numeric      ``30:60``            (split points; segment = #points past)
  categorical  ``[a, b]:[c]``       (value groups; segment = group index)

TPU design: all candidate splits are evaluated in ONE device pass — branch
codes for every (record, split) via SplitSet (vectorized predicates), then a
(split, segment, class) histogram by one-hot contraction; the 4 criteria are
closed-form reductions over that histogram.  The reference walks predicates
per record per split in the mapper and shuffles per (split, segment).

NaN guard: the reference's classConfidenceRatio produces NaN when a segment
has zero count for some class (0 * log 0); we evaluate the intended limit 0.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.schema import FeatureField, FeatureSchema
from ..core.table import ColumnarTable
from .tree import CandidateSplit, SplitSet, generate_candidate_splits, _info

ALG_ENTROPY = "entropy"
ALG_GINI = "giniIndex"
ALG_HELLINGER = "hellingerDistance"
ALG_CLASS_CONF = "classConfidenceRatio"


# --------------------------------------------------------------------------
# split-key formatting
# --------------------------------------------------------------------------

def split_key(split: CandidateSplit) -> str:
    """Reference split-key string for a candidate split."""
    if split.groups is not None:
        return ":".join("[" + ", ".join(g) + "]" for g in split.groups)
    return ":".join(_fmt_num(t) for t in split.thresholds)


def _fmt_num(t: float) -> str:
    return str(int(t)) if float(t).is_integer() else str(t)


def parse_split_key(field: FeatureField, key: str):
    """Returns (segment_fn, n_segments): segment_fn maps a raw string column
    -> int segment indices (AttributeSplitHandler Integer/CategoricalSplit
    .getSegmentIndex)."""
    if field.is_categorical:
        groups = []
        for part in key.split(":"):
            part = part.strip()
            if not (part.startswith("[") and part.endswith("]")):
                raise ValueError(f"bad categorical split key {key!r}")
            groups.append([v.strip() for v in part[1:-1].split(",")])
        value_to_seg = {v: i for i, g in enumerate(groups) for v in g}

        def seg_cat(col: np.ndarray) -> np.ndarray:
            out = np.empty(len(col), dtype=np.int32)
            for i, v in enumerate(col):
                try:
                    out[i] = value_to_seg[str(v)]
                except KeyError:
                    raise ValueError(f"split segment not found for {v!r}")
            return out

        return seg_cat, len(groups)

    points = np.asarray([float(p) for p in key.split(":")])

    def seg_num(col: np.ndarray) -> np.ndarray:
        vals = col.astype(np.float64)
        return (vals[:, None] > points[None, :]).sum(axis=1).astype(np.int32)

    return seg_num, len(points) + 1


# --------------------------------------------------------------------------
# split statistics over the (split, segment, class) histogram
# --------------------------------------------------------------------------

def split_histograms(table: ColumnarTable, splits: List[CandidateSplit],
                     chunk: int = 1 << 20) -> np.ndarray:
    """(S, B, C) class counts per split segment — one one-hot contraction
    per row chunk (replaces the reference's per-record mapper emit +
    shuffle count)."""
    schema = table.schema
    sset = SplitSet(splits, schema)
    X = sset.feature_matrix(table)
    cls = table.class_codes()
    C = len(schema.class_attr_field.cardinality or [])
    B = sset.max_branches
    S = sset.n_splits
    out = np.zeros((S, B, C), dtype=np.float64)
    for lo in range(0, table.n_rows, chunk):
        xb = jnp.asarray(X[lo:lo + chunk])
        cb = cls[lo:lo + chunk]
        codes = np.asarray(sset.branch_codes(xb))          # (n, S)
        oh_cls = np.zeros((len(cb), C), dtype=np.float32)
        valid = cb >= 0
        oh_cls[np.arange(len(cb))[valid], cb[valid]] = 1.0
        oh_branch = (codes[:, :, None] ==
                     np.arange(B)[None, None, :]).astype(np.float32)
        out += np.einsum("nsb,nc->sbc", oh_branch, oh_cls,
                         optimize=True).astype(np.float64)
    return out


def _weighted_info(counts: np.ndarray, algo: str) -> float:
    """Population-weighted entropy/gini over segments
    (AttributeSplitStat.SplitInfoContent.processStat)."""
    seg_tot = counts.sum(axis=-1)                          # (B,)
    stats = _info(counts, algo, axis=-1)                   # (B,)
    total = seg_tot.sum()
    return float((stats * seg_tot).sum() / max(total, 1e-12))


def _hellinger(counts: np.ndarray) -> float:
    """sqrt(sum_seg (sqrt(n_s0/N0) - sqrt(n_s1/N1))^2)
    (AttributeSplitStat.SplitHellingerDistance.processStat)."""
    if counts.shape[-1] != 2:
        raise ValueError("Hellinger distance algorithm is only valid for "
                         "binary valued class attributes")
    class_tot = counts.sum(axis=0)                         # (2,)
    frac = counts / np.maximum(class_tot[None, :], 1e-12)  # (B, 2)
    d = np.sqrt(frac[:, 0]) - np.sqrt(frac[:, 1])
    return float(np.sqrt((d * d).sum()))


def _class_conf_ratio(counts: np.ndarray) -> float:
    """Weighted entropy of per-segment class-confidence ratios
    (AttributeSplitStat.SplitClassCofidenceRatio + SplitStatSegment
    .processClassConfidenceRatio)."""
    class_tot = counts.sum(axis=0)                         # (C,)
    conf = counts / np.maximum(class_tot[None, :], 1e-12)  # (B, C)
    conf_sum = conf.sum(axis=1, keepdims=True)
    ratio = conf / np.maximum(conf_sum, 1e-12)
    with np.errstate(divide="ignore", invalid="ignore"):
        logr = np.where(ratio > 0, np.log2(np.maximum(ratio, 1e-300)), 0.0)
    ent = -(ratio * logr).sum(axis=1)                      # (B,)
    seg_tot = counts.sum(axis=-1)
    total = seg_tot.sum()
    return float((ent * seg_tot).sum() / max(total, 1e-12))


def _intrinsic_value(counts: np.ndarray) -> float:
    """Entropy of the segment-population distribution
    (AttributeSplitStat.SplitStat.getInfoContent)."""
    seg_tot = counts.sum(axis=-1)
    return float(_info(seg_tot[None, :], "entropy", axis=-1)[0])


def split_stat(counts: np.ndarray, n_branches: int, algo: str) -> float:
    """One split's stat under the chosen criterion; ``counts`` is (B, C)
    with only the first ``n_branches`` rows meaningful."""
    counts = counts[:n_branches]
    if algo in (ALG_ENTROPY, ALG_GINI):
        return _weighted_info(counts, algo)
    if algo == ALG_HELLINGER:
        return _hellinger(counts)
    if algo == ALG_CLASS_CONF:
        return _class_conf_ratio(counts)
    raise ValueError(f"unknown split algorithm {algo!r}")


def root_info(table: ColumnarTable, algo: str) -> float:
    """Dataset-level info content — the root-mode output
    (ClassPartitionGenerator reducer :515-519)."""
    cls = table.class_codes()
    C = len(table.schema.class_attr_field.cardinality or [])
    counts = np.bincount(cls[cls >= 0], minlength=C).astype(np.float64)
    return float(_info(counts[None, :], algo, axis=-1)[0])


@dataclass
class ScoredSplit:
    attr: int
    key: str
    score: float        # gainRatio for entropy/gini; raw stat otherwise
    n_segments: int

    def to_line(self, delim: str = ",") -> str:
        return f"{self.attr}{delim}{self.key}{delim}{self.score:.9g}"


def score_candidate_splits(table: ColumnarTable, attrs: Sequence[int],
                           algo: str, parent_info: float
                           ) -> List[ScoredSplit]:
    """All candidate splits of the given attributes, scored.  For
    entropy/gini the emitted score is gainRatio = (parentInfo - stat) /
    intrinsicValue (reducer :536-538); other criteria emit the stat."""
    splits = generate_candidate_splits(table.schema, attrs)
    if not splits:
        return []
    hists = split_histograms(table, splits)
    out: List[ScoredSplit] = []
    for si, s in enumerate(splits):
        counts = hists[si]
        stat = split_stat(counts, s.n_branches, algo)
        if algo in (ALG_ENTROPY, ALG_GINI):
            iv = _intrinsic_value(counts[:s.n_branches])
            score = (parent_info - stat) / iv if iv > 0 else 0.0
        else:
            score = stat
        out.append(ScoredSplit(s.attr, split_key(s), score, s.n_branches))
    return out


# --------------------------------------------------------------------------
# data partitioning by a chosen split
# --------------------------------------------------------------------------

@dataclass
class ChosenSplit:
    index: int          # line index in the candidate file
    attr: int
    key: str
    score: float
    n_segments: int


def choose_split(lines: Sequence[str], schema: FeatureSchema,
                 strategy: str = "best", num_top: int = 5,
                 seed: Optional[int] = None,
                 delim: str = ";") -> ChosenSplit:
    """Pick from the candidate-splits file: descending score, 'best' takes
    the top, 'randomFromTop' a uniform pick among the first num_top
    (DataPartitioner.java:157-201)."""
    parsed = []
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        items = line.split(delim)
        parsed.append((i, int(items[0]), items[1], float(items[2])))
    if not parsed:
        raise ValueError("empty candidate splits file")
    parsed.sort(key=lambda t: -t[3])
    idx = 0
    if strategy == "randomFromTop":
        rng = np.random.default_rng(seed)
        idx = int(rng.integers(0, min(num_top, len(parsed))))
    i, attr, key, score = parsed[idx]
    field = schema.find_field_by_ordinal(attr)
    _, n_seg = parse_split_key(field, key)
    return ChosenSplit(i, attr, key, score, n_seg)


def partition_rows(raw_lines: Sequence[str], schema: FeatureSchema,
                   chosen: ChosenSplit, delim_regex: str = ","
                   ) -> List[List[str]]:
    """Route every input line to its split segment (PartitionerMapper
    :324-337); returns per-segment line lists (the reducer's part files)."""
    field = schema.find_field_by_ordinal(chosen.attr)
    seg_fn, n_seg = parse_split_key(field, chosen.key)
    pat = re.compile(delim_regex)
    lit = re.escape(delim_regex) == delim_regex
    vals = []
    kept = []
    for line in raw_lines:
        line = line.rstrip("\n")
        if not line:
            continue
        items = line.split(delim_regex) if lit else pat.split(line)
        vals.append(items[chosen.attr])
        kept.append(line)
    segs = seg_fn(np.asarray(vals, dtype=object)) if kept else np.array([])
    out: List[List[str]] = [[] for _ in range(n_seg)]
    for line, s in zip(kept, segs):
        out[int(s)].append(line)
    return out
