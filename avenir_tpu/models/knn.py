"""K nearest neighbor: batched top-k classification / regression.

Capability parity with org.avenir.knn (SURVEY.md §2.3, call stack §3.4):

  * top-k neighbors per test record == the secondary-sorted shuffle +
    reducer truncation (knn/NearestNeighbor.java:80-81, 345-349), here a
    single ``lax.top_k`` over the distance matrix;
  * kernels none / linearMultiplicative / linearAdditive / gaussian with the
    reference's integer score arithmetic (knn/Neighborhood.java:150-200:
    KERNEL_SCALE=100, d==0 -> 2*scale, integer division for
    linearMultiplicative); the reference's 'sigmoid' branch is an empty stub
    (:195) — we raise instead of silently classifying nothing;
  * class-conditional probability weighting (score x featurePostProb,
    optional x 1/distance — Neighborhood.Neighbor.setScore :393-403);
  * decision threshold on pos/neg score ratio (:272-290) and cost-based
    arbitration via integer class probability (:300-320,
    NearestNeighbor.java:383-387);
  * KNN regression: average / median / per-test-record simple linear
    regression (Neighborhood.doRegression :223-250) vectorized closed-form.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.metrics import CostBasedArbitrator

KERNEL_SCALE = 100
PROB_SCALE = 100
# sentinel distance for ragged per-test neighbor lists (rows padded to the
# max candidate count); entries at/above it contribute nothing
PAD_DISTANCE = 1 << 30


@dataclass
class KnnParams:
    """The nen.* knobs (resource/knn.properties)."""
    top_match_count: int = 10
    kernel_function: str = "none"    # none|linearMultiplicative|linearAdditive|gaussian
    kernel_param: int = -1
    class_cond_weighted: bool = False
    inverse_distance_weighted: bool = False
    decision_threshold: float = -1.0
    pos_class: Optional[str] = None
    neg_class: Optional[str] = None
    use_cost_based_classifier: bool = False
    false_pos_cost: int = 1
    false_neg_cost: int = 1
    prediction_mode: str = "classification"   # classification | regression
    regression_method: str = "average"        # average|median|linearRegression


def kernel_scores(distances: jnp.ndarray, kernel: str,
                  kernel_param: int) -> jnp.ndarray:
    """Integer neighbor scores per the reference kernels (d is the scaled int
    distance)."""
    d = distances.astype(jnp.int32)
    if kernel == "none":
        return jnp.ones_like(d)
    if kernel == "linearMultiplicative":
        return jnp.where(d == 0, 2 * KERNEL_SCALE,
                         KERNEL_SCALE // jnp.maximum(d, 1))
    if kernel == "linearAdditive":
        return KERNEL_SCALE - d
    if kernel == "gaussian":
        t = d.astype(jnp.float32) / float(kernel_param)
        return (KERNEL_SCALE * jnp.exp(-0.5 * t * t)).astype(jnp.int32)
    if kernel == "sigmoid":
        raise NotImplementedError(
            "kernel 'sigmoid' is an empty stub in the reference "
            "(knn/Neighborhood.java:195) and is not supported")
    raise ValueError(f"unknown kernel function {kernel!r}")


@dataclass
class KnnResult:
    pred_class: Optional[List[str]] = None           # classification
    pred_value: Optional[np.ndarray] = None          # regression (int)
    class_distr: Optional[np.ndarray] = None         # (n, C) int scores
    weighted_class_distr: Optional[np.ndarray] = None  # (n, C) float
    pos_class_prob: Optional[np.ndarray] = None      # (n,) int percent


@functools.partial(jax.jit, static_argnums=3)
def _topk_kernel(d, cls, fpp, k):
    """Module-level jit (per-call closures recompiled on every classify)."""
    neg_topv, idx = jax.lax.top_k(-d, k)
    return -neg_topv, cls[idx], fpp[idx]


@functools.partial(jax.jit, static_argnames=("kernel_function",
                                             "kernel_param", "C",
                                             "inverse_distance_weighted"))
def _distr_kernel(nd, ncls, nfpp, kernel_function, kernel_param, C,
                  inverse_distance_weighted):
    """Neighbor scores -> (class_distr, weighted) per test row; module-level
    jit keyed on the scalar knobs."""
    valid = nd < PAD_DISTANCE
    scores = kernel_scores(nd, kernel_function, kernel_param)
    scores = scores * valid.astype(scores.dtype)
    oh = jax.nn.one_hot(ncls, C, dtype=jnp.int32)   # (n, k, C)
    class_distr = (scores[:, :, None] * oh).sum(axis=1)     # (n, C)
    wscores = jnp.where(nfpp > 0, scores * nfpp, scores.astype(jnp.float32))
    if inverse_distance_weighted:
        wscores = wscores / jnp.maximum(nd.astype(jnp.float32), 1e-9)
    weighted = (wscores[:, :, None] * oh.astype(jnp.float32)).sum(axis=1)
    return class_distr, weighted


def classify(distances: np.ndarray,            # (n_test, n_train) int
             train_classes: np.ndarray,        # (n_train,) int codes
             class_values: Sequence[str],
             params: KnnParams,
             feature_post_prob: Optional[np.ndarray] = None,  # (n_train,)
             ) -> KnnResult:
    """Vectorized Neighborhood over a SHARED train set: every test row draws
    neighbors from the same train vectors."""
    fpp = feature_post_prob if feature_post_prob is not None else \
        np.full((distances.shape[1],), -1.0, dtype=np.float32)
    k = min(params.top_match_count, distances.shape[1])
    nd, ncls, nfpp = (np.asarray(x) for x in _topk_kernel(
        jnp.asarray(distances), jnp.asarray(train_classes),
        jnp.asarray(fpp, dtype=jnp.float32), k))
    return _classify_topk(nd, ncls, nfpp, class_values, params)


def classify_topk(nd: np.ndarray, ncls: np.ndarray,
                  class_values: Sequence[str], params: KnnParams,
                  fpp: Optional[np.ndarray] = None) -> KnnResult:
    """Classify from already-selected top-k neighbors per test row (the
    public entry for fused device pipelines: ops/distance.pairwise_topk
    feeds (distances, neighbor class codes) straight in, no all-pairs
    matrix)."""
    if fpp is None:
        fpp = np.full(nd.shape, -1.0, dtype=np.float32)
    return _classify_topk(nd, ncls, fpp, class_values, params)


def _topk_rows(dmat: np.ndarray, k: int, *mats: Optional[np.ndarray]):
    """Stable nearest-k selection within each row; returns (nd, gathered mats)
    where a None mat stays None."""
    k = min(k, dmat.shape[1])
    idx = np.argsort(dmat, axis=1, kind="stable")[:, :k]
    nd = np.take_along_axis(dmat, idx, axis=1)
    out = [np.take_along_axis(m, idx, axis=1) if m is not None else None
           for m in mats]
    return (nd, *out)


def classify_grouped(dmat: np.ndarray, cmat: np.ndarray,
                     class_values: Sequence[str], params: KnnParams,
                     fmat: Optional[np.ndarray] = None) -> KnnResult:
    """Per-row neighbor lists (the NearestNeighbor job's input layout, where
    each test entity carries its own candidate set): top-k within each row."""
    nd, ncls, nfpp = _topk_rows(dmat, params.top_match_count, cmat, fmat)
    if nfpp is None:
        nfpp = np.full_like(nd, -1.0, dtype=np.float32)
    return _classify_topk(nd, ncls, nfpp, class_values, params)


def _classify_topk(nd: np.ndarray, ncls: np.ndarray, nfpp: np.ndarray,
                   class_values: Sequence[str], params: KnnParams) -> KnnResult:
    """Kernel scores -> per-class sums -> classify/arbitrate, given the
    already-selected top-k neighbors per test row."""
    C = len(class_values)
    if params.kernel_function == "sigmoid":
        raise NotImplementedError(
            "kernel 'sigmoid' is an empty stub in the reference "
            "(knn/Neighborhood.java:195) and is not supported")
    if params.kernel_function not in ("none", "linearMultiplicative",
                                      "linearAdditive", "gaussian"):
        raise ValueError(f"unknown kernel function {params.kernel_function!r}")

    class_distr, weighted = (np.asarray(x) for x in _distr_kernel(
        jnp.asarray(nd.astype(np.int32)), jnp.asarray(ncls),
        jnp.asarray(nfpp, dtype=jnp.float32),
        kernel_function=params.kernel_function,
        kernel_param=params.kernel_param, C=C,
        inverse_distance_weighted=params.inverse_distance_weighted))

    if params.prediction_mode == "regression":
        vals = np.asarray(
            [[float(class_values[c]) for c in row] for row in ncls])
        return KnnResult(pred_value=_regress(vals, nd, params,
                                             valid=nd < PAD_DISTANCE))

    cls_index = {v: i for i, v in enumerate(class_values)}
    if params.class_cond_weighted:
        best = np.argmax(weighted, axis=1)
        pred = [class_values[b] for b in best]
        totals = weighted.sum(axis=1)
        pos_prob = None
        if params.pos_class is not None:
            pi = cls_index[params.pos_class]
            pos_prob = ((weighted[:, pi] * PROB_SCALE) /
                        np.maximum(totals, 1e-12)).astype(np.int32)
    else:
        pos_prob = None
        if params.pos_class is not None:
            pi = cls_index[params.pos_class]
            totals = class_distr.sum(axis=1)
            pos_prob = ((class_distr[:, pi] * PROB_SCALE) //
                        np.maximum(totals, 1)).astype(np.int32)
        if params.decision_threshold > 0:
            pi = cls_index[params.pos_class]
            ni = cls_index[params.neg_class]
            with np.errstate(divide="ignore"):
                ratio = class_distr[:, pi] / np.maximum(class_distr[:, ni], 1e-12)
            pred = [params.pos_class if r > params.decision_threshold
                    else params.neg_class for r in ratio]
        else:
            best = np.argmax(class_distr, axis=1)
            pred = [class_values[b] for b in best]

    if params.use_cost_based_classifier:
        arb = CostBasedArbitrator(params.neg_class, params.pos_class,
                                  params.false_neg_cost, params.false_pos_cost)
        pred = [arb.classify(int(p)) for p in pos_prob]

    return KnnResult(pred_class=pred, class_distr=class_distr,
                     weighted_class_distr=weighted, pos_class_prob=pos_prob)


def _regress(vals: np.ndarray, dists: np.ndarray, params: KnnParams,
             regr_input: Optional[np.ndarray] = None,
             neighbor_input: Optional[np.ndarray] = None,
             valid: Optional[np.ndarray] = None) -> np.ndarray:
    """Regression over neighbor values (integer results like the reference,
    which divides by neighbors.size() — the count of REAL neighbors).
    ``valid`` masks ragged-padding entries out of every statistic."""
    v = valid if valid is not None else np.ones(vals.shape, dtype=bool)
    cnt = np.maximum(v.sum(axis=1), 1)
    if params.regression_method == "average":
        return ((vals * v).sum(axis=1) / cnt).astype(np.int64)
    if params.regression_method == "median":
        out = np.zeros((vals.shape[0],), dtype=np.int64)
        for i in range(vals.shape[0]):
            s = np.sort(vals[i][v[i]]).astype(np.int64)
            mid = len(s) // 2
            out[i] = s[mid] if len(s) % 2 == 1 else (s[mid - 1] + s[mid]) // 2
        return out
    if params.regression_method == "linearRegression":
        # per-test-row simple regression y ~ x over neighbors
        # (Neighborhood.doRegression :241-246, SimpleRegression closed form),
        # evaluated at the test record's regression input var
        if neighbor_input is None:
            raise ValueError(
                "linearRegression requires per-neighbor regression input "
                "values (the trainRegrNumFld column of the reference layout)")
        x = np.where(v, neighbor_input, 0.0).astype(np.float64)
        y = np.where(v, vals, 0.0)
        xm = (x.sum(axis=1) / cnt)[:, None]
        ym = (y.sum(axis=1) / cnt)[:, None]
        cov = (((x - xm) * (y - ym)) * v).sum(axis=1)
        var = (((x - xm) ** 2) * v).sum(axis=1)
        slope = np.where(var > 0, cov / np.maximum(var, 1e-12), 0.0)
        intercept = ym[:, 0] - slope * xm[:, 0]
        x0 = regr_input if regr_input is not None else np.zeros(len(slope))
        return (intercept + slope * x0).astype(np.int64)
    raise ValueError(f"unknown regression method {params.regression_method!r}")


def regress_grouped(dmat: np.ndarray, vals: np.ndarray, params: KnnParams,
                    regr_input: Optional[np.ndarray] = None,
                    neighbor_input: Optional[np.ndarray] = None) -> np.ndarray:
    """KNN regression over per-row neighbor lists: top-k then _regress.
    ``vals`` (n, m) neighbor target values; PAD_DISTANCE rows are masked."""
    nd, nv, ni = _topk_rows(dmat, params.top_match_count,
                            vals.astype(np.float64), neighbor_input)
    return _regress(nv, nd, params, regr_input=regr_input, neighbor_input=ni,
                    valid=nd < PAD_DISTANCE)


def regress(distances: np.ndarray, train_values: np.ndarray, params: KnnParams,
            regr_input: Optional[np.ndarray] = None,
            train_regr_input: Optional[np.ndarray] = None) -> np.ndarray:
    """KNN regression over a shared train set: top-k then _regress."""
    n_train = distances.shape[1]
    vals = np.broadcast_to(train_values.astype(np.float64),
                           (distances.shape[0], n_train))
    ni = np.broadcast_to(train_regr_input, distances.shape) \
        if train_regr_input is not None else None
    return regress_grouped(distances, vals, params, regr_input=regr_input,
                           neighbor_input=ni)
