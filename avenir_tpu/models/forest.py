"""Random forest + the generic prediction engine.

Parity targets (SURVEY.md §2.1 'Random Forest' + §2.8 model package):

  * RF in the reference is not a class: it is DecisionTreeBuilder configured
    with bootstrap sampling + random attribute subsets + randomAmongTop split
    choice (resource/rafo.properties:15-17), re-run once per tree by the
    driver script (resource/rafo.sh:34-43).  Here ``build_forest`` runs the
    whole ensemble: per-tree bootstrap weights, per-tree RNG, same TreeParams
    knobs.
  * ``EnsembleModel``   == model/EnsemblePredictiveModel.java:69-113 —
    weighted majority vote, min-odds-ratio veto (ambiguous -> None).
  * ``model_predictor`` == model/ModelPredictor.java:46-82 — map-only job
    loading N model files, output modes withRecord / withKId /
    withActualClassAttr, optional error counting.

TPU design: each tree reuses the TreeBuilder level kernels over the same
device-resident feature/branch arrays (encoded once); only the per-record
bootstrap weights and the host-side random choices differ per tree.
Ensemble prediction batches all trees' paths into one pass per tree and
reduces votes as arrays.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..core.metrics import Counters
from ..parallel.mesh import MeshContext
from .tree import (DecisionPath, DecisionPathList, DecisionTreeModel,
                   Predicate, TreeBuilder, TreeParams, sampling_weights)


@dataclass
class ForestParams:
    tree: TreeParams = dc_field(default_factory=lambda: TreeParams(
        attr_select_strategy="randomNotUsedYet",
        split_select_strategy="randomAmongTop",
        sub_sampling="withReplace", sub_sampling_rate=90.0))
    num_trees: int = 5
    seed: int = 0


@functools.lru_cache(maxsize=None)
def _jitted_forest_count_kernel(S: int, B: int, C: int):
    """Tree-batched level histogram (SURVEY.md §7.4 'RF = vmap over trees'):
    one einsum advances ALL trees one level.  Row-leading layout so the
    existing row sharding applies; the tree axis rides along as a batch dim
    of the MXU contraction."""
    def kernel(node_ids, branches, cls_codes, weights, n_nodes):
        # node_ids, weights (n, T); branches (n, S); cls_codes (n,)
        # Factored form: the (class x split x branch) one-hot is IDENTICAL
        # for every tree, so it is built once and the per-tree part is only
        # the (n, T, N) weighted node one-hot — one (T*N, n) x (n, C*S*B)
        # contraction with balanced GEMM dims (2x faster than the fused
        # (n, T, N*C) formulation, measured on CPU; same exact counts).
        active = node_ids >= 0
        w = weights * active.astype(jnp.float32)                 # (n, T)
        oh_node = jax.nn.one_hot(jnp.where(active, node_ids, 0), n_nodes,
                                 dtype=jnp.float32) * w[..., None]  # (n,T,N)
        oh_c = jax.nn.one_hot(cls_codes, C, dtype=jnp.float32)   # (n, C)
        oh_b = jax.nn.one_hot(branches, B, dtype=jnp.float32)    # (n, S, B)
        oh_cb = jnp.einsum("nc,nsb->ncsb", oh_c, oh_b)           # (n, C, S, B)
        counts = jnp.einsum("ntm,ncsb->tmcsb", oh_node, oh_cb)   # (T,N,C,S,B)
        return counts.transpose(0, 1, 3, 4, 2)                   # (T,N,S,B,C)
    return jax.jit(kernel, static_argnums=4)


# batched record re-tagging: vmap the single-tree reassign over the tree
# axis (axis 1 of node_ids); branch codes are shared across trees
_REASSIGN_FOREST = jax.jit(jax.vmap(TreeBuilder._reassign,
                                    in_axes=(1, None, 0, 0), out_axes=1))


class ForestBuilder:
    """All trees advance one level per kernel launch (VERDICT r1 #4).

    Equivalent to the sequential per-tree loop — each tree keeps its own
    bootstrap weights and RNG stream, so the resulting models are
    bit-identical to ``build_forest(..., batched=False)`` — but the level
    histogram runs once for the whole forest ((n, T) node/weight arrays,
    counts (T, N, S, B, C) in one einsum) and records are re-tagged for all
    trees in one vmapped gather."""

    def __init__(self, table: ColumnarTable, params: ForestParams,
                 ctx: Optional[MeshContext] = None):
        self.params = params
        self.base = TreeBuilder(table, replace(params.tree, seed=params.seed),
                                ctx or MeshContext())
        self.tree_builders = [
            self.base.with_params(
                replace(params.tree, seed=params.seed + 1000 * (t + 1)))
            for t in range(params.num_trees)]

    def _level_counts(self, kernel, node_ids, weights, n_nodes: int,
                      chunk: int = 1 << 19) -> np.ndarray:
        """One level for the whole forest.  Chunks accumulate ON DEVICE in
        f32 (async dispatch pipelines them; one host transfer per level) when
        that is exact — sampling weights are integral, so partial sums are
        exact integers until a cell could reach 2^24, gated by the actual
        per-tree weight mass (set in build_all).  Otherwise each chunk is
        accumulated on host in float64, matching the single-tree path."""
        base = self.base
        T = len(self.tree_builders)
        chunk = max(1024, chunk // max(T, 1))
        device_acc = getattr(self, "_f32_exact", False)
        acc = None
        total = None
        for start in range(0, base.n_padded, chunk):
            end = min(start + chunk, base.n_padded)
            c = kernel(node_ids[start:end], base.branches[start:end],
                       base.cls_codes[start:end], weights[start:end], n_nodes)
            if device_acc:
                acc = c if acc is None else acc + c
            else:
                h = np.asarray(c, dtype=np.float64)
                total = h if total is None else total + h
        return np.asarray(acc, dtype=np.float64) if device_acc else total

    def build_all(self) -> List[DecisionPathList]:
        base, builders = self.base, self.tree_builders
        p = self.params.tree
        T, n = len(builders), base.n_padded
        ctx = base.ctx
        mask = np.asarray(jax.device_get(base.base_mask), dtype=np.float32)
        w_cols = []
        for b in builders:
            w = sampling_weights(n, b.params, b.rng)
            w_cols.append((w if w is not None else
                           np.ones((n,), np.float32)) * mask)
        # integral weights: f32 partial sums stay exact while no cell can
        # reach 2^24, i.e. while each tree's total weight mass is below it
        self._f32_exact = max(
            (float(c.sum()) for c in w_cols), default=0.0) < float(1 << 24)
        weights = ctx.shard_rows(np.stack(w_cols, axis=1).astype(np.float32))
        node_ids = ctx.shard_rows(np.zeros((n, T), dtype=np.int32))
        S, B, C = base.split_set.n_splits, base.split_set.max_branches, base.C
        kernel = _jitted_forest_count_kernel(S, B, C)

        counts = self._level_counts(kernel, node_ids, weights, 1)
        leaves = [[b._root_state(counts[t, 0])] for t, b in enumerate(builders)]
        finals: List[List[DecisionPath]] = [[] for _ in range(T)]
        roots = [l[0] for l in leaves]

        levels = p.max_depth if p.stopping_strategy == "maxDepth" else 64
        for _level in range(levels):
            active = [[l for l in leaves[t] if not l.stopped] for t in range(T)]
            n_nodes = max((len(a) for a in active), default=0)
            if n_nodes == 0:
                break
            counts = self._level_counts(kernel, node_ids, weights, n_nodes)
            sel_split = np.full((T, n_nodes), -1, dtype=np.int32)
            child_table = np.full((T, n_nodes, B), -1, dtype=np.int32)
            for t, b in enumerate(builders):
                if not active[t]:
                    leaves[t] = []
                    continue
                new_l, stopped, sel, ctab = b._choose_splits(
                    active[t], counts[t, :len(active[t])])
                finals[t].extend(stopped)
                leaves[t] = new_l
                sel_split[t, :len(sel)] = sel
                child_table[t, :ctab.shape[0]] = ctab
            node_ids = _REASSIGN_FOREST(
                node_ids, base.branches,
                ctx.replicate(jnp.asarray(sel_split)),
                ctx.replicate(jnp.asarray(child_table)))
            if not any(leaves):
                break

        out: List[DecisionPathList] = []
        for t in range(T):
            paths = list(finals[t])
            for leaf in leaves[t]:
                paths.append(DecisionPath(
                    predicates=leaf.predicates,
                    population=int(round(leaf.population)),
                    info_content=leaf.info_content, stopped=True,
                    class_val_pr=leaf.class_val_pr))
            if not paths:
                r = roots[t]
                paths.append(DecisionPath(
                    predicates=[Predicate.root()],
                    population=int(round(r.population)),
                    info_content=r.info_content, stopped=True,
                    class_val_pr=r.class_val_pr))
            out.append(DecisionPathList(decision_paths=paths))
        return out


def build_forest(table: ColumnarTable, params: ForestParams,
                 ctx: Optional[MeshContext] = None,
                 batched: bool = True) -> List[DecisionPathList]:
    """Train num_trees trees, each with an independent bootstrap + RNG
    (the rafo.sh per-tree rerun loop, in-process).  ``batched=True`` (the
    default) advances all trees level-by-level through one shared kernel;
    ``batched=False`` is the sequential per-tree loop kept as the parity and
    benchmark baseline — both produce identical models."""
    ctx = ctx or MeshContext()
    if batched:
        return ForestBuilder(table, params, ctx).build_all()
    models: List[DecisionPathList] = []
    # data is encoded and branch codes computed once; each tree shares them
    base_builder = TreeBuilder(table, replace(params.tree, seed=params.seed), ctx)
    for t in range(params.num_trees):
        tree_params = replace(params.tree, seed=params.seed + 1000 * (t + 1))
        models.append(base_builder.with_params(tree_params).build())
    return models


class EnsembleModel:
    """Weighted-vote ensemble with min-odds veto
    (model/EnsemblePredictiveModel.java:69-113).  The reference requires an
    odd number of models for unweighted votes; we keep that check."""

    def __init__(self, models: List[DecisionTreeModel],
                 weights: Optional[Sequence[float]] = None,
                 min_odds_ratio: float = 1.0,
                 require_odd: bool = True):
        if require_odd and weights is None and len(models) % 2 == 0:
            raise ValueError("need odd number of models in ensemble")
        self.models = models
        self.weights = list(weights) if weights is not None else [1.0] * len(models)
        self.min_odds_ratio = min_odds_ratio
        # vote vocabulary is fixed by the member models; "" is the no-paths
        # sentinel a degenerate member can emit
        self.classes = sorted({c for m in models for c in m.matrix.classes}
                              | {""})
        self._cls_arr = np.array(self.classes)

    def predict(self, table: ColumnarTable) -> List[Optional[str]]:
        """Weighted vote as one (n, K) reduction: each member contributes its
        weight at its predicted class index (no per-record Python)."""
        n = table.n_rows
        cls_arr = self._cls_arr
        mat = np.zeros((n, len(cls_arr)), dtype=np.float64)
        rows = np.arange(n)
        for model, w in zip(self.models, self.weights):
            pred, _ = model.predict(table)
            idx = np.searchsorted(cls_arr, np.asarray(pred))
            # (rows, idx) pairs are unique within one model's votes, so plain
            # fancy-index += is exact (and much faster than np.add.at)
            mat[rows, idx] += w
        order = np.argsort(-mat, axis=1)
        best = cls_arr[order[:, 0]]
        out = best.astype(object)
        if self.min_odds_ratio > 1.0 and mat.shape[1] > 1:
            top = mat[rows, order[:, 0]]
            second = np.maximum(mat[rows, order[:, 1]], 1e-12)
            out[top / second <= self.min_odds_ratio] = None
        return list(out)


OUTPUT_WITH_RECORD = "withRecord"
OUTPUT_WITH_ID = "withKId"
OUTPUT_WITH_CLASS_ATTR = "withActualClassAttr"


def model_predictor(table: ColumnarTable, schema: FeatureSchema,
                    path_lists: List[DecisionPathList],
                    output_mode: str = OUTPUT_WITH_RECORD,
                    id_ordinal: int = 0,
                    class_attr_ordinal: Optional[int] = None,
                    class_attr_values: Optional[Sequence[str]] = None,
                    error_counting: bool = False,
                    weights: Optional[Sequence[float]] = None,
                    min_odds_ratio: float = 1.0,
                    out_delim: str = ",",
                    counters: Optional[Counters] = None) -> List[str]:
    """The generic predictor job body: ensemble (or single-model) prediction
    with the reference's output modes (model/ModelPredictor.java:87-150) and
    optional per-member vote weights (:144-151)."""
    models = [DecisionTreeModel(pl, schema) for pl in path_lists]
    if len(models) == 1:
        preds, _ = models[0].predict(table)
        pred_list: List[Optional[str]] = list(preds)
    else:
        pred_list = EnsembleModel(models, weights=weights,
                                  min_odds_ratio=min_odds_ratio,
                                  require_odd=min_odds_ratio <= 1.0 and
                                  weights is None).predict(table)
    lines = []
    raw = table.raw_rows
    for i in range(table.n_rows):
        pred = pred_list[i] if pred_list[i] is not None else "ambiguous"
        if output_mode == OUTPUT_WITH_RECORD and raw is not None:
            lines.append(out_delim.join(raw[i]) + out_delim + pred)
        elif output_mode == OUTPUT_WITH_ID:
            rid = (table.str_columns.get(id_ordinal, [str(i)] * table.n_rows))[i]
            lines.append(rid + out_delim + pred)
        elif output_mode == OUTPUT_WITH_CLASS_ATTR and raw is not None:
            actual = raw[i][class_attr_ordinal] if class_attr_ordinal is not None \
                else ""
            lines.append(out_delim.join([str(i), actual, pred]))
        else:
            lines.append(pred)
    if error_counting and class_attr_ordinal is not None and raw is not None:
        errors = sum(1 for i in range(table.n_rows)
                     if pred_list[i] != raw[i][class_attr_ordinal])
        if counters is not None:
            counters.increment("Prediction", "Error count", errors)
            counters.increment("Prediction", "Total count", table.n_rows)
    return lines
