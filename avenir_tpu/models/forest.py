"""Random forest + the generic prediction engine.

Parity targets (SURVEY.md §2.1 'Random Forest' + §2.8 model package):

  * RF in the reference is not a class: it is DecisionTreeBuilder configured
    with bootstrap sampling + random attribute subsets + randomAmongTop split
    choice (resource/rafo.properties:15-17), re-run once per tree by the
    driver script (resource/rafo.sh:34-43).  Here ``build_forest`` runs the
    whole ensemble: per-tree bootstrap weights, per-tree RNG, same TreeParams
    knobs.
  * ``EnsembleModel``   == model/EnsemblePredictiveModel.java:69-113 —
    weighted majority vote, min-odds-ratio veto (ambiguous -> None).
  * ``model_predictor`` == model/ModelPredictor.java:46-82 — map-only job
    loading N model files, output modes withRecord / withKId /
    withActualClassAttr, optional error counting.

TPU design: each tree reuses the TreeBuilder level kernels over the same
device-resident feature/branch arrays (encoded once); only the per-record
bootstrap weights and the host-side random choices differ per tree.
Ensemble prediction batches all trees' paths into one pass per tree and
reduces votes as arrays.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field as dc_field, replace
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..core.metrics import Counters
from ..parallel.mesh import MeshContext, runtime_context
from ..telemetry import span
from ..utils.tracing import fetch, note_dispatch
from .tree import (acc_counts, DecisionPath, DecisionPathList, DecisionTreeModel,
                   Predicate, TreeBuilder, TreeParams, level_chunk,
                   sampling_weights)


@dataclass
class ForestParams:
    tree: TreeParams = dc_field(default_factory=lambda: TreeParams(
        attr_select_strategy="randomNotUsedYet",
        split_select_strategy="randomAmongTop",
        sub_sampling="withReplace", sub_sampling_rate=90.0))
    num_trees: int = 5
    seed: int = 0


def _pad_chunk(chunk, node_ids, branches, cls_codes, weights):
    """Pad a tail slice up to the full chunk shape (node_id -1 = inactive,
    weight 0) so the level kernels only ever compile ONE row shape per
    level: un-padded tails used to trigger a fresh multi-second XLA
    compile of the big count kernel for every (level, total-row-count)
    pair, which dominated deep-scale builds.  The pad rows contribute
    nothing (inactive AND zero weight), so counts are unchanged."""
    short = chunk - node_ids.shape[0]
    if short <= 0:
        return node_ids, branches, cls_codes, weights
    return (jnp.pad(node_ids, ((0, short), (0, 0)), constant_values=-1),
            jnp.pad(branches, ((0, short), (0, 0))),
            jnp.pad(cls_codes, ((0, short),)),
            jnp.pad(weights, ((0, short), (0, 0))))


@jax.jit
def _unpack_weights4(packed):
    """(n, ceil(T/2)) uint8 of 4-bit weight pairs -> (n, T_padded) uint8 on
    device: the decode costs one elementwise launch; the wire cost is the
    packed half."""
    lo = packed & np.uint8(15)
    hi = packed >> np.uint8(4)
    return jnp.stack([lo, hi], axis=2).reshape(packed.shape[0], -1)


@functools.lru_cache(maxsize=None)
def _jitted_forest_count_kernel(S: int, B: int, C: int,
                                backend: str = "xla",
                                interpret: bool = False):
    """``backend`` is part of the cache key ON PURPOSE (TPU_NOTES §24):
    the dispatch decision happens at trace time, so a program traced
    under one backend must never serve a call made under the other."""
    def kernel(node_ids, branches, cls_codes, weights, n_nodes):
        if backend == "pallas":
            from ..ops.pallas.histogram import forest_level_counts
            return forest_level_counts(node_ids, branches, cls_codes,
                                       weights, n_nodes, B, C,
                                       interpret=interpret)
        return _count_body(node_ids, branches, cls_codes, weights,
                           n_nodes, B, C)
    return jax.jit(kernel, static_argnums=4)


def _count_body(node_ids, branches, cls_codes, weights, n_nodes, B, C):
    """Tree-batched level histogram (SURVEY.md §7.4 'RF = vmap over trees'):
    one einsum advances ALL trees one level.  Row-leading layout so the
    existing row sharding applies; the tree axis rides along as a batch dim
    of the MXU contraction.

    node_ids, weights (n, T); branches (n, S); cls_codes (n,).
    Factored form: the (class x split x branch) one-hot is IDENTICAL
    for every tree, so it is built once and the per-tree part is only
    the (n, T, N) weighted node one-hot — one (T*N, n) x (n, C*S*B)
    contraction with balanced GEMM dims (2x faster than the fused
    (n, T, N*C) formulation, measured on CPU; same exact counts).  weights
    may arrive as uint16 (compact transfer form) or f32."""
    active = node_ids >= 0
    w = weights.astype(jnp.float32) * active.astype(jnp.float32)  # (n, T)
    oh_node = jax.nn.one_hot(jnp.where(active, node_ids, 0), n_nodes,
                             dtype=jnp.float32) * w[..., None]  # (n,T,N)
    oh_c = jax.nn.one_hot(cls_codes, C, dtype=jnp.float32)   # (n, C)
    oh_b = jax.nn.one_hot(branches, B, dtype=jnp.float32)    # (n, S, B)
    oh_cb = jnp.einsum("nc,nsb->ncsb", oh_c, oh_b)           # (n, C, S, B)
    # HIGHEST: default TPU matmul precision would round weights > 256 (the
    # oh_node operand carries them) through bf16 before accumulating
    counts = jnp.einsum("ntm,ncsb->tmcsb", oh_node, oh_cb,
                        precision=jax.lax.Precision.HIGHEST)  # (T,N,C,S,B)
    return counts.transpose(0, 1, 3, 4, 2)                   # (T,N,S,B,C)


def _reassign_body(node_ids, branches, sel_split, child_table):
    """Batched record re-tagging for all trees, formulated as one-hot
    einsums instead of gathers: XLA lowers multi-dim gathers to scalar
    loops on this TPU (~775 ms/level at 400k x 16 for the old vmapped
    gather version vs ~30 ms for this one); every lookup table here is
    tiny, so the MXU contractions are effectively free.  precision=HIGHEST
    is mandatory: the TPU's default matmul precision feeds bf16 into the
    MXU, which rounds looked-up integers above 256 (split indices / node
    ids corrupt silently at wide frontiers — verified on hardware)."""
    hi = jax.lax.Precision.HIGHEST
    active = node_ids >= 0
    node_safe = jnp.where(active, node_ids, 0)               # (n, T)
    n_prev = sel_split.shape[1]
    oh_node = jax.nn.one_hot(node_safe, n_prev, dtype=jnp.float32)  # (n,T,Np)
    s = jnp.einsum("ntm,tm->nt", oh_node,
                   sel_split.astype(jnp.float32),
                   precision=hi).astype(jnp.int32)
    S = branches.shape[1]
    oh_sel = jax.nn.one_hot(jnp.clip(s, 0, S - 1), S,
                            dtype=jnp.float32)               # (n, T, S)
    br = jnp.einsum("nts,ns->nt", oh_sel,
                    branches.astype(jnp.float32),
                    precision=hi).astype(jnp.int32)
    oh_br = jax.nn.one_hot(br, child_table.shape[2], dtype=jnp.float32)
    new_ids = jnp.einsum("ntm,ntb,tmb->nt", oh_node, oh_br,
                         child_table.astype(jnp.float32),
                         precision=hi).astype(jnp.int32)
    return jnp.where(active & (s >= 0), new_ids,
                     jnp.where(active, -2, node_ids))  # -2: stopped leaf member


@functools.lru_cache(maxsize=None)
def _jitted_forest_level_kernel(S: int, B: int, C: int,
                                backend: str = "xla",
                                interpret: bool = False):
    """Fused per-level program: re-tag every record for every tree with the
    previous level's chosen splits, then histogram the new frontier — ONE
    launch and ONE host readback per level (the counts; new node ids stay
    on device).  The (n, T) node-id state is DONATED: its output twin has
    identical shape/dtype/sharding and every caller rebinds, so the level
    loop's biggest carry updates in place instead of paying a defensive
    HBM copy per level (the chunked path donates the per-chunk pad/slice
    copies, which are equally dead after the call).

    ``backend="pallas"`` swaps the histogram half for the VMEM-resident
    pallas kernel (ops/pallas/histogram.forest_level_counts — counts
    bit-identical, interpret-mode parity pinned); the reassign stays the
    XLA one-hot form either way (it is lookup-table matmuls, already the
    right formulation).  The backend is part of the lru key — see
    ``_jitted_forest_count_kernel``."""
    def kernel(node_ids, branches, cls_codes, weights, sel_split,
               child_table, n_new):
        new_ids = _reassign_body(node_ids, branches, sel_split, child_table)
        if backend == "pallas":
            from ..ops.pallas.histogram import forest_level_counts
            counts = forest_level_counts(new_ids, branches, cls_codes,
                                         weights, n_new, B, C,
                                         interpret=interpret)
        else:
            counts = _count_body(new_ids, branches, cls_codes, weights,
                                 n_new, B, C)
        return new_ids, counts
    return jax.jit(kernel, static_argnums=6, donate_argnums=(0,))


class ForestBuilder:
    """All trees advance one level per kernel launch (VERDICT r1 #4).

    Equivalent to the sequential per-tree loop — each tree keeps its own
    bootstrap weights and RNG stream, so the resulting models are
    bit-identical to ``build_forest(..., batched=False)`` — but the level
    histogram runs once for the whole forest ((n, T) node/weight arrays,
    counts (T, N, S, B, C) in one einsum) and records are re-tagged for all
    trees by the fused one-hot reassign inside the level kernel."""

    def __init__(self, table: Optional[ColumnarTable], params: ForestParams,
                 ctx: Optional[MeshContext] = None,
                 base: Optional[TreeBuilder] = None):
        """``base`` injects a pre-built TreeBuilder (e.g. one assembled by
        TreeBuilder.from_stream over CSV row blocks) — it must carry
        ``replace(params.tree, seed=params.seed)``; otherwise the builder
        is constructed from ``table``."""
        self.params = params
        self.base = base if base is not None else TreeBuilder(
            table, replace(params.tree, seed=params.seed),
            ctx or runtime_context())
        self.tree_builders = [
            self.base.with_params(
                replace(params.tree, seed=params.seed + 1000 * (t + 1)))
            for t in range(params.num_trees)]
        # resolved per build in build_all (trace-time decision); default
        # for any direct _level_counts caller
        self._kernel_backend = "xla"

    def _level_counts(self, kernel, node_ids, weights, n_nodes: int
                      ) -> np.ndarray:
        """One level for the whole forest, fully device-resident: chunk
        partial sums are exact f32 integers (chunk mass capped below 2^24 by
        ``level_chunk``), converted to int32 and accumulated ON DEVICE —
        exact to 2^31 per cell, far past the 100M-row regime — with one host
        transfer per level.  A 400k x 16 level is a single launch (the old
        2^19/T chunking was dispatch-latency-bound; VERDICT r2 weak #1)."""
        base = self.base
        T = len(self.tree_builders)
        S, B, C = base.split_set.n_splits, base.split_set.max_branches, base.C
        chunk = level_chunk(n_nodes, T, S, B, C, self._w_max)
        n = base.n_padded
        from ..ops.pallas.dispatch import note_backend
        if n <= chunk:
            note_dispatch(site="forest.level")
            note_backend("forest.level", self._kernel_backend)
            c = kernel(node_ids, base.branches, base.cls_codes, weights,
                       n_nodes)
            return base._reduce_counts(fetch(c, dtype=np.float64))
        acc = None
        for start in range(0, n, chunk):
            end = min(start + chunk, n)
            nid, br, cc, ww = _pad_chunk(
                chunk, node_ids[start:end], base.branches[start:end],
                base.cls_codes[start:end], weights[start:end])
            note_dispatch(2, site="forest.level")  # count + accumulate
            note_backend("forest.level", self._kernel_backend)
            c = kernel(nid, br, cc, ww, n_nodes)
            acc = c.astype(jnp.int32) if acc is None \
                else acc_counts(acc, c)
        return base._reduce_counts(fetch(acc, dtype=np.float64))

    def _level_fused(self, fused, node_ids, weights, sel_split: np.ndarray,
                     child_table: np.ndarray, n_new: int):
        """Advance the forest one level: reassign with the previous level's
        winners and histogram the new frontier in one launch (chunked over
        rows with the same on-device int32 accumulation as _level_counts).
        Returns (new node_ids device array, counts float64 host array)."""
        base = self.base
        T = len(self.tree_builders)
        S, B, C = base.split_set.n_splits, base.split_set.max_branches, base.C
        ctx = base.ctx
        sel = ctx.replicate(jnp.asarray(sel_split))
        ctab = ctx.replicate(jnp.asarray(child_table))
        n_prev = sel_split.shape[1]
        # the fused kernel's extra (chunk, T, {Np, S, B}) reassign one-hots
        # ride the same budget via an inflated node-count term
        chunk = level_chunk(n_new + n_prev + S + B, T, S, B, C, self._w_max)
        n = base.n_padded
        from ..ops.pallas.dispatch import note_backend
        if n <= chunk:
            note_dispatch(site="forest.level")
            note_backend("forest.level", self._kernel_backend)
            new_ids, c = fused(node_ids, base.branches, base.cls_codes,
                               weights, sel, ctab, n_new)
            # ONE stacked (T, N, S, B, C) transfer per level for the whole
            # forest — never per tree (pinned by tests/test_transfers.py)
            # — and, sharded, ONE all-reduce of it per level
            return new_ids, base._reduce_counts(fetch(c, dtype=np.float64))
        ids_parts, acc = [], None
        for start in range(0, n, chunk):
            end = min(start + chunk, n)
            nid, br, cc, ww = _pad_chunk(
                chunk, node_ids[start:end], base.branches[start:end],
                base.cls_codes[start:end], weights[start:end])
            note_dispatch(2, site="forest.level")  # fused level + accumulate
            note_backend("forest.level", self._kernel_backend)
            ni, c = fused(nid, br, cc, ww, sel, ctab, n_new)
            ids_parts.append(ni[:end - start])
            acc = c.astype(jnp.int32) if acc is None \
                else acc_counts(acc, c)
        return jnp.concatenate(ids_parts, axis=0), \
            base._reduce_counts(fetch(acc, dtype=np.float64))

    def build_all(self) -> List[DecisionPathList]:
        base, builders = self.base, self.tree_builders
        p = self.params.tree
        T, n = len(builders), base.n_padded
        ctx = base.ctx
        w_cols = []
        for b in builders:
            # drawn over the TRUE row count, placed at the valid device
            # positions: model bytes must not depend on how many pad rows
            # the mesh size (or per-block streamed padding) added — see
            # TreeBuilder's identical rule
            w_cols.append(base._expand_weights(
                sampling_weights(base.n_rows, b.params, b.rng)))
        # per-record weight cap feeds the exactness bound in level_chunk
        self._w_max = max((float(c.max()) for c in w_cols if c.size),
                          default=1.0)
        # integral weights ship in the narrowest form that holds w_max —
        # the host->device link is the build's bottleneck; kernels cast to
        # f32 on device.  Bootstrap counts are tiny, so the common case is
        # 4-bit: two trees per byte, halving the (n, T) upload again
        wdtype = (np.uint8 if self._w_max < 256 else
                  np.uint16 if self._w_max < float(1 << 16) else np.float32)
        wst = np.stack(w_cols, axis=1).astype(wdtype)
        if wdtype is np.uint8 and self._w_max < 16 and T > 1 and n > 0:
            if T % 2:
                wst = np.concatenate(
                    [wst, np.zeros((n, 1), np.uint8)], axis=1)
            packed = wst[:, 0::2] | (wst[:, 1::2] << 4)
            weights = _unpack_weights4(
                ctx.shard_rows_streamed(packed))[:, :T]
        else:
            weights = ctx.shard_rows_streamed(wst)
        node_ids = ctx.zeros_rows((n, T), np.int32)
        S, B, C = base.split_set.n_splits, base.split_set.max_branches, base.C
        # backend resolved ONCE per build (trace-time decision, so the
        # jit caches key on it); which form actually ran lands in the
        # ledger's KernelBackends group at every forest.level launch
        from ..ops.pallas.dispatch import pallas_interpret, resolve_backend
        self._kernel_backend = resolve_backend(ctx.device_platform,
                                               ctx.n_devices)
        interp = pallas_interpret(ctx.device_platform)
        count_k = _jitted_forest_count_kernel(S, B, C,
                                              self._kernel_backend, interp)
        fused_k = _jitted_forest_level_kernel(S, B, C,
                                              self._kernel_backend, interp)

        # the root histogram (every record at node 0) IS the level-0 frontier
        # histogram, so one launch serves both
        counts = self._level_counts(count_k, node_ids, weights, 1)
        leaves = [[b._root_state(counts[t, 0])] for t, b in enumerate(builders)]
        finals: List[List[DecisionPath]] = [[] for _ in range(T)]
        roots = [l[0] for l in leaves]
        sel_split = child_table = None

        levels = p.max_depth if p.stopping_strategy == "maxDepth" else 64
        for _level in range(levels):
            active = [[l for l in leaves[t] if not l.stopped] for t in range(T)]
            n_nodes = max((len(a) for a in active), default=0)
            if n_nodes == 0:
                break
            with span("forest.level", cat="compute", level=_level,
                      nodes=n_nodes):
                if _level > 0:
                    # one fused launch: re-tag with last level's winners +
                    # count
                    node_ids, counts = self._level_fused(
                        fused_k, node_ids, weights, sel_split, child_table,
                        n_nodes)
                sel_split = np.full((T, n_nodes), -1, dtype=np.int32)
                child_table = np.full((T, n_nodes, B), -1, dtype=np.int32)
                for t, b in enumerate(builders):
                    if not active[t]:
                        leaves[t] = []
                        continue
                    new_l, stopped, sel, ctab = b._choose_splits(
                        active[t], counts[t, :len(active[t])])
                    finals[t].extend(stopped)
                    leaves[t] = new_l
                    sel_split[t, :len(sel)] = sel
                    child_table[t, :ctab.shape[0]] = ctab
            if not any(leaves):
                break

        out: List[DecisionPathList] = []
        for t in range(T):
            paths = list(finals[t])
            for leaf in leaves[t]:
                paths.append(DecisionPath(
                    predicates=leaf.predicates,
                    population=int(round(leaf.population)),
                    info_content=leaf.info_content, stopped=True,
                    class_val_pr=leaf.class_val_pr))
            if not paths:
                r = roots[t]
                paths.append(DecisionPath(
                    predicates=[Predicate.root()],
                    population=int(round(r.population)),
                    info_content=r.info_content, stopped=True,
                    class_val_pr=r.class_val_pr))
            out.append(DecisionPathList(decision_paths=paths))
        return out


def build_forest(table: ColumnarTable, params: ForestParams,
                 ctx: Optional[MeshContext] = None,
                 batched: bool = True) -> List[DecisionPathList]:
    """Train num_trees trees, each with an independent bootstrap + RNG
    (the rafo.sh per-tree rerun loop, in-process).  ``batched=True`` (the
    default) advances all trees level-by-level through one shared kernel;
    ``batched=False`` is the sequential per-tree loop kept as the parity and
    benchmark baseline — both produce identical models."""
    ctx = ctx or runtime_context()
    if batched:
        return ForestBuilder(table, params, ctx).build_all()
    models: List[DecisionPathList] = []
    # data is encoded and branch codes computed once; each tree shares them
    base_builder = TreeBuilder(table, replace(params.tree, seed=params.seed), ctx)
    for t in range(params.num_trees):
        tree_params = replace(params.tree, seed=params.seed + 1000 * (t + 1))
        models.append(base_builder.with_params(tree_params).build())
    return models


def build_forest_from_stream(blocks, schema, params: ForestParams,
                             ctx: Optional[MeshContext] = None,
                             stats: Optional[dict] = None,
                             checkpoint=None, checkpoint_every: int = 0,
                             resume_state=None,
                             reducer=None, baseline=None,
                             fuse: bool = True) -> List[DecisionPathList]:
    """Train the forest from an iterator of ColumnarTable row blocks — the
    streaming CSV->device ingest pipeline's training entry.  Each block is
    encoded to branch/class codes on device and released, so host memory
    holds one in-flight block instead of the whole dataset; the resident
    device arrays are uploaded ONCE and reused across all trees and
    levels.  Wrap the source in ``core.table.prefetch_chunks`` so block
    i+1 parses while block i transfers.

    Models are bit-identical to ``build_forest(assembled_table, ...)``:
    the bootstrap draws, RNG streams and level histograms see exactly the
    same records (per-block pad rows carry zero weight).

    ``stats`` (optional dict) collects phase timings: ``parse_s`` (from
    prefetch_chunks), ``transfer_s`` (staging thread),
    ``ingest_compute_s`` (consumer branch-code dispatch + final sync),
    ``queue_wait_s``, ``ingest_wall_s``, ``build_s`` — the bench derives
    the parse/transfer/compute pipeline-overlap decomposition from them.

    ``checkpoint``/``checkpoint_every``/``resume_state`` thread straight
    through to ``TreeBuilder.from_stream`` (see its docstring for the
    resume contract): an interrupted-then-resumed streaming build trains
    the bit-identical forest of an uninterrupted run.

    ``reducer`` (a ``parallel.collectives.AllReducer``) turns the build
    multi-host data-parallel: ``blocks`` must be this process's row-range
    shard (``iter_csv_chunks(shard=reducer.spec)``); every tree level
    pays exactly ONE all-reduce of the stacked (T, N, S, B, C) count
    matrix, and every process returns the identical forest, bit-identical
    to the single-host build (TPU_NOTES §20).

    ``baseline``/``fuse`` thread to ``TreeBuilder.from_stream``
    (TPU_NOTES §22): with ``fuse=True`` (default) the per-chunk encode —
    and, when a ``BaselineBuilder`` rides along, its bin-count absorb —
    run as ONE ProgramCache-compiled XLA launch per chunk;
    ``fuse=False`` keeps the eager per-stage path (``baseline`` then
    tees the stream host-side).  Models and baseline are bit-identical
    either way."""
    import time as _time
    t0 = _time.perf_counter()
    base = TreeBuilder.from_stream(blocks, schema,
                                   replace(params.tree, seed=params.seed),
                                   ctx, stats=stats,
                                   checkpoint=checkpoint,
                                   checkpoint_every=checkpoint_every,
                                   resume_state=resume_state,
                                   reducer=reducer, baseline=baseline,
                                   fuse=fuse)
    t1 = _time.perf_counter()
    models = ForestBuilder(None, params, ctx, base=base).build_all()
    if stats is not None:
        stats["ingest_wall_s"] = t1 - t0
        stats["build_s"] = _time.perf_counter() - t1
    return models


def _member_votes_body(vals, codes, lo, hi, num_r, cat_m, cat_r, cls_oh,
                       wvec):
    """The (n, K) weighted vote tally: per-member first-match, one-hot,
    weighted sum over the member (tree) axis.  A trailing always-match
    sentinel path per member carries its fallback class, so first-match
    == the member's predict-with-fallback semantics.

    This half of the vote is what shards over the tree axis: vote counts
    are sums of integer-valued f32 terms (``stacked_host`` rejects
    non-small-integer weights), so f32 addition over any tree partition
    is exact and order-independent — per-shard partial tallies psum'd
    across a mesh are BIT-identical to the single-device sum."""
    from .tree import _match_ok
    P = cls_oh.shape[1]
    # the per-member matcher IS tree._match_ok, vmapped over the member
    # axis — one predicate-semantics implementation for both paths
    ok = jax.vmap(
        lambda l, h, nr, cm, cr: _match_ok(vals, codes, l, h, nr, cm,
                                           cr, jnp)
    )(lo, hi, num_r, cat_m, cat_r)                    # (T, n, P)
    ok = ok.transpose(1, 0, 2)                        # (n, T, P)
    first = jnp.argmax(ok, axis=2)                    # (n, T)
    foh = jax.nn.one_hot(first, P, dtype=jnp.float32)
    return jnp.einsum("ntp,tpk,t->nk", foh, cls_oh, wvec,
                      precision=jax.lax.Precision.HIGHEST)  # (n, K)


def _vote_finalize(votes, min_odds):
    """(n, K) vote tallies -> (n,) int32 vote indices: argmax + the
    min-odds veto (index K = veto).  Runs on the COMPLETE tally — after
    the cross-shard merge when the tree axis is sharded."""
    K = votes.shape[1]
    best = jnp.argmax(votes, axis=1)
    top = votes.max(axis=1)
    second = jnp.where(jax.nn.one_hot(best, K, dtype=bool), -jnp.inf,
                       votes).max(axis=1)
    veto = (min_odds > 1.0) & \
        (top / jnp.maximum(second, 1e-12) <= min_odds)
    return jnp.where(veto, K, best).astype(jnp.int32)


def _ensemble_vote_body(vals, codes, lo, hi, num_r, cat_m, cat_r, cls_oh,
                        wvec, min_odds):
    """The fused ensemble vote: per-member first-match, weighted vote,
    argmax + min-odds veto — all on device, one (n,) readback.  Shared by
    the batch predict kernel below and the serving layer's per-predictor
    jit (serving/predictor.py hooks a trace counter around it).  The
    body is the composition of :func:`_member_votes_body` (the tally the
    tree-sharded serving core computes per shard) and
    :func:`_vote_finalize` (the post-merge decision) — one vote-math
    implementation for the single-chip, mesh-sharded, and pallas forms."""
    return _vote_finalize(
        _member_votes_body(vals, codes, lo, hi, num_r, cat_m, cat_r,
                           cls_oh, wvec), min_odds)


@functools.lru_cache(maxsize=None)
def _jitted_ensemble_vote_kernel(T: int, P: int, F: int, C: int, K: int,
                                 backend: str = "xla",
                                 interpret: bool = False):
    """One fused launch for the WHOLE ensemble: every member's path tensors
    stacked on a leading member axis (see ``_ensemble_vote_body``).
    ``backend="pallas"`` runs the identical body tiled through the VMEM
    kernel (ops/pallas/vote.ensemble_vote) — same votes, one launch; the
    backend is part of the lru key (trace-time decision)."""
    if backend == "pallas":
        from ..ops.pallas.vote import ensemble_vote
        return jax.jit(functools.partial(ensemble_vote,
                                         interpret=interpret))
    return jax.jit(_ensemble_vote_body)


class EnsembleModel:
    """Weighted-vote ensemble with min-odds veto
    (model/EnsemblePredictiveModel.java:69-113).  The reference requires an
    odd number of models for unweighted votes; we keep that check.

    Device path: all members' predicate tensors are stacked (padded to the
    widest member, plus one always-match fallback sentinel path each) and
    the entire vote happens in one fused launch per row chunk — per-member
    prediction uploads/readbacks made ensemble predict transfer-bound on
    the chip tunnel.  Falls back to the per-member host path when a member
    is degenerate or the features are not f32-exact."""

    def __init__(self, models: List[DecisionTreeModel],
                 weights: Optional[Sequence[float]] = None,
                 min_odds_ratio: float = 1.0,
                 require_odd: bool = True,
                 stack: bool = True):
        if require_odd and weights is None and len(models) % 2 == 0:
            raise ValueError("need odd number of models in ensemble")
        self.models = models
        self.weights = list(weights) if weights is not None else [1.0] * len(models)
        self.min_odds_ratio = min_odds_ratio
        # vote vocabulary is fixed by the member models; "" is the no-paths
        # sentinel a degenerate member can emit
        self.classes = sorted({c for m in models for c in m.matrix.classes}
                              | {""})
        self._cls_arr = np.array(self.classes)
        # vote-index -> label decode (trailing None = min-odds veto): one
        # table for the batch path and the serving layer
        self._lut = np.concatenate([self._cls_arr.astype(object), [None]])
        self._vote_backend = "xla"
        # stack=False skips device placement entirely: callers that only
        # need stacked_host's layout/slices (registry delta publish) must
        # not pay an upload or touch the runtime mesh
        self._stacked = self._stack_members() if stack else None

    def stacked_host(self):
        """The HOST (numpy) form of the stacked member tensors
        ``(lo, hi, num_r, cat_m, cat_r, cls_oh)`` — shared by the device
        vote path and the int8 quantizer (serving/quantized.py), so both
        see the identical pad/sentinel layout.  None when any member is
        degenerate (no paths/classes), bounds are not f32-exact, or the
        vote weights are not small integers — fractional weights must
        accumulate in the host path's float64 (f32 vote sums could flip
        argmax/veto decisions near ties)."""
        mats = [m.matrix for m in self.models]
        if not mats or any(m.n_paths == 0 or not m.classes or
                           not m._bounds_f32_exact for m in mats):
            return None
        if any(w != round(w) or abs(w) >= float(1 << 24)
               for w in self.weights):
            return None
        F = len(mats[0].feat_ordinals)
        cmax = max(m.cat_mask.shape[2] for m in mats)
        P = max(m.n_paths for m in mats) + 1          # + fallback sentinel
        T, K = len(mats), len(self.classes)
        cls_idx = {c: i for i, c in enumerate(self.classes)}
        lo = np.full((T, P, F), np.inf, dtype=np.float32)   # pad: never match
        hi = np.full((T, P, F), -np.inf, dtype=np.float32)
        num_r = np.ones((T, P, F), dtype=bool)
        cat_m = np.zeros((T, P, F, cmax), dtype=bool)
        cat_r = np.zeros((T, P, F), dtype=bool)
        cls_oh = np.zeros((T, P, K), dtype=np.float32)
        for t, m in enumerate(mats):
            p = m.n_paths
            lo[t, :p] = m.lo.astype(np.float32)
            hi[t, :p] = m.hi.astype(np.float32)
            num_r[t, :p] = m.num_restricted
            cat_m[t, :p, :, :m.cat_mask.shape[2]] = m.cat_mask
            cat_r[t, :p] = m.cat_restricted
            for pi in range(p):
                cls_oh[t, pi, cls_idx[m.classes[m.path_cls[pi]]]] = 1.0
            # sentinel: always matches, votes the member's fallback class
            lo[t, p] = -np.inf
            hi[t, p] = np.inf
            num_r[t, p] = False
            cls_oh[t, p, cls_idx[m.classes[int(m.fallback_cls)]]] = 1.0
        return lo, hi, num_r, cat_m, cat_r, cls_oh

    def _stack_members(self):
        """Device placement + jit of :meth:`stacked_host` (None passes
        through: the host vote path serves those ensembles)."""
        host = self.stacked_host()
        if host is None:
            return None
        lo, hi, num_r, cat_m, cat_r, cls_oh = host
        T, P, F = lo.shape
        cmax, K = cat_m.shape[3], cls_oh.shape[2]
        dev = tuple(jnp.asarray(a) for a in
                    (lo, hi, num_r, cat_m, cat_r, cls_oh))
        from ..ops.pallas.dispatch import pallas_interpret, resolve_backend
        ctx = runtime_context()
        platform = ctx.device_platform
        self._vote_backend = resolve_backend(platform, ctx.n_devices)
        return dev + (jnp.asarray(np.asarray(self.weights, np.float32)),
                      _jitted_ensemble_vote_kernel(
                          T, P, F, cmax, K, self._vote_backend,
                          pallas_interpret(platform)))

    def device_inputs(self, table: ColumnarTable, cache=None):
        """The single gate for the fused device vote: (d_vals, d_codes)
        when this table can take it — members stacked, rows present, and
        features f32-exact — else None (host path).  Shared by predict()
        and the serving layer's per-predictor jit so the two paths can
        never disagree on WHEN the device kernel applies."""
        from .tree import FeatureCache
        if self._stacked is None or table.n_rows == 0:
            return None
        cache = cache if cache is not None else FeatureCache()
        m0 = self.models[0].matrix
        vals, codes = cache.host(m0, table)
        if not m0._f32_safe(vals):
            return None
        return cache.device(vals, codes)

    def predict(self, table: ColumnarTable) -> List[Optional[str]]:
        """Weighted vote; fused device path when available, else one
        (n, K) host reduction over per-member predictions (members still
        share one feature build/upload via FeatureCache)."""
        from .tree import FeatureCache
        cache = FeatureCache()
        dev = self.device_inputs(table, cache)
        if dev is not None:
            return self._predict_device(*dev)
        return self._predict_host(table, cache)

    def _predict_device(self, d_vals, d_codes) -> List[Optional[str]]:
        *consts, wvec, kernel = self._stacked
        T, P, F = consts[0].shape
        C = consts[3].shape[3]
        n = d_vals.shape[0]
        # budget covers both the (n, T, P, F) match intermediate and the
        # (n, F, C) categorical one-hot (dominant for high cardinality)
        per_row = max(T * P * F, F * C, 1)
        chunk = max(1024, (1 << 26) // per_row)
        from ..ops.pallas.dispatch import note_backend
        out = []
        for s in range(0, n, chunk):
            note_dispatch(site="ensemble.vote")
            note_backend("ensemble.vote", self._vote_backend)
            out.append(kernel(d_vals[s:s + chunk], d_codes[s:s + chunk],
                              *consts, wvec,
                              jnp.float32(self.min_odds_ratio)))
        # chunk results stay device-side; ONE readback for the whole
        # batch (each separate np.asarray costs a full ~62 ms tunnel
        # round trip — TPU_NOTES section 5)
        if len(out) == 1:
            idx = fetch(out[0])
        else:
            note_dispatch(site="ensemble.vote")  # the concat launches too
            idx = fetch(jnp.concatenate(out))
        return list(self._lut[idx])

    def _predict_host(self, table: ColumnarTable, cache) -> List[Optional[str]]:
        n = table.n_rows
        cls_arr = self._cls_arr
        mat = np.zeros((n, len(cls_arr)), dtype=np.float64)
        rows = np.arange(n)
        for model, w in zip(self.models, self.weights):
            pred, _ = model.predict(table, features=cache)
            idx = np.searchsorted(cls_arr, np.asarray(pred))
            # (rows, idx) pairs are unique within one model's votes, so plain
            # fancy-index += is exact (and much faster than np.add.at)
            mat[rows, idx] += w
        order = np.argsort(-mat, axis=1)
        best = cls_arr[order[:, 0]]
        out = best.astype(object)
        if self.min_odds_ratio > 1.0 and mat.shape[1] > 1:
            top = mat[rows, order[:, 0]]
            second = np.maximum(mat[rows, order[:, 1]], 1e-12)
            out[top / second <= self.min_odds_ratio] = None
        return list(out)


OUTPUT_WITH_RECORD = "withRecord"
OUTPUT_WITH_ID = "withKId"
OUTPUT_WITH_CLASS_ATTR = "withActualClassAttr"


def model_predictor(table: ColumnarTable, schema: FeatureSchema,
                    path_lists: List[DecisionPathList],
                    output_mode: str = OUTPUT_WITH_RECORD,
                    id_ordinal: int = 0,
                    class_attr_ordinal: Optional[int] = None,
                    class_attr_values: Optional[Sequence[str]] = None,
                    error_counting: bool = False,
                    weights: Optional[Sequence[float]] = None,
                    min_odds_ratio: float = 1.0,
                    out_delim: str = ",",
                    counters: Optional[Counters] = None) -> List[str]:
    """The generic predictor job body: ensemble (or single-model) prediction
    with the reference's output modes (model/ModelPredictor.java:87-150) and
    optional per-member vote weights (:144-151)."""
    models = [DecisionTreeModel(pl, schema) for pl in path_lists]
    if len(models) == 1:
        preds, _ = models[0].predict(table)
        pred_list: List[Optional[str]] = list(preds)
    else:
        pred_list = EnsembleModel(models, weights=weights,
                                  min_odds_ratio=min_odds_ratio,
                                  require_odd=min_odds_ratio <= 1.0 and
                                  weights is None).predict(table)
    raw = table.raw_rows
    preds = [p if p is not None else "ambiguous" for p in pred_list]
    # bulk formatting: one mode branch, one comprehension — not a
    # per-record mode dispatch (VERDICT r2 weak #9: a 100M-row predict was
    # string-handling-bound)
    if output_mode == OUTPUT_WITH_RECORD and raw is not None:
        lines = [out_delim.join(r) + out_delim + p
                 for r, p in zip(raw, preds)]
    elif output_mode == OUTPUT_WITH_ID:
        rids = table.str_columns[id_ordinal] \
            if id_ordinal in table.str_columns \
            else map(str, range(table.n_rows))
        lines = [rid + out_delim + p for rid, p in zip(rids, preds)]
    elif output_mode == OUTPUT_WITH_CLASS_ATTR and raw is not None:
        if class_attr_ordinal is not None:
            lines = [f"{i}{out_delim}{r[class_attr_ordinal]}{out_delim}{p}"
                     for i, (r, p) in enumerate(zip(raw, preds))]
        else:
            lines = [f"{i}{out_delim}{out_delim}{p}"
                     for i, p in enumerate(preds)]
    else:
        lines = list(preds)
    if error_counting and class_attr_ordinal is not None and raw is not None:
        actual = np.fromiter((r[class_attr_ordinal] for r in raw),
                             dtype=object, count=table.n_rows)
        errors = int((np.asarray(pred_list, dtype=object) != actual).sum())
        if counters is not None:
            counters.increment("Prediction", "Error count", errors)
            counters.increment("Prediction", "Total count", table.n_rows)
    return lines
