"""Random forest + the generic prediction engine.

Parity targets (SURVEY.md §2.1 'Random Forest' + §2.8 model package):

  * RF in the reference is not a class: it is DecisionTreeBuilder configured
    with bootstrap sampling + random attribute subsets + randomAmongTop split
    choice (resource/rafo.properties:15-17), re-run once per tree by the
    driver script (resource/rafo.sh:34-43).  Here ``build_forest`` runs the
    whole ensemble: per-tree bootstrap weights, per-tree RNG, same TreeParams
    knobs.
  * ``EnsembleModel``   == model/EnsemblePredictiveModel.java:69-113 —
    weighted majority vote, min-odds-ratio veto (ambiguous -> None).
  * ``model_predictor`` == model/ModelPredictor.java:46-82 — map-only job
    loading N model files, output modes withRecord / withKId /
    withActualClassAttr, optional error counting.

TPU design: each tree reuses the TreeBuilder level kernels over the same
device-resident feature/branch arrays (encoded once); only the per-record
bootstrap weights and the host-side random choices differ per tree.
Ensemble prediction batches all trees' paths into one pass per tree and
reduces votes as arrays.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..core.metrics import Counters
from ..parallel.mesh import MeshContext
from .tree import (DecisionPathList, DecisionTreeModel, TreeBuilder,
                   TreeParams, sampling_weights)


@dataclass
class ForestParams:
    tree: TreeParams = dc_field(default_factory=lambda: TreeParams(
        attr_select_strategy="randomNotUsedYet",
        split_select_strategy="randomAmongTop",
        sub_sampling="withReplace", sub_sampling_rate=90.0))
    num_trees: int = 5
    seed: int = 0


def build_forest(table: ColumnarTable, params: ForestParams,
                 ctx: Optional[MeshContext] = None) -> List[DecisionPathList]:
    """Train num_trees trees; each gets an independent bootstrap + RNG
    (the rafo.sh per-tree rerun loop, in-process)."""
    ctx = ctx or MeshContext()
    models: List[DecisionPathList] = []
    # data is encoded and branch codes computed once; each tree shares them
    base_builder = TreeBuilder(table, replace(params.tree, seed=params.seed), ctx)
    for t in range(params.num_trees):
        tree_params = replace(params.tree, seed=params.seed + 1000 * (t + 1))
        models.append(base_builder.with_params(tree_params).build())
    return models


class EnsembleModel:
    """Weighted-vote ensemble with min-odds veto
    (model/EnsemblePredictiveModel.java:69-113).  The reference requires an
    odd number of models for unweighted votes; we keep that check."""

    def __init__(self, models: List[DecisionTreeModel],
                 weights: Optional[Sequence[float]] = None,
                 min_odds_ratio: float = 1.0,
                 require_odd: bool = True):
        if require_odd and weights is None and len(models) % 2 == 0:
            raise ValueError("need odd number of models in ensemble")
        self.models = models
        self.weights = list(weights) if weights is not None else [1.0] * len(models)
        self.min_odds_ratio = min_odds_ratio

    def predict(self, table: ColumnarTable) -> List[Optional[str]]:
        """Weighted vote as one (n, K) reduction: each member contributes its
        weight at its predicted class index (no per-record Python)."""
        n = table.n_rows
        classes = sorted({c for m in self.models for c in m.matrix.classes}
                         | {""})
        cls_arr = np.array(classes)
        mat = np.zeros((n, len(classes)), dtype=np.float64)
        rows = np.arange(n)
        for model, w in zip(self.models, self.weights):
            pred, _ = model.predict(table)
            idx = np.searchsorted(cls_arr, np.asarray(pred))
            np.add.at(mat, (rows, idx), w)
        order = np.argsort(-mat, axis=1)
        best = cls_arr[order[:, 0]]
        out = best.astype(object)
        if self.min_odds_ratio > 1.0 and mat.shape[1] > 1:
            top = mat[rows, order[:, 0]]
            second = np.maximum(mat[rows, order[:, 1]], 1e-12)
            out[top / second <= self.min_odds_ratio] = None
        return list(out)


OUTPUT_WITH_RECORD = "withRecord"
OUTPUT_WITH_ID = "withKId"
OUTPUT_WITH_CLASS_ATTR = "withActualClassAttr"


def model_predictor(table: ColumnarTable, schema: FeatureSchema,
                    path_lists: List[DecisionPathList],
                    output_mode: str = OUTPUT_WITH_RECORD,
                    id_ordinal: int = 0,
                    class_attr_ordinal: Optional[int] = None,
                    class_attr_values: Optional[Sequence[str]] = None,
                    error_counting: bool = False,
                    weights: Optional[Sequence[float]] = None,
                    min_odds_ratio: float = 1.0,
                    out_delim: str = ",",
                    counters: Optional[Counters] = None) -> List[str]:
    """The generic predictor job body: ensemble (or single-model) prediction
    with the reference's output modes (model/ModelPredictor.java:87-150) and
    optional per-member vote weights (:144-151)."""
    models = [DecisionTreeModel(pl, schema) for pl in path_lists]
    if len(models) == 1:
        preds, _ = models[0].predict(table)
        pred_list: List[Optional[str]] = list(preds)
    else:
        pred_list = EnsembleModel(models, weights=weights,
                                  min_odds_ratio=min_odds_ratio,
                                  require_odd=min_odds_ratio <= 1.0 and
                                  weights is None).predict(table)
    lines = []
    raw = table.raw_rows
    for i in range(table.n_rows):
        pred = pred_list[i] if pred_list[i] is not None else "ambiguous"
        if output_mode == OUTPUT_WITH_RECORD and raw is not None:
            lines.append(out_delim.join(raw[i]) + out_delim + pred)
        elif output_mode == OUTPUT_WITH_ID:
            rid = (table.str_columns.get(id_ordinal, [str(i)] * table.n_rows))[i]
            lines.append(rid + out_delim + pred)
        elif output_mode == OUTPUT_WITH_CLASS_ATTR and raw is not None:
            actual = raw[i][class_attr_ordinal] if class_attr_ordinal is not None \
                else ""
            lines.append(out_delim.join([str(i), actual, pred]))
        else:
            lines.append(pred)
    if error_counting and class_attr_ordinal is not None and raw is not None:
        errors = sum(1 for i in range(table.n_rows)
                     if pred_list[i] != raw[i][class_attr_ordinal])
        if counters is not None:
            counters.increment("Prediction", "Error count", errors)
            counters.increment("Prediction", "Total count", table.n_rows)
    return lines
