"""Decision tree: level-synchronous distributed builder, TPU-native.

Capability parity with org.avenir.tree (SURVEY.md §2.1, call stack §3.1):

  * candidate splits from schema knobs — numeric attrs scanned at
    splitScanInterval with up to maxSplit-1 thresholds per split
    (tree/SplitManager.java:292-330), categorical attrs partitioned into
    2..maxSplit groups (:405-575);
  * one pass grows the whole frontier one level: per (node, split, branch)
    class histograms -> weighted entropy/gini -> best (or random-among-top)
    split per node (tree/DecisionTreeBuilder.java:499-616);
  * attribute selection strategies all/notUsedYet/randomAll/randomNotUsedYet
    (:365-381), stopping maxDepth/minPopulation/minInfoGain
    (tree/DecisionPathStoppingStrategy.java:57-69);
  * sub-sampling none/withReplace/withoutReplace for the first pass
    (:125-127,208-244) — expressed as per-record weights;
  * the model is a DecisionPathList serialized to the reference's exact
    Jackson JSON (tree/DecisionPathList.java; format sample
    resource/dec_tree_rules.json).

TPU design: records never move.  Each level is one jitted pass over
row-sharded arrays computing, for every (node, candidate-split, branch,
class), a weighted count via two one-hot MXU contractions — the exact
mapper x shuffle x reducer of the reference collapsed into one matmul.
The per-record node id is a dense int32 vector updated on device after the
host picks winners (a one-hot-select reassign fused into the next level's
kernel).  All shapes are static per level.

Known deliberate divergence: for multi-threshold splits the reference emits
a record into EVERY matching predicate, and its unbounded last 'le'
predicate overlaps the earlier segments (SplitManager.java:644-657 — records
with x<=t0 also match 'le t1'), inflating middle-branch counts.  We implement
the disjoint segmentation the bounded predicates intend: branch i holds
t_{i-1} < x <= t_i.
"""

from __future__ import annotations

import functools
import itertools
import json
import math
import random as pyrandom
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema, FeatureField
from ..core.table import ColumnarTable, stage_chunks
from ..parallel.mesh import MeshContext, runtime_context
from ..telemetry import span
from ..utils.tracing import fetch, note_dispatch, note_h2d

ROOT_PATH = "$root"
SPLIT_DELIM = ":"          # splitId:predicate in shuffle keys (not in model)
PRED_DELIM = ";"           # dtb.dec.path.delim default


# --------------------------------------------------------------------------
# predicates
# --------------------------------------------------------------------------

@dataclass
class Predicate:
    """One arm of a split; serializes to the reference predicate string
    '<attr> le <v> [<lower>]' / '<attr> gt <v>' / '<attr> in a:b'."""
    attribute: int
    operator: str                      # 'le' | 'gt' | 'in' | None for root
    value_int: int = 0
    value_dbl: float = 0.0
    categorical_values: Optional[List[str]] = None
    other_bound_int: Optional[int] = None
    other_bound_dbl: Optional[float] = None
    is_int: bool = True
    pred_str: str = ""

    @classmethod
    def root(cls) -> "Predicate":
        return cls(attribute=0, operator=None, pred_str=ROOT_PATH)

    @classmethod
    def num(cls, attr: int, op: str, value, other=None, is_int=True) -> "Predicate":
        p = cls(attribute=attr, operator=op, is_int=is_int)
        if is_int:
            p.value_int = int(value)
            p.other_bound_int = None if other is None else int(other)
            s = f"{attr} {op} {int(value)}"
            if other is not None:
                s += f" {int(other)}"
        else:
            p.value_dbl = float(value)
            p.other_bound_dbl = None if other is None else float(other)
            s = f"{attr} {op} {p.value_dbl}"
            if other is not None:
                s += f" {p.other_bound_dbl}"
        p.pred_str = s
        return p

    @classmethod
    def cat(cls, attr: int, values: Sequence[str]) -> "Predicate":
        vals = list(values)
        return cls(attribute=attr, operator="in", categorical_values=vals,
                   pred_str=f"{attr} in {':'.join(vals)}")

    def to_dict(self) -> Dict[str, Any]:
        """Jackson field layout of DecisionPathList.DecisionPathPredicate
        (see resource/dec_tree_rules.json)."""
        return {
            "attribute": self.attribute,
            "predicateStr": self.pred_str,
            "operator": self.operator,
            "valueInt": self.value_int,
            "valueDbl": self.value_dbl,
            "categoricalValues": self.categorical_values,
            "otherBoundInt": self.other_bound_int,
            "otherBoundDbl": self.other_bound_dbl,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Predicate":
        return cls(attribute=d.get("attribute", 0),
                   operator=d.get("operator"),
                   value_int=d.get("valueInt", 0) or 0,
                   value_dbl=d.get("valueDbl", 0.0) or 0.0,
                   categorical_values=d.get("categoricalValues"),
                   other_bound_int=d.get("otherBoundInt"),
                   other_bound_dbl=d.get("otherBoundDbl"),
                   pred_str=d.get("predicateStr", ""))

    @property
    def threshold(self) -> float:
        """Numeric comparison value: valueDbl wins when set (Jackson leaves the
        unused slot at 0, mirroring DecisionPathPredicate's int/dbl pair)."""
        return self.value_dbl if self.value_dbl != 0.0 else float(self.value_int)

    @property
    def lower_bound(self) -> Optional[float]:
        if self.other_bound_int is not None:
            return float(self.other_bound_int)
        return self.other_bound_dbl

    # host-side evaluation (predict path); device evaluation lives in SplitSet
    def evaluate(self, value) -> bool:
        if self.pred_str == ROOT_PATH or self.operator is None:
            return True
        if self.operator == "in":
            return str(value) in (self.categorical_values or [])
        v = float(value)
        if self.operator == "le":
            ok = v <= self.threshold
            if self.lower_bound is not None:
                ok = ok and v > self.lower_bound
            return ok
        if self.operator == "gt":
            return v > self.threshold
        raise ValueError(f"bad operator {self.operator}")


# --------------------------------------------------------------------------
# candidate split generation (host, from schema — static shapes)
# --------------------------------------------------------------------------

@dataclass
class CandidateSplit:
    attr: int
    predicates: List[Predicate]        # branch order
    thresholds: Optional[List[float]] = None     # numeric
    groups: Optional[List[List[str]]] = None     # categorical

    @property
    def n_branches(self) -> int:
        return len(self.predicates)


def _set_partitions(items: List[str], n_groups: int):
    """All partitions of items into exactly n_groups non-empty groups
    (restricted-growth enumeration; same partition set as
    SplitManager.createCategoricalPartitions, canonical order)."""
    n = len(items)
    if n_groups > n or n_groups < 1:
        return

    def rec(i, groups):
        if i == n:
            if len(groups) == n_groups:
                yield [list(g) for g in groups]
            return
        remaining = n - i - 1  # items left after placing items[i]
        # join an existing group (still need n_groups-len(groups) new groups)
        if remaining >= n_groups - len(groups):
            for g in groups:
                g.append(items[i])
                yield from rec(i + 1, groups)
                g.pop()
        # open a new group
        if len(groups) < n_groups and remaining >= n_groups - len(groups) - 1:
            groups.append([items[i]])
            yield from rec(i + 1, groups)
            groups.pop()

    yield from rec(0, [])


def _numeric_threshold_sets(field: FeatureField) -> List[List[float]]:
    """All increasing threshold tuples on the scan grid with 1..maxSplit-1
    points (SplitManager.createIntPartitions :292-330)."""
    lo, hi = float(field.min), float(field.max)
    interval = float(field.split_scan_interval or 0)
    if interval <= 0 or int((hi - lo) / interval) == 0:
        interval = (hi - lo) / 2
    points = []
    p = lo + interval
    while p < hi:
        points.append(int(p) if field.is_integer else p)
        p += interval
    max_split = field.max_split or 2
    out: List[List[float]] = []
    max_len = max(1, max_split - 1)
    for k in range(1, max_len + 1):
        for combo in itertools.combinations(points, k):
            out.append(list(combo))
    return out


def _numeric_split_predicates(field: FeatureField, thresholds: List[float]
                              ) -> List[Predicate]:
    attr = field.ordinal
    is_int = field.is_integer
    preds = []
    for i, t in enumerate(thresholds):
        if i == 0:
            preds.append(Predicate.num(attr, "le", t, is_int=is_int))
        else:
            preds.append(Predicate.num(attr, "le", t, thresholds[i - 1], is_int=is_int))
    preds.append(Predicate.num(attr, "gt", thresholds[-1], is_int=is_int))
    return preds


def generate_candidate_splits(schema: FeatureSchema,
                              attrs: Optional[Sequence[int]] = None
                              ) -> List[CandidateSplit]:
    """All candidate splits for the given attrs (default: all feature attrs)."""
    out: List[CandidateSplit] = []
    fields = [schema.find_field_by_ordinal(a) for a in attrs] if attrs is not None \
        else schema.feature_fields
    for f in fields:
        if f.is_categorical:
            card = [str(c) for c in (f.cardinality or [])]
            max_split = f.max_split or 2
            for g in range(2, max_split + 1):
                for groups in _set_partitions(card, g):
                    preds = [Predicate.cat(f.ordinal, grp) for grp in groups]
                    out.append(CandidateSplit(attr=f.ordinal, predicates=preds,
                                              groups=groups))
        elif f.is_numeric:
            for thresholds in _numeric_threshold_sets(f):
                preds = _numeric_split_predicates(f, thresholds)
                out.append(CandidateSplit(attr=f.ordinal, predicates=preds,
                                          thresholds=[float(t) for t in thresholds]))
    return out


class SplitSet:
    """Device-side branch evaluator for a fixed list of candidate splits.

    Precomputes (host, once):
      * thresholds  (S, Tmax) float32, +inf padded  — numeric branch =
        sum(x > t), giving branch i == t_{i-1} < x <= t_i
      * cat_table   (S, CardMax) int32              — categorical branch =
        table[split, value_code]
      * attr column index per split into the stacked feature matrix

    ``branch_codes`` then evaluates all splits for all records in one
    vectorized pass — the replacement for the reference's per-record
    predicate loop (DecisionTreeBuilder.java:323-357, HOT LOOP #1).
    """

    def __init__(self, splits: List[CandidateSplit], schema: FeatureSchema):
        self.splits = splits
        self.schema = schema
        feat_fields = schema.feature_fields
        self.feat_ordinals = [f.ordinal for f in feat_fields]
        col_of = {o: i for i, o in enumerate(self.feat_ordinals)}
        S = len(splits)
        tmax = max([len(s.thresholds) for s in splits if s.thresholds] + [1])
        cmax = max([len(f.cardinality or []) for f in feat_fields
                    if f.is_categorical] + [1])
        self.max_branches = max((s.n_branches for s in splits), default=2)
        thr = np.full((S, tmax), np.inf, dtype=np.float32)
        cat_tab = np.zeros((S, cmax), dtype=np.int32)
        is_cat = np.zeros((S,), dtype=bool)
        attr_col = np.zeros((S,), dtype=np.int32)
        for si, s in enumerate(splits):
            attr_col[si] = col_of[s.attr]
            f = schema.find_field_by_ordinal(s.attr)
            if s.groups is not None:
                is_cat[si] = True
                for gi, grp in enumerate(s.groups):
                    for v in grp:
                        cat_tab[si, f.cat_code(v)] = gi
            else:
                thr[si, :len(s.thresholds)] = s.thresholds
        self.thresholds = thr
        self.cat_table = cat_tab
        self.is_cat = is_cat
        self.attr_col = attr_col
        self.n_splits = S

    def feature_matrix(self, table: ColumnarTable) -> np.ndarray:
        """(n, F) feature values (categorical as codes).  Ships int16 when
        every value is integral and in range — exact (int16 -> f32 device
        cast is lossless) and half the f32 upload on the tunnel, which is
        the build's bottleneck at deep row counts; anything else stays
        float32."""
        cols = [table.columns[o] for o in self.feat_ordinals]
        if not cols:
            return np.zeros((table.n_rows, 0), np.float32)

        def narrow_ok(c):
            if c.size == 0:
                return True
            if np.issubdtype(c.dtype, np.integer):
                return bool(c.min() > -(1 << 15) and c.max() < (1 << 15))
            # float column: integral AND in range, checked per column so
            # the first fractional column bails out instead of scanning
            # a full stacked (n, F) f64 matrix
            return bool(np.all((c == np.trunc(c)) &
                               (np.abs(c) < float(1 << 15))))

        if all(narrow_ok(c) for c in cols):
            return np.stack([c.astype(np.int16) for c in cols], axis=1)
        return np.stack([c.astype(np.float32) for c in cols], axis=1)

    def branch_codes(self, X: jnp.ndarray) -> jnp.ndarray:
        """(n, S) int32 branch index of every record under every split.
        Delegates to the module-level jitted kernel so every SplitSet instance
        of the same shape shares ONE compiled program (a per-instance
        ``jax.jit`` used to recompile ~25 s per builder on the tunneled TPU)."""
        note_dispatch(site="ingest.encode")
        return _branch_codes_kernel(X, jnp.asarray(self.attr_col),
                                    jnp.asarray(self.thresholds),
                                    jnp.asarray(self.cat_table),
                                    jnp.asarray(self.is_cat))


def _branch_codes_body(X, attr_col, thresholds, cat_table, is_cat):
    """The branch evaluator's pure body — shared VERBATIM by the eager
    jit below and the fused ingest pipeline stage (one implementation,
    so fused and unfused streams are bit-identical by construction).
    All split-set constants arrive as arrays so callers key on shapes,
    and X may arrive int16 (feature_matrix's narrow wire format) — the
    device upcast below is lossless."""
    # upcast BEFORE the column gather: int16 is not a native TPU compute
    # type, and gathering it lowers far worse than gathering f32
    vals = X.astype(jnp.float32)[:, attr_col]                # (n, S)
    num_branch = (vals[:, :, None] > thresholds[None]
                  ).sum(axis=2).astype(jnp.int32)            # (n, S)
    codes = vals.astype(jnp.int32)
    safe = jnp.clip(codes, 0, cat_table.shape[1] - 1)
    cat_branch = cat_table[
        jnp.arange(thresholds.shape[0])[None, :], safe]      # (n, S)
    return jnp.where(is_cat[None, :], cat_branch, num_branch)


# shared compiled form (see SplitSet.branch_codes): module-level jit so
# every SplitSet instance of the same shape shares one compiled program
_branch_codes_kernel = jax.jit(_branch_codes_body)


def _encode_stage(split_set: SplitSet, cls_ordinal: int):
    """The streaming build's encode stage for the pipeline compiler:
    host half = feature matrix + class codes (runs on the staging
    thread), device half = the exact ``_branch_codes_body``.  Split-set
    tensors travel as stage CONSTANTS (runtime arguments of the fused
    program), so two builders over the same schema/shapes share ONE
    compiled executable — the Execution Templates split between staged
    program and parameters (TPU_NOTES §22)."""
    from ..pipeline.compiler import Stage
    consts = {"attr_col": jnp.asarray(split_set.attr_col),
              "thresholds": jnp.asarray(split_set.thresholds),
              "cat_table": jnp.asarray(split_set.cat_table),
              "is_cat": jnp.asarray(split_set.is_cat)}

    def prepare(block):
        return {"X": split_set.feature_matrix(block),
                "cls": block.columns[cls_ordinal].astype(np.int32)}

    def kernel(carry, consts, inputs, upstream):
        return carry, {"branches": _branch_codes_body(
            inputs["X"], consts["attr_col"], consts["thresholds"],
            consts["cat_table"], consts["is_cat"])}

    return Stage(name="encode", kernel=kernel, version="1",
                 prepare=prepare, consts=consts, returns=("branches",))


# --------------------------------------------------------------------------
# decision path list (the model artifact)
# --------------------------------------------------------------------------

@dataclass
class DecisionPath:
    predicates: List[Predicate]
    population: int
    info_content: float
    stopped: bool
    class_val_pr: Dict[str, float]

    @property
    def path_str(self) -> str:
        return PRED_DELIM.join(p.pred_str for p in self.predicates)

    def predicted_class(self) -> Tuple[str, float]:
        best = max(self.class_val_pr.items(), key=lambda kv: kv[1])
        return best

    def to_dict(self) -> Dict[str, Any]:
        return {
            "stopped": self.stopped,
            "classValPr": self.class_val_pr,
            "infoContent": self.info_content,
            "predicates": [p.to_dict() for p in self.predicates],
            "population": self.population,
        }


@dataclass
class DecisionPathList:
    decision_paths: List[DecisionPath] = dc_field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps({"decisionPaths": [p.to_dict() for p in self.decision_paths]},
                          indent=3)

    @classmethod
    def from_json(cls, text: str) -> "DecisionPathList":
        d = json.loads(text)
        paths = []
        for pd in d.get("decisionPaths", []):
            paths.append(DecisionPath(
                predicates=[Predicate.from_dict(x) for x in pd.get("predicates", [])],
                population=pd.get("population", 0),
                info_content=pd.get("infoContent", 0.0),
                stopped=pd.get("stopped", False),
                class_val_pr=pd.get("classValPr", {})))
        return cls(decision_paths=paths)

    def find(self, path_str: str) -> Optional[DecisionPath]:
        for p in self.decision_paths:
            if p.path_str == path_str:
                return p
        return None


# --------------------------------------------------------------------------
# builder
# --------------------------------------------------------------------------

@dataclass
class TreeParams:
    """The dtb.* knobs (resource/detr.properties / rafo.properties)."""
    split_algorithm: str = "entropy"            # entropy | giniIndex
    attr_select_strategy: str = "notUsedYet"    # all|notUsedYet|randomAll|randomNotUsedYet
    random_split_set_size: int = 3              # dtb.random.split.set.size
    split_select_strategy: str = "best"         # best | randomAmongTop
    top_split_count: int = 3                    # dtb.custom.base.attribute.ordinals? no: top count
    stopping_strategy: str = "maxDepth"         # maxDepth|minPopulation|minInfoGain
    max_depth: int = 3
    min_info_gain: float = -1.0
    min_population: int = -1
    sub_sampling: str = "none"                  # none|withReplace|withoutReplace
    sub_sampling_rate: float = 100.0            # percent
    seed: Optional[int] = None

    def should_stop(self, population: float, info_content: float,
                    parent_info: float, depth: int) -> bool:
        """DecisionPathStoppingStrategy.shouldStop :57-69."""
        if self.stopping_strategy == "minPopulation":
            return population < self.min_population
        if self.stopping_strategy == "minInfoGain":
            return (parent_info - info_content) < self.min_info_gain
        if self.stopping_strategy == "maxDepth":
            return depth >= self.max_depth
        raise ValueError(f"invalid stopping strategy {self.stopping_strategy}")


def _info(counts: np.ndarray, algo: str, axis=-1) -> np.ndarray:
    """entropy (log2) or gini of count vectors along axis
    (util/InfoContentStat.java:71-95)."""
    total = counts.sum(axis=axis, keepdims=True)
    p = counts / np.maximum(total, 1e-12)
    if algo == "entropy":
        with np.errstate(divide="ignore", invalid="ignore"):
            logp = np.where(p > 0, np.log2(np.maximum(p, 1e-300)), 0.0)
        return -(p * logp).sum(axis=axis)
    # giniIndex
    return 1.0 - (p * p).sum(axis=axis)


class _LeafState:
    __slots__ = ("predicates", "depth", "info_content", "population",
                 "class_val_pr", "used_attrs", "stopped")

    def __init__(self, predicates, depth, info_content, population,
                 class_val_pr, used_attrs, stopped):
        self.predicates = predicates
        self.depth = depth
        self.info_content = info_content
        self.population = population
        self.class_val_pr = class_val_pr
        self.used_attrs = used_attrs
        self.stopped = stopped


def sampling_weights(n: int, params: TreeParams,
                     rng: np.random.Generator) -> Optional[np.ndarray]:
    """First-iteration sub-sampling as per-record weights
    (DecisionTreeBuilder rootMapHelper :208-244): withReplace -> bootstrap
    multinomial counts at rate% of n; withoutReplace -> Bernoulli(rate%);
    none -> None."""
    if params.sub_sampling == "withReplace":
        m = int(n * params.sub_sampling_rate / 100.0)
        # uniform multinomial == histogram of m uniform draws (much faster
        # than rng.multinomial's per-category walk at bootstrap sizes)
        counts = np.bincount(rng.integers(0, n, size=m), minlength=n)
        return counts.astype(np.float32)
    if params.sub_sampling == "withoutReplace":
        keep = rng.random(n) < (params.sub_sampling_rate / 100.0)
        return keep.astype(np.float32)
    return None


@functools.partial(jax.jit, donate_argnums=(0,))
def acc_counts(acc, c):
    """Fused chunk accumulate (astype + add in ONE dispatch): the eager
    pair costs two dispatches per chunk in the deep-scale chunked regime.
    Shared by the single-tree and forest builders.  The running
    accumulator is DONATED — every caller rebinds ``acc = acc_counts(acc,
    c)``, so XLA updates the (N, S, B, C) buffer in place instead of
    copying it per chunk."""
    return acc + c.astype(jnp.int32)


def level_chunk(n_nodes: int, n_trees: int, S: int, B: int, C: int,
                w_max: float, mem_elems: int = 128 << 20) -> int:
    """Rows per level-kernel launch, bounded by (a) the f32 one-hot
    intermediates — (chunk, T, N) node one-hot + (chunk, C, S, B) class x
    branch one-hot — staying under ``mem_elems`` f32 elements (~512 MB),
    and (b) exactness: per-cell f32 partial sums stay exact integers while
    the chunk's weight mass is < 2^24 (weights are integral: bootstrap
    counts / Bernoulli keeps / ones).  A 400k x 16-tree level fits in ONE
    launch; the old fixed 2^19/T chunking issued 13+ dispatch-latency-bound
    launches per level on the tunneled TPU (VERDICT r2 weak #1a)."""
    per_row = max(n_trees * max(n_nodes, 1) + C * S * B, 1)
    mem_chunk = max(mem_elems // per_row, 1)
    exact_chunk = max(int(((1 << 24) - 1) / max(w_max, 1.0)), 1)
    return max(1024, min(mem_chunk, exact_chunk))


@functools.lru_cache(maxsize=None)
def make_level_count_kernel(S: int, B: int, C: int):
    """The tree builder's hot kernel: one frontier pass of histogramming
    (the reference reducer accumulation, tree/DecisionTreeBuilder.java
    :730-767, as a single one-hot contraction).  Module-level so the driver
    compile-check (__graft_entry__) exercises the exact production kernel."""
    def kernel(node_ids, branches, cls_codes, weights, n_nodes):
        """counts[node, split, branch, class] for active records
        (node_id >= 0).  n_nodes is static per level.  weights may arrive
        as uint16 (the compact host->device transfer form) or f32."""
        active = (node_ids >= 0)
        w = weights.astype(jnp.float32) * active.astype(jnp.float32)
        nc = jnp.where(active, node_ids, 0) * C + cls_codes       # (n,)
        oh_nc = jax.nn.one_hot(nc, n_nodes * C, dtype=jnp.float32) * w[:, None]
        oh_b = jax.nn.one_hot(branches, B, dtype=jnp.float32)     # (n, S, B)
        # HIGHEST: TPU default matmul precision would round weights > 256
        # (carried by oh_nc) through bf16 before accumulating
        counts = jnp.einsum("na,nsb->asb", oh_nc, oh_b,
                            precision=jax.lax.Precision.HIGHEST)  # (N*C, S, B)
        return counts.reshape(n_nodes, C, S, B).transpose(0, 2, 3, 1)
    return kernel


@functools.lru_cache(maxsize=None)
def _jitted_level_count_kernel(S: int, B: int, C: int):
    return jax.jit(make_level_count_kernel(S, B, C), static_argnums=4)


def _save_stream_checkpoint(mgr, blocks_done: int, br_parts, cls_parts,
                            mask_parts, n_rows: int,
                            source_rows_done: Optional[int],
                            complete: bool, shard=None) -> None:
    """Persist the accumulated streamed-ingest state as one checkpoint
    step.  Full-state snapshots (not increments): any single intact step
    is sufficient to resume, which is what lets CheckpointManager retain
    only the newest few and skip corrupt ones.  The host copies force a
    device sync — size the ``checkpoint_every`` stride so this stays a
    small fraction of ingest time."""
    with span("checkpoint.write", cat="checkpoint", blocks=blocks_done,
              rows=int(n_rows), complete=bool(complete)):
        _save_stream_checkpoint_body(mgr, blocks_done, br_parts, cls_parts,
                                     mask_parts, n_rows, source_rows_done,
                                     complete, shard)


def _save_stream_checkpoint_body(mgr, blocks_done, br_parts, cls_parts,
                                 mask_parts, n_rows, source_rows_done,
                                 complete, shard):
    arrays = {
        "branches": np.concatenate([np.asarray(p) for p in br_parts])
        if br_parts else np.zeros((0, 0), np.int32),
        "cls_codes": np.concatenate([np.asarray(p) for p in cls_parts])
        if cls_parts else np.zeros((0,), np.int32),
        "mask": np.concatenate(mask_parts)
        if mask_parts else np.zeros((0,), np.float32),
    }
    meta = {"n_rows": int(n_rows), "blocks_done": int(blocks_done),
            "source_rows_done": None if source_rows_done is None
            else int(source_rows_done),
            "ingest_complete": bool(complete)}
    if shard is not None:
        # the shard spec travels with the checkpoint: a sharded build's
        # state is one shard's rows, and resuming it under a different
        # process count would re-partition the file around it
        meta["shard"] = {"index": int(shard.index),
                         "count": int(shard.count)}
    mgr.save(blocks_done, arrays, meta)


class TreeBuilder:
    """Level-synchronous tree growth over a device mesh.

    One instance holds the device-resident encoded features and branch codes;
    ``build()`` runs the whole iterative loop (the reference's shell-script
    rotation detr.sh:35-41 collapsed into Python), ``run_level()`` exposes a
    single level for the per-level job parity mode.
    """

    def __init__(self, table: ColumnarTable, params: TreeParams,
                 ctx: Optional[MeshContext] = None,
                 splits: Optional[List[CandidateSplit]] = None):
        self.ctx = ctx or runtime_context()
        self.params = params
        self.schema = table.schema
        self.class_field = self.schema.class_attr_field
        self.class_values = list(self.class_field.cardinality or [])
        self.C = len(self.class_values)
        self.splits = splits if splits is not None else \
            generate_candidate_splits(self.schema)
        self.split_set = SplitSet(self.splits, self.schema)
        self.rng = np.random.default_rng(params.seed)
        self.pyrng = pyrandom.Random(params.seed)

        padded = table.pad_to_multiple(self.ctx.n_devices)
        self.n_rows = table.n_rows
        self.n_padded = padded.n_rows
        X = self.split_set.feature_matrix(padded)
        # streamed uploads: the deep-scale bottleneck is the host->device
        # link, and one opaque multi-hundred-MB device_put is exactly the
        # transfer shape that stalled the tunnel at 20M rows (TPU_NOTES
        # section 7) — chunked transfers keep progress observable
        self.X = self.ctx.shard_rows_streamed(X)
        self.cls_codes = self.ctx.shard_rows_streamed(
            padded.columns[self.class_field.ordinal].astype(np.int32))
        # host copy of the padding mask: weight builders multiply by it on
        # host, so the mask never needs a device copy or round-trip
        self.mask_np = padded.valid_mask.astype(np.float32)
        # branch codes computed once; (n, S) int32 on device.  All kernels
        # (branch codes, level counts, reassign) are module-level jits keyed
        # on shapes, so a new builder per forest/bench run never recompiles.
        self.branches = self.split_set.branch_codes(self.X)

        S, B, C = self.split_set.n_splits, self.split_set.max_branches, self.C
        self._count_kernel = _jitted_level_count_kernel(S, B, C)
        self._reassign_kernel = _REASSIGN_JIT
        # single-host/monolithic: no cross-process reduce, weights map 1:1
        self._reducer = None
        self._local_rows = self.n_rows
        self._row_offset = 0

        # splits grouped by attr for selection strategies
        self.splits_by_attr: Dict[int, List[int]] = {}
        for i, s in enumerate(self.splits):
            self.splits_by_attr.setdefault(s.attr, []).append(i)

    @classmethod
    def from_stream(cls, blocks, schema: FeatureSchema, params: TreeParams,
                    ctx: Optional[MeshContext] = None,
                    splits: Optional[List[CandidateSplit]] = None,
                    stats: Optional[dict] = None,
                    checkpoint=None, checkpoint_every: int = 0,
                    resume_state=None, reducer=None,
                    baseline=None, fuse: bool = True) -> "TreeBuilder":
        """Build the device-resident state from an iterator of ColumnarTable
        row blocks instead of one assembled table — the consume stage of
        the streaming CSV->device ingest pipeline.

        Per block: host feature matrix (narrow int16 wire when exact) ->
        device upload -> branch codes ON DEVICE; only the (n, S) branch
        codes and (n,) class codes stay resident, so peak host memory is
        a couple of in-flight blocks.  The encode + upload runs on a
        dedicated STAGING thread (core.table.stage_chunks, two committed
        buffers deep): block i+1 device_puts while block i's branch-code
        kernel computes, so with a prefetching block source
        (core.table.prefetch_chunks) the pipeline is parse || transfer ||
        compute — three overlapped stages, not two.

        Each block pads independently to the mesh size, so valid rows are
        NOT necessarily a prefix of the device arrays — per-record weights
        are placed by mask position (``_expand_weights``); pad rows carry
        zero weight and node id 0, contributing nothing to any level
        histogram.  Models built from a streamed table are bit-identical
        to ``TreeBuilder(assembled_table, ...)`` (tests/test_forest.py).

        ``stats['transfer_s']`` accumulates staging-thread encode/upload
        time; ``stats['ingest_compute_s']`` the consumer-side branch-code
        dispatch time plus the final device sync (the sync point where
        every outstanding upload AND kernel completes).

        Checkpoint/resume: with a ``checkpoint``
        (core.checkpoint.CheckpointManager) and ``checkpoint_every`` > 0,
        every Nth ingested block persists the accumulated device state
        (branch codes, class codes, pad mask — int32/f32 host copies) plus
        meta ``{n_rows, blocks_done, source_rows_done, ingest_complete}``;
        a final step with ``ingest_complete=True`` lands after the last
        block.  ``resume_state`` is a ``(arrays, meta)`` pair from
        ``CheckpointManager.restore``: the restored state is re-uploaded
        and ``blocks`` must be the REMAINING stream (construct it with
        ``iter_csv_chunks(..., start_row=meta['source_rows_done'])``).
        Because branch/class codes are exact integers and per-record
        weights are placed by mask position over the TRUE row count, an
        interrupted-then-resumed ingest trains the bit-identical model of
        an uninterrupted run (pinned by tests/test_faults.py).

        Multi-host data-parallel mode (``reducer`` — a
        ``parallel.collectives.AllReducer``): ``blocks`` is this
        process's ROW-RANGE SHARD of the source
        (``iter_csv_chunks(shard=(index, count))``), staged onto this
        process's LOCAL devices only (no global array, no lock-step
        block schedule — shards may have unequal block counts).  One
        allgather after ingest exchanges per-shard row counts, giving
        every process the global row total (the bootstrap RNG's
        denominator) and its own global row offset (its slice of the
        globally-drawn weight vectors).  Training then all-reduces ONE
        stacked count matrix per level (``_reduce_counts``), so the host
        epilogue — and therefore the model — is bit-identical on every
        process to the single-host build (TPU_NOTES §20, pinned by
        tests/test_sharded_stream.py).  A shard that owns no rows (more
        processes than blocks) participates with empty arrays.
        Checkpoints persist the shard spec; resume refuses a changed
        process count (the file would be re-partitioned around the saved
        state).

        Pipeline compiler (``fuse=True``, the default — TPU_NOTES §22):
        the per-chunk device work runs as ONE fused XLA program through
        ``avenir_tpu.pipeline.ChunkPipeline`` — the branch-code encode
        plus (with ``baseline``, a ``monitor.baseline.BaselineBuilder``)
        the baseline's bin-count absorb with a DONATED device-resident
        count carry — compiled once per argument signature and cached in
        the process-global ``ProgramCache`` (0 retraces on a warm
        re-run; ``stats['pipeline']`` reports this run's
        chunks/hits/misses/retraces).  ``fuse=False`` keeps the eager
        per-stage path: ``baseline`` then tees the block stream exactly
        like the historic ``tee_blocks`` consumer.  Branch codes, class
        codes, the trained model, and the finalized baseline are
        bit-identical either way (pinned by tests/test_pipeline.py);
        only the launch count per chunk differs."""
        import time as _time
        self = cls.__new__(cls)
        if reducer is not None and ctx is None:
            # shard-local arrays: never route through the multi-host
            # global-array ingest — cross-process sync is the explicit
            # per-level collective
            from ..parallel.mesh import local_context
            ctx = local_context()
        self.ctx = ctx or runtime_context()
        self.params = params
        self.schema = schema
        self.class_field = schema.class_attr_field
        self.class_values = list(self.class_field.cardinality or [])
        self.C = len(self.class_values)
        self.splits = splits if splits is not None else \
            generate_candidate_splits(schema)
        self.split_set = SplitSet(self.splits, schema)
        self.rng = np.random.default_rng(params.seed)
        self.pyrng = pyrandom.Random(params.seed)

        align = self.ctx.n_devices
        cls_ord = self.class_field.ordinal
        spec = reducer.spec if reducer is not None else None
        self._reducer = reducer
        br_parts, cls_parts, mask_parts = [], [], []
        n_rows = 0
        blocks_done = 0
        source_rows_done: Optional[int] = None
        t_compute = 0.0
        if resume_state is not None:
            arrays, meta = resume_state
            saved_shard = meta.get("shard")
            want_shard = None if spec is None else \
                {"index": spec.index, "count": spec.count}
            if saved_shard != want_shard:
                raise ValueError(
                    f"checkpoint belongs to shard {saved_shard}, this "
                    f"process is {want_shard}: a sharded build must "
                    f"resume under the SAME process count and shard "
                    f"assignment (the row-range split would move around "
                    f"the saved state); clear the checkpoint dir to "
                    f"restart cold")
            rb = np.asarray(arrays["branches"], dtype=np.int32)
            if rb.shape[0]:
                if rb.shape[1] != self.split_set.n_splits:
                    raise ValueError(
                        f"checkpoint branch width {rb.shape[1]} does not "
                        f"match the schema's {self.split_set.n_splits} "
                        f"candidate splits; the checkpoint belongs to a "
                        f"different config")
                if rb.shape[0] % align:
                    raise ValueError(
                        f"checkpoint rows {rb.shape[0]} not aligned to the "
                        f"{align}-device mesh it must resume on")
                br_parts.append(self.ctx.shard_rows_streamed(rb))
                cls_parts.append(self.ctx.shard_rows_streamed(
                    np.asarray(arrays["cls_codes"], dtype=np.int32)))
                mask_parts.append(
                    np.asarray(arrays["mask"], dtype=np.float32))
            n_rows = int(meta["n_rows"])
            blocks_done = int(meta.get("blocks_done", 0))
            source_rows_done = meta.get("source_rows_done")
        pipeline = None
        if fuse:
            # the fused per-chunk program (TPU_NOTES §22): encode (+
            # optional baseline absorb) as ONE cached XLA launch per
            # chunk, intermediates device-resident
            from ..pipeline import (ChunkPipeline, mesh_fingerprint,
                                    schema_fingerprint)
            pl_stages = [_encode_stage(self.split_set, cls_ord)]
            if baseline is not None:
                pl_stages.append(baseline.as_stage())
            pipeline = ChunkPipeline(
                pl_stages, ctx=self.ctx,
                schema_fp=schema_fingerprint(schema),
                mesh_fp=mesh_fingerprint(self.ctx, reducer),
                name="rf-ingest")
        elif baseline is not None:
            # unfused: the historic host-side tee — the baseline rides
            # the same single pass as a second consumer of each block
            from ..monitor.baseline import tee_blocks
            blocks = tee_blocks(blocks, baseline)

        def _stage(block):
            """Staging-thread half of the ingest: host encode + padded
            device upload of ONE block (its time lands in
            stats['transfer_s'] via stage_chunks).  Only numpy work and
            async device_puts happen here; the branch-code kernel stays
            on the consumer thread."""
            bn = block.n_rows
            pad = (-bn) % align
            X = self.split_set.feature_matrix(block)
            cc = block.columns[cls_ord].astype(np.int32)
            if pad:
                X = np.pad(X, ((0, pad), (0, 0)))
                cc = np.pad(cc, (0, pad))
            mask = np.zeros((bn + pad,), dtype=np.float32)
            mask[:bn] = 1.0
            Xd = self.ctx.shard_rows_streamed(X)
            ccd = self.ctx.shard_rows_streamed(cc)
            return ((Xd, ccd), mask, bn,
                    getattr(block, "source_row_end", None))

        def _stage_fused(block):
            """Pipeline twin of ``_stage``: every stage's host prepare
            (feature matrix, class codes, monitor codes) runs here, all
            arrays pad uniformly to the mesh alignment, and the merged
            input dict uploads onto the staging thread's own buffers."""
            bn = block.n_rows
            pad = (-bn) % align
            host = pipeline.prepare(block)
            if pad:
                host = {k: np.pad(v, ((0, pad),) + ((0, 0),) * (v.ndim - 1))
                        for k, v in host.items()}
            mask = np.zeros((bn + pad,), dtype=np.float32)
            mask[:bn] = 1.0
            host["mask"] = mask
            return (pipeline.upload(host), mask, bn,
                    getattr(block, "source_row_end", None))

        for dev, mask, bn, src_end in stage_chunks(
                blocks, _stage_fused if pipeline is not None else _stage,
                depth=2, stats=stats):
            t0 = _time.perf_counter()
            with span("device.compute", cat="compute", block=blocks_done,
                      rows=bn):
                if pipeline is not None:
                    outs = pipeline.run_chunk(dev)
                    br_parts.append(outs["encode.branches"])
                    cls_parts.append(dev["cls"])
                else:
                    Xd, ccd = dev
                    br_parts.append(self.split_set.branch_codes(Xd))
                    cls_parts.append(ccd)
            mask_parts.append(mask)
            n_rows += bn
            blocks_done += 1
            if src_end is not None:
                source_rows_done = int(src_end)
            t_compute += _time.perf_counter() - t0
            if (checkpoint is not None and checkpoint_every > 0
                    and blocks_done % checkpoint_every == 0):
                _save_stream_checkpoint(
                    checkpoint, blocks_done, br_parts, cls_parts,
                    mask_parts, n_rows, source_rows_done, False,
                    shard=spec)
        if checkpoint is not None and checkpoint_every > 0:
            # the ingest-complete step: a crash in the BUILD phase resumes
            # straight to training, re-reading zero source rows
            _save_stream_checkpoint(
                checkpoint, blocks_done, br_parts, cls_parts, mask_parts,
                n_rows, source_rows_done, True, shard=spec)
        t0 = _time.perf_counter()
        if not br_parts and spec is None:
            # the monolithic path cannot train on 0 rows either; fail with
            # the cause instead of a downstream shape error
            raise ValueError("from_stream got an empty block stream "
                             "(no rows to train on)")
        from ..parallel.mesh import _concat_jit
        if not br_parts:
            # a sharded participant that owns no blocks (more processes
            # than ingest blocks): it still joins every collective with
            # all-zero partials
            S = self.split_set.n_splits
            self.branches = jnp.zeros((0, S), jnp.int32)
            self.cls_codes = jnp.zeros((0,), jnp.int32)
            mask_parts = [np.zeros((0,), np.float32)]
        elif len(br_parts) == 1:
            self.branches, self.cls_codes = br_parts[0], cls_parts[0]
        else:
            sharding = self.ctx.row_sharding()
            self.branches = _concat_jit(len(br_parts), sharding)(br_parts)
            self.cls_codes = _concat_jit(len(cls_parts), sharding)(cls_parts)
        self.mask_np = np.concatenate(mask_parts)
        self._local_rows = n_rows
        self._row_offset = 0
        if reducer is not None:
            # ONE allgather: every process learns the global row total
            # (the RNG denominator — the model bytes must not depend on
            # the shard layout) and its own offset into the globally
            # drawn per-record weight vectors
            per_shard = reducer.allgather(int(n_rows))
            self._row_offset = int(sum(per_shard[:spec.index]))
            n_rows = int(sum(per_shard))
            if n_rows == 0:
                raise ValueError("sharded from_stream: no shard produced "
                                 "any rows (empty source)")
        self.n_rows = n_rows
        self.n_padded = int(self.mask_np.shape[0])
        # the streamed state never keeps the feature matrix: branch codes
        # are the only per-record view any level kernel reads
        self.X = None
        with span("device.compute", cat="compute", phase="final_sync"):
            jax.block_until_ready((self.branches, self.cls_codes))
        if pipeline is not None:
            # hand each stage its final donated carry (the baseline's
            # accumulated device counts install back into its builder)
            pipeline.finalize()
        t_compute += _time.perf_counter() - t0
        if stats is not None:
            stats["ingest_compute_s"] = (stats.get("ingest_compute_s", 0.0)
                                         + t_compute)
            if pipeline is not None:
                # per-run program-cache tallies: the warm-re-run
                # "0 retraces" acceptance counter reads these
                stats["pipeline"] = pipeline.run_stats()

        S, B, C = self.split_set.n_splits, self.split_set.max_branches, self.C
        self._count_kernel = _jitted_level_count_kernel(S, B, C)
        self._reassign_kernel = _REASSIGN_JIT
        self.splits_by_attr = {}
        for i, s in enumerate(self.splits):
            self.splits_by_attr.setdefault(s.attr, []).append(i)
        return self

    def _expand_weights(self, w: Optional[np.ndarray]) -> np.ndarray:
        """Per-record weights drawn over the TRUE row count, placed at the
        valid positions of the padded device layout (zero on pad rows).
        The monolithic path's mask is a prefix, where this reduces to the
        old pad-then-mask form byte for byte; streamed ingest pads per
        block, so valid positions may interleave with padding.

        Sharded streams draw ``w`` over the GLOBAL row count (every
        process replays the identical RNG stream) and keep only this
        shard's slice — global row i gets the same weight whichever host
        holds it, which is half of what makes the sharded model
        bit-identical (the other half is the per-level count reduce)."""
        if w is None:
            w = np.ones((self.n_rows,), dtype=np.float32)
        if self._reducer is not None:
            w = w[self._row_offset:self._row_offset + self._local_rows]
        full = np.zeros((self.n_padded,), dtype=np.float32)
        full[self.mask_np > 0] = w.astype(np.float32)
        return full

    def with_params(self, params: TreeParams) -> "TreeBuilder":
        """Shallow copy sharing the device-resident encoded data and compiled
        kernels, with fresh params/RNG — one bootstrap tree of a forest."""
        b = TreeBuilder.__new__(TreeBuilder)
        b.__dict__.update(self.__dict__)
        b.params = params
        b.rng = np.random.default_rng(params.seed)
        b.pyrng = pyrandom.Random(params.seed)
        return b

    def _reduce_counts(self, counts: np.ndarray) -> np.ndarray:
        """The ONE cross-process collective per tree level (TPU_NOTES
        §20): sum this shard's stacked count matrix with every peer's —
        after it, all processes hold the identical global histogram and
        the host epilogue (split choice, stopping, RNG draws) replays
        identically everywhere.  Exact: counts are integers, so the sum
        is order-independent and the sharded model is bit-identical to
        the single-host build.  No-op on monolithic builds (no reducer);
        a sharded build still records the collective site into the
        ledger's ``Collectives`` group even at shard count 1, which is
        what lets a single-process test pin the
        one-all-reduce-per-level discipline.

        Wire dtype is chosen from a GLOBALLY AGREED bound, never from
        this shard's values (every process must issue the identical
        collective — see AllReducer._jax_sum): a count cell is at most
        the global weight mass, which every process can derive from the
        global row count and the sub-sampling rate alone.  Within int32
        the payload rides the device psum path on a real pod; past it
        (toward the 1B-row regime with heavy bootstrap rates) it ships
        int64 over the exact host transport."""
        if self._reducer is None:
            return counts
        p = self.params
        rate = p.sub_sampling_rate / 100.0 \
            if p.sub_sampling != "none" else 1.0
        mass_bound = float(self.n_rows) * max(1.0, rate)
        wire = np.int32 if mass_bound < float(2 ** 31 - 1) else np.int64
        return self._reducer.sum(counts.astype(wire)).astype(np.float64)

    # ---- kernels ----
    def _make_count_kernel(self, S, B, C):
        return make_level_count_kernel(S, B, C)

    @staticmethod
    def _reassign(node_ids, branches, sel_split, child_table):
        """new node id = child_table[node, branch of selected split]
        (the reducer's re-tagging of records :764-765, as a device gather)."""
        active = node_ids >= 0
        node_safe = jnp.where(active, node_ids, 0)
        sel = sel_split[node_safe]                                    # (n,)
        br = jnp.take_along_axis(branches, sel[:, None], axis=1)[:, 0]
        new_ids = child_table[node_safe, br]
        return jnp.where(active & (sel >= 0), new_ids,
                         jnp.where(active, -2, node_ids))  # -2: stopped leaf member

    # ---- level counts ----
    def level_counts(self, node_ids, weights, n_nodes: int,
                     chunk: Optional[int] = None,
                     w_max: Optional[float] = None,
                     integral: Optional[bool] = None) -> np.ndarray:
        """(N, S, B, C) float64 counts for the level.

        Device-resident accumulation end to end: each chunk's f32 partial
        sums are exact integers (chunk weight mass is capped below 2^24 by
        ``level_chunk``), converted to int32 on device and accumulated there
        — exact up to 2^31 per cell, i.e. beyond the 100M-row north-star
        regime — with ONE host transfer per level.  Fractional weights (no
        caller today) fall back to host float64 accumulation."""
        S, B, C = self.split_set.n_splits, self.split_set.max_branches, self.C
        n = self.n_padded
        if w_max is None:
            w_max = getattr(self, "_w_max", None)
        if integral is None:
            integral = getattr(self, "_w_integral", True)
        if chunk is None:
            chunk = level_chunk(n_nodes, 1, S, B, C,
                                w_max if w_max is not None else 1.0)
        if integral and n > chunk:
            acc = None
            for start in range(0, n, chunk):
                end = min(start + chunk, n)
                note_dispatch(2, site="tree.level")  # count + accumulate
                c = self._count_kernel(
                    node_ids[start:end], self.branches[start:end],
                    self.cls_codes[start:end], weights[start:end], n_nodes)
                acc = c.astype(jnp.int32) if acc is None \
                    else acc_counts(acc, c)
            return self._reduce_counts(fetch(acc, dtype=np.float64))
        if n <= chunk:
            note_dispatch(site="tree.level")
            c = self._count_kernel(node_ids, self.branches, self.cls_codes,
                                   weights, n_nodes)
            return self._reduce_counts(fetch(c, dtype=np.float64))
        total = np.zeros((n_nodes, S, B, C), dtype=np.float64)
        for start in range(0, n, chunk):
            end = min(start + chunk, n)
            note_dispatch(site="tree.level")
            c = self._count_kernel(node_ids[start:end], self.branches[start:end],
                                   self.cls_codes[start:end], weights[start:end],
                                   n_nodes)
            total += fetch(c, dtype=np.float64)
        return self._reduce_counts(total)

    # ---- attribute selection (DecisionTreeBuilder.getSplitAttributes :365-381)
    def _allowed_attrs(self, leaf: _LeafState) -> List[int]:
        strategy = self.params.attr_select_strategy
        all_attrs = list(self.splits_by_attr.keys())
        if strategy == "all":
            return all_attrs
        if strategy == "notUsedYet":
            return [a for a in all_attrs if a not in leaf.used_attrs] or all_attrs
        if strategy == "randomAll":
            k = min(self.params.random_split_set_size, len(all_attrs))
            return self.pyrng.sample(all_attrs, k)
        if strategy == "randomNotUsedYet":
            cand = [a for a in all_attrs if a not in leaf.used_attrs] or all_attrs
            k = min(self.params.random_split_set_size, len(cand))
            return self.pyrng.sample(cand, k)
        raise ValueError(f"invalid attr selection strategy {strategy}")

    # ---- the full build loop ----
    def build(self, max_levels: Optional[int] = None) -> DecisionPathList:
        p = self.params
        # draw over the TRUE row count, pad with zeros: the RNG stream (and
        # therefore the model bytes) must depend on the data only, never on
        # how many pad rows the mesh size added
        weights_np = self._expand_weights(
            sampling_weights(self.n_rows, p, self.rng))
        self._w_max = float(weights_np.max()) if weights_np.size else 1.0
        self._w_integral = True  # sampling_weights are counts/keeps/ones
        weights = self.ctx.shard_rows(weights_np.astype(np.float32))

        # root pass (generateRoot :478-494)
        node_ids = self.ctx.shard_rows(np.zeros((self.n_padded,), dtype=np.int32))
        counts = self.level_counts(node_ids, weights, 1)
        root = self._root_state(counts[0])
        root_pop, root_info, root_pr = \
            root.population, root.info_content, root.class_val_pr
        leaves = [root]
        final_paths: List[DecisionPath] = []

        levels = max_levels if max_levels is not None else \
            (p.max_depth if p.stopping_strategy == "maxDepth" else 64)
        for level in range(levels):
            active = [l for l in leaves if not l.stopped]
            if not active:
                break
            leaves, stopped_paths, node_ids = self._grow(active, node_ids, weights)
            final_paths.extend(stopped_paths)
            if not leaves:
                break

        # any leaves still active at the end become stopped paths
        for leaf in leaves:
            final_paths.append(DecisionPath(
                predicates=leaf.predicates, population=int(round(leaf.population)),
                info_content=leaf.info_content, stopped=True,
                class_val_pr=leaf.class_val_pr))
        if not final_paths:
            final_paths.append(DecisionPath(
                predicates=[Predicate.root()], population=int(round(root_pop)),
                info_content=root_info, stopped=True, class_val_pr=root_pr))
        return DecisionPathList(decision_paths=final_paths)

    def _root_state(self, counts0: np.ndarray) -> _LeafState:
        """Root leaf from a (S, B, C) root-level count block
        (generateRoot :478-494; every split partitions the full population,
        so averaging over splits recovers the root class histogram)."""
        root_class = counts0.sum(axis=(0, 1)) / max(self.split_set.n_splits, 1)
        pop = float(root_class.sum())
        info = float(_info(root_class[None], self.params.split_algorithm)[0])
        pr = {cv: float(root_class[i] / max(pop, 1e-12))
              for i, cv in enumerate(self.class_values)}
        return _LeafState([Predicate.root()], 0, info, pop, pr, set(), False)

    def _grow(self, active: List[_LeafState], node_ids, weights):
        """One level of frontier expansion (the expandTree epilogue
        :499-616): compute counts, choose per-node winning split, derive
        children + stopping, reassign records on device.
        Returns (new_active_leaves, newly_stopped_DecisionPaths, new_node_ids)."""
        counts = self.level_counts(node_ids, weights, len(active))
        new_leaves, stopped_paths, sel_split, child_table = \
            self._choose_splits(active, counts)
        note_dispatch(site="tree.reassign")
        node_ids = self._reassign_kernel(
            node_ids, self.branches,
            self.ctx.replicate(jnp.asarray(sel_split)),
            self.ctx.replicate(jnp.asarray(child_table)))
        return new_leaves, stopped_paths, node_ids

    def _choose_splits(self, active: List[_LeafState], counts: np.ndarray):
        """Host epilogue of one level: per active node pick the winning split
        from its (S, B, C) counts, derive children + stopping.  Shared by the
        single-tree path and ForestBuilder (which batches the count kernel
        across trees and calls this once per tree).
        Returns (new_leaves, stopped_paths, sel_split (N,), child_table (N,B))."""
        p = self.params
        n_nodes = len(active)
        sel_split = np.full((n_nodes,), -1, dtype=np.int32)
        child_table = np.full((n_nodes, self.split_set.max_branches), -1,
                              dtype=np.int32)
        new_leaves: List[_LeafState] = []
        stopped_paths: List[DecisionPath] = []
        for ni, leaf in enumerate(active):
            attrs = self._allowed_attrs(leaf)
            cand_splits = [si for a in attrs for si in self.splits_by_attr[a]]
            if not cand_splits:
                leaf.stopped = True
                stopped_paths.append(DecisionPath(
                    predicates=leaf.predicates,
                    population=int(round(leaf.population)),
                    info_content=leaf.info_content, stopped=True,
                    class_val_pr=leaf.class_val_pr))
                continue
            node_counts = counts[ni]                       # (S, B, C)
            br_tot = node_counts.sum(axis=2)               # (S, B)
            info = _info(node_counts, p.split_algorithm)   # (S, B)
            tot = br_tot.sum(axis=1)                       # (S,)
            weighted = (info * br_tot).sum(axis=1) / np.maximum(tot, 1e-12)
            order = sorted(cand_splits, key=lambda si: weighted[si])
            if p.split_select_strategy == "randomAmongTop":
                top = order[:max(1, p.top_split_count)]
                chosen = self.pyrng.choice(top)
            else:
                chosen = order[0]
            sel_split[ni] = chosen
            split = self.splits[chosen]
            # children: only branches that received records (the reducer only
            # sees keys that were emitted)
            for b in range(split.n_branches):
                pop = float(br_tot[chosen, b])
                if pop <= 0:
                    continue
                cdist = node_counts[chosen, b]
                cinfo = float(_info(cdist[None], p.split_algorithm)[0])
                cpr = {cv: float(cdist[i] / pop)
                       for i, cv in enumerate(self.class_values)}
                preds = leaf.predicates + [split.predicates[b]]
                stopped = p.should_stop(pop, cinfo, leaf.info_content,
                                        len(preds) - 1)
                child = _LeafState(preds, leaf.depth + 1, cinfo, pop, cpr,
                                   leaf.used_attrs | {split.attr}, stopped)
                if stopped:
                    stopped_paths.append(DecisionPath(
                        predicates=preds, population=int(round(pop)),
                        info_content=cinfo, stopped=True, class_val_pr=cpr))
                else:
                    child_table[ni, b] = len(new_leaves)
                    new_leaves.append(child)
        return new_leaves, stopped_paths, sel_split, child_table

    # ---- per-level job parity mode (detr.sh rotation contract) ----
    @staticmethod
    def _leaf_from_path(path: DecisionPath) -> _LeafState:
        used = {pr.attribute for pr in path.predicates if pr.operator is not None}
        return _LeafState(path.predicates, len(path.predicates) - 1,
                          path.info_content, path.population, path.class_val_pr,
                          used, path.stopped)

    def assign_node_ids(self, table: ColumnarTable,
                        active: List[_LeafState]) -> np.ndarray:
        """Route records to active leaves by evaluating predicate chains
        (what the reference gets for free from its re-tagged record files).
        Leaf paths compile to a PathMatrix and every record routes in one
        vectorized first-match pass — the old per-leaf-per-predicate host
        loop was O(leaves x depth x n) full-column numpy work (VERDICT r2
        weak #8); leaves partition the frontier, so first-match equals the
        old last-writer-wins assignment."""
        dpl = DecisionPathList([
            DecisionPath(predicates=l.predicates, population=0,
                         info_content=0.0, stopped=False, class_val_pr={})
            for l in active])
        ids = np.full((self.n_padded,), -1, dtype=np.int32)
        # numpy twin: the frontier's path count changes every level, so the
        # device kernel would recompile per call for host-instant work
        ids[:table.n_rows] = PathMatrix(dpl, self.schema).match_index(
            table, use_device=False)
        return ids

    def build_one_level(self, table: ColumnarTable,
                        dpl: Optional[DecisionPathList]) -> DecisionPathList:
        """One invocation of the reference DecisionTreeBuilder job: iteration 0
        (dpl None) writes the root path; otherwise expands every non-stopped
        path one level.  Stopped paths are carried forward so the output file
        is always a complete tree."""
        weights_np = np.ones((self.n_padded,), dtype=np.float32)
        weights_np *= self.mask_np
        self._w_max, self._w_integral = 1.0, True
        weights = self.ctx.shard_rows(weights_np)
        if dpl is None or not dpl.decision_paths:
            node_ids = self.ctx.shard_rows(np.zeros((self.n_padded,), np.int32))
            counts = self.level_counts(node_ids, weights, 1)
            root = self._root_state(counts[0])
            return DecisionPathList([DecisionPath(
                predicates=[Predicate.root()],
                population=int(round(root.population)),
                info_content=root.info_content, stopped=False,
                class_val_pr=root.class_val_pr)])
        carried = [p for p in dpl.decision_paths if p.stopped]
        active = [self._leaf_from_path(p) for p in dpl.decision_paths
                  if not p.stopped]
        if not active:
            return dpl
        node_ids = self.ctx.shard_rows(self.assign_node_ids(table, active))
        new_leaves, stopped_paths, _ = self._grow(active, node_ids, weights)
        paths = carried + stopped_paths + [
            DecisionPath(predicates=l.predicates,
                         population=int(round(l.population)),
                         info_content=l.info_content, stopped=False,
                         class_val_pr=l.class_val_pr)
            for l in new_leaves]
        return DecisionPathList(paths)


# process-wide jit of the (pure, static) reassignment kernel: every builder
# shares one compiled version per shape signature.  node_ids is DONATED —
# the level loop always rebinds ``node_ids = reassign(node_ids, ...)`` and
# the output has identical shape/dtype/sharding, so XLA re-tags records in
# the same HBM buffer instead of the defensive copy it makes per dispatch
_REASSIGN_JIT = jax.jit(TreeBuilder._reassign, donate_argnums=(0,))


# --------------------------------------------------------------------------
# prediction over a DecisionPathList (tree/DecisionTreeModel.java)
# --------------------------------------------------------------------------

def _match_ok(vals, codes, lo, hi, num_restricted, cat_mask, cat_restricted,
              xp):
    """(n, P) bool match matrix shared by the jnp and numpy backends (xp is
    the array namespace): record matches path iff every restricted feature
    passes its interval / allowed-code mask.

    The device backend computes categorical membership as a one-hot einsum
    — the (n, P, F) advanced-index gather lowers to a scalar loop on TPU
    and throttled predict to ~0.6M rows/sec; exact because each (n, f) row
    of the one-hot selects a single 0/1 mask cell."""
    P, F = lo.shape
    if xp is jnp:
        # vals may arrive int16 (FeatureCache narrow wire); upcast on
        # device — lossless, and keeps the comparisons in native f32.
        # The numpy twin keeps the incoming dtype: int16 vs f64 bounds
        # promotes exactly, and its f64 vals must stay f64.
        vals = vals.astype(jnp.float32)
    interval = (vals[:, None, :] > lo[None]) & (vals[:, None, :] <= hi[None])
    num_ok = xp.where(num_restricted[None], interval, True)
    C = cat_mask.shape[2]
    safe = xp.clip(codes, 0, C - 1)
    if xp is jnp:
        oh = jax.nn.one_hot(safe, C, dtype=jnp.float32)        # (n, F, C)
        gathered = jnp.einsum("nfc,pfc->npf", oh,
                              cat_mask.astype(jnp.float32)) > 0
    else:
        gathered = cat_mask[xp.arange(P)[None, :, None],
                            xp.arange(F)[None, None, :],
                            safe[:, None, :]]                  # (n, P, F)
    cat_ok = xp.where(cat_restricted[None],
                      gathered & (codes >= 0)[:, None, :], True)
    return (num_ok & cat_ok).all(axis=2)


@jax.jit
def _match_first(vals, codes, lo, hi, num_restricted, cat_mask,
                 cat_restricted):
    """(n,) int32 index of the first matching path, -1 if none."""
    ok = _match_ok(vals, codes, lo, hi, num_restricted, cat_mask,
                   cat_restricted, jnp)
    return jnp.where(ok.any(axis=1), jnp.argmax(ok, axis=1), -1).astype(
        jnp.int32)


@jax.jit
def _match_paths(vals: jnp.ndarray,        # (n, F) float
                 codes: jnp.ndarray,       # (n, F) int32 (cat codes, -1 unk)
                 lo: jnp.ndarray,          # (P, F) interval lower (exclusive)
                 hi: jnp.ndarray,          # (P, F) interval upper (inclusive)
                 num_restricted: jnp.ndarray,  # (P, F) bool numeric pred exists
                 cat_mask: jnp.ndarray,    # (P, F, Cmax) bool allowed codes
                 cat_restricted: jnp.ndarray,  # (P, F) bool 'in' pred exists
                 path_cls: jnp.ndarray,    # (P,) int32 class idx per path
                 path_prob: jnp.ndarray,   # (P,) float32
                 fallback_cls: jnp.ndarray,   # () int32
                 fallback_prob: jnp.ndarray,  # () float32
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All paths x all records in one fused pass: a record matches a path iff
    its value lies in every *numerically restricted* feature's (lo, hi]
    interval and its code is in every *'in'-restricted* categorical mask;
    unrestricted features never veto (so NaN/garbage in a column a path does
    not test cannot block the match — same as the reference's per-predicate
    walk, tree/DecisionTreeModel.java:37-42).  First matching path wins."""
    ok = _match_ok(vals, codes, lo, hi, num_restricted, cat_mask,
                   cat_restricted, jnp)
    matched = ok.any(axis=1)
    first = jnp.argmax(ok, axis=1)          # first True along path axis
    cls = jnp.where(matched, path_cls[first], fallback_cls)
    prob = jnp.where(matched, path_prob[first], fallback_prob)
    return cls, prob


def _match_paths_np(vals, codes, lo, hi, num_restricted, cat_mask,
                    cat_restricted, path_cls, path_prob,
                    fallback_cls, fallback_prob):
    """Host float64 twin of ``_match_paths`` — used when the data does not
    round-trip float32 exactly (a boundary value near a split threshold could
    flip branches under f32 rounding) and the jax backend has x64 disabled."""
    ok = _match_ok(vals, codes, lo, hi, num_restricted, cat_mask,
                   cat_restricted, np)
    matched = ok.any(axis=1)
    first = np.argmax(ok, axis=1)
    cls = np.where(matched, path_cls[first], fallback_cls)
    prob = np.where(matched, path_prob[first], fallback_prob)
    return cls.astype(np.int32), prob.astype(np.float32)


class FeatureCache:
    """Per-table feature arrays shared across ensemble members: host build
    once, host->device upload once (ensemble predict was uploading the same
    ~32 MB per member on the tunneled chip).  Valid for PathMatrix instances
    over the same schema — their feature layout (feat_ordinals order) is
    identical by construction.  A cache is bound to the FIRST table it sees
    and fails loudly on reuse with a different one."""

    def __init__(self):
        self._host = None
        self._dev = None
        self._table_id = None

    def host(self, matrix: "PathMatrix", table: ColumnarTable):
        if self._host is None:
            self._host = matrix.feature_arrays(table)
            self._table_id = id(table)
        elif self._table_id != id(table):
            raise ValueError("FeatureCache reused across tables; create one "
                             "cache per table")
        return self._host

    def device(self, vals: np.ndarray, codes: np.ndarray):
        if self._dev is None:
            # ship the NARROW dtype (int16 when feature_arrays chose it —
            # half the link bytes); kernels upcast on device in _match_ok
            note_h2d(vals.nbytes + codes.nbytes, transfers=2)
            self._dev = (jnp.asarray(vals), jnp.asarray(codes))
        return self._dev


class PathMatrix:
    """A DecisionPathList compiled to dense predicate tensors (SURVEY.md §7.5
    'tree paths as predicate matrices -> batched evaluation').

    Per path and feature column the predicate chain collapses to
      * numeric: one (lo, hi] interval — 'le t' chains intersect to
        (lower_bound, t], 'gt t' to (t, +inf) (Predicate.evaluate semantics);
      * categorical: an allowed-code bitmask (intersection of 'in' sets).
    Evaluation of all paths over all records is then a single jitted
    gather/compare/reduce — the batched replacement for the reference's
    per-record predicate walk (model/ModelPredictor.java:46-82)."""

    def __init__(self, path_list: DecisionPathList, schema: FeatureSchema):
        paths = path_list.decision_paths
        feat_fields = schema.feature_fields
        self.feat_ordinals = [f.ordinal for f in feat_fields]
        col_of = {o: i for i, o in enumerate(self.feat_ordinals)}
        P, F = len(paths), len(feat_fields)
        cmax = max([len(f.cardinality or []) for f in feat_fields
                    if f.is_categorical] + [1])
        lo = np.full((P, F), -np.inf, dtype=np.float64)
        hi = np.full((P, F), np.inf, dtype=np.float64)
        cat_mask = np.ones((P, F, cmax), dtype=bool)
        num_restricted = np.zeros((P, F), dtype=bool)
        cat_restricted = np.zeros((P, F), dtype=bool)
        for pi, path in enumerate(paths):
            for pred in path.predicates:
                if pred.pred_str == ROOT_PATH or pred.operator is None:
                    continue
                ci = col_of[pred.attribute]
                f = schema.find_field_by_ordinal(pred.attribute)
                if pred.operator == "in":
                    m = np.zeros((cmax,), dtype=bool)
                    for v in pred.categorical_values or []:
                        code = f.cat_code(v)
                        if code >= 0:
                            m[code] = True
                    cat_mask[pi, ci] &= m
                    # explicit flag: even an all-values 'in' must still reject
                    # unknown codes, so restriction is tracked independently
                    # of whether the intersected mask happens to be all-true
                    cat_restricted[pi, ci] = True
                elif pred.operator == "le":
                    hi[pi, ci] = min(hi[pi, ci], pred.threshold)
                    if pred.lower_bound is not None:
                        lo[pi, ci] = max(lo[pi, ci], pred.lower_bound)
                    num_restricted[pi, ci] = True
                elif pred.operator == "gt":
                    lo[pi, ci] = max(lo[pi, ci], pred.threshold)
                    num_restricted[pi, ci] = True
                else:
                    raise ValueError(f"bad operator {pred.operator}")
        self.lo, self.hi = lo, hi
        self.cat_mask = cat_mask
        self.num_restricted = num_restricted
        self.cat_restricted = cat_restricted
        self.is_cat_col = np.array([f.is_categorical for f in feat_fields],
                                   dtype=bool)
        # bounds survive float32 exactly? (decides device-f32 eligibility)
        fin = np.isfinite(lo)
        self._bounds_f32_exact = bool(
            (lo[fin].astype(np.float32).astype(np.float64) == lo[fin]).all())
        fin = np.isfinite(hi)
        self._bounds_f32_exact &= bool(
            (hi[fin].astype(np.float32).astype(np.float64) == hi[fin]).all())
        self._dev_consts = None  # lazily-built device-resident constants
        # per-path predicted class / prob, over the union class vocabulary
        self.classes: List[str] = sorted(
            {cv for p in paths for cv in p.class_val_pr})
        cls_idx = {c: i for i, c in enumerate(self.classes)}
        self.path_cls = np.array(
            [cls_idx[p.predicted_class()[0]] if p.class_val_pr else 0
             for p in paths], dtype=np.int32)
        self.path_prob = np.array(
            [p.predicted_class()[1] if p.class_val_pr else 0.0 for p in paths],
            dtype=np.float32)
        # fallback for unmatched records: population-weighted class vote
        agg: Dict[str, float] = {}
        for p in paths:
            for cv, pr in p.class_val_pr.items():
                agg[cv] = agg.get(cv, 0.0) + pr * p.population
        self.fallback_cls = np.int32(
            cls_idx[max(agg.items(), key=lambda kv: kv[1])[0]]) if agg \
            else np.int32(0)
        self.n_paths = P

    def feature_arrays(self, table: ColumnarTable
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """(vals float64, codes int32), both (n, F).  Only the columns a
        comparison kind actually reads are cast: categorical slots in ``vals``
        (and numeric slots in ``codes``) stay zero."""
        n = table.n_rows
        F = len(self.feat_ordinals)
        vals = np.zeros((n, F), dtype=np.float64)
        codes = np.zeros((n, F), dtype=np.int32)
        for i, o in enumerate(self.feat_ordinals):
            if self.is_cat_col[i]:
                codes[:, i] = table.columns[o].astype(np.int32)
            else:
                vals[:, i] = table.columns[o].astype(np.float64)
        return vals, codes

    def _device_consts(self):
        if self._dev_consts is None:
            self._dev_consts = tuple(jnp.asarray(a) for a in (
                self.lo.astype(np.float32), self.hi.astype(np.float32),
                self.num_restricted, self.cat_mask, self.cat_restricted,
                self.path_cls, self.path_prob))
        return self._dev_consts

    def _f32_safe(self, vals: np.ndarray) -> bool:
        """Shared backend gate: the jitted f32 device kernels run only when
        every value AND bound round-trips float32 exactly (always true for
        the integer scan grids the split manager produces); otherwise the
        float64 host twins run so a value half-an-ulp from a threshold
        cannot flip branches relative to the reference's double math."""
        fin = np.isfinite(vals)
        return self._bounds_f32_exact and bool(
            (vals[fin].astype(np.float32).astype(np.float64) == vals[fin])
            .all())

    def _row_chunk(self, chunk: int) -> int:
        """Shared clamp: keep the per-chunk device intermediates around the
        2^26-element mark — both the (n, P, F) match matrix and the
        (n, F, Cmax) categorical one-hot (the latter dominates for
        high-cardinality features)."""
        F = max(len(self.feat_ordinals), 1)
        per_row = max(self.n_paths * F, F * self.cat_mask.shape[2], 1)
        return max(1024, min(chunk, (1 << 26) // per_row))

    def predict_codes(self, table: ColumnarTable,
                      chunk: int = 1 << 20,
                      features: Optional[Tuple] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """(class idx per record, prob) as arrays; row-chunked, f32 device
        kernel or f64 host twin per the shared ``_f32_safe`` gate.

        ``features`` optionally carries a FeatureCache so ensemble members
        share ONE feature build + host->device upload per table — the
        upload dominates predict wall time on the tunneled chip, and every
        member reads the identical arrays."""
        cache = features if features is not None else FeatureCache()
        vals, codes = cache.host(self, table)
        n = table.n_rows
        if n == 0 or self.n_paths == 0 or not self.classes:
            return (np.zeros((n,), np.int32) - 1, np.zeros((n,), np.float32))
        f32_safe = self._f32_safe(vals)
        chunk = self._row_chunk(chunk)
        out_cls, out_prob = [], []
        d_vals = d_codes = None
        if f32_safe:
            d_vals, d_codes = cache.device(vals, codes)
        for s in range(0, n, chunk):
            if f32_safe:
                lo, hi, num_r, cat_m, cat_r, pc, pp = self._device_consts()
                c, p = _match_paths(
                    d_vals[s:s + chunk], d_codes[s:s + chunk],
                    lo, hi, num_r, cat_m, cat_r, pc, pp,
                    self.fallback_cls, jnp.float32(0.5))
                out_cls.append(np.asarray(c))
                out_prob.append(np.asarray(p))
            else:
                c, p = _match_paths_np(
                    vals[s:s + chunk], codes[s:s + chunk],
                    self.lo, self.hi, self.num_restricted,
                    self.cat_mask, self.cat_restricted,
                    self.path_cls, self.path_prob,
                    self.fallback_cls, np.float32(0.5))
                out_cls.append(c)
                out_prob.append(p)
        return np.concatenate(out_cls), np.concatenate(out_prob)

    def match_index(self, table: ColumnarTable,
                    chunk: int = 1 << 20,
                    use_device: bool = True) -> np.ndarray:
        """(n,) int32 index of the FIRST matching path per record, -1 when
        none matches — the vectorized record router (used by the per-level
        job mode to re-derive node assignments without per-leaf host
        loops).  Same f32-exactness gate as predict_codes;
        ``use_device=False`` forces the numpy twin (callers whose path
        count changes every invocation — per-level routing — would retrace
        the jitted kernel each time for work the host does instantly)."""
        vals, codes = self.feature_arrays(table)
        n = table.n_rows
        if n == 0 or self.n_paths == 0:
            return np.full((n,), -1, dtype=np.int32)
        f32_safe = use_device and self._f32_safe(vals)
        chunk = self._row_chunk(chunk)
        out = []
        for s in range(0, n, chunk):
            if f32_safe:
                lo, hi, num_r, cat_m, cat_r, _, _ = self._device_consts()
                idx = _match_first(
                    jnp.asarray(vals[s:s + chunk].astype(np.float32)),
                    jnp.asarray(codes[s:s + chunk]),
                    lo, hi, num_r, cat_m, cat_r)
                out.append(np.asarray(idx))
            else:
                ok = _match_ok(vals[s:s + chunk], codes[s:s + chunk],
                               self.lo, self.hi, self.num_restricted,
                               self.cat_mask, self.cat_restricted, np)
                out.append(np.where(ok.any(axis=1), np.argmax(ok, axis=1),
                                    -1).astype(np.int32))
        return np.concatenate(out)


class DecisionTreeModel:
    """Vectorized evaluator: the path list is compiled once into a PathMatrix
    and every batch is classified in one jitted pass."""

    def __init__(self, path_list: DecisionPathList, schema: FeatureSchema):
        self.paths = path_list.decision_paths
        self.schema = schema
        self.matrix = PathMatrix(path_list, schema)

    def predict(self, table: ColumnarTable,
                features: Optional["FeatureCache"] = None
                ) -> Tuple[List[str], np.ndarray]:
        """(pred_class per record, prob).  Records matching no path get the
        globally most probable class (population-weighted).  ``features``
        shares one feature build/upload across ensemble members."""
        cls_idx, prob = self.matrix.predict_codes(table, features=features)
        if table.n_rows == 0 or self.matrix.n_paths == 0 \
                or not self.matrix.classes:
            return [""] * table.n_rows, np.zeros((table.n_rows,))
        lut = np.array(self.matrix.classes, dtype=object)
        return list(lut[cls_idx]), prob.astype(np.float64)

    def _pred_mask(self, pred: Predicate, table: ColumnarTable) -> np.ndarray:
        n = table.n_rows
        if pred.pred_str == ROOT_PATH or pred.operator is None:
            return np.ones((n,), dtype=bool)
        f = self.schema.find_field_by_ordinal(pred.attribute)
        if pred.operator == "in":
            codes = table.columns[pred.attribute]
            want = {f.cat_code(v) for v in (pred.categorical_values or [])}
            return np.isin(codes, list(want))
        vals = table.columns[pred.attribute].astype(np.float64)
        if pred.operator == "le":
            m = vals <= pred.threshold
            if pred.lower_bound is not None:
                m &= vals > pred.lower_bound
            return m
        if pred.operator == "gt":
            return vals > pred.threshold
        raise ValueError(f"bad operator {pred.operator}")

    def _predict_loop(self, table: ColumnarTable
                      ) -> Tuple[List[str], np.ndarray]:
        """Reference implementation (per-path host loop) kept as the parity
        oracle for PathMatrix tests; production code uses ``predict``."""
        n = table.n_rows
        pred_class = [""] * n
        prob = np.zeros((n,))
        assigned = np.zeros((n,), dtype=bool)
        for path in self.paths:
            mask = np.ones((n,), dtype=bool)
            for p in path.predicates:
                mask &= self._pred_mask(p, table)
            mask &= ~assigned
            if not mask.any():
                continue
            cv, pr = path.predicted_class()
            for i in np.nonzero(mask)[0]:
                pred_class[i] = cv
                prob[i] = pr
            assigned |= mask
        if not assigned.all():
            # fallback: population-weighted class distribution
            agg: Dict[str, float] = {}
            for path in self.paths:
                for cv, pr in path.class_val_pr.items():
                    agg[cv] = agg.get(cv, 0.0) + pr * path.population
            cv = max(agg.items(), key=lambda kv: kv[1])[0] if agg else ""
            for i in np.nonzero(~assigned)[0]:
                pred_class[i] = cv
                prob[i] = 0.5
        return pred_class, prob
