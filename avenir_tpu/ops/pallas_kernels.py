"""Pallas TPU kernels for the framework's hot counting ops.

The universal primitive of the rebuild is the coded histogram: every Hadoop
reducer in the reference is "sum 1s per composite key" (SURVEY.md §2.10), and
ops/histogram.py expresses that as XLA one-hot contractions.  Those
materialize an (n, F, K) one-hot in HBM between fusion boundaries; the Pallas
version here streams row tiles HBM->VMEM and keeps the (F, K) accumulator
resident in VMEM across the whole grid, so HBM traffic is just the codes read
once — the op is bandwidth-bound and this is its roofline.

Everything degrades gracefully: on non-TPU backends the kernel runs in
interpreter mode (tests), and callers fall back to the XLA path if pallas is
unavailable.

MEASURED VERDICT (round 3, TPU v5e via bench.pallas_probe — reps chained on
device, one readback): coded_histogram 154M rows/s vs the XLA one-hot's
515M rows/s at (4M, 6, 24) — the XLA formulation is 3.3x FASTER than this
hand-written kernel on real hardware, so it stays the production default
(ops/histogram.py) and pallas remains opt-in (AVENIR_TPU_USE_PALLAS=1) +
interpret-mode tested.  bench.py re-measures the ratio every round in
extra_metrics, so the decision tracks future runtime/kernel changes.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

# VMEM budget for the per-tile one-hot intermediate (float32 words).
_ONEHOT_BUDGET = 2 << 20  # 2M f32 = 8 MB


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_tile(n_cols: int, num_codes: int, requested: Optional[int]) -> int:
    """Sublane-aligned tile such that the (tile, F, K) one-hot fits the VMEM
    budget; the budget wins over the efficiency floor, never the other way
    around (large K shrinks tile).  Returns 0 when even an 8-row tile would
    blow the budget — the caller must fail over to the XLA path."""
    if requested is not None:
        return requested
    tile = _ONEHOT_BUDGET // max(n_cols * num_codes, 1)
    tile = min(4096, (tile // 8) * 8)
    return tile if tile >= 8 else 0


@partial(jax.jit, static_argnames=("num_codes", "tile", "interpret"))
def coded_histogram(codes: jnp.ndarray, num_codes: int,
                    tile: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """counts[f, k] = #rows with codes[row, f] == k, for k in [0, num_codes).

    ``codes`` is (n, F) int32 with invalid/masked entries already set to a
    negative value (they count toward nothing).  This is the shared kernel
    behind class-bin histograms (codes = class*B + bin), tree node
    histograms (codes = (node*C + class)*B + bin), and contingency tables.
    """
    if interpret is None:
        interpret = _auto_interpret()
    n, F = codes.shape
    if n == 0:  # grid=(0,) would never run the zero-init step
        return jnp.zeros((F, num_codes), dtype=jnp.float32)
    tile = _pick_tile(F, num_codes, tile)
    if tile == 0:  # F*K too large for any VMEM-safe tile: XLA scatter-add
        # (O(n*F), no (n, F, K) intermediate; out-of-range codes drop)
        return jnp.zeros((F, num_codes), jnp.float32).at[
            jnp.arange(F)[None, :], codes
        ].add((codes >= 0).astype(jnp.float32), mode="drop")
    pad = (-n) % tile
    codes = jnp.pad(codes, ((0, pad), (0, 0)), constant_values=-1)
    n_tiles = codes.shape[0] // tile

    def kernel(codes_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)
        c = codes_ref[:]                                           # (tile, F)
        k = jax.lax.broadcasted_iota(jnp.int32, (tile, F, num_codes), 2)
        oh = (c[:, :, None] == k).astype(jnp.float32)              # (tile,F,K)
        out_ref[:] += oh.sum(axis=0)

    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[pl.BlockSpec((tile, F), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((F, num_codes), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((F, num_codes), jnp.float32),
        interpret=interpret,
    )(codes)


def class_bin_histogram_pallas(class_codes: jnp.ndarray,  # (n,)
                               bin_codes: jnp.ndarray,    # (n, F)
                               num_classes: int, num_bins: int,
                               mask: Optional[jnp.ndarray] = None,
                               tile: Optional[int] = None,
                               interpret: Optional[bool] = None) -> jnp.ndarray:
    """Drop-in pallas fast path for ops.histogram.class_bin_histogram:
    counts[c, f, b] of shape (C, F, B)."""
    valid = (bin_codes >= 0) & (bin_codes < num_bins)
    if mask is not None:
        valid = valid & mask[:, None]
    combined = class_codes[:, None].astype(jnp.int32) * num_bins \
        + bin_codes.astype(jnp.int32)
    combined = jnp.where(valid, combined, -1)
    flat = coded_histogram(combined, num_classes * num_bins,
                           tile=tile, interpret=interpret)     # (F, C*B)
    F = bin_codes.shape[1]
    return flat.reshape(F, num_classes, num_bins).transpose(1, 0, 2)


def node_class_bin_histogram_pallas(node_codes: jnp.ndarray,   # (n,)
                                    class_codes: jnp.ndarray,  # (n,)
                                    bin_codes: jnp.ndarray,    # (n, F)
                                    num_nodes: int, num_classes: int,
                                    num_bins: int,
                                    mask: Optional[jnp.ndarray] = None,
                                    tile: Optional[int] = None,
                                    interpret: Optional[bool] = None
                                    ) -> jnp.ndarray:
    """counts[node, c, f, b] — the decision-tree frontier histogram (one
    level of DecisionTreeBuilder's reducer accumulation, reference
    tree/DecisionTreeBuilder.java:730-767) in a single kernel launch.
    Negative node codes (records that left the frontier) count nowhere."""
    valid = (bin_codes >= 0) & (bin_codes < num_bins) \
        & (node_codes >= 0)[:, None] & (class_codes >= 0)[:, None]
    if mask is not None:
        valid = valid & mask[:, None]
    base = (node_codes.astype(jnp.int32) * num_classes
            + class_codes.astype(jnp.int32)) * num_bins
    combined = jnp.where(valid, base[:, None] + bin_codes.astype(jnp.int32), -1)
    K = num_nodes * num_classes * num_bins
    flat = coded_histogram(combined, K, tile=tile, interpret=interpret)
    F = bin_codes.shape[1]
    return flat.reshape(F, num_nodes, num_classes, num_bins).transpose(1, 2, 0, 3)
