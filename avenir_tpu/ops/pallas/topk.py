"""Pallas KNN kernel: tiled distance + running best-k in on-chip state.

The XLA path (``ops.distance._topk_scan_kernel``) scans stacked train
tiles with a ``lax.top_k`` + stable-sort merge; every tile's distance
matrix and the running best lists round-trip HBM between scan steps.
Here the whole scan is ONE pallas launch per test chunk: the grid walks
(test tile, train tile), the running best-k lives in VMEM scratch that
persists across the sequential train-tile steps ("in registers" at the
kernel's altitude), and the distance tile never leaves VMEM.

The distance body is ``ops.distance._dist_kernels`` — the ONE
implementation shared with the eager and scan forms, so the pallas
form cannot drift from the parity the tests pin.  The merge is a k-step
lexicographic (distance, train-index) selection: ``lax.top_k`` + stable
sort are unavailable inside Mosaic, but the XLA merge's result is
exactly "the k smallest (d, i) pairs, ascending" (stability + tile
order resolve ties to the lowest global train index), which the
selection reproduces — bit-identical, pinned in interpret mode by
tests/test_pallas_kernels.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# test rows / train rows per grid step: the in-flight distance tile is
# (TM, TW) f32 (~512 KB) + the (TM, TW + k) merge candidates
TEST_TILE = 256
TRAIN_TILE = 512

_INT_MAX = np.int32(np.iinfo(np.int32).max)


def topk_scan(tn, toh, rn, roh, k: int, metric: str, n_cat: float,
              denom: float, fscale: float, interpret: bool = True):
    """(best_d (nt, k) f32, best_i (nt, k) i32), rows sorted
    nearest-first, ties to the lowest train index — the exact contract
    of the XLA scan kernel.  ``rn``/``roh`` are the FLAT train arrays
    (this kernel owns its own tiling); ``toh``/``roh`` may arrive int8
    (the narrow wire form) — the distance body upcasts on device."""
    from ..distance import _dist_kernels
    eu, ma = _dist_kernels(n_cat, denom, fscale)
    dist = eu if metric == "euclidean" else ma
    nt, n_train = tn.shape[0], rn.shape[0]
    k = int(k)
    # zero-width feature axes (all-categorical / all-numeric schemas)
    # cannot block; one zero column contributes exactly +0.0 to every
    # sum, so parity is preserved
    if tn.shape[1] == 0:
        tn = jnp.zeros((nt, 1), tn.dtype)
        rn = jnp.zeros((n_train, 1), rn.dtype)
    if toh.shape[1] == 0:
        toh = jnp.zeros((nt, 1), toh.dtype)
        roh = jnp.zeros((n_train, 1), roh.dtype)
    tm, tw = TEST_TILE, TRAIN_TILE
    pad_t = (-nt) % tm
    pad_r = (-n_train) % tw
    if pad_t:
        tn = jnp.pad(tn, ((0, pad_t), (0, 0)))
        toh = jnp.pad(toh, ((0, pad_t), (0, 0)))
    if pad_r:
        rn = jnp.pad(rn, ((0, pad_r), (0, 0)))
        roh = jnp.pad(roh, ((0, pad_r), (0, 0)))
    grid = (tn.shape[0] // tm, rn.shape[0] // tw)
    Fn, Fc = tn.shape[1], toh.shape[1]

    def kernel(tn_ref, toh_ref, rn_ref, roh_ref, od_ref, oi_ref, bd, bi):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            bd[...] = jnp.full_like(bd, jnp.inf)
            bi[...] = jnp.full_like(bi, -1)

        d = dist(tn_ref[...], toh_ref[...], rn_ref[...], roh_ref[...])
        # pad train columns: +inf distance, so with k <= n_train they can
        # never reach the final best list (same rule as the XLA scan)
        col = j * tw + jax.lax.broadcasted_iota(jnp.int32, (1, tw), 1)
        d = jnp.where(col < n_train, d, jnp.inf)
        idx = jnp.broadcast_to(col, d.shape)
        cand_d = jnp.concatenate([bd[...], d], axis=1)
        cand_i = jnp.concatenate([bi[...], idx], axis=1)
        # k-step (d, i)-lexicographic selection; (d, i) pairs are unique
        # among finite candidates (each train row is visited once), so
        # the remove-selected mask hits exactly one finite entry
        nd, ni = [], []
        for _ in range(k):
            m = jnp.min(cand_d, axis=1)
            sel = jnp.min(jnp.where(cand_d == m[:, None], cand_i,
                                    _INT_MAX), axis=1)
            nd.append(m)
            ni.append(sel)
            hit = (cand_d == m[:, None]) & (cand_i == sel[:, None])
            cand_d = jnp.where(hit, jnp.inf, cand_d)
        bd[...] = jnp.stack(nd, axis=1)
        bi[...] = jnp.stack(ni, axis=1)

        @pl.when(j == pl.num_programs(1) - 1)
        def _emit():
            od_ref[...] = bd[...]
            oi_ref[...] = bi[...]

    od, oi = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, Fn), lambda i, j: (i, 0)),
            pl.BlockSpec((tm, Fc), lambda i, j: (i, 0)),
            pl.BlockSpec((tw, Fn), lambda i, j: (j, 0)),
            pl.BlockSpec((tw, Fc), lambda i, j: (j, 0)),
        ],
        out_specs=[pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
                   pl.BlockSpec((tm, k), lambda i, j: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((tn.shape[0], k), jnp.float32),
                   jax.ShapeDtypeStruct((tn.shape[0], k), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((tm, k), jnp.float32),
                        pltpu.VMEM((tm, k), jnp.int32)],
        interpret=interpret,
    )(tn, toh, rn, roh)
    return od[:nt], oi[:nt]
