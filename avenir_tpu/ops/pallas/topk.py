"""Pallas KNN kernel: tiled distance + running best-k in on-chip state.

The XLA path (``ops.distance._topk_scan_kernel``) scans stacked train
tiles with a ``lax.top_k`` + stable-sort merge; every tile's distance
matrix and the running best lists round-trip HBM between scan steps.
Here the whole scan is ONE pallas launch per test chunk: the grid walks
(test tile, train tile), the running best-k lives in VMEM scratch that
persists across the sequential train-tile steps ("in registers" at the
kernel's altitude), and the distance tile never leaves VMEM.

The distance body is ``ops.distance._dist_kernels`` — the ONE
implementation shared with the eager and scan forms, so the pallas
form cannot drift from the parity the tests pin.  The merge is a k-step
lexicographic (distance, train-index) selection: ``lax.top_k`` + stable
sort are unavailable inside Mosaic, but the XLA merge's result is
exactly "the k smallest (d, i) pairs, ascending" (stability + tile
order resolve ties to the lowest global train index), which the
selection reproduces — bit-identical, pinned in interpret mode by
tests/test_pallas_kernels.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# test rows / train rows per grid step: the in-flight distance tile is
# (TM, TW) f32 (~512 KB) + the (TM, TW + k) merge candidates
TEST_TILE = 256
TRAIN_TILE = 512

_INT_MAX = np.int32(np.iinfo(np.int32).max)


def topk_scan(tn, toh, rn, roh, k: int, metric: str, n_cat: float,
              denom: float, fscale: float, interpret: bool = True):
    """(best_d (nt, k) f32, best_i (nt, k) i32), rows sorted
    nearest-first, ties to the lowest train index — the exact contract
    of the XLA scan kernel.  ``rn``/``roh`` are the FLAT train arrays
    (this kernel owns its own tiling); ``toh``/``roh`` may arrive int8
    (the narrow wire form) — the distance body upcasts on device."""
    from ..distance import _dist_kernels
    eu, ma = _dist_kernels(n_cat, denom, fscale)
    dist = eu if metric == "euclidean" else ma
    nt, n_train = tn.shape[0], rn.shape[0]
    k = int(k)
    # zero-width feature axes (all-categorical / all-numeric schemas)
    # cannot block; one zero column contributes exactly +0.0 to every
    # sum, so parity is preserved
    if tn.shape[1] == 0:
        tn = jnp.zeros((nt, 1), tn.dtype)
        rn = jnp.zeros((n_train, 1), rn.dtype)
    if toh.shape[1] == 0:
        toh = jnp.zeros((nt, 1), toh.dtype)
        roh = jnp.zeros((n_train, 1), roh.dtype)
    tm, tw = TEST_TILE, TRAIN_TILE
    pad_t = (-nt) % tm
    pad_r = (-n_train) % tw
    if pad_t:
        tn = jnp.pad(tn, ((0, pad_t), (0, 0)))
        toh = jnp.pad(toh, ((0, pad_t), (0, 0)))
    if pad_r:
        rn = jnp.pad(rn, ((0, pad_r), (0, 0)))
        roh = jnp.pad(roh, ((0, pad_r), (0, 0)))
    grid = (tn.shape[0] // tm, rn.shape[0] // tw)
    Fn, Fc = tn.shape[1], toh.shape[1]

    def kernel(tn_ref, toh_ref, rn_ref, roh_ref, od_ref, oi_ref, bd, bi):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            bd[...] = jnp.full_like(bd, jnp.inf)
            bi[...] = jnp.full_like(bi, -1)

        d = dist(tn_ref[...], toh_ref[...], rn_ref[...], roh_ref[...])
        # pad train columns: +inf distance, so with k <= n_train they can
        # never reach the final best list (same rule as the XLA scan)
        col = j * tw + jax.lax.broadcasted_iota(jnp.int32, (1, tw), 1)
        d = jnp.where(col < n_train, d, jnp.inf)
        idx = jnp.broadcast_to(col, d.shape)
        cand_d = jnp.concatenate([bd[...], d], axis=1)
        cand_i = jnp.concatenate([bi[...], idx], axis=1)
        # k-step (d, i)-lexicographic selection; (d, i) pairs are unique
        # among finite candidates (each train row is visited once), so
        # the remove-selected mask hits exactly one finite entry
        nd, ni = [], []
        for _ in range(k):
            m = jnp.min(cand_d, axis=1)
            sel = jnp.min(jnp.where(cand_d == m[:, None], cand_i,
                                    _INT_MAX), axis=1)
            nd.append(m)
            ni.append(sel)
            hit = (cand_d == m[:, None]) & (cand_i == sel[:, None])
            cand_d = jnp.where(hit, jnp.inf, cand_d)
        bd[...] = jnp.stack(nd, axis=1)
        bi[...] = jnp.stack(ni, axis=1)

        @pl.when(j == pl.num_programs(1) - 1)
        def _emit():
            od_ref[...] = bd[...]
            oi_ref[...] = bi[...]

    od, oi = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, Fn), lambda i, j: (i, 0)),
            pl.BlockSpec((tm, Fc), lambda i, j: (i, 0)),
            pl.BlockSpec((tw, Fn), lambda i, j: (j, 0)),
            pl.BlockSpec((tw, Fc), lambda i, j: (j, 0)),
        ],
        out_specs=[pl.BlockSpec((tm, k), lambda i, j: (i, 0)),
                   pl.BlockSpec((tm, k), lambda i, j: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((tn.shape[0], k), jnp.float32),
                   jax.ShapeDtypeStruct((tn.shape[0], k), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((tm, k), jnp.float32),
                        pltpu.VMEM((tm, k), jnp.int32)],
        interpret=interpret,
    )(tn, toh, rn, roh)
    return od[:nt], oi[:nt]


def topk_scan_sharded(tn, toh, rn, roh, k: int, metric: str, n_cat: float,
                      denom: float, fscale: float, mesh, axis_name: str,
                      interpret: bool = True):
    """Mesh-aware ``topk_scan``: the TRAIN axis shards over ``mesh``'s
    ``axis_name``, each shard runs the pallas scan over its local train
    slice, and ONE all_gather of the (nt, 2k)-packed per-shard best
    lists feeds a final lexicographic k-selection on every shard.

    Exact, not approximate: every global top-k pair is in its own
    shard's top-k (distances are per-pair), so the union of per-shard
    best lists contains the global answer, and the merge reproduces the
    XLA contract — k smallest (d, global-i), ascending, ties to the
    lowest train index (local ties resolve low inside each shard and the
    offsets keep that order globally).  Bit-identical to the
    single-device scan; pinned in interpret mode by
    tests/test_pallas_kernels.py.  The (d, i) pair lists ride one
    collective via an int32<->f32 bitcast pack."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    n_train = rn.shape[0]
    S = mesh.shape[axis_name]
    k = int(k)
    pad = (-n_train) % S
    if pad:
        rn = jnp.pad(rn, ((0, pad), (0, 0)))
        roh = jnp.pad(roh, ((0, pad), (0, 0)))
    local_n = (n_train + pad) // S

    def body(tn_l, toh_l, rn_l, roh_l):
        bd, bi = topk_scan(tn_l, toh_l, rn_l, roh_l, k, metric, n_cat,
                           denom, fscale, interpret=interpret)
        off = jax.lax.axis_index(axis_name) * np.int32(local_n)
        gi = bi + off
        # shard-pad train rows / unfilled local slots must never win
        dead = (bi < 0) | (gi >= n_train)
        bd = jnp.where(dead, jnp.inf, bd)
        gi = jnp.where(dead, _INT_MAX, gi)
        nt_l = bd.shape[0]
        packed = jnp.concatenate(
            [bd, jax.lax.bitcast_convert_type(gi, jnp.float32)], axis=1)
        g = jax.lax.all_gather(packed, axis_name, axis=1, tiled=True)
        g = g.reshape(nt_l, S, 2 * k)
        cand_d = g[:, :, :k].reshape(nt_l, S * k)
        cand_i = jax.lax.bitcast_convert_type(
            g[:, :, k:], jnp.int32).reshape(nt_l, S * k)
        # same k-step lexicographic selection as the kernel's tile merge
        nd, ni = [], []
        for _ in range(k):
            m = jnp.min(cand_d, axis=1)
            sel = jnp.min(jnp.where(cand_d == m[:, None], cand_i,
                                    _INT_MAX), axis=1)
            nd.append(m)
            ni.append(sel)
            hit = (cand_d == m[:, None]) & (cand_i == sel[:, None])
            cand_d = jnp.where(hit, jnp.inf, cand_d)
        bd_out = jnp.stack(nd, axis=1)
        bi_out = jnp.stack(ni, axis=1)
        # unfilled slots (k > n_train) decode back to the -1 contract
        return bd_out, jnp.where(jnp.isinf(bd_out), -1, bi_out)

    sh = shard_map(body, mesh=mesh, check_rep=False,
                   in_specs=(P(), P(), P(axis_name), P(axis_name)),
                   out_specs=(P(), P()))
    return sh(tn, toh, rn, roh)
