"""Hand-written Pallas kernels for the ledger-measured hot loops
(TPU_NOTES §24), platform-selected through :mod:`.dispatch`:

* :mod:`.histogram` — fused encode -> scatter-add level/bin counting,
  VMEM-resident accumulator (the forest per-level stacked (T,N,S,B,C)
  histogram and the monitor's (R,B) bin counts);
* :mod:`.topk`      — KNN tiled distance + running best-k in on-chip
  scratch across the train-tile walk;
* :mod:`.vote`      — the serving ensemble vote, float and int8
  (quantized) forms.

Training kernels are bit-identical to their XLA twins (interpret-mode
parity pinned in the tier-1 lane under the ``kernels`` marker); the
quantized serving path is accuracy-budget-pinned at publish time
instead (serving/quantized.py).

Heavy deps load lazily: importing the dispatch knob must not drag
pallas into every process start.
"""

from .dispatch import (BACKENDS, BACKEND_ENV, BACKEND_KEY, force_backend,
                       kernel_backend, note_backend, pallas_interpret,
                       resolve_backend, set_kernel_backend, use_pallas)

__all__ = [
    "BACKENDS", "BACKEND_ENV", "BACKEND_KEY", "force_backend",
    "kernel_backend", "note_backend", "pallas_interpret",
    "resolve_backend", "set_kernel_backend", "use_pallas",
]
