"""Pallas histogram kernels: fused encode -> scatter-add, VMEM-resident.

The XLA paths build counting as one-hot contractions; XLA materializes
the (rows, ...) one-hot operands in HBM before the MXU pass.  These
kernels walk the row axis as a sequential grid and keep everything —
the per-tile one-hots AND the full count accumulator — in VMEM: one
pallas launch replaces the launch-per-chunk + HBM round trip of the
composed form.  Counts are exact integers in f32 (integral weights,
chunk mass < 2^24 by the callers' ``level_chunk`` discipline), so any
tile partitioning sums to the bit-identical result of the XLA twin —
pinned in interpret mode by tests/test_pallas_kernels.py.

Shared-body discipline (TPU_NOTES §24): the forest kernel's per-tile
math IS ``models.forest._count_body`` — the pallas form changes WHERE
the one-hots live, never WHAT is summed.  A drifted copy would silently
break the parity the tier-1 lane pins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# f32 elements of per-tile intermediates we allow in flight (~8 MB) —
# well under the ~16 MB/core VMEM budget with the accumulator resident
_TILE_BUDGET_ELEMS = 2 << 20
_MIN_ROWS = 8
_MAX_ROWS = 1024


def _rows_tile(per_row: int, n: int) -> int:
    """Static rows-per-grid-step: bound the per-tile one-hot footprint,
    8-row aligned (f32 sublane), never wider than needed."""
    r = max(_TILE_BUDGET_ELEMS // max(per_row, 1), _MIN_ROWS)
    r = min(r, _MAX_ROWS, max(n, _MIN_ROWS))
    return max((r // 8) * 8, _MIN_ROWS)


def forest_level_counts(node_ids, branches, cls_codes, weights,
                        n_nodes: int, B: int, C: int,
                        interpret: bool = True):
    """Stacked (T, N, S, B, C) forest level histogram, ONE pallas launch.

    Same contract as ``models.forest._count_body`` (whose body computes
    each tile): node_ids/weights (n, T), branches (n, S), cls_codes
    (n,); rows with node_id < 0 are inactive and weight-masked.  The
    count accumulator lives in the output block — its index_map pins the
    same (T, N, S, B, C) block every grid step, so it stays VMEM-resident
    across the whole row walk — while the (rows, T, N) node one-hot and
    (rows, C, S, B) class x branch one-hot exist only per tile.  Pad
    rows (node_id -1, weight 0) contribute nothing, so the result is
    bit-identical to the XLA einsum for any tiling."""
    from ...models.forest import _count_body
    n, T = node_ids.shape
    S = branches.shape[1]
    N = int(n_nodes)
    if n == 0:
        return jnp.zeros((T, N, S, B, C), jnp.float32)
    rows = _rows_tile(T * N + C * S * B + T * S, n)
    pad = (-n) % rows
    if pad:
        node_ids = jnp.pad(node_ids, ((0, pad), (0, 0)), constant_values=-1)
        branches = jnp.pad(branches, ((0, pad), (0, 0)))
        cls_codes = jnp.pad(cls_codes, ((0, pad),))
        weights = jnp.pad(weights, ((0, pad), (0, 0)))
    grid = (node_ids.shape[0] // rows,)

    def kernel(nid_ref, br_ref, cls_ref, w_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        out_ref[...] += _count_body(nid_ref[...], br_ref[...],
                                    cls_ref[...][:, 0], w_ref[...],
                                    N, B, C)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, T), lambda i: (i, 0)),
            pl.BlockSpec((rows, S), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, T), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((T, N, S, B, C),
                               lambda i: (0, 0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((T, N, S, B, C), jnp.float32),
        interpret=interpret,
    )(node_ids, branches, cls_codes[:, None],
      weights.astype(jnp.float32))


def bin_counts(codes, num_bins: int, mask=None, interpret: bool = True):
    """(R, B) monitored-row bin counts, the pallas twin of
    ``ops.histogram.feature_bin_counts``: codes (n, R) int32, out-of-
    range codes drop, masked rows contribute nothing.  The (rows, R, B)
    one-hot exists only per VMEM tile; the (R, B) accumulator block is
    revisited every grid step."""
    n, R = codes.shape
    B = int(num_bins)
    if n == 0 or R == 0:
        return jnp.zeros((R, B), jnp.float32)
    m = mask if mask is not None else jnp.ones((n,), bool)
    m = m.astype(jnp.float32)
    rows = _rows_tile(R * B + R, n)
    pad = (-n) % rows
    if pad:
        codes = jnp.pad(codes, ((0, pad), (0, 0)), constant_values=-1)
        m = jnp.pad(m, ((0, pad),))
    grid = (codes.shape[0] // rows,)

    def kernel(c_ref, m_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
        c = c_ref[...]
        valid = (c >= 0) & (c < B)
        w = valid.astype(jnp.float32) * m_ref[...][:, 0][:, None]  # (r, R)
        oh = jax.nn.one_hot(jnp.clip(c, 0, B - 1), B,
                            dtype=jnp.float32)                     # (r, R, B)
        out_ref[...] += jnp.sum(oh * w[:, :, None], axis=0)

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, R), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((R, B), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((R, B), jnp.float32),
        interpret=interpret,
    )(codes, m[:, None])
