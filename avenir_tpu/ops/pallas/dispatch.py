"""Kernel-backend dispatch: the ONE place that decides pallas vs XLA.

Three hot loops carry hand-written Pallas twins (TPU_NOTES §24): the
forest per-level stacked (T, N, S, B, C) histogram, the KNN tiled
distance + top-k scan, and the serving ensemble vote.  Every call site
resolves its backend HERE, so an operator (or a test) flips one knob and
the whole framework follows:

    kernel.backend = auto | xla | pallas      (CLI -D / conf key)
    AVENIR_TPU_KERNEL_BACKEND                 (env twin)

``auto`` (the default) selects pallas on a real TPU mesh and XLA
everywhere else.  ``pallas`` forces the pallas kernels on any platform —
off-TPU they run in *interpret mode* (:func:`pallas_interpret`), which
is how the CPU tier-1 lane pins bit-identical parity against the XLA
twins without a device.  ``xla`` pins the composed-op path everywhere
(the escape hatch when a Mosaic compile regresses).

Training kernels (histogram, top-k) are bit-identical across backends —
pinned by the interpret-mode parity tests (tests/test_pallas_kernels.py,
``kernels`` marker); the quantized serving vote is budget-pinned instead
(serving/quantized.py).  Which backend actually ran at each hot site is
recorded into the active TransferLedger (``KernelBackends`` counter
group) via :func:`note_backend`, so a silent fallback can never flatter
a pallas number (the bench roofline blocks assert on it).

Jit-cache discipline: the backend is resolved at TRACE time, so every
jit/lru cache wrapping a dispatched kernel must carry the resolved
backend in its key (the forest level kernels and the vote kernel key on
it; ``ChunkPipeline`` adds a backend axis to the ProgramCache key) — a
program traced under one backend must never serve a call made under the
other.
"""

from __future__ import annotations

import contextlib
import os
import threading
import warnings
from typing import Iterator, Optional

BACKEND_AUTO = "auto"
BACKEND_XLA = "xla"
BACKEND_PALLAS = "pallas"
BACKENDS = (BACKEND_AUTO, BACKEND_XLA, BACKEND_PALLAS)

BACKEND_ENV = "AVENIR_TPU_KERNEL_BACKEND"
BACKEND_KEY = "kernel.backend"

# process-level override (cli.run installs the kernel.backend knob here);
# a plain attribute read is the hot-path cost
_process_backend: Optional[str] = None
_lock = threading.Lock()


def _check(name: str) -> str:
    name = (name or "").strip().lower()
    if name not in BACKENDS:
        raise ValueError(
            f"unknown kernel backend {name!r}; must be one of {BACKENDS} "
            f"({BACKEND_KEY} config key / {BACKEND_ENV} env)")
    return name


def set_kernel_backend(name: Optional[str]) -> None:
    """Install the process-level backend selection (``None`` clears it
    back to env/auto resolution).  cli.run calls this from the
    ``kernel.backend`` knob and clears it in its ``finally`` so one
    in-process job cannot leak its selection into the next."""
    global _process_backend
    with _lock:
        _process_backend = _check(name) if name is not None else None


def kernel_backend() -> str:
    """The requested backend: process override, else the env twin, else
    ``auto``.  (Resolution to a concrete xla/pallas choice is
    :func:`resolve_backend` — it needs the platform.)"""
    b = _process_backend
    if b is not None:
        return b
    env = os.environ.get(BACKEND_ENV)
    if env:
        return _check(env)
    return BACKEND_AUTO


def _runtime():
    from ...parallel.mesh import runtime_context
    return runtime_context()


# one-time multi-chip downgrade warning (process flag, not per call site:
# the point is a single loud line per process, the per-event record lives
# in the ledger via note_backend)
_warned_multichip = False


def _reset_multichip_warning() -> None:
    """Test helper: re-arm the one-time multi-chip downgrade warning."""
    global _warned_multichip
    with _lock:
        _warned_multichip = False


def resolve_backend(platform: Optional[str] = None,
                    n_devices: Optional[int] = None,
                    mesh_aware: bool = False,
                    site: Optional[str] = None) -> str:
    """``"xla"`` or ``"pallas"`` for the current request + placement:
    ``auto`` means pallas on a TPU, EXCEPT multi-chip call sites whose
    kernel does not yet speak shard_map (``mesh_aware=False``) — there
    XLA would gather the row axis around every pallas call, so the
    composed-op path is the measured winner (TPU_NOTES §24).  Mesh-aware
    call sites (``mesh_aware=True`` — the serving vote's shard-local
    partial-tally kernel runs inside shard_map, one psum merges it) keep
    pallas on any chip count.  Off-TPU ``auto`` is always XLA (pallas
    would run interpreted).  An explicit ``xla``/``pallas`` selection is
    always honored.

    A forced multi-chip pallas→XLA downgrade is never silent: the first
    one per process emits a structured ``RuntimeWarning`` and every one
    lands in the active TransferLedger's ``KernelBackends`` group under
    ``<site>.xla_downgrade`` (``site`` defaults to ``auto.multichip``).

    Callers holding a MeshContext should pass both ``platform`` and
    ``n_devices`` from it; either omitted falls back to the runtime
    context."""
    global _warned_multichip
    b = kernel_backend()
    if b == BACKEND_AUTO:
        if platform is None:
            platform = _runtime().device_platform
        if platform != "tpu":
            return BACKEND_XLA
        if n_devices is None:
            n_devices = _runtime().n_devices
        if n_devices == 1 or mesh_aware:
            return BACKEND_PALLAS
        # multi-chip + non-mesh-aware kernel: forced downgrade, loudly
        note_backend(site or "auto.multichip", "xla_downgrade")
        if not _warned_multichip:
            with _lock:
                first = not _warned_multichip
                _warned_multichip = True
            if first:
                warnings.warn(
                    f"kernel.backend=auto downgraded pallas->xla at "
                    f"site={site or 'auto.multichip'!s}: {n_devices} "
                    f"devices and the kernel is not mesh-aware "
                    f"(TPU_NOTES §24/§32); set kernel.backend=pallas to "
                    f"force, or use a mesh-aware call site",
                    RuntimeWarning, stacklevel=2)
        return BACKEND_XLA
    return b


def use_pallas(platform: Optional[str] = None,
               n_devices: Optional[int] = None) -> bool:
    return resolve_backend(platform, n_devices) == BACKEND_PALLAS


def pallas_interpret(platform: Optional[str] = None) -> bool:
    """Interpret-mode flag for a pallas call: True off-TPU (the CPU
    tier-1 parity lane), False on a real TPU (Mosaic compile)."""
    p = platform if platform is not None else _runtime().device_platform
    return p != "tpu"


@contextlib.contextmanager
def force_backend(name: str) -> Iterator[None]:
    """Scoped backend override (tests, benches): restores the previous
    process-level selection on exit."""
    global _process_backend
    with _lock:
        prev = _process_backend
        _process_backend = _check(name)
    try:
        yield
    finally:
        with _lock:
            _process_backend = prev


def note_backend(site: str, backend: str, n: int = 1) -> None:
    """Record which kernel actually ran at a hot site into every active
    TransferLedger (``KernelBackends`` counter group, key
    ``<site>.<backend>``).  ``backend`` here is the EXECUTED form —
    ``xla`` | ``pallas`` | ``quantized`` — not the requested knob, so a
    fallback is visible as the wrong key."""
    from ...utils.tracing import note_kernel_backend
    note_kernel_backend(site, backend, n)
