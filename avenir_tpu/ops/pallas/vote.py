"""Pallas ensemble-vote kernels: the serving hot loop, float and int8.

One grid walk over row tiles; every member's predicate tensors sit in
VMEM for the whole launch (they are KB-scale constants), each tile's
(rows, T, P) match matrix and (rows, K) vote tally never leave VMEM.
The float kernel's body IS ``models.forest._ensemble_vote_body`` —
the pallas form relocates the intermediates, the vote math has exactly
one implementation, so backend parity is structural (pinned by
tests/test_pallas_kernels.py in interpret mode).

The int8 kernel is the quantized serving twin (serving/quantized.py):
identical vote structure over int8-binned values/thresholds — NOT
bit-identical to the float path by design; its accuracy delta is
budget-pinned at publish time instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_TILE = 256


def _full_spec(shape):
    n = len(shape)
    return pl.BlockSpec(tuple(shape), lambda i, _n=n: (0,) * _n)


def _tiled_vote(body, vals, codes, consts, min_odds, interpret: bool):
    """Shared driver: pad rows to the tile, run ``body`` per tile with
    the stacked member tensors resident, slice the pad back off.
    ``min_odds`` rides as a (1, 1) input block (a pallas kernel cannot
    close over traced values)."""
    n = vals.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.int32)
    tm = min(ROW_TILE, max(8, ((n + 7) // 8) * 8))
    pad = (-n) % tm
    if pad:
        # pad rows are a copy of the last row (any valid row works: per
        # -row votes are independent and the pad slice is dropped)
        vals = jnp.concatenate(
            [vals, jnp.broadcast_to(vals[-1:], (pad,) + vals.shape[1:])])
        codes = jnp.concatenate(
            [codes, jnp.broadcast_to(codes[-1:], (pad,) + codes.shape[1:])])
    grid = (vals.shape[0] // tm,)
    mo = jnp.asarray(min_odds, jnp.float32).reshape(1, 1)

    def kernel(v_ref, c_ref, *refs):
        out_ref = refs[-1]
        mo_ref = refs[-2]
        cref = refs[:-2]
        out_ref[...] = body(v_ref[...], c_ref[...],
                            *[r[...] for r in cref],
                            mo_ref[0, 0])[:, None]

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tm, vals.shape[1]), lambda i: (i, 0)),
                  pl.BlockSpec((tm, codes.shape[1]), lambda i: (i, 0))]
        + [_full_spec(c.shape) for c in consts]
        + [_full_spec((1, 1))],
        out_specs=pl.BlockSpec((tm, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((vals.shape[0], 1), jnp.int32),
        interpret=interpret,
    )(vals, codes, *consts, mo)
    return out[:n, 0]


def ensemble_partial_votes(vals, codes, lo, hi, num_r, cat_m, cat_r, cls_oh,
                           wvec, interpret: bool = True):
    """(n, K) f32 vote tallies — the pallas twin of
    ``models.forest._member_votes_body`` (same body, tiled).

    This is the mesh-aware serving form: each shard of a tree-sharded
    mesh runs it over its local member slice, and ONE psum of the (n, K)
    tallies merges the shards.  Tallies are sums of integer-valued f32
    terms (``stacked_host`` rejects anything else), so the partial-sum +
    psum composition is bit-identical to the single-device vote; the
    min-odds finalize runs post-merge (``_vote_finalize``) outside the
    kernel."""
    from ...models.forest import _member_votes_body
    n = vals.shape[0]
    K = cls_oh.shape[2]
    if n == 0:
        return jnp.zeros((0, K), jnp.float32)
    consts = (lo, hi, num_r, cat_m, cat_r, cls_oh, wvec)
    tm = min(ROW_TILE, max(8, ((n + 7) // 8) * 8))
    pad = (-n) % tm
    if pad:
        vals = jnp.concatenate(
            [vals, jnp.broadcast_to(vals[-1:], (pad,) + vals.shape[1:])])
        codes = jnp.concatenate(
            [codes, jnp.broadcast_to(codes[-1:], (pad,) + codes.shape[1:])])
    grid = (vals.shape[0] // tm,)

    def kernel(v_ref, c_ref, *refs):
        out_ref = refs[-1]
        cref = refs[:-1]
        out_ref[...] = _member_votes_body(v_ref[...], c_ref[...],
                                          *[r[...] for r in cref])

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tm, vals.shape[1]), lambda i: (i, 0)),
                  pl.BlockSpec((tm, codes.shape[1]), lambda i: (i, 0))]
        + [_full_spec(c.shape) for c in consts],
        out_specs=pl.BlockSpec((tm, K), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((vals.shape[0], K), jnp.float32),
        interpret=interpret,
    )(vals, codes, *consts)
    return out[:n]


def ensemble_vote(vals, codes, lo, hi, num_r, cat_m, cat_r, cls_oh, wvec,
                  min_odds, interpret: bool = True):
    """(n,) int32 vote indices — the pallas twin of
    ``models.forest._ensemble_vote_body`` (same body, tiled)."""
    from ...models.forest import _ensemble_vote_body
    return _tiled_vote(_ensemble_vote_body, vals, codes,
                       (lo, hi, num_r, cat_m, cat_r, cls_oh, wvec),
                       min_odds, interpret)


def quantized_vote(qvals, qcodes, q_lo, q_hi, num_r, cat_m, cat_r, cls_oh,
                   wvec, min_odds, interpret: bool = True):
    """(n,) int32 vote indices over int8-binned inputs — the pallas twin
    of ``serving.quantized._quantized_vote_body`` (same body, tiled)."""
    from ...serving.quantized import _quantized_vote_body
    return _tiled_vote(_quantized_vote_body, qvals, qcodes,
                       (q_lo, q_hi, num_r, cat_m, cat_r, cls_oh, wvec),
                       min_odds, interpret)
