"""Mixed-type record distance + tiled all-pairs computation.

Replaces the sifarish ``SameTypeSimilarity`` MR job of the reference KNN
pipeline (resource/knn.sh:47) and avenir-spark's ``RecordSimilarity`` bucket-
pair replication join (spark/.../similarity/RecordSimilarity.scala:65-103).
chombo's ``InterRecordDistance`` (not vendored in the reference) defines the
per-attribute semantics we reproduce: numeric attrs contribute
|a-b| / (max-min) in [0,1]; categorical attrs contribute 0/1 mismatch;
aggregation is euclidean sqrt(mean of squares) or manhattan mean.  Distances
are emitted as ints scaled by ``distance scale`` (sts.distance.scale=1000 in
resource/knn.properties).

TPU design (SURVEY.md §2.10 'bucket-pair replication join' row): all-pairs
distance is a matmul problem, not a join problem —

  * euclidean numeric part:  |a'-b'|^2 summed over attrs = |a'|^2 + |b'|^2
    - 2 a'·b'  with a' = a/range  -> one (n_test, n_train) GEMM;
  * categorical mismatch count = F_cat - matches, matches = block-one-hot
    GEMM  A(n_test, sum_card) @ B(n_train, sum_card)^T;
  * manhattan falls back to a broadcast-tiled pass (bandwidth-bound).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema, FeatureField
from ..core.table import ColumnarTable


class DistanceComputer:
    """Precomputes per-attr normalization + categorical one-hot layout for a
    schema, then computes all-pairs int distances on device."""

    def __init__(self, schema: FeatureSchema, metric: str = "euclidean",
                 scale: int = 1000):
        self.schema = schema
        self.metric = metric
        self.scale = scale
        self.num_fields = [f for f in schema.feature_fields if f.is_numeric]
        self.cat_fields = [f for f in schema.feature_fields if f.is_categorical]
        self.n_attrs = len(self.num_fields) + len(self.cat_fields)
        self.ranges = np.array(
            [max(float(f.max) - float(f.min), 1e-12) if f.max is not None
             and f.min is not None else 1.0 for f in self.num_fields],
            dtype=np.float32)
        self.cards = [len(f.cardinality or []) for f in self.cat_fields]
        # jit once per computer: a fresh closure per pairwise() call would
        # retrace + recompile every invocation
        n_cat = float(len(self.cat_fields))
        denom = float(max(self.n_attrs, 1))
        fscale = float(self.scale)

        def _euclid(tn, toh, rn, roh):
            sq = (tn * tn).sum(1)[:, None] + (rn * rn).sum(1)[None, :] \
                - 2.0 * tn @ rn.T                                  # (nt, nr)
            cat_match = toh @ roh.T                                # matches
            cat_mismatch = n_cat - cat_match
            total = jnp.maximum(sq, 0.0) + cat_mismatch            # d in {0,1}: d^2=d
            mean = total / denom
            return jnp.floor(jnp.sqrt(jnp.maximum(mean, 0.0)) * fscale)

        def _manh(tn_tile, toh_tile, rn, roh):
            num = jnp.abs(tn_tile[:, None, :] - rn[None, :, :]).sum(2)
            cat = n_cat - toh_tile @ roh.T
            return jnp.floor((num + cat) / denom * fscale)

        self._euclid_jit = jax.jit(_euclid)
        self._manh_jit = jax.jit(_manh)

    # ---- encode a table into (numeric matrix, categorical block one-hot) ----
    def encode(self, table: ColumnarTable) -> Tuple[np.ndarray, np.ndarray]:
        n = table.n_rows
        if self.num_fields:
            num = np.stack([table.columns[f.ordinal] / r for f, r in
                            zip(self.num_fields, self.ranges)], axis=1
                           ).astype(np.float32)
        else:
            num = np.zeros((n, 0), dtype=np.float32)
        total_card = sum(self.cards)
        oh = np.zeros((n, total_card), dtype=np.float32)
        off = 0
        for f, card in zip(self.cat_fields, self.cards):
            codes = table.columns[f.ordinal]
            valid = codes >= 0
            oh[np.arange(n)[valid], off + codes[valid]] = 1.0
            off += card
        return num, oh

    def pairwise(self, test: ColumnarTable, train: ColumnarTable,
                 tile: int = 4096) -> np.ndarray:
        """(n_test, n_train) int32 scaled distances."""
        tn, toh = self.encode(test)
        rn, roh = self.encode(train)
        if self.metric == "euclidean":
            d = self._euclidean(jnp.asarray(tn), jnp.asarray(toh),
                                jnp.asarray(rn), jnp.asarray(roh))
        elif self.metric == "manhattan":
            d = self._manhattan_tiled(tn, toh, rn, roh, tile)
        else:
            raise ValueError(f"unknown metric {self.metric!r}")
        return np.asarray(d).astype(np.int32)

    def _euclidean(self, tn, toh, rn, roh):
        return self._euclid_jit(tn, toh, rn, roh)

    def _manhattan_tiled(self, tn, toh, rn, roh, tile):
        out = np.zeros((tn.shape[0], rn.shape[0]), dtype=np.float32)
        for s in range(0, tn.shape[0], tile):
            e = min(s + tile, tn.shape[0])
            out[s:e] = np.asarray(self._manh_jit(
                jnp.asarray(tn[s:e]), jnp.asarray(toh[s:e]),
                jnp.asarray(rn), jnp.asarray(roh)))
        return out
