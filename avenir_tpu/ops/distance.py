"""Mixed-type record distance + tiled all-pairs computation.

Replaces the sifarish ``SameTypeSimilarity`` MR job of the reference KNN
pipeline (resource/knn.sh:47) and avenir-spark's ``RecordSimilarity`` bucket-
pair replication join (spark/.../similarity/RecordSimilarity.scala:65-103).
chombo's ``InterRecordDistance`` (not vendored in the reference) defines the
per-attribute semantics we reproduce: numeric attrs contribute
|a-b| / (max-min) in [0,1]; categorical attrs contribute 0/1 mismatch;
aggregation is euclidean sqrt(mean of squares) or manhattan mean.  Distances
are emitted as ints scaled by ``distance scale`` (sts.distance.scale=1000 in
resource/knn.properties).

TPU design (SURVEY.md §2.10 'bucket-pair replication join' row): all-pairs
distance is a matmul problem, not a join problem —

  * euclidean numeric part:  |a'-b'|^2 summed over attrs = |a'|^2 + |b'|^2
    - 2 a'·b'  with a' = a/range  -> one (n_test, n_train) GEMM;
  * categorical mismatch count = F_cat - matches, matches = block-one-hot
    GEMM  A(n_test, sum_card) @ B(n_train, sum_card)^T;
  * manhattan falls back to a broadcast-tiled pass (bandwidth-bound).
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable


@functools.lru_cache(maxsize=None)
def _topk_merge_kernel(k: int):
    """Merge a fresh distance tile into the running best-k per test row:
    reduce the tile to its own best-k with ``lax.top_k`` (ties -> lowest
    position), then one stable 2k-wide multi-operand sort against the
    running list.  Sorting the raw (k + tile)-wide concat instead was the
    dominant cost of the whole KNN pass; a row gather (top_k + take) is
    not an option — gathers lower to scalar loops on this TPU.  Stability
    + tile order makes ties resolve to the lowest global train index,
    matching a stable argsort over the full matrix."""
    def merge(best_d, best_i, d_tile, base):
        kk = min(k, d_tile.shape[1])
        neg_v, pos = jax.lax.top_k(-d_tile.astype(jnp.float32), kk)
        tile_i = base + pos.astype(jnp.int32)
        cand_d = jnp.concatenate([best_d, -neg_v], axis=1)
        cand_i = jnp.concatenate([best_i, tile_i], axis=1)
        d_sorted, i_sorted = jax.lax.sort((cand_d, cand_i), dimension=1,
                                          num_keys=1)
        return d_sorted[:, :k], i_sorted[:, :k]
    return jax.jit(merge)


class DistanceComputer:
    """Precomputes per-attr normalization + categorical one-hot layout for a
    schema, then computes all-pairs int distances on device."""

    def __init__(self, schema: FeatureSchema, metric: str = "euclidean",
                 scale: int = 1000):
        self.schema = schema
        self.metric = metric
        self.scale = scale
        self.num_fields = [f for f in schema.feature_fields if f.is_numeric]
        self.cat_fields = [f for f in schema.feature_fields if f.is_categorical]
        self.n_attrs = len(self.num_fields) + len(self.cat_fields)
        self.ranges = np.array(
            [max(float(f.max) - float(f.min), 1e-12) if f.max is not None
             and f.min is not None else 1.0 for f in self.num_fields],
            dtype=np.float32)
        self.cards = [len(f.cardinality or []) for f in self.cat_fields]
        # jit once per computer: a fresh closure per pairwise() call would
        # retrace + recompile every invocation
        n_cat = float(len(self.cat_fields))
        denom = float(max(self.n_attrs, 1))
        fscale = float(self.scale)

        def _euclid(tn, toh, rn, roh):
            sq = (tn * tn).sum(1)[:, None] + (rn * rn).sum(1)[None, :] \
                - 2.0 * tn @ rn.T                                  # (nt, nr)
            cat_match = toh @ roh.T                                # matches
            cat_mismatch = n_cat - cat_match
            total = jnp.maximum(sq, 0.0) + cat_mismatch            # d in {0,1}: d^2=d
            mean = total / denom
            return jnp.floor(jnp.sqrt(jnp.maximum(mean, 0.0)) * fscale)

        def _manh(tn_tile, toh_tile, rn, roh):
            num = jnp.abs(tn_tile[:, None, :] - rn[None, :, :]).sum(2)
            cat = n_cat - toh_tile @ roh.T
            return jnp.floor((num + cat) / denom * fscale)

        self._euclid_jit = jax.jit(_euclid)
        self._manh_jit = jax.jit(_manh)

    # ---- encode a table into (numeric matrix, categorical block one-hot) ----
    def encode(self, table: ColumnarTable) -> Tuple[np.ndarray, np.ndarray]:
        n = table.n_rows
        if self.num_fields:
            num = np.stack([table.columns[f.ordinal] / r for f, r in
                            zip(self.num_fields, self.ranges)], axis=1
                           ).astype(np.float32)
        else:
            num = np.zeros((n, 0), dtype=np.float32)
        total_card = sum(self.cards)
        oh = np.zeros((n, total_card), dtype=np.float32)
        off = 0
        for f, card in zip(self.cat_fields, self.cards):
            codes = table.columns[f.ordinal]
            valid = codes >= 0
            oh[np.arange(n)[valid], off + codes[valid]] = 1.0
            off += card
        return num, oh

    def pairwise(self, test: ColumnarTable, train: ColumnarTable,
                 tile: int = 4096) -> np.ndarray:
        """(n_test, n_train) int32 scaled distances."""
        tn, toh = self.encode(test)
        rn, roh = self.encode(train)
        if self.metric == "euclidean":
            d = self._euclidean(jnp.asarray(tn), jnp.asarray(toh),
                                jnp.asarray(rn), jnp.asarray(roh))
        elif self.metric == "manhattan":
            d = self._manhattan_tiled(tn, toh, rn, roh, tile)
        else:
            raise ValueError(f"unknown metric {self.metric!r}")
        return np.asarray(d).astype(np.int32)

    def _euclidean(self, tn, toh, rn, roh):
        return self._euclid_jit(tn, toh, rn, roh)

    def pairwise_topk(self, test: ColumnarTable, train: ColumnarTable,
                      k: int, train_tile: int = 1 << 14,
                      test_chunk: int = 1 << 13
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Fused all-pairs distance + nearest-k, tiled over the train axis:
        the (n_test, n_train) matrix never exists — each train tile's
        distances merge into a running (n_test, k) device-resident best list
        (one stable sort per tile), and only ids + distances come back to
        host.  Replaces the all-pairs-file -> secondary-sort-reducer pipeline
        of the reference (knn/NearestNeighbor.java:80-81, resource/knn.sh:47)
        and lifts the full-matrix memory ceiling (20k x 200k needed 16 GB
        through ``pairwise``; here it is ~170 MB per in-flight tile).

        Returns (distances (n_test, k) int32, train indices (n_test, k)
        int32), rows sorted nearest-first, ties to the lowest train index.

        Multi-device: the test axis is embarrassingly parallel (every kernel
        is per-test-row), so when the runtime mesh has >1 device each test
        chunk is row-sharded over it with the train tiles replicated — GSPMD
        fans the distance + running-top-k work across the data axis with no
        cross-device traffic until the final gather.  Chunks not divisible
        by the device count fall back to single-device placement."""
        from ..parallel.mesh import runtime_context
        tn, toh = self.encode(test)
        rn, roh = self.encode(train)
        n_test, n_train = tn.shape[0], rn.shape[0]
        k = min(k, n_train)
        merge = _topk_merge_kernel(k)
        # keep each (test_chunk, train_tile) tile around 2^27 f32 elements
        train_tile = max(1024, min(train_tile, (1 << 27) // max(test_chunk, 1)))
        ctx = runtime_context()
        # single-process only: device_put of a HOST-LOCAL array to a
        # sharding spanning non-addressable devices bypasses the
        # from_process_local ingest discipline and is version-sensitive
        # (round-4 advisor).  Under multi-process the knnPipeline job
        # already splits the test axis by process (dist=partition), so
        # each process places plain local arrays here.
        from ..parallel.distributed import is_multiprocess
        mesh_on = ctx.n_devices > 1 and not is_multiprocess()
        if mesh_on:
            rn_d = jax.device_put(jnp.asarray(rn), ctx.replicated_sharding())
            roh_d = jax.device_put(jnp.asarray(roh), ctx.replicated_sharding())
        else:
            rn_d, roh_d = jnp.asarray(rn), jnp.asarray(roh)
        if self.metric == "euclidean":
            dist_fn = self._euclid_jit
        elif self.metric == "manhattan":
            dist_fn = None
        else:
            raise ValueError(f"unknown metric {self.metric!r}")
        out_d: List[np.ndarray] = []
        out_i: List[np.ndarray] = []
        for ts in range(0, n_test, test_chunk):
            te = min(ts + test_chunk, n_test)
            if mesh_on and (te - ts) % ctx.n_devices == 0:
                put = lambda a: jax.device_put(a, ctx.row_sharding())
            else:
                put = lambda a: a
            tn_c = put(jnp.asarray(tn[ts:te]))
            toh_c = put(jnp.asarray(toh[ts:te]))
            best_d = put(jnp.full((te - ts, k), np.inf, dtype=jnp.float32))
            best_i = put(jnp.full((te - ts, k), -1, dtype=jnp.int32))
            for s in range(0, n_train, train_tile):
                e = min(s + train_tile, n_train)
                if dist_fn is not None:
                    d = dist_fn(tn_c, toh_c, rn_d[s:e], roh_d[s:e])
                else:
                    d = self._manh_jit(tn_c, toh_c, rn_d[s:e], roh_d[s:e])
                best_d, best_i = merge(best_d, best_i, d, s)
            # chunk results stay device-side; the whole test axis reads
            # back in ONE transfer per output below (each separate
            # np.asarray costs a full ~62 ms tunnel round trip)
            out_d.append(best_d)
            out_i.append(best_i)
        d_all = out_d[0] if len(out_d) == 1 else jnp.concatenate(out_d)
        i_all = out_i[0] if len(out_i) == 1 else jnp.concatenate(out_i)
        return (np.asarray(d_all).astype(np.int32), np.asarray(i_all))

    def _manhattan_tiled(self, tn, toh, rn, roh, tile):
        out = np.zeros((tn.shape[0], rn.shape[0]), dtype=np.float32)
        for s in range(0, tn.shape[0], tile):
            e = min(s + tile, tn.shape[0])
            out[s:e] = np.asarray(self._manh_jit(
                jnp.asarray(tn[s:e]), jnp.asarray(toh[s:e]),
                jnp.asarray(rn), jnp.asarray(roh)))
        return out
