"""Mixed-type record distance + tiled all-pairs computation.

Replaces the sifarish ``SameTypeSimilarity`` MR job of the reference KNN
pipeline (resource/knn.sh:47) and avenir-spark's ``RecordSimilarity`` bucket-
pair replication join (spark/.../similarity/RecordSimilarity.scala:65-103).
chombo's ``InterRecordDistance`` (not vendored in the reference) defines the
per-attribute semantics we reproduce: numeric attrs contribute
|a-b| / (max-min) in [0,1]; categorical attrs contribute 0/1 mismatch;
aggregation is euclidean sqrt(mean of squares) or manhattan mean.  Distances
are emitted as ints scaled by ``distance scale`` (sts.distance.scale=1000 in
resource/knn.properties).

TPU design (SURVEY.md §2.10 'bucket-pair replication join' row): all-pairs
distance is a matmul problem, not a join problem —

  * euclidean numeric part:  |a'-b'|^2 summed over attrs = |a'|^2 + |b'|^2
    - 2 a'·b'  with a' = a/range  -> one (n_test, n_train) GEMM;
  * categorical mismatch count = F_cat - matches, matches = block-one-hot
    GEMM  A(n_test, sum_card) @ B(n_train, sum_card)^T;
  * manhattan falls back to a broadcast-tiled pass (bandwidth-bound).

Link discipline (TPU_NOTES §18): the categorical one-hot ships int8 (4x
fewer H2D bytes than f32; the device upcast is lossless), the train-side
encode + upload is cached across calls/test chunks, the whole
tile-loop of ``pairwise_topk`` is ONE ``lax.scan`` launch per test chunk
(it used to be two dispatches per train tile), and the running best-k
carries are donated.  Every transfer/dispatch records into the active
``utils.tracing.TransferLedger``, and tests pin the exact counts.
"""

from __future__ import annotations

import functools
import weakref
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.schema import FeatureSchema
from ..core.table import ColumnarTable
from ..utils.tracing import fetch, note_dispatch, note_h2d


@functools.lru_cache(maxsize=None)
def _dist_kernels(n_cat: float, denom: float, fscale: float):
    """The ONE implementation of both distance formulations, shared by the
    eager per-computer jits and the fused top-k scan (a drifted copy would
    silently break the scan-vs-full-matrix parity the tests pin).  The
    one-hot operands may arrive int8 (the narrow wire form): the f32
    upcast on device is lossless."""

    def _euclid(tn, toh, rn, roh):
        toh = toh.astype(jnp.float32)
        roh = roh.astype(jnp.float32)
        sq = (tn * tn).sum(1)[:, None] + (rn * rn).sum(1)[None, :] \
            - 2.0 * tn @ rn.T                                  # (nt, nr)
        cat_match = toh @ roh.T                                # matches
        cat_mismatch = n_cat - cat_match
        total = jnp.maximum(sq, 0.0) + cat_mismatch            # d in {0,1}: d^2=d
        mean = total / denom
        return jnp.floor(jnp.sqrt(jnp.maximum(mean, 0.0)) * fscale)

    def _manh(tn_tile, toh_tile, rn, roh):
        num = jnp.abs(tn_tile[:, None, :] - rn[None, :, :]).sum(2)
        cat = n_cat - toh_tile.astype(jnp.float32) @ roh.astype(jnp.float32).T
        return jnp.floor((num + cat) / denom * fscale)

    return _euclid, _manh


@functools.lru_cache(maxsize=None)
def _euclid_jit(n_cat: float, denom: float, fscale: float):
    return jax.jit(_dist_kernels(n_cat, denom, fscale)[0])


@functools.lru_cache(maxsize=None)
def _manh_jit(n_cat: float, denom: float, fscale: float):
    return jax.jit(_dist_kernels(n_cat, denom, fscale)[1])


def _merge_topk_body(best_d, best_i, d_tile, base, k: int):
    """Merge a fresh distance tile into the running best-k per test row:
    reduce the tile to its own best-k with ``lax.top_k`` (ties -> lowest
    position), then one stable 2k-wide multi-operand sort against the
    running list.  Sorting the raw (k + tile)-wide concat instead was the
    dominant cost of the whole KNN pass; a row gather (top_k + take) is
    not an option — gathers lower to scalar loops on this TPU.  Stability
    + tile order makes ties resolve to the lowest global train index,
    matching a stable argsort over the full matrix."""
    kk = min(k, d_tile.shape[1])
    neg_v, pos = jax.lax.top_k(-d_tile.astype(jnp.float32), kk)
    tile_i = base + pos.astype(jnp.int32)
    cand_d = jnp.concatenate([best_d, -neg_v], axis=1)
    cand_i = jnp.concatenate([best_i, tile_i], axis=1)
    d_sorted, i_sorted = jax.lax.sort((cand_d, cand_i), dimension=1,
                                      num_keys=1)
    return d_sorted[:, :k], i_sorted[:, :k]


@functools.lru_cache(maxsize=None)
def _topk_merge_kernel(k: int):
    """Standalone jitted merge step (see ``_merge_topk_body``).  The fused
    scan below subsumes it on the hot path; it remains the single-tile
    building block for external callers.  The running best lists are
    DONATED: the caller always rebinds ``best_d, best_i = merge(...)``, so
    XLA may update the (n_test, k) carries in place instead of making the
    defensive HBM copy every dispatch."""
    def merge(best_d, best_i, d_tile, base):
        return _merge_topk_body(best_d, best_i, d_tile, base, k)
    return jax.jit(merge, donate_argnums=(0, 1))


@functools.lru_cache(maxsize=None)
def _topk_scan_kernel(k: int, metric: str, n_cat: float, denom: float,
                      fscale: float):
    """ONE launch per test chunk: ``lax.scan`` over the stacked uniform
    train tiles, distance + running-best-k merge fused (the per-tile
    Python loop used to cost 2 dispatches x T tiles per chunk — pure
    dispatch latency on the tunneled link).  Tiles are padded to one
    uniform width; pad columns get distance +inf, so with k <= n_train
    they can never reach the final best list and results are bit-identical
    to the per-tile merge (tests pin scan == full-matrix argsort)."""
    eu, ma = _dist_kernels(n_cat, denom, fscale)
    dist = eu if metric == "euclidean" else ma

    def kernel(tn, toh, rn_t, roh_t, base, nvalid):
        def body(carry, xs):
            bd, bi = carry
            rn, roh, b, nv = xs
            d = dist(tn, toh, rn, roh)
            col = jnp.arange(d.shape[1], dtype=jnp.int32)
            d = jnp.where(col[None, :] < nv, d, jnp.inf)
            return _merge_topk_body(bd, bi, d, b, k), None

        nt = tn.shape[0]
        bd0 = jnp.full((nt, k), jnp.inf, dtype=jnp.float32)
        bi0 = jnp.full((nt, k), -1, dtype=jnp.int32)
        (bd, bi), _ = jax.lax.scan(body, (bd0, bi0),
                                   (rn_t, roh_t, base, nvalid))
        return bd, bi

    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _topk_pallas_jit(k: int, metric: str, n_cat: float, denom: float,
                     fscale: float, interpret: bool):
    """The pallas twin of ``_topk_scan_kernel`` (ops/pallas/topk): ONE
    launch per test chunk over the FLAT train arrays — the kernel owns
    its own tiling, the running best-k lives in VMEM scratch across the
    train walk, and the distance body is the same ``_dist_kernels``
    implementation, so results are bit-identical (interpret-mode parity
    pinned by tests/test_pallas_kernels.py).  The backend is resolved
    per call in ``pairwise_topk``; this cache keys on everything the
    lowered kernel depends on."""
    from .pallas.topk import topk_scan

    def kernel(tn, toh, rn, roh):
        return topk_scan(tn, toh, rn, roh, k, metric, n_cat, denom,
                         fscale, interpret=interpret)
    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _topk_sharded_jit(k: int, metric: str, n_cat: float, denom: float,
                      fscale: float, interpret: bool, mesh, axis_name: str):
    """The mesh-aware pallas form (ops/pallas/topk.topk_scan_sharded):
    the train axis shards over the mesh, each chip scans its local slice
    with the same VMEM kernel, ONE packed all_gather + lexicographic
    k-selection merges — bit-identical to the single-device scan.  The
    mesh rides in the lru key (a program traced over one mesh must never
    serve another)."""
    from .pallas.topk import topk_scan_sharded

    def kernel(tn, toh, rn, roh):
        return topk_scan_sharded(tn, toh, rn, roh, k, metric, n_cat,
                                 denom, fscale, mesh, axis_name,
                                 interpret=interpret)
    return jax.jit(kernel)


@functools.lru_cache(maxsize=None)
def _pair_concat_jit(n_parts: int):
    """Concatenate the per-chunk (best_d, best_i) part lists in ONE
    dispatch (two eager concatenates would be two)."""
    return jax.jit(lambda ds, is_: (jnp.concatenate(ds),
                                    jnp.concatenate(is_)))


class DistanceComputer:
    """Precomputes per-attr normalization + categorical one-hot layout for a
    schema, then computes all-pairs int distances on device.

    The train-side encode AND its device upload are cached across calls
    (one slot, keyed by the train table): the KNN pipeline hits the same
    train set with every test chunk, and re-encoding/re-uploading it per
    call was half the H2D bytes of the whole pass."""

    def __init__(self, schema: FeatureSchema, metric: str = "euclidean",
                 scale: int = 1000):
        self.schema = schema
        self.metric = metric
        self.scale = scale
        self.num_fields = [f for f in schema.feature_fields if f.is_numeric]
        self.cat_fields = [f for f in schema.feature_fields if f.is_categorical]
        self.n_attrs = len(self.num_fields) + len(self.cat_fields)
        self.ranges = np.array(
            [max(float(f.max) - float(f.min), 1e-12) if f.max is not None
             and f.min is not None else 1.0 for f in self.num_fields],
            dtype=np.float32)
        self.cards = [len(f.cardinality or []) for f in self.cat_fields]
        # kernel constants double as the module-level jit cache keys, so
        # every computer over the same shape shares ONE compiled program
        self._n_cat = float(len(self.cat_fields))
        self._denom = float(max(self.n_attrs, 1))
        self._fscale = float(self.scale)
        self._euclid_jit = _euclid_jit(self._n_cat, self._denom, self._fscale)
        self._manh_jit = _manh_jit(self._n_cat, self._denom, self._fscale)
        # one-slot train-side cache: weakref so a GC'd table can never
        # false-hit via id() reuse
        self._train_ref = lambda: None
        self._train_host: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._train_dev: dict = {}

    # ---- encode a table into (numeric matrix, categorical block one-hot) ----
    def encode(self, table: ColumnarTable) -> Tuple[np.ndarray, np.ndarray]:
        """(numeric (n, Fn) float32, one-hot (n, sum_card) int8).  The
        one-hot ships int8 — 4x less on the host->device link than the old
        f32 form — and the kernels upcast on device (lossless: values are
        0/1)."""
        n = table.n_rows
        if self.num_fields:
            num = np.stack([table.columns[f.ordinal] / r for f, r in
                            zip(self.num_fields, self.ranges)], axis=1
                           ).astype(np.float32)
        else:
            num = np.zeros((n, 0), dtype=np.float32)
        total_card = sum(self.cards)
        oh = np.zeros((n, total_card), dtype=np.int8)
        off = 0
        for f, card in zip(self.cat_fields, self.cards):
            codes = table.columns[f.ordinal]
            valid = codes >= 0
            oh[np.arange(n)[valid], off + codes[valid]] = 1
            off += card
        return num, oh

    def _encode_train(self, train: ColumnarTable
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Cached train-side encode (host arrays); rebinding to a different
        table drops the old entry and its device arrays."""
        if self._train_ref() is not train or self._train_host is None:
            self._train_host = self.encode(train)
            self._train_dev = {}
            self._train_ref = weakref.ref(train)
        return self._train_host

    def _train_device(self, key, build):
        """Cached device placement of train-side arrays (``build`` uploads
        on miss and its transfers hit the ledger exactly once per train
        table, not once per call)."""
        hit = self._train_dev.get(key)
        if hit is None:
            hit = self._train_dev[key] = build()
        return hit

    def pairwise(self, test: ColumnarTable, train: ColumnarTable,
                 tile: int = 4096) -> np.ndarray:
        """(n_test, n_train) int32 scaled distances."""
        tn, toh = self.encode(test)
        rn, roh = self._encode_train(train)
        if self.metric == "euclidean":
            note_h2d(tn.nbytes + toh.nbytes, transfers=2)
            rn_d, roh_d = self._train_device(
                "flat", lambda: (note_h2d(rn.nbytes + roh.nbytes, 2),
                                 (jnp.asarray(rn), jnp.asarray(roh)))[1])
            note_dispatch()
            d = fetch(self._euclid_jit(jnp.asarray(tn), jnp.asarray(toh),
                                       rn_d, roh_d))
        elif self.metric == "manhattan":
            d = self._manhattan_tiled(tn, toh, rn, roh, tile)
        else:
            raise ValueError(f"unknown metric {self.metric!r}")
        return np.asarray(d).astype(np.int32)

    def pairwise_topk(self, test: ColumnarTable, train: ColumnarTable,
                      k: int, train_tile: int = 1 << 14,
                      test_chunk: int = 1 << 13,
                      shard_reducer=None, shard_base: int = 0
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Fused all-pairs distance + nearest-k, tiled over the train axis:
        the (n_test, n_train) matrix never exists — the train set is
        stacked into uniform tiles and ONE ``lax.scan`` launch per test
        chunk folds every tile into the device-resident running best list;
        only ids + distances come back to host (one transfer each).
        Replaces the all-pairs-file -> secondary-sort-reducer pipeline
        of the reference (knn/NearestNeighbor.java:80-81, resource/knn.sh:47)
        and lifts the full-matrix memory ceiling (20k x 200k needed 16 GB
        through ``pairwise``; here it is ~170 MB per in-flight tile).

        Returns (distances (n_test, k) int32, train indices (n_test, k)
        int32), rows sorted nearest-first, ties to the lowest train index.

        Dispatch/transfer shape (pinned by tests/test_transfers.py): with
        a warm train cache, each test chunk costs 2 H2D transfers (its
        numeric + one-hot arrays) and exactly 1 dispatch; the whole call
        adds 1 concat dispatch (when >1 chunk) and 2 D2H transfers.  The
        old per-tile loop was ~2T dispatches per chunk.

        Multi-device: the test axis is embarrassingly parallel (every kernel
        is per-test-row), so when the runtime mesh has >1 device each test
        chunk is row-sharded over it with the train tiles replicated — GSPMD
        fans the distance + running-top-k work across the data axis with no
        cross-device traffic until the final gather.  Chunks not divisible
        by the device count fall back to single-device placement.

        Multi-HOST (``shard_reducer``, a ``parallel.collectives.AllReducer``):
        ``train`` is this process's row-range shard of the global train set
        starting at global row ``shard_base``.  Each test chunk's local
        best-k (indices lifted to global train rows) is merged with every
        peer's through ONE lock-step collective per chunk
        (``AllReducer.merge_topk``) — device-resident partials, one
        collective per step, and the merged result is bit-identical to the
        single-host full-train scan (ties to the lowest global train
        index).  All processes must walk identical test chunks; the
        returned lists are identical everywhere."""
        from ..parallel.mesh import runtime_context
        tn, toh = self.encode(test)
        rn, roh = self._encode_train(train)
        n_test, n_train = tn.shape[0], rn.shape[0]
        if shard_reducer is None:
            k = min(k, n_train)
        if n_train == 0 or n_test == 0:
            if shard_reducer is not None:
                # an empty train shard still joins every per-chunk
                # collective with zero-width partials (lock-step contract)
                out_d, out_i = [], []
                for ts in range(0, n_test, test_chunk):
                    te = min(ts + test_chunk, n_test)
                    d, i = shard_reducer.merge_topk(
                        np.zeros((te - ts, 0), np.float32),
                        np.zeros((te - ts, 0), np.int32), k)
                    out_d.append(d)
                    out_i.append(i)
                if out_d:
                    return (np.concatenate(out_d).astype(np.int32),
                            np.concatenate(out_i))
            return (np.zeros((n_test, k), np.int32),
                    np.zeros((n_test, k), np.int32))
        if self.metric not in ("euclidean", "manhattan"):
            raise ValueError(f"unknown metric {self.metric!r}")
        # keep each (test_chunk, train_tile) tile around 2^27 f32 elements
        train_tile = max(1024, min(train_tile, (1 << 27) // max(test_chunk, 1)))
        ctx = runtime_context()
        # single-process only: device_put of a HOST-LOCAL array to a
        # sharding spanning non-addressable devices bypasses the
        # from_process_local ingest discipline and is version-sensitive
        # (round-4 advisor).  Under multi-process the knnPipeline job
        # already splits the test axis by process (dist=partition), so
        # each process places plain local arrays here.
        from ..parallel.distributed import is_multiprocess
        mesh_on = ctx.n_devices > 1 and not is_multiprocess()

        def build_tiles():
            T = -(-n_train // train_tile)
            pad = T * train_tile - n_train
            rn_p = np.pad(rn, ((0, pad), (0, 0))) if pad else rn
            roh_p = np.pad(roh, ((0, pad), (0, 0))) if pad else roh
            rn_t = rn_p.reshape(T, train_tile, rn.shape[1])
            roh_t = roh_p.reshape(T, train_tile, roh.shape[1])
            base = (np.arange(T, dtype=np.int32) * train_tile)
            nvalid = np.minimum(n_train - base, train_tile).astype(np.int32)
            note_h2d(rn_t.nbytes + roh_t.nbytes + base.nbytes + nvalid.nbytes,
                     transfers=4)
            put = (lambda a: jax.device_put(jnp.asarray(a),
                                            ctx.replicated_sharding())) \
                if mesh_on else jnp.asarray
            return tuple(put(a) for a in (rn_t, roh_t, base, nvalid))

        # backend dispatch (TPU_NOTES §24): the pallas kernel owns its own
        # train tiling over the FLAT arrays and keeps the running best-k
        # in VMEM scratch; the XLA form scans pre-stacked uniform tiles.
        # Results are bit-identical; which form ran lands in the ledger's
        # KernelBackends group under the knn.topk site.
        from .pallas.dispatch import (note_backend, pallas_interpret,
                                      resolve_backend)
        # the pallas top-k IS mesh-aware on a single-axis single-process
        # mesh (train axis shards, one all_gather merges), so auto no
        # longer downgrades it there; hybrid/multi-process meshes still do
        single_axis = isinstance(ctx.axis, str)
        backend = resolve_backend(ctx.device_platform, ctx.n_devices,
                                  mesh_aware=mesh_on and single_axis,
                                  site="knn.topk")
        k_loc = min(k, n_train)
        sharded_knn = (backend == "pallas" and ctx.n_devices > 1
                       and mesh_on and single_axis)
        if backend == "pallas":
            rn_d, roh_d = self._train_device(
                "pallas-flat",
                lambda: (note_h2d(rn.nbytes + roh.nbytes, 2),
                         (jnp.asarray(rn), jnp.asarray(roh)))[1])
            if sharded_knn:
                kernel = _topk_sharded_jit(
                    k_loc, self.metric, self._n_cat, self._denom,
                    self._fscale, pallas_interpret(ctx.device_platform),
                    ctx.mesh, ctx.axis)
            else:
                kernel = _topk_pallas_jit(
                    k_loc, self.metric, self._n_cat, self._denom,
                    self._fscale,
                    pallas_interpret(ctx.device_platform))
        else:
            rn_t, roh_t, base_d, nv_d = self._train_device(
                ("tiled", train_tile, mesh_on), build_tiles)
            kernel = _topk_scan_kernel(k_loc, self.metric, self._n_cat,
                                       self._denom, self._fscale)
        out_d: List = []
        out_i: List = []
        for ts in range(0, n_test, test_chunk):
            te = min(ts + test_chunk, n_test)
            if backend != "pallas" and mesh_on \
                    and (te - ts) % ctx.n_devices == 0:
                put = lambda a: jax.device_put(a, ctx.row_sharding())
            else:
                put = lambda a: a
            tn_h, toh_h = tn[ts:te], toh[ts:te]
            note_h2d(tn_h.nbytes + toh_h.nbytes, transfers=2)
            tn_c = put(jnp.asarray(tn_h))
            toh_c = put(jnp.asarray(toh_h))
            note_dispatch(site="knn.topk")
            note_backend("knn.topk", backend)
            if backend == "pallas":
                best_d, best_i = kernel(tn_c, toh_c, rn_d, roh_d)
            else:
                best_d, best_i = kernel(tn_c, toh_c, rn_t, roh_t,
                                        base_d, nv_d)
            if shard_reducer is not None:
                # lock-step merge: this chunk's local best-k (lifted to
                # GLOBAL train rows) against every peer's — the ONE
                # collective per test chunk (pinned by
                # tests/test_sharded_stream.py)
                d_h = fetch(best_d)
                i_h = fetch(best_i) + np.int32(shard_base)
                d_h, i_h = shard_reducer.merge_topk(d_h, i_h, k)
                out_d.append(d_h)
                out_i.append(i_h)
                continue
            # chunk results stay device-side; the whole test axis reads
            # back in ONE transfer per output below (each separate
            # np.asarray costs a full ~62 ms tunnel round trip)
            out_d.append(best_d)
            out_i.append(best_i)
        if shard_reducer is not None:
            return (np.concatenate(out_d).astype(np.int32),
                    np.concatenate(out_i))
        if len(out_d) == 1:
            d_all, i_all = out_d[0], out_i[0]
        else:
            note_dispatch()
            d_all, i_all = _pair_concat_jit(len(out_d))(out_d, out_i)
        return (fetch(d_all).astype(np.int32), fetch(i_all))

    def _manhattan_tiled(self, tn, toh, rn, roh, tile):
        out = np.zeros((tn.shape[0], rn.shape[0]), dtype=np.float32)
        rn_d, roh_d = self._train_device(
            "flat", lambda: (note_h2d(rn.nbytes + roh.nbytes, 2),
                             (jnp.asarray(rn), jnp.asarray(roh)))[1])
        for s in range(0, tn.shape[0], tile):
            e = min(s + tile, tn.shape[0])
            note_h2d(tn[s:e].nbytes + toh[s:e].nbytes, transfers=2)
            note_dispatch()
            out[s:e] = fetch(self._manh_jit(
                jnp.asarray(tn[s:e]), jnp.asarray(toh[s:e]), rn_d, roh_d))
        return out
