"""Counting kernels: the universal primitive of the rebuilt framework.

Almost every reducer in the reference is 'sum 1s (or moments) per composite
key' (SURVEY.md §7 guiding translation).  The counting kernels build that
sum by SCATTER-ADD over a flattened composite key (ISSUE 11) — the only
intermediate is an (n, F) int32 key matrix, so large (F, B, C) shapes
never materialize the (n, F, B) x (n, C) one-hot pair the old MXU
contraction needed; under GSPMD with row-sharded inputs the per-shard
partial sums + all-reduce reproduce the combiner+shuffle exactly
(map-side combine for free).  ``class_moments`` keeps the one-hot
contraction (its values are real moments, not 0/1 — the MXU form is the
right one), and ``_class_bin_histogram_onehot`` preserves the original
formulation as the scatter rewrite's parity oracle.  The forest/monitor
hot paths additionally carry hand-written pallas twins under
``ops/pallas/`` (TPU_NOTES §24), platform-selected via
``ops.pallas.dispatch``.

All kernels take a ``mask`` so padded rows (ColumnarTable.pad_to_multiple)
contribute nothing.  Counts are accumulated in float32 by default — exact for
counts < 2^24 per partial; callers that stream >16M rows per shard should use
the chunked variants which accumulate in float32 across chunks of bounded
one-hot materialization.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def class_bin_histogram(class_codes: jnp.ndarray,    # (n,) int
                        bin_codes: jnp.ndarray,      # (n, F) int
                        num_classes: int,
                        num_bins: int,
                        mask: Optional[jnp.ndarray] = None,
                        dtype=jnp.float32) -> jnp.ndarray:
    """counts[c, f, b] = #records with class c and feature f in bin b.

    The one-shot kernel behind BayesianDistribution's mapper+reducer
    (bayesian/BayesianDistribution.java:139-178, 263-327) and the per-node
    class histograms of the tree builder.  Out-of-range / negative bin codes
    (unknown categorical values) are dropped, as is anything with mask=False.

    Built by SCATTER-ADD over the flattened (class, feature, bin) key
    (the ``_support_kernel_mxu`` candidate-matrix trick, ISSUE 11): the
    only intermediate is the (n, F) int32 key matrix, where the old
    one-hot contraction materialized an (n, F, B) x (n, C) f32 pair —
    a B-fold memory blowup at large (F, B, C) shapes regardless of
    backend.  Counts are sums of 0/1 in ``dtype``, exact below 2^24 per
    cell in f32 — bit-identical to the one-hot form (pinned against
    ``_class_bin_histogram_onehot`` by tests/test_pallas_kernels.py)."""
    n, F = bin_codes.shape
    valid = (bin_codes >= 0) & (bin_codes < num_bins) \
        & ((class_codes >= 0) & (class_codes < num_classes))[:, None]
    if mask is not None:
        valid = valid & mask[:, None]
    c = jnp.clip(class_codes, 0, num_classes - 1).astype(jnp.int32)
    b = jnp.clip(bin_codes, 0, num_bins - 1).astype(jnp.int32)
    f = jnp.arange(F, dtype=jnp.int32)[None, :]
    key = (c[:, None] * F + f) * num_bins + b                 # (n, F)
    flat = jnp.zeros((num_classes * F * num_bins,), dtype
                     ).at[key.ravel()].add(valid.ravel().astype(dtype))
    return flat.reshape(num_classes, F, num_bins)


def _class_bin_histogram_onehot(class_codes, bin_codes, num_classes,
                                num_bins, mask=None, dtype=jnp.float32):
    """The original one-hot contraction form, kept as the parity oracle
    for the scatter rewrite (and the MXU formulation a dense-matmul
    backend could still prefer).  Same drop semantics."""
    valid = (bin_codes >= 0) & (bin_codes < num_bins)
    if mask is not None:
        valid = valid & mask[:, None]
    oh_c = jax.nn.one_hot(class_codes, num_classes, dtype=dtype)        # (n, C)
    oh_b = jax.nn.one_hot(bin_codes, num_bins, dtype=dtype)             # (n, F, B)
    oh_b = oh_b * valid.astype(dtype)[:, :, None]
    # (n,C) x (n,F,B) -> (C,F,B): one big MXU contraction
    return jnp.einsum("nc,nfb->cfb", oh_c, oh_b)


def class_bin_histogram_chunked(class_codes, bin_codes, num_classes, num_bins,
                                mask=None, chunk: int = 1 << 18,
                                dtype=jnp.float32) -> jnp.ndarray:
    """Streaming variant: scan over row chunks so the (chunk, F, B) one-hot is
    the only large intermediate.  Used for big ingests where n*F*B floats
    would blow HBM."""
    n = class_codes.shape[0]
    pad = (-n) % chunk
    cc = jnp.pad(class_codes, (0, pad), constant_values=0)
    bc = jnp.pad(bin_codes, ((0, pad), (0, 0)), constant_values=-1)
    m = mask if mask is not None else jnp.ones((n,), dtype=bool)
    m = jnp.pad(m, (0, pad), constant_values=False)
    n_chunks = cc.shape[0] // chunk
    cc = cc.reshape(n_chunks, chunk)
    bc = bc.reshape(n_chunks, chunk, -1)
    m = m.reshape(n_chunks, chunk)

    def body(acc, xs):
        c, b, mm = xs
        return acc + class_bin_histogram(c, b, num_classes, num_bins, mm, dtype), None

    init = jnp.zeros((num_classes, bin_codes.shape[1], num_bins), dtype=dtype)
    acc, _ = jax.lax.scan(body, init, (cc, bc, m))
    return acc


def feature_bin_counts(bin_codes: jnp.ndarray,   # (n, F) int
                       num_bins: int,
                       mask: Optional[jnp.ndarray] = None,
                       dtype=jnp.float32) -> jnp.ndarray:
    """counts[f, b] = #records with feature f in bin b — the classless
    marginal of :func:`class_bin_histogram` (one dummy class).  The
    counting primitive of the drift-monitoring subsystem: baseline
    profiles and window accumulators are sums of these over row blocks
    (monitor/baseline.py, monitor/accumulator.py).  Out-of-range codes
    drop, masked rows contribute nothing."""
    n = bin_codes.shape[0]
    zeros = jnp.zeros((n,), dtype=jnp.int32)
    return class_bin_histogram(zeros, bin_codes, 1, num_bins, mask,
                               dtype)[0]


def class_moments(class_codes: jnp.ndarray,   # (n,)
                  values: jnp.ndarray,        # (n, F) float
                  num_classes: int,
                  mask: Optional[jnp.ndarray] = None,
                  dtype=jnp.float32) -> jnp.ndarray:
    """moments[c, f, :] = (count, sum x, sum x^2) per class for continuous
    features (the unbinned-numeric path of BayesianDistribution.java:166-171)."""
    oh_c = jax.nn.one_hot(class_codes, num_classes, dtype=dtype)  # (n, C)
    if mask is not None:
        oh_c = oh_c * mask.astype(dtype)[:, None]
    v = values.astype(dtype)
    stacked = jnp.stack([jnp.ones_like(v), v, v * v], axis=-1)    # (n, F, 3)
    return jnp.einsum("nc,nfm->cfm", oh_c, stacked)


def joint_histogram(a_codes: jnp.ndarray, b_codes: jnp.ndarray,
                    num_a: int, num_b: int,
                    mask: Optional[jnp.ndarray] = None,
                    dtype=jnp.float32) -> jnp.ndarray:
    """counts[a, b] joint histogram of two code columns (contingency matrix /
    MutualInformation pair distributions, explore/MutualInformation.java).
    Scatter-add over the flattened pair key — no (n, A) x (n, B) one-hot
    pair; bit-identical to the one-hot form (0/1 sums)."""
    valid = (a_codes >= 0) & (b_codes >= 0) \
        & (a_codes < num_a) & (b_codes < num_b)
    if mask is not None:
        valid = valid & mask
    a = jnp.clip(a_codes, 0, num_a - 1).astype(jnp.int32)
    b = jnp.clip(b_codes, 0, num_b - 1).astype(jnp.int32)
    flat = jnp.zeros((num_a * num_b,), dtype
                     ).at[a * num_b + b].add(valid.astype(dtype))
    return flat.reshape(num_a, num_b)


def entropy(p: jnp.ndarray, axis=-1, eps: float = 1e-12) -> jnp.ndarray:
    """Shannon entropy of a probability vector along an axis (natural log?
    no — the reference uses log2: util/InfoContentStat.java entropy via
    Math.log(p)/Math.log(2))."""
    p = jnp.clip(p, eps, 1.0)
    return -(p * jnp.log2(p)).sum(axis=axis)


def gini(p: jnp.ndarray, axis=-1) -> jnp.ndarray:
    """Gini index 1 - sum p^2 (util/InfoContentStat.java:71 gini branch)."""
    return 1.0 - (p * p).sum(axis=axis)
