"""The scrapeable serving endpoint: /metrics + /healthz on a stdlib
``http.server`` daemon thread.

One ThreadingHTTPServer per process, bound to the operator-chosen port
(``telemetry.metrics.port``; port 0 binds ephemeral and the chosen port
is printed/exposed via ``.port``).  ``/metrics`` renders the registry's
Prometheus text; ``/healthz`` aggregates the registry's health providers
— 200 with ``{"status": "ok"}`` when every provider reports healthy,
503 with the failing checks when any is degraded, which is exactly the
contract a load balancer's health probe consumes (a degraded serving
worker stops pulling traffic).  ``/exemplars`` is the JSON twin of the
histogram exemplars (bucket -> last sampled request id).  Anything else
is 404.

The server must never take the job down: handler errors answer 500,
logging is suppressed (stdlib BaseHTTPRequestHandler logs every request
to stderr otherwise), and ``stop()`` is idempotent.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import MetricsRegistry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


class MetricsServer:
    """Serve one registry's /metrics and /healthz until stopped."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        self.registry = registry
        self.host = host
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet: one line per scrape
                pass               # would flood the job's stderr

            def _answer(self, code: int, body: bytes,
                        content_type: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        # content negotiation, the real Prometheus
                        # protocol: exemplars are only legal in the
                        # OpenMetrics exposition, so the classic 0.0.4
                        # body stays exemplar-free and a scraper asking
                        # for openmetrics (what Prometheus sends when
                        # exemplar scraping is on) gets them
                        accept = self.headers.get("Accept", "") or ""
                        if "application/openmetrics-text" in accept:
                            self._answer(
                                200,
                                registry.render_openmetrics()
                                .encode("utf-8"),
                                OPENMETRICS_CONTENT_TYPE)
                        else:
                            self._answer(
                                200, registry.render().encode("utf-8"),
                                PROM_CONTENT_TYPE)
                    elif path == "/exemplars":
                        # the /metrics-adjacent JSON: histogram bucket
                        # -> last sampled request id, for tooling that
                        # should not have to parse the text exposition
                        self._answer(
                            200,
                            json.dumps(registry.exemplars_json(),
                                       sort_keys=True).encode(),
                            "application/json")
                    elif path == "/healthz":
                        ok, payload = registry.health()
                        self._answer(
                            200 if ok else 503,
                            json.dumps(payload, sort_keys=True).encode(),
                            "application/json")
                    elif path.startswith("/healthz/"):
                        # per-provider probe: /healthz/<name> answers for
                        # ONE health source (a fleet worker), so a load
                        # balancer can pull one degraded worker while its
                        # peers keep taking traffic
                        res = registry.health_one(path[len("/healthz/"):])
                        if res is None:
                            self._answer(404, b"no such health check\n",
                                         "text/plain")
                        else:
                            ok, payload = res
                            self._answer(
                                200 if ok else 503,
                                json.dumps(payload,
                                           sort_keys=True).encode(),
                                "application/json")
                    else:
                        self._answer(404, b"not found\n", "text/plain")
                except Exception as exc:  # scrape must not kill serving
                    try:
                        self._answer(500, f"{type(exc).__name__}: {exc}\n"
                                     .encode(), "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="avenir-metrics-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
