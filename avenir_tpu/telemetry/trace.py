"""Span tracing: per-run Tracer + the process-global ``span()`` entry.

Design constraints (in priority order):

1. **Free when off.**  Every instrumented hot path calls ``span(...)``
   unconditionally; with no tracer installed that is one module-global
   read and the return of a shared null context manager — no allocation,
   no branching in the caller.  The streaming ingest loop and the serving
   batch path are instrumented at block/batch granularity (never per row),
   so even when ON the cost is a dict append per multi-ms unit of work
   (<2% of wall, recorded by the e2e_rf bench's telemetry block).

2. **Events ARE Chrome trace events.**  The JSONL buffer flushes lines
   that are already catapult dicts (``ph: "X"`` complete events with
   epoch-anchored microsecond ``ts``/``dur``, ``ph: "i"`` instants,
   ``ph: "M"`` thread/process metadata), so the Chrome export is a sort +
   wrap, and multi-process merge (tools/tracetool.py) is a concatenation:
   every process anchors its monotonic clock to the epoch at tracer
   construction, which aligns same-machine shard lanes to ~ms — enough to
   see collective skew, which is the point.

3. **Threads are lanes.**  ``tid`` is a stable small integer per thread
   (announced once via a ``thread_name`` metadata event), so the parse
   thread, the H2D staging thread, and the consumer/compute thread of the
   streaming pipeline land on separate lanes and their overlap is visible
   as horizontal concurrency instead of a bench-computed fraction.

The tracer is process-global (``install_tracer``), like the transfer
ledger's stack and for the same reason: the staging/prefetch threads a
pipeline spawns must land their spans in the run that spawned them.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

TRACE_SUFFIX = ".jsonl"
CHROME_SUFFIX = ".chrome.json"

# Chrome trace-event schema subset this module emits (and the validator
# checks): complete spans, instants, metadata, and — since ISSUE 15 —
# legacy flow events (s/t/f) carrying one sampled request's id across
# process lanes (client enqueue -> worker pop -> dispatch -> reply).
_REQUIRED_KEYS = {
    "X": ("name", "ph", "ts", "dur", "pid", "tid"),
    "i": ("name", "ph", "ts", "pid", "tid"),
    "B": ("name", "ph", "ts", "pid", "tid"),
    "E": ("ph", "ts", "pid", "tid"),
    "M": ("name", "ph", "pid"),
    "s": ("name", "ph", "ts", "pid", "tid", "id"),
    "t": ("name", "ph", "ts", "pid", "tid", "id"),
    "f": ("name", "ph", "ts", "pid", "tid", "id"),
}

FLOW_PHASES = ("s", "t", "f")


class Tracer:
    """Buffered span/event recorder for ONE process of ONE run.

    Writes ``trace-<run_id>.p<index>.jsonl`` under ``trace_dir`` — one
    JSON trace event per line, first line a ``process_name`` metadata
    event carrying the run id — and, on :meth:`close`, a ready-to-load
    Chrome export next to it (``...chrome.json``).  ``flush()`` is called
    automatically every ``buffer_events`` records, so a killed process
    leaves at most one buffer of spans unwritten (the survivors' stall
    events are what name it)."""

    def __init__(self, trace_dir: str, run_id: str = "run",
                 process_index: int = 0, buffer_events: int = 2048):
        os.makedirs(trace_dir, exist_ok=True)
        self.dir = trace_dir
        self.run_id = str(run_id)
        self.process_index = int(process_index)
        self.path = os.path.join(
            trace_dir, f"trace-{self.run_id}.p{self.process_index:05d}"
            f"{TRACE_SUFFIX}")
        self.buffer_events = int(buffer_events)
        self._buf: List[dict] = []
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        # weak keys: exited threads fall out instead of pinning their
        # Thread objects (and a recycled ident can never alias a lane)
        self._tids: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._next_tid = 1
        self._closed = False
        self.events_recorded = 0
        # epoch-anchored monotonic clock: ts = unix time at construction
        # plus a perf_counter delta — monotonic within the process, and
        # aligned across same-machine shard processes to wall-clock skew
        self._t0_unix_us = time.time() * 1e6
        self._t0_perf = time.perf_counter()
        # APPEND and announce the process lane: a resumed sharded run
        # derives the identical run id (cli.run hashes job+input so all
        # shards agree), so truncating here would destroy the crashed
        # attempt's timeline — including the allreduce.stall events that
        # name the dead shard, the exact evidence the operator is about
        # to look for.  Both attempts share the run id and epoch-anchored
        # clocks, so the merged timeline stays laminar per lane.
        with open(self.path, "ab") as fh:
            # a crashed attempt can leave a torn final line (killed
            # mid-flush, no trailing newline) — appending our header
            # straight onto it would fuse both into one unparseable
            # line; seal the torn tail first so only the fragment is
            # lost, not the resumed run's metadata too
            if fh.tell() > 0:
                with open(self.path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        fh.write(b"\n")
            fh.write((json.dumps({
                "ph": "M", "name": "process_name",
                "pid": self.process_index, "tid": 0,
                "args": {"name": f"{self.run_id} shard "
                                 f"{self.process_index}"},
                "run_id": self.run_id},
                separators=(",", ":")) + "\n").encode())

    # ---- clock ----
    def now_us(self) -> float:
        return self._t0_unix_us + \
            (time.perf_counter() - self._t0_perf) * 1e6

    # ---- recording ----
    def _tid(self) -> int:
        """Stable small lane id for the calling thread; announces a
        ``thread_name`` metadata event the first time a thread records.
        Keyed by the Thread OBJECT (weakly), not ``get_ident()``: the OS
        recycles idents, so a later thread reusing a dead staging
        thread's ident must get a fresh lane — not record its spans
        under the dead thread's name on the dead thread's lane."""
        return self._tid_for(threading.current_thread())

    def _tid_for(self, thread: threading.Thread) -> int:
        tid = self._tids.get(thread)
        if tid is not None:
            return tid
        with self._lock:
            tid = self._tids.get(thread)
            if tid is None:
                tid = self._next_tid
                self._next_tid += 1
                self._tids[thread] = tid
                self._buf.append({
                    "ph": "M", "name": "thread_name",
                    "pid": self.process_index, "tid": tid,
                    "args": {"name": thread.name}})
        return tid

    def _append(self, ev: dict) -> None:
        with self._lock:
            self._buf.append(ev)
            self.events_recorded += 1
            # after close() nothing will ever flush again, so write
            # through immediately: a straggler thread finishing its span
            # during teardown records the TAIL of an aborted job — the
            # part of the trace that matters most (the chrome export is
            # already written; the JSONL stays the source of truth and
            # tracetool re-exports)
            need_flush = self._closed or \
                len(self._buf) >= self.buffer_events
        if need_flush:
            self.flush()

    def complete(self, name: str, t0_us: float, dur_us: float,
                 cat: Optional[str] = None, args: Optional[dict] = None
                 ) -> None:
        """One finished span as a Chrome complete ('X') event."""
        ev = {"ph": "X", "name": name, "ts": round(t0_us, 1),
              "dur": round(max(dur_us, 0.0), 1),
              "pid": self.process_index, "tid": self._tid()}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self._append(ev)

    def instant(self, name: str, cat: Optional[str] = None,
                on_thread: Optional[threading.Thread] = None,
                **args) -> None:
        """A point-in-time event (Chrome 'i', process scope) — stall
        events, degradation flips, hot-swaps.  ``on_thread`` pins the
        event to that thread's lane instead of the caller's: a watchdog
        Timer firing on behalf of a blocked caller must mark the
        CALLER's lane, not scatter one-event lanes named Thread-N."""
        lane = self._tid() if on_thread is None else \
            self._tid_for(on_thread)
        ev = {"ph": "i", "s": "p", "name": name,
              "ts": round(self.now_us(), 1),
              "pid": self.process_index, "tid": lane}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self._append(ev)

    def flow(self, name: str, phase: str, flow_id,
             cat: Optional[str] = None, ts_us: Optional[float] = None,
             args: Optional[dict] = None) -> None:
        """One leg of a Chrome legacy flow (``s`` start / ``t`` step /
        ``f`` finish): the arrow connecting one sampled request's hops
        across process lanes.  All legs of one flow must share cat, name
        AND id (catapult binds on the triplet), so callers keep the name
        constant and put the hop label in ``args``.  ``ts_us`` pins the
        event to a timestamp the caller already took (a stamped wire
        enqueue time) instead of now."""
        if phase not in FLOW_PHASES:
            raise ValueError(f"flow phase must be one of {FLOW_PHASES}, "
                             f"got {phase!r}")
        ev = {"ph": phase, "name": name, "id": str(flow_id),
              "ts": round(self.now_us() if ts_us is None else ts_us, 1),
              "pid": self.process_index, "tid": self._tid()}
        if phase == "f":
            ev["bp"] = "e"   # bind to the enclosing slice, chrome-style
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self._append(ev)

    # ---- persistence ----
    def flush(self) -> None:
        """Append the buffered events to the JSONL file.  IO runs outside
        the record lock so a slow disk never blocks the hot paths for
        longer than one buffer swap."""
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        lines = "".join(json.dumps(ev, separators=(",", ":")) + "\n"
                        for ev in buf)
        with self._io_lock:
            with open(self.path, "a") as fh:
                fh.write(lines)

    def chrome_export(self, out_path: Optional[str] = None) -> str:
        """Write the catapult JSON (``{"traceEvents": [...]}``, ts-sorted)
        for THIS process's trace file; returns the path written.
        Tmp-then-rename, so a crash mid-export never leaves a torn file
        that chrome://tracing would half-load."""
        self.flush()
        out = out_path or (self.path[:-len(TRACE_SUFFIX)] + CHROME_SUFFIX)
        events = read_trace_file(self.path)
        _write_chrome(out, events)
        return out

    def close(self) -> str:
        """Flush and write the Chrome export; idempotent."""
        if self._closed:
            return self.path
        self._closed = True
        self.flush()
        self.chrome_export()
        return self.path


# --------------------------------------------------------------------------
# the process-global tracer + the span() fast path
# --------------------------------------------------------------------------

_active: Optional[Tracer] = None


def install_tracer(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-global recorder every ``span()`` call
    site writes into (one at a time — telemetry is per run)."""
    global _active
    _active = tracer
    return tracer


def uninstall_tracer() -> Optional[Tracer]:
    global _active
    t, _active = _active, None
    return t


def current_tracer() -> Optional[Tracer]:
    return _active


class _NullSpan:
    """The off path: a shared, reusable, do-nothing context manager."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **args) -> None:
        """No-op twin of _LiveSpan.add."""


NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("_tr", "_name", "_cat", "_args", "_t0")

    def __init__(self, tr: Tracer, name: str, cat: Optional[str],
                 args: Optional[dict]):
        self._tr = tr
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._t0 = self._tr.now_us()
        return self

    def __exit__(self, *exc):
        self._tr.complete(self._name, self._t0,
                          self._tr.now_us() - self._t0,
                          cat=self._cat, args=self._args)
        return False

    def add(self, **args) -> None:
        """Attach attributes discovered mid-span (e.g. rows parsed)."""
        if self._args is None:
            self._args = dict(args)
        else:
            self._args.update(args)


def span(name: str, cat: Optional[str] = None, **args):
    """Context manager timing one pipeline stage.  THE instrumentation
    entry: ``with span("parse.chunk", cat="parse", block=i): ...``.
    Returns the shared null span when no tracer is installed."""
    tr = _active
    if tr is None:
        return NULL_SPAN
    return _LiveSpan(tr, name, cat, args or None)


def instant(name: str, cat: Optional[str] = None,
            on_thread: Optional[threading.Thread] = None, **args) -> None:
    """Record a point event on the installed tracer (no-op when off).
    ``on_thread`` pins the event to that thread's lane (watchdogs firing
    on behalf of a blocked caller)."""
    tr = _active
    if tr is not None:
        tr.instant(name, cat=cat, on_thread=on_thread, **args)


def flow(name: str, phase: str, flow_id, cat: Optional[str] = None,
         ts_us: Optional[float] = None, **args) -> None:
    """Record one flow leg on the installed tracer (no-op when off) —
    see :meth:`Tracer.flow`."""
    tr = _active
    if tr is not None:
        tr.flow(name, phase, flow_id, cat=cat, ts_us=ts_us,
                args=args or None)


# --------------------------------------------------------------------------
# trace-file reading / validation / merge (shared with tools/tracetool.py)
# --------------------------------------------------------------------------

def read_trace_file(path: str) -> List[dict]:
    """All events of one per-process JSONL trace file.  A torn final line
    (killed process mid-append) is dropped with the rest intact — exactly
    the crash the multi-shard stall scenario produces."""
    events: List[dict] = []
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail from a killed writer
    return events


def validate_trace_events(events: List[dict]) -> List[str]:
    """Check ``events`` against the Chrome trace-event schema subset this
    module emits; returns a list of problem strings (empty == valid).

    Rules: every event carries the required keys for its phase; ts/dur
    are non-negative numbers; within one (pid, tid) lane the 'X' spans
    form a laminar family — disjoint or fully nested, never partially
    crossing (spans on one lane come from a LIFO stack of context
    managers on one thread, so a crossing means the clock ran backwards,
    e.g. events with mixed epoch anchors merged into one lane); any
    legacy B/E duration events pair up per lane; per flow id, at most
    one ``s`` start and one ``f`` finish (a dangling ``t``/``f`` with
    no ``s`` is NOT flagged — one process's file is a legitimate
    partial view of a multi-process flow)."""
    problems: List[str] = []
    open_stacks: Dict[tuple, List[str]] = {}
    lane_spans: Dict[tuple, List[tuple]] = {}
    flow_counts: Dict[str, List[int]] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph not in _REQUIRED_KEYS:
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        for key in _REQUIRED_KEYS[ph]:
            if key not in ev:
                problems.append(f"event {i} (ph={ph}): missing {key!r}")
        for key in ("ts", "dur"):
            if key in ev and (not isinstance(ev[key], (int, float))
                              or ev[key] < 0):
                problems.append(
                    f"event {i} (ph={ph}): {key} must be a non-negative "
                    f"number, got {ev[key]!r}")
        if ph in ("s", "f") and "id" in ev:
            cnt = flow_counts.setdefault(str(ev["id"]), [0, 0])
            cnt[0 if ph == "s" else 1] += 1
        if ph == "X" and isinstance(ev.get("ts"), (int, float)) \
                and isinstance(ev.get("dur"), (int, float)):
            lane_spans.setdefault(
                (ev.get("pid"), ev.get("tid")), []).append(
                (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]),
                 ev.get("name"), i))
        elif ph == "B":
            open_stacks.setdefault(
                (ev.get("pid"), ev.get("tid")), []).append(ev.get("name"))
        elif ph == "E":
            stack = open_stacks.get((ev.get("pid"), ev.get("tid")), [])
            if not stack:
                problems.append(f"event {i}: 'E' with no open 'B' on its "
                                f"(pid, tid) lane")
            else:
                stack.pop()
    for (pid, tid), stack in open_stacks.items():
        for name in stack:
            problems.append(f"unmatched 'B' event {name!r} on lane "
                            f"(pid={pid}, tid={tid})")
    for fid, (n_s, n_f) in sorted(flow_counts.items()):
        if n_s > 1:
            problems.append(f"flow {fid!r}: {n_s} 's' start events "
                            f"(must be at most one)")
        if n_f > 1:
            problems.append(f"flow {fid!r}: {n_f} 'f' finish events "
                            f"(must be at most one)")
    # lane timeline check: 1µs slack absorbs the 0.1µs ts/dur rounding
    eps = 1.0
    for (pid, tid), spans in lane_spans.items():
        spans.sort(key=lambda s: (s[0], s[0] - s[1]))
        stack: List[tuple] = []
        for t0, t1, name, i in spans:
            while stack and t0 >= stack[-1][1] - eps:
                stack.pop()
            if stack and t1 > stack[-1][1] + eps:
                problems.append(
                    f"event {i} (ph=X): span {name!r} crosses "
                    f"{stack[-1][2]!r} on lane (pid={pid}, tid={tid}) — "
                    f"not a valid single-thread timeline")
                continue
            stack.append((t0, t1, name))
    return problems


def merge_trace_files(paths: List[str]) -> List[dict]:
    """Concatenate the events of several per-process trace files into one
    ts-sorted timeline.  Epoch-anchored timestamps make this a plain
    merge; distinct run ids are allowed (tracetool warns) because merging
    a re-run shard's tail onto a crashed run's lanes is sometimes exactly
    what the operator wants to look at."""
    events: List[dict] = []
    for p in paths:
        events.extend(read_trace_file(p))
    return _ts_sorted(events)


def _ts_sorted(events: List[dict]) -> List[dict]:
    # metadata events carry no ts; keep them first so lanes are named
    # before any span lands on them
    return sorted(events,
                  key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))


def _write_chrome(out_path: str, events: List[dict]) -> None:
    payload = {"traceEvents": _ts_sorted(events),
               "displayTimeUnit": "ms"}
    tmp = f"{out_path}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, separators=(",", ":"))
    os.replace(tmp, out_path)


def write_chrome_trace(out_path: str, events: List[dict]) -> str:
    """Public wrapper: write ``events`` as a catapult JSON file."""
    _write_chrome(out_path, events)
    return out_path
