"""MetricsRegistry: one counters/gauges/histograms API over the
framework's three pre-existing metric channels.

``core.metrics.Counters`` (Hadoop-style job counters),
``utils.tracing.TransferLedger`` (measured link traffic), and
``utils.tracing.StepTimer`` (wall-time + latency percentiles) each grew
up exporting their own group; the registry unifies them behind one
sampling surface without changing any of them: ``attach_counters`` /
``attach_ledger`` / ``attach_timer`` register *probes* — callables run
before every render/snapshot that refresh gauges from the live source
objects.  The serving integration registers its own probe the same way
(queue depth, in-flight, degraded), so ``/metrics`` mid-job shows the
pipeline moving, not an end-of-job summary.

Exposition is Prometheus text format 0.0.4 (the de-facto scrape wire):
``# HELP`` / ``# TYPE`` headers, ``name{label="v"} value`` samples,
histograms as cumulative ``_bucket{le=}`` series plus ``_sum``/``_count``.

A background snapshot thread (:meth:`MetricsRegistry.start_snapshots`)
re-runs the probes on an interval and optionally appends one JSON sample
line per tick — the flight recorder for jobs nobody was scraping.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")

DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def sanitize_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label(name: str) -> str:
    name = _LABEL_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v) -> str:
    """Label-value escaping per the Prometheus text-format spec:
    backslash first (or the other escapes would double up), then
    double-quote and newline.  A host label or service name carrying any
    of the three otherwise emits an unparseable scrape — pinned by
    tests/test_reqtrace.py."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP-text escaping per the spec (backslash and newline only —
    quotes are legal in HELP)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = [(n, v) for n, v in zip(names, values)] + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{sanitize_label(n)}="{_escape_label_value(v)}"'
                    for n, v in pairs)
    return "{" + body + "}"


class _Metric:
    """One named family: counter | gauge | histogram, with optional
    labels.  Values keyed by the label-value tuple; lock shared with the
    registry (metric updates are a few ops per multi-ms unit of work)."""

    __slots__ = ("name", "kind", "help", "label_names", "values",
                 "buckets", "exemplars", "_lock")

    def __init__(self, name: str, kind: str, help_text: str,
                 label_names: Sequence[str], lock: threading.Lock,
                 buckets: Sequence[float] = ()):
        self.name = sanitize_name(name)
        self.kind = kind
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = lock
        # counter/gauge: labels -> float
        # histogram: labels -> [bucket_counts..., sum, count]
        self.values: Dict[tuple, object] = {}
        self.buckets = tuple(sorted(buckets)) if kind == "histogram" else ()
        # histogram exemplars (ISSUE 15): labels -> {native bucket index
        # -> (trace_id, value, unix_ts)} — each bucket remembers the
        # LAST sampled observation that landed in it, so a p99 spike
        # resolves to a concrete request id in one step
        self.exemplars: Dict[tuple, Dict[int, tuple]] = {}

    def _key(self, labels: Dict[str, str]) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.label_names)}")
        return tuple(str(labels[n]) for n in self.label_names)

    # counter / gauge
    def inc(self, amount: float = 1.0, **labels) -> None:
        if self.kind == "histogram":
            raise TypeError(f"{self.name} is a histogram; use observe()")
        key = self._key(labels)
        with self._lock:
            self.values[key] = float(self.values.get(key, 0.0)) + amount

    def set(self, value: float, **labels) -> None:
        if self.kind != "gauge":
            raise TypeError(f"{self.name} is a {self.kind}; only gauges "
                            f"set()")
        key = self._key(labels)
        with self._lock:
            self.values[key] = float(value)

    def get(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            v = self.values.get(key, 0.0)
        return float(v) if not isinstance(v, list) else float(v[-1])

    def drop_series(self, **labels) -> int:
        """Remove every series whose label values match the given subset
        (e.g. ``drop_series(service="m1")``); returns how many were
        dropped.  An unbinding owner uses this so a retired source's
        last-written values do not render in every later scrape as if
        they were live."""
        idx = [self.label_names.index(n) for n in labels]
        want = [str(labels[n]) for n in labels]
        with self._lock:
            doomed = [k for k in self.values
                      if all(k[i] == w for i, w in zip(idx, want))]
            for k in doomed:
                del self.values[k]
                self.exemplars.pop(k, None)
        return len(doomed)

    # histogram
    def observe(self, value: float, exemplar=None, **labels) -> None:
        """One observation; ``exemplar`` (a sampled request's trace id)
        is remembered by the NATIVE bucket — the smallest bucket the
        value fits, last write wins — and rendered OpenMetrics-style on
        that ``_bucket`` line."""
        if self.kind != "histogram":
            raise TypeError(f"{self.name} is a {self.kind}; use inc()/set()")
        key = self._key(labels)
        with self._lock:
            st = self.values.get(key)
            if st is None:
                st = self.values[key] = [0] * len(self.buckets) + [0.0, 0]
            native = len(self.buckets)
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    st[i] += 1
                    if i < native:
                        native = i
            st[-2] += float(value)
            st[-1] += 1
            if exemplar is not None:
                self.exemplars.setdefault(key, {})[native] = (
                    str(exemplar), float(value), time.time())

    def _exemplar_suffix(self, ex: Optional[Dict[int, tuple]],
                         idx: int) -> str:
        """The OpenMetrics exemplar tail for one ``_bucket`` line:
        `` # {trace_id="<id>"} <value> <unix_ts>`` — metric spike to
        concrete request id in one scrape."""
        if not ex or idx not in ex:
            return ""
        rid, val, ts = ex[idx]
        return (f' # {{trace_id="{_escape_label_value(rid)}"}} '
                f"{_fmt_value(val)} {ts:.3f}")

    # exposition
    def render(self, openmetrics: bool = False) -> List[str]:
        """Text-format lines.  ``openmetrics=True`` renders the
        OpenMetrics dialect: exemplar tails on ``_bucket`` lines and the
        mandatory ``_total`` suffix on counter samples — both ILLEGAL /
        absent in the classic 0.0.4 exposition (whose parser rejects
        tokens after the value), so the default render stays classic."""
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        sample_name = self.name
        if openmetrics and self.kind == "counter":
            # OpenMetrics REQUIRES counter samples named <family>_total;
            # a bare-name counter fails the whole scrape at the parser
            sample_name = f"{self.name}_total"
        with self._lock:
            items = sorted(self.values.items())
            ex_copy = {k: dict(v) for k, v in self.exemplars.items()} \
                if openmetrics else {}
        for key, v in items:
            if self.kind == "histogram":
                ex = ex_copy.get(key)
                cum = 0
                for i, edge in enumerate(self.buckets):
                    cum = v[i]
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_fmt_labels(self.label_names, key, [('le', _fmt_value(edge))])}"
                        f" {cum}{self._exemplar_suffix(ex, i)}")
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.label_names, key, [('le', '+Inf')])}"
                    f" {v[-1]}"
                    f"{self._exemplar_suffix(ex, len(self.buckets))}")
                lines.append(f"{self.name}_sum"
                             f"{_fmt_labels(self.label_names, key)}"
                             f" {_fmt_value(v[-2])}")
                lines.append(f"{self.name}_count"
                             f"{_fmt_labels(self.label_names, key)} {v[-1]}")
            else:
                lines.append(f"{sample_name}"
                             f"{_fmt_labels(self.label_names, key)}"
                             f" {_fmt_value(v)}")
        return lines


class MetricsRegistry:
    """The process's metric surface: create/lookup metric families, run
    refresh probes, render Prometheus text, host health providers."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._probes: List[Callable[[], None]] = []
        self._probe_strikes: Dict[int, int] = {}
        self._health: Dict[str, Callable[[], Tuple[bool, dict]]] = {}
        self._lock = threading.Lock()
        self._snap_thread: Optional[threading.Thread] = None
        self._snap_stop = threading.Event()
        self.snapshots_taken = 0

    # ---- metric families ----
    def _family(self, name: str, kind: str, help_text: str,
                labels: Sequence[str], buckets: Sequence[float] = ()
                ) -> _Metric:
        key = sanitize_name(name)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = _Metric(
                    name, kind, help_text, labels, threading.Lock(),
                    buckets)
            elif m.kind != kind or m.label_names != tuple(labels):
                raise ValueError(
                    f"metric {key} re-registered as {kind}{tuple(labels)}, "
                    f"was {m.kind}{m.label_names}")
            elif (kind == "histogram"
                  and m.buckets != tuple(sorted(buckets))):
                # silently serving the first caller's edges would bucket
                # the second caller's observations against the wrong grid
                raise ValueError(
                    f"histogram {key} re-registered with buckets "
                    f"{tuple(buckets)}, was {tuple(m.buckets)}")
        return m

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> _Metric:
        return self._family(name, "counter", help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> _Metric:
        return self._family(name, "gauge", help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Metric:
        return self._family(name, "histogram", help_text, labels, buckets)

    # ---- probes: refresh-before-read adapters ----
    def register_probe(self, fn: Callable[[], None]) -> None:
        """``fn()`` runs before every render/snapshot to refresh gauges
        from a live source object.  A probe that raises is warned about
        and KEPT — probes read live objects without their writers' locks,
        so a scrape racing a hot-path mutation (deque append mid-read) is
        expected noise; only three CONSECUTIVE failures drop a probe,
        so a genuinely broken one cannot take the endpoint down but a
        single benign race never silently freezes the gauges forever."""
        with self._lock:
            self._probes.append(fn)

    _PROBE_MAX_STRIKES = 3

    def run_probes(self) -> None:
        import warnings
        with self._lock:
            probes = list(self._probes)
        dead = []
        for fn in probes:
            try:
                fn()
                with self._lock:
                    self._probe_strikes.pop(id(fn), None)
            except Exception as exc:
                with self._lock:
                    n = self._probe_strikes.get(id(fn), 0) + 1
                    self._probe_strikes[id(fn)] = n
                if n >= self._PROBE_MAX_STRIKES:
                    dead.append(fn)
                    warnings.warn(
                        f"telemetry: metrics probe {fn!r} failed "
                        f"{n} times in a row ({type(exc).__name__}: "
                        f"{exc}); dropping it", RuntimeWarning)
                else:
                    warnings.warn(
                        f"telemetry: metrics probe {fn!r} failed "
                        f"({type(exc).__name__}: {exc}); keeping it "
                        f"({n}/{self._PROBE_MAX_STRIKES} strikes)",
                        RuntimeWarning)
        if dead:
            with self._lock:
                self._probes = [p for p in self._probes if p not in dead]
                for fn in dead:
                    self._probe_strikes.pop(id(fn), None)

    def unregister_probe(self, fn: Callable[[], None]) -> None:
        """Remove a probe registered with :meth:`register_probe` — the
        unbind half a torn-down service needs so a dead object is not
        probed (and pinned in memory) for the process lifetime."""
        with self._lock:
            self._probes = [p for p in self._probes if p is not fn]
            self._probe_strikes.pop(id(fn), None)

    # ---- the three pre-existing channels ----
    def attach_counters(self, counters,
                        metric: str = "avenir_job_counter") -> None:
        """Export every (group, name) of a ``core.metrics.Counters`` as
        one labeled gauge family — the Hadoop dump, scrapeable live."""
        g = self.gauge(metric, "job counters (core.metrics.Counters)",
                       labels=("group", "name"))

        def probe():
            for grp, names in counters.as_dict().items():
                for n, v in names.items():
                    g.set(v, group=grp, name=n)
        self.register_probe(probe)

    def attach_ledger(self, ledger) -> None:
        """Gauges over a ``TransferLedger`` snapshot (h2d/d2h bytes,
        transfers, dispatches, collectives) — live link traffic."""
        g = self.gauge("avenir_transfer", "measured link traffic "
                       "(utils.tracing.TransferLedger)", labels=("key",))

        def probe():
            for k, v in ledger.snapshot().items():
                g.set(v, key=k)
        self.register_probe(probe)

    def attach_timer(self, timer, metric: str = "avenir_step") -> None:
        """Gauges over a ``StepTimer``: total seconds + calls per step,
        and p50/p95/p99 milliseconds for steps with a sample window."""
        gs = self.gauge(f"{metric}_seconds_total",
                        "per-step wall time (utils.tracing.StepTimer)",
                        labels=("step",))
        gc = self.gauge(f"{metric}_calls_total", "per-step call count",
                        labels=("step",))
        gp = self.gauge(f"{metric}_latency_ms", "per-step latency "
                        "percentiles", labels=("step", "quantile"))

        def probe():
            for name, total in list(timer.totals.items()):
                gs.set(total, step=name)
                gc.set(timer.calls.get(name, 0), step=name)
                if timer.samples.get(name):
                    for q in (50, 95, 99):
                        gp.set(timer.percentile_ms(name, q), step=name,
                               quantile=f"p{q}")
        self.register_probe(probe)

    # ---- health providers (consumed by server.MetricsServer) ----
    def add_health(self, name: str,
                   fn: Callable[[], Tuple[bool, dict]]) -> None:
        """Register a health source: ``fn() -> (ok, payload)``.  The
        ``/healthz`` endpoint is OK only when every provider is."""
        with self._lock:
            self._health[name] = fn

    def has_health(self, name: str) -> bool:
        """Whether a health provider is registered under ``name`` —
        lets a binder pick a non-colliding identity instead of silently
        overwriting another source's provider."""
        with self._lock:
            return name in self._health

    def remove_health(self, name: str) -> None:
        with self._lock:
            self._health.pop(name, None)

    def health_one(self, name: str) -> Optional[Tuple[bool, dict]]:
        """Run ONE health provider — looked up by its exact key, by the
        key minus a ``<kind>:`` prefix (so ``/healthz/churn-w0`` reaches
        the provider registered as ``serving:churn-w0``), or by the
        LAST ``:`` segment (so the same probe reaches a host-qualified
        ``serving:<host>:churn-w0``; with several hosts sharing one
        registry, disambiguate with ``/healthz/<host>:churn-w0`` — the
        prefix-stripped match).  First match wins.  None when no
        provider matches: the per-worker probe a load balancer points at
        one fleet member, where the aggregate :meth:`health` would flip
        every worker's target on one degraded peer."""
        with self._lock:
            fn = self._health.get(name)
            if fn is None:
                for key, cand in self._health.items():
                    if key.split(":", 1)[-1] == name \
                            or key.rsplit(":", 1)[-1] == name:
                        fn = cand
                        break
        if fn is None:
            return None
        try:
            ok, payload = fn()
        except Exception as exc:
            ok, payload = False, {"error": f"{type(exc).__name__}: {exc}"}
        return bool(ok), {"status": "ok" if ok else "degraded", **payload}

    def health(self) -> Tuple[bool, dict]:
        with self._lock:
            providers = dict(self._health)
        ok = True
        checks = {}
        for name, fn in providers.items():
            try:
                c_ok, payload = fn()
            except Exception as exc:
                c_ok, payload = False, {"error": f"{type(exc).__name__}: "
                                                 f"{exc}"}
            ok = ok and bool(c_ok)
            checks[name] = {"ok": bool(c_ok), **payload}
        return ok, {"status": "ok" if ok else "degraded",
                    "checks": checks}

    # ---- exposition ----
    def render(self) -> str:
        """Prometheus text format 0.0.4 of every family, probes run
        first so attached sources are fresh at scrape time.  NO
        exemplars — the classic parser rejects them; scrapers that want
        them negotiate :meth:`render_openmetrics` via Accept."""
        self.run_probes()
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """The OpenMetrics exposition: same families, ``_bucket`` lines
        carrying their exemplar tails, counters suffixed ``_total``,
        ``# EOF`` terminated — what a scraper sending ``Accept:
        application/openmetrics-text`` gets, and the ONLY text form
        exemplars legally ride."""
        self.run_probes()
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render(openmetrics=True))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def sample(self) -> Dict[str, object]:
        """One probe-refreshed flat sample: {metric{labels}: value} plus
        a unix timestamp — the snapshot thread's JSONL record."""
        self.run_probes()
        out: Dict[str, object] = {"ts": time.time()}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                items = sorted(m.values.items())
            for key, v in items:
                label = _fmt_labels(m.label_names, key)
                if m.kind == "histogram":
                    out[f"{m.name}{label}.count"] = v[-1]
                    out[f"{m.name}{label}.sum"] = v[-2]
                else:
                    out[f"{m.name}{label}"] = v
        return out

    def exemplars_json(self) -> Dict[str, List[dict]]:
        """The ``/metrics``-adjacent JSON view of every histogram
        exemplar: ``{metric: [{labels, le, trace_id, value, unix_ts}]}``
        — what ``tracetool`` and dashboards resolve a p99 bucket's
        request id from without parsing the text exposition."""
        out: Dict[str, List[dict]] = {}
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        for m in metrics:
            if m.kind != "histogram":
                continue
            with m._lock:
                ex = {k: dict(v) for k, v in m.exemplars.items()}
            rows: List[dict] = []
            for key, by_bucket in sorted(ex.items()):
                labels = dict(zip(m.label_names, key))
                for i, (rid, val, ts) in sorted(by_bucket.items()):
                    le = "+Inf" if i >= len(m.buckets) \
                        else _fmt_value(m.buckets[i])
                    rows.append({"labels": labels, "le": le,
                                 "trace_id": rid, "value": val,
                                 "unix_ts": ts})
            if rows:
                out[m.name] = rows
        return out

    # ---- background snapshot thread ----
    def start_snapshots(self, interval_s: float = 5.0,
                        snapshot_path: Optional[str] = None
                        ) -> "MetricsRegistry":
        """Refresh the probes every ``interval_s`` on a daemon thread,
        appending one JSON sample line per tick to ``snapshot_path``
        when given — gauges stay fresh even with nobody scraping, and
        the JSONL is the post-mortem flight recorder."""
        if self._snap_thread is not None:
            return self
        self._snap_stop.clear()
        if snapshot_path:
            # one run, one recorder: truncate up front (same semantics as
            # the counters.json sibling) so a rerun with the same output
            # path never interleaves two runs' samples in one file
            try:
                open(snapshot_path, "w").close()
            except OSError:
                snapshot_path = None

        def loop():
            while not self._snap_stop.wait(interval_s):
                try:
                    rec = self.sample()
                    self.snapshots_taken += 1
                    if snapshot_path:
                        with open(snapshot_path, "a") as fh:
                            fh.write(json.dumps(
                                rec, separators=(",", ":"),
                                sort_keys=True) + "\n")
                except Exception:
                    # the flight recorder must never take the job down
                    pass

        self._snap_thread = threading.Thread(
            target=loop, daemon=True, name="avenir-metrics-snapshot")
        self._snap_thread.start()
        return self

    def stop_snapshots(self) -> None:
        if self._snap_thread is None:
            return
        self._snap_stop.set()
        self._snap_thread.join(timeout=5.0)
        self._snap_thread = None


# --------------------------------------------------------------------------
# the process-default registry (what serving binds to when cli.run opened
# a metrics endpoint for the job)
# --------------------------------------------------------------------------

_default: Optional[MetricsRegistry] = None


def set_default_registry(reg: Optional[MetricsRegistry]
                         ) -> Optional[MetricsRegistry]:
    global _default
    _default = reg
    return reg


def get_default_registry() -> Optional[MetricsRegistry]:
    return _default
