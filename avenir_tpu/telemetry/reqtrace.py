"""Per-request distributed trace context for the serving path (ISSUE 15).

The serving wire protocol grows ONE optional, backward-compatible field:

    predict,<id>,t=<enqueue_us>:<sampled>,<field0>,<field1>,...

Absent field = old behavior, byte for byte.  The field is stamped
head-based on the CLIENT (``RespClient``/``ShardedRespClient`` at push
time): ``set_sample_rate(N)`` — the ``ps.trace.sample`` config key, env
twin ``AVENIR_TPU_TRACE_SAMPLE`` — samples every Nth predict message, so
with sampling off (the default 0) the whole module is one global read
per push batch and the wire bytes are unchanged.  Consumers (the fleet
drain, ``RespPredictionLoop``, ``PredictionService.process_batch``)
parse the field whether or not THEIR process samples: tracing is decided
at the head, everyone downstream just carries the context.

A sampled request travels as a :class:`RequestTrace` and leaves:

  * one Chrome legacy **flow** per hop — all legs named ``request``
    (catapult binds flow arrows on the cat+name+id triplet, so the hop
    label rides in ``args.step``): ``s`` at client enqueue (with the
    owning broker shard), ``t`` at worker pop and device dispatch,
    ``f`` at reply push — the one-arrow-per-request view across process
    lanes in the merged timeline;
  * **component timings** — queue_wait (enqueue->pop), coalesce
    (pop->dispatch), device (dispatch->readback), reply
    (readback->reply push) — derived purely from timestamps the loops
    already take (no new syncs), summing EXACTLY to reply-enqueue by
    construction (the e2e pin), observed into the
    ``avenir_request_component_seconds`` histogram family with the
    request id as each bucket's exemplar.

Timestamps are epoch microseconds on the installed tracer's
epoch-anchored clock (``time.time()`` when no tracer is installed), the
same clock the span events use.  The component SUM always telescopes to
reply-enqueue exactly; within it, ``coalesce``/``device``/``reply``
pair stamps one process took, while ``queue_wait`` (and therefore
``total``) bridge the client→worker clock boundary and absorb whatever
skew exists there (same-machine: ~ms) — histogram observation clamps at
zero so a skewed-negative component can never corrupt the bucket
counts.  Flow ids are namespaced ``<run_id>:<request_id>`` so two runs
(or a resumed attempt) sharing one trace dir never collide.
"""

from __future__ import annotations

import itertools
import os
import re
import time
from typing import List, Optional, Sequence, Tuple

from .trace import current_tracer, flow

TRACE_FIELD_PREFIX = "t="
SAMPLE_ENV = "AVENIR_TPU_TRACE_SAMPLE"
FLOW_NAME = "request"
FLOW_CAT = "request"
COMPONENTS = ("queue_wait", "coalesce", "device", "reply")

_sample_n = 0
# racy-by-design modulo counters: head sampling is statistical, and a
# lost increment under thread races only perturbs WHICH request is the
# Nth — never correctness.  itertools.count increments in C.
_counter = itertools.count(1)
_local_ids = itertools.count(1)


def set_sample_rate(n) -> int:
    """Sample every Nth predict push (0 = off, the default)."""
    global _sample_n
    _sample_n = max(0, int(n or 0))
    return _sample_n


def sample_rate() -> int:
    return _sample_n


def enabled() -> bool:
    return _sample_n > 0


def configure_from_env() -> int:
    """Honor the ``AVENIR_TPU_TRACE_SAMPLE`` env twin (ignored when
    unparseable — a bad env var must not abort serving)."""
    raw = os.environ.get(SAMPLE_ENV)
    if raw:
        try:
            return set_sample_rate(int(raw))
        except ValueError:
            pass
    return _sample_n


def now_us() -> float:
    """Epoch microseconds on the installed tracer's epoch-anchored
    clock, so request stamps and span events share one timeline."""
    tr = current_tracer()
    if tr is not None:
        return tr.now_us()
    return time.time() * 1e6


def flow_id_of(rid: str) -> str:
    """The namespaced flow id for a request: ``<run_id>:<rid>`` under an
    installed tracer, the bare rid otherwise.  Every process of one run
    shares the run id by contract (fleet_host ``--run-id`` /
    ``telemetry.run.id``), so all legs of one request's flow still bind
    — while a SECOND run (or a resumed attempt appending into the same
    trace dir) can never collide ids with the first.  Request ids must
    not contain ``:`` (the wire delimiter is ``,``; row indexes and
    uuids are fine); ``tracetool request`` accepts either form."""
    tr = current_tracer()
    if tr is not None:
        return f"{tr.run_id}:{rid}"
    return str(rid)


def emit_flow(phase: str, rid: str, step: str,
              ts_us: Optional[float] = None, **args) -> None:
    """THE flow-emission funnel: every request-flow leg goes through
    here so the name/cat/id-namespacing contract lives in one place.
    Returns before any id/args formatting when no tracer is installed —
    this runs per sampled request on the serving hot path."""
    if current_tracer() is None:
        return
    flow(FLOW_NAME, phase, flow_id_of(rid), cat=FLOW_CAT, ts_us=ts_us,
         step=step, **args)


class RequestTrace:
    """One sampled request's context: identity + the hop timestamps the
    serving loops fill in as it moves.  ``wire`` marks a context that
    entered over the wire (its ``f`` finish belongs to the reply PUSH,
    emitted by the fleet flush / wire loop, not the in-process reply)."""

    __slots__ = ("rid", "enqueue_us", "wire", "t_pop_us",
                 "t_dispatch_us", "t_done_us", "t_reply_us")

    def __init__(self, rid: str, enqueue_us: float, wire: bool = False):
        self.rid = str(rid)
        self.enqueue_us = float(enqueue_us)
        self.wire = wire
        self.t_pop_us: Optional[float] = None
        self.t_dispatch_us: Optional[float] = None
        self.t_done_us: Optional[float] = None
        self.t_reply_us: Optional[float] = None

    def components_ms(self) -> dict:
        """The latency decomposition.  Missing stamps degrade to the
        previous hop (a busy-rejected request never dispatched: its
        coalesce/device read 0), so the sum ALWAYS telescopes to
        ``total`` = reply - enqueue."""
        enq = self.enqueue_us
        pop = self.t_pop_us if self.t_pop_us is not None else enq
        disp = self.t_dispatch_us if self.t_dispatch_us is not None \
            else pop
        done = self.t_done_us if self.t_done_us is not None else disp
        reply = self.t_reply_us if self.t_reply_us is not None else done
        return {
            "queue_wait": (pop - enq) / 1e3,
            "coalesce": (disp - pop) / 1e3,
            "device": (done - disp) / 1e3,
            "reply": (reply - done) / 1e3,
            "total": (reply - enq) / 1e3,
        }


# --------------------------------------------------------------------------
# wire field
# --------------------------------------------------------------------------

def encode_field(enqueue_us: float, sampled: int = 1) -> str:
    return f"{TRACE_FIELD_PREFIX}{int(enqueue_us)}:{1 if sampled else 0}"


# the EXACT grammar the backward-compat rule promises (TPU_NOTES §27):
# strip only `t=<int>:<0|1>`.  Anything laxer would eat a legitimate
# old-format feature that merely starts with "t=" — and fabricate a
# sampled context from it with tracing off.
_FIELD_RE = re.compile(r"^t=(\d+):([01])$")


def parse_field(tok: str) -> Optional[Tuple[float, bool]]:
    """``(enqueue_us, sampled)`` for a trace-field token, None when the
    token is not one (it is then an ordinary feature value — the
    backward-compatibility rule: only ``t=<int>:<0|1>`` parses)."""
    m = _FIELD_RE.match(tok)
    if m is None:
        return None
    return float(m.group(1)), m.group(2) == "1"


DEADLINE_FIELD_PREFIX = "d="

# same backward-compat rule as the trace field (TPU_NOTES §27/§29):
# only `d=<int>` is a deadline; anything laxer would eat a legitimate
# feature value that merely starts with "d=".
_DEADLINE_RE = re.compile(r"^d=(\d+)$")


def encode_deadline(deadline_us: float) -> str:
    return f"{DEADLINE_FIELD_PREFIX}{int(deadline_us)}"


def parse_deadline(tok: str) -> Optional[float]:
    """Absolute epoch-microsecond deadline for a deadline-field token,
    None when the token is not one (ordinary feature value)."""
    m = _DEADLINE_RE.match(tok)
    if m is None:
        return None
    return float(m.group(1))


MODEL_FIELD_PREFIX = "m="

# same backward-compat rule again (TPU_NOTES §27/§30): only
# `m=<name>` or `m=<name>:<version>` routes, where <name> is
# [A-Za-z0-9_.-]+ (registry model names) and <version> is digits.
# Anything laxer would eat a legitimate feature value starting "m=".
_MODEL_RE = re.compile(r"^m=([A-Za-z0-9_.\-]+)(?::(\d+))?$")


def encode_model(name: str, version: Optional[int] = None) -> str:
    if version is None:
        return f"{MODEL_FIELD_PREFIX}{name}"
    return f"{MODEL_FIELD_PREFIX}{name}:{int(version)}"


def parse_model(tok: str) -> Optional[Tuple[str, Optional[int]]]:
    """``(model_name, version_or_None)`` for a model-routing token, None
    when the token is not one (ordinary feature value — only
    ``m=<name>[:<version>]`` routes)."""
    m = _MODEL_RE.match(tok)
    if m is None:
        return None
    v = m.group(2)
    return m.group(1), (int(v) if v is not None else None)


def split_predict_route(parts: Sequence[str]):
    """Consumer-side parse of an already-split predict message:
    ``(request_id, row_fields, ctx_or_None, deadline_us_or_None,
    model_tag_or_None)``.

    The optional fields ride in order after the id — ``t=...`` then
    ``d=...`` then ``m=...``, each independently absent — and each is
    recognized only when at least one token follows it (a row must
    remain).  The deadline (ISSUE 17) is absolute epoch microseconds on
    the :func:`now_us` clock: consumers answer ``<id>,late`` without a
    device dispatch once it has passed.  The model tag (ISSUE 18) is
    ``(name, version_or_None)``: a multi-model router dispatches to that
    resident model; a single-model service strips it and serves its own
    model (the tag is advisory, never a feature value)."""
    rid = parts[1]
    i = 2
    ctx = None
    deadline = None
    model_tag = None
    if len(parts) >= i + 2 and parts[i].startswith(TRACE_FIELD_PREFIX):
        parsed = parse_field(parts[i])
        if parsed is not None:
            enqueue_us, sampled = parsed
            if sampled:
                ctx = RequestTrace(rid, enqueue_us, wire=True)
            i += 1
    if len(parts) >= i + 2 and parts[i].startswith(DEADLINE_FIELD_PREFIX):
        d = parse_deadline(parts[i])
        if d is not None:
            deadline = d
            i += 1
    if len(parts) >= i + 2 and parts[i].startswith(MODEL_FIELD_PREFIX):
        mt = parse_model(parts[i])
        if mt is not None:
            model_tag = mt
            i += 1
    return rid, list(parts[i:]), ctx, deadline, model_tag


def split_predict_deadline(parts: Sequence[str]):
    """Consumer-side parse of an already-split predict message:
    ``(request_id, row_fields, ctx_or_None, deadline_us_or_None)``.
    A model-routing field is stripped too (multi-model consumers use
    :func:`split_predict_route`)."""
    rid, row, ctx, deadline, _ = split_predict_route(parts)
    return rid, row, ctx, deadline


def split_predict(parts: Sequence[str]):
    """Consumer-side parse of an already-split predict message:
    ``(request_id, row_fields, ctx_or_None)``.  The trace field — when
    present and parseable — is stripped from the row whether or not it
    is sampled; unsampled or absent yields ctx None.  A deadline field
    is stripped too (callers that enforce deadlines use
    :func:`split_predict_deadline`)."""
    rid, row, ctx, _ = split_predict_deadline(parts)
    return rid, row, ctx


# --------------------------------------------------------------------------
# head-based stamping (the client side)
# --------------------------------------------------------------------------

def stamp_values(values: List[str], delim: str = ",",
                 broker: Optional[str] = None) -> List[str]:
    """Stamp every Nth un-stamped predict message in a push batch with
    the trace field, emitting the flow ``s`` start (client enqueue) for
    each stamped one.  With sampling off this is ONE global read and the
    input list is returned unchanged (same object, no scan)."""
    n = _sample_n
    if n <= 0:
        return values
    pred_prefix = "predict" + delim
    out: Optional[List[str]] = None
    for i, v in enumerate(values):
        if not v.startswith(pred_prefix):
            continue
        parts = v.split(delim, 2)
        if len(parts) < 3:
            continue
        if parse_field(parts[2].split(delim, 1)[0]) is not None:
            continue   # already stamped upstream (e.g. the shard ring)
        if next(_counter) % n:
            continue
        t = now_us()
        rid = parts[1]
        if out is None:
            out = list(values)
        out[i] = delim.join((parts[0], rid, encode_field(t), parts[2]))
        emit_flow("s", rid, "enqueue", ts_us=t, broker=broker)
    return out if out is not None else values


def stamp_deadline(values: List[str], ttl_ms: float,
                   delim: str = ",") -> List[str]:
    """Stamp every un-stamped request message in a push batch with an
    absolute deadline ``ttl_ms`` from now (the ``ps.request.ttl.ms``
    producer knob).  Rides AFTER a trace field when one is present;
    already-stamped messages keep their original deadline (a re-offer
    or re-route must not extend the budget).  ``ttl_ms <= 0`` returns
    the input unchanged (same object)."""
    if not ttl_ms or ttl_ms <= 0:
        return values
    field = encode_deadline(now_us() + float(ttl_ms) * 1e3)
    out: Optional[List[str]] = None
    for i, v in enumerate(values):
        parts = v.split(delim)
        if parts[0] not in ("predict", "predictq") or len(parts) < 3:
            continue
        j = 2
        if len(parts) > j + 1 and parse_field(parts[j]) is not None:
            j += 1
        if len(parts) > j + 1 and parse_deadline(parts[j]) is not None:
            continue
        if out is None:
            out = list(values)
        out[i] = delim.join(parts[:j] + [field] + parts[j:])
    return out if out is not None else values


def stamp_model(values: List[str], model_spec: str,
                delim: str = ",") -> List[str]:
    """Stamp every un-stamped predict message in a push batch with a
    model-routing field (``ps.client.model`` producer knob;
    ``model_spec`` is ``<name>`` or ``<name>:<version>``).  Rides AFTER
    trace and deadline fields when present; already-tagged messages keep
    their original tag (a re-offer must not re-route).  A false-y spec
    returns the input unchanged (same object)."""
    if not model_spec:
        return values
    if parse_model(MODEL_FIELD_PREFIX + str(model_spec)) is None:
        raise ValueError(f"bad model spec: {model_spec!r}")
    field = MODEL_FIELD_PREFIX + str(model_spec)
    out: Optional[List[str]] = None
    for i, v in enumerate(values):
        parts = v.split(delim)
        if parts[0] not in ("predict", "predictq") or len(parts) < 3:
            continue
        j = 2
        if len(parts) > j + 1 and parse_field(parts[j]) is not None:
            j += 1
        if len(parts) > j + 1 and parse_deadline(parts[j]) is not None:
            j += 1
        if len(parts) > j + 1 and parse_model(parts[j]) is not None:
            continue
        if out is None:
            out = list(values)
        out[i] = delim.join(parts[:j] + [field] + parts[j:])
    return out if out is not None else values


def maybe_sample_local() -> Optional[RequestTrace]:
    """Head sampling for the in-process transport (``submit()``): every
    Nth submit gets a context with a process-unique synthetic id.  One
    global read when off."""
    n = _sample_n
    if n <= 0 or next(_counter) % n:
        return None
    t = now_us()
    rid = f"inproc-{os.getpid()}-{next(_local_ids)}"
    ctx = RequestTrace(rid, t, wire=False)
    emit_flow("s", rid, "enqueue", ts_us=t, broker="inprocess")
    return ctx


configure_from_env()
