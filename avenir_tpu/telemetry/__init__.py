"""Unified runtime telemetry (TPU_NOTES §21).

Three layers, all off by default and cheap when off:

* **span tracing** (:mod:`.trace`) — a per-run :class:`Tracer` buffering
  lightweight ``span(stage, **attrs)`` events from every pipeline stage
  (CSV/colcache parse, H2D staging, per-level device compute, AllReducer
  waits, checkpoint writes, serving assemble/predict/reply) into a
  per-process JSONL trace file whose lines ARE Chrome trace events —
  one lane per thread, merged across shards by ``tools/tracetool.py``
  into a catapult JSON timeline.  With no tracer installed, ``span()``
  returns a shared null context manager: one global read per call site.

* **metrics** (:mod:`.metrics`) — a :class:`MetricsRegistry` unifying
  the Counters/TransferLedger/StepTimer exports behind one
  counters/gauges/histograms API with probe-driven refresh, a background
  snapshot thread, and Prometheus text exposition.

* **serving endpoint** (:mod:`.server`) — :class:`MetricsServer`, a
  stdlib ``http.server`` daemon thread exposing ``/metrics`` (Prometheus
  text) and ``/healthz`` (aggregate of the registry's health providers,
  503 when any is degraded) so a load balancer can see a degraded
  worker.

Per-REQUEST distributed tracing (:mod:`.reqtrace`, TPU_NOTES §27) rides
the span layer: head-sampled requests carry a wire trace field end to
end, leave Chrome flow events (``flow()``) across process lanes, and
land component-timing histograms with request-id exemplars in the
metrics registry — off by default, one global read when off.

Collective stall detection lives with the transports
(``parallel.collectives.AllReducer``): a heartbeat deadline emits a
structured ``allreduce.stall`` instant event (through :func:`instant`)
naming the missing shard(s) long before the hard timeout.
"""

from .trace import (NULL_SPAN, Tracer, current_tracer, flow,
                    install_tracer, instant, merge_trace_files, span,
                    uninstall_tracer, validate_trace_events)

# metrics/server are LAZY (PEP 562): every hot module (table, tree,
# forest, colcache, collectives) imports span()/instant() from here for
# the off-by-default no-op path, and must not drag http.server /
# socketserver / the registry machinery into every process start
_LAZY = {
    "MetricsRegistry": ".metrics",
    "get_default_registry": ".metrics",
    "set_default_registry": ".metrics",
    "MetricsServer": ".server",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module
    return getattr(import_module(mod, __name__), name)


__all__ = [
    "Tracer", "span", "instant", "flow", "install_tracer",
    "uninstall_tracer", "current_tracer", "NULL_SPAN",
    "validate_trace_events", "merge_trace_files", "MetricsRegistry",
    "set_default_registry", "get_default_registry", "MetricsServer",
]
