"""Simulated annealing: vmapped Metropolis chains under one jitted scan.

Parity target: spark/.../optimize/SimulatedAnnealing.scala:96-255
(SURVEY.md §3.3).  The reference runs numOptimizers independent annealing
chains via mapPartitions; here every chain is a row of a batched state and
the whole run is ONE ``lax.scan`` over iterations with all chains advancing
per step (vmapped Metropolis), sharded over the mesh via the chain-fanout
idiom.  Semantics preserved:

  * accept better always; accept worse with prob exp((cur-next)/temp)
    (:139-170);
  * temperature updated every temp.update.interval iterations, geometric
    temp *= rate, or the reference's linear form temp -= initial - i*rate
    clamped at 0 (:172-184);
  * accumulators better/best/worse/accepted + cost-increase sum (:88-92);
  * optional greedy local-descent pass (:197-232);
  * estimated initial temperature diagnostic = mean cost increase of worse
    moves (:244-249).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .domain import SearchDomain, StepSize, cached_jit_run
from ..parallel.mesh import MeshContext, runtime_context


# chain-summed run counters (the reference's Spark accumulators); the SA
# job's empty-slice branch must emit the SAME key set for the cross-process
# counter reduce, so the single source of truth lives here
COUNTER_KEYS = ("betterSolnCount", "bestSolnCount", "worseSolnCount",
                "worseSolnAcceptCount", "costIncreaseAcum")


@dataclass
class AnnealingParams:
    """The simulatedAnnealing block knobs (resource/opt.conf)."""
    max_num_iterations: int = 300
    num_optimizers: int = 8
    initial_temp: float = 30.0
    cooling_rate: float = 0.99
    cooling_rate_geometric: bool = True
    temp_update_interval: int = 2
    max_step_size: int = 1
    # neighborhood step-size strategy (optimize/StepSize.java:28-101):
    # constant | uniform | gaussian — how many components one move replaces
    step_size_strategy: str = "constant"
    step_size_mean: float = 1.0
    step_size_std_dev: float = 1.0
    locally_optimize: bool = False
    max_num_local_iterations: int = 50
    seed: int = 0


@dataclass
class AnnealingResult:
    best_solutions: np.ndarray        # (chains, L)
    best_costs: np.ndarray            # (chains,)
    counters: Dict[str, float]
    estimated_initial_temp: float


def simulated_annealing(domain: SearchDomain, params: AnnealingParams,
                        ctx: Optional[MeshContext] = None,
                        start_solutions: Optional[np.ndarray] = None
                        ) -> AnnealingResult:
    ctx = ctx or runtime_context()
    rng = np.random.default_rng(params.seed)
    k = params.num_optimizers
    cur = start_solutions if start_solutions is not None else \
        domain.initial_solutions(rng, k)
    cur = jnp.asarray(cur, dtype=jnp.int32)
    # chain-fanout idiom: independent chains are rows, data-parallel over the
    # mesh (the reference's mapPartitions axis); GSPMD carries the sharding
    # through the scan
    if cur.shape[0] % ctx.n_devices == 0:
        cur = ctx.shard_rows(cur)
    key = jax.random.PRNGKey(params.seed)
    step_size = StepSize(max_step_size=params.max_step_size,
                         strategy=params.step_size_strategy,
                         mean=params.step_size_mean,
                         std_dev=params.step_size_std_dev)

    cur_cost = domain.cost_batch(cur)

    def step(carry, i):
        (cur, cur_cost, best, best_cost, temp, upd_counter, key,
         n_better, n_best, n_worse, n_accept, cost_inc) = carry
        # the constant (default) strategy draws no step key, so its RNG
        # stream — and the golden SA fixture — is unchanged by the
        # StepSize feature
        if step_size.strategy != "constant":
            key, k_mut, k_step, k_acc = jax.random.split(key, 4)
            steps = step_size.sample(k_step, cur.shape[0])
        else:
            key, k_mut, k_acc = jax.random.split(key, 3)
            steps = None
        nxt = domain.mutate(k_mut, cur, params.max_step_size,
                            step_sizes=steps)
        nxt_cost = domain.cost_batch(nxt)

        better = nxt_cost < cur_cost
        is_best = nxt_cost < best_cost
        u = jax.random.uniform(k_acc, cur_cost.shape)
        accept_worse = (~better) & (jnp.exp((cur_cost - nxt_cost) / temp) > u)
        take = better | accept_worse

        new_cur = jnp.where(take[:, None], nxt, cur)
        new_cur_cost = jnp.where(take, nxt_cost, cur_cost)
        new_best = jnp.where(is_best[:, None], nxt, best)
        new_best_cost = jnp.where(is_best, nxt_cost, best_cost)

        n_better += better.sum()
        n_best += is_best.sum()
        n_worse += (~better).sum()
        n_accept += accept_worse.sum()
        cost_inc += jnp.where(~better, nxt_cost - cur_cost, 0.0).sum()

        upd_counter = upd_counter + 1
        do_update = upd_counter == params.temp_update_interval
        if params.cooling_rate_geometric:
            new_temp = jnp.where(do_update, temp * params.cooling_rate, temp)
        else:
            # reference linear form (:176-181), clamped at zero
            new_temp = jnp.where(
                do_update,
                jnp.maximum(temp - (params.initial_temp -
                                    (i + 1.0) * params.cooling_rate), 0.0),
                temp)
        upd_counter = jnp.where(do_update, 0, upd_counter)

        return (new_cur, new_cur_cost, new_best, new_best_cost, new_temp,
                upd_counter, key, n_better, n_best, n_worse, n_accept,
                cost_inc), None

    init = (cur, cur_cost, cur, cur_cost,
            jnp.asarray(params.initial_temp, dtype=jnp.float32),
            jnp.asarray(0, dtype=jnp.int32), key,
            jnp.asarray(0, dtype=jnp.int32), jnp.asarray(0, dtype=jnp.int32),
            jnp.asarray(0, dtype=jnp.int32), jnp.asarray(0, dtype=jnp.int32),
            jnp.asarray(0.0, dtype=jnp.float32))

    def build_run():
        def run(init):
            carry, _ = jax.lax.scan(step, init,
                                    jnp.arange(params.max_num_iterations,
                                               dtype=jnp.float32))
            return carry
        return run

    from dataclasses import astuple
    run = cached_jit_run(domain, "_sa_run", astuple(params), build_run)
    carry = run(init)
    (_, _, best, best_cost, _, _, key,
     n_better, n_best, n_worse, n_accept, cost_inc) = carry

    if params.locally_optimize:
        best, best_cost = local_descent(domain, best, best_cost,
                                        params.max_num_local_iterations, key)

    n_worse_v = float(n_worse)
    counters = dict(zip(COUNTER_KEYS,
                        (float(n_better), float(n_best), n_worse_v,
                         float(n_accept), float(cost_inc))))
    est_temp = float(cost_inc) / n_worse_v if n_worse_v > 0 else 0.0
    return AnnealingResult(best_solutions=np.asarray(best),
                           best_costs=np.asarray(best_cost),
                           counters=counters,
                           estimated_initial_temp=est_temp)


def local_descent(domain: SearchDomain, solutions, costs,
                  iterations: int, key):
    """Greedy pass: accept only improvements (the optional second
    mapPartitions of the reference, :197-232)."""

    def step(carry, _):
        cur, cur_cost, key = carry
        key, k_mut = jax.random.split(key)
        nxt = domain.mutate(k_mut, cur, 1)
        nxt_cost = domain.cost_batch(nxt)
        better = nxt_cost < cur_cost
        return (jnp.where(better[:, None], nxt, cur),
                jnp.where(better, nxt_cost, cur_cost), key), None

    def build_run():
        def run(solutions, costs, key):
            carry, _ = jax.lax.scan(step, (solutions, costs, key), None,
                                    length=iterations)
            return carry[0], carry[1]
        return run

    run = cached_jit_run(domain, "_descent_run", iterations, build_run)
    out, out_cost = run(solutions, costs, key)
    return out, out_cost
