"""TaskSchedule example domain: project assignment with travel costs.

Parity target: examples/TaskScheduleSearch.java (+ TaskSchedule/Task/
Employee/Location beans) configured by resource/taskSched.json — the
reference domain for the SA/GA optimizers (SURVEY.md §2.7).

Cost of assigning employee e to task t (TaskScheduleSearch.calculateCost
:182-237) = average of four costScale-normalized parts:
  * travel: haversine miles between task and employee home locations;
    < airTravelDistThreshold -> 2*dist*perMileDriveCost, else the quadratic
    air-fare estimator; normalized by maxTravelCost;
  * per-diem: task location per-diem rate / maxPerDiemRate;
  * hotel: task location hotel rate / maxHotelRate;
  * skill match: unmatched required skills fraction.
Validity (isValid :267-287): tasks assigned to the same employee must be
at least minDaysGap days apart; invalid solutions cost
inavlidSolutionCost (reference's key spelling preserved).

TPU design: the whole cost function collapses to a precomputed
(tasks, employees) matrix + a task-pair conflict matrix, so a batch of
solutions evaluates as one gather + reduction (MatrixCostDomain).
"""

from __future__ import annotations

import json
import math
import re
from datetime import datetime
from typing import Dict

import numpy as np


from .domain import MatrixCostDomain

EARTH_RADIUS_MILES = 3958.75


def geo_distance(lat1, lon1, lat2, lon2) -> float:
    """Haversine distance in miles (chombo BasicUtils.getGeoDistance)."""
    la1, lo1, la2, lo2 = map(math.radians, (lat1, lon1, lat2, lon2))
    a = math.sin((la2 - la1) / 2) ** 2 + \
        math.cos(la1) * math.cos(la2) * math.sin((lo2 - lo1) / 2) ** 2
    return 2 * EARTH_RADIUS_MILES * math.asin(min(1.0, math.sqrt(a)))


def _lenient_json(text: str):
    """Jackson-lenient parse: the reference's taskSched.json has trailing
    commas that strict json rejects."""
    cleaned = re.sub(r",\s*([}\]])", r"\1", text)
    return json.loads(cleaned)


class TaskScheduleDomain(MatrixCostDomain):
    """positions = tasks, choices = employees."""

    def __init__(self, config: Dict):
        self.config = config
        locations = {l["id"]: l for l in config["locations"]}
        tasks = config["tasks"]
        employees = config["employees"]
        self.task_ids = [t["id"] for t in tasks]
        self.employee_ids = [e["id"] for e in employees]
        from ..utils.timefmt import java_time_format
        py_fmt = java_time_format(config.get("dateFormat", "MM-dd-yyyy"))
        scale = float(config.get("costScale", 100))
        air_thr = float(config.get("airTravelDistThreshold", 100))
        per_mile = float(config.get("perMileDriveCost", 0.56))
        air_est = config.get("airFareEstimator", [0.0, 0.0, 0.0])
        max_travel = float(config.get("maxTravelCost", 1))
        max_per_diem = float(config.get("maxPerDiemRate", 1))
        max_hotel = float(config.get("maxHotelRate", 1))

        T, E = len(tasks), len(employees)
        cost = np.zeros((T, E))
        starts = np.zeros((T,), dtype=np.int64)
        ends = np.zeros((T,), dtype=np.int64)
        for ti, task in enumerate(tasks):
            t_loc = locations[task["location"]]
            t_gps = t_loc["gps"]
            start = datetime.strptime(task["startDate"], py_fmt)
            end = datetime.strptime(task["endDate"], py_fmt)
            starts[ti] = int(start.timestamp() * 1000)
            ends[ti] = int(end.timestamp() * 1000)
            # duration in days (reference adds 4 ms slop then divides)
            duration = max((ends[ti] - starts[ti] + 4) // 86_400_000, 1)
            per_diem = duration * t_loc.get("perDiemCost", 0)
            per_diem = per_diem / (duration * max_per_diem) * scale
            hotel = duration * t_loc.get("hotelCost", 0)
            hotel = hotel / (duration * max_hotel) * scale
            t_skills = set(task.get("skills", []))
            for ei, emp in enumerate(employees):
                e_loc = locations[emp["location"]]
                e_gps = e_loc["gps"]
                dist = geo_distance(t_gps[0], t_gps[1], e_gps[0], e_gps[1])
                if dist < air_thr:
                    travel = 2 * dist * per_mile
                else:
                    travel = air_est[0] * dist * dist + air_est[1] * dist + \
                        air_est[2]
                travel = travel / max_travel * scale
                matched = len(t_skills & set(emp.get("skills", [])))
                skill = (len(t_skills) - matched) * scale / max(len(t_skills), 1)
                cost[ti, ei] = (travel + per_diem + hotel + skill) / 4.0

        # conflict matrix: pairs of tasks too close together in time cannot
        # share an employee (isValid's minDaysGap check)
        min_gap_ms = config.get("minDaysGap", 0) * 86_400_000 - 4
        conflict = np.zeros((T, T))
        for i in range(T):
            for j in range(i + 1, T):
                gap = max(starts[j] - ends[i], starts[i] - ends[j])
                if gap < min_gap_ms:
                    conflict[i, j] = conflict[j, i] = 1.0
        # missing key must not make invalid solutions the optimum; a large
        # FINITE penalty keeps Metropolis deltas and counter sums arithmetic-
        # safe (inf would propagate into cost accumulators and overflow int())
        invalid_cost = float(config.get("inavlidSolutionCost", 1e9))

        super().__init__(cost_matrix=cost, conflict=conflict,
                         conflict_penalty=invalid_cost, average=True)

    @classmethod
    def load(cls, path: str) -> "TaskScheduleDomain":
        with open(path) as fh:
            return cls(_lenient_json(fh.read()))

    # reference component format: 'taskId:employeeId'
    def component_str(self, position: int, choice: int) -> str:
        return f"{self.task_ids[position]}:{self.employee_ids[choice]}"

    def parse_component(self, comp: str):
        t, e = comp.split(":")
        return self.task_ids.index(t), self.employee_ids.index(e)
