"""Genetic algorithm: vmapped generations over island populations.

Parity target: spark/.../optimize/GeneticAlgorithm.scala:69-176 — per
partition, a population evolves by binary tournament selection, single-point
crossover with probability, and mutation with probability.  Here each island
is a slice of a batched (islands * pop, L) matrix; one jitted scan runs all
generations for all islands at once (the mapPartitions fan-out as an array
axis)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .domain import SearchDomain, cached_jit_run, set_components
from ..parallel.mesh import MeshContext, runtime_context


@dataclass
class GeneticParams:
    num_generations: int = 100
    population_size: int = 32
    num_islands: int = 4
    crossover_prob: float = 0.8
    mutation_prob: float = 0.2
    seed: int = 0


@dataclass
class GeneticResult:
    best_solution: np.ndarray
    best_cost: float
    island_best: np.ndarray           # (islands, L)
    island_best_costs: np.ndarray     # (islands,)


def genetic_algorithm(domain: SearchDomain, params: GeneticParams,
                      ctx: Optional[MeshContext] = None) -> GeneticResult:
    ctx = ctx or runtime_context()
    rng = np.random.default_rng(params.seed)
    I, P = params.num_islands, params.population_size
    pop = domain.initial_solutions(rng, I * P).reshape(I, P, -1)
    pop = jnp.asarray(pop, dtype=jnp.int32)
    # islands are independent (mapPartitions axis): shard island dim over mesh
    if I % ctx.n_devices == 0:
        pop = ctx.shard_rows(pop)
    key = jax.random.PRNGKey(params.seed)
    L = domain.n_components

    def island_generation(key, pop, costs):
        """One generation for one island (P, L)."""
        (k_t1, k_t2, k_cx, k_cxp, k_mut, k_mutv,
         k_mutp) = jax.random.split(key, 7)
        # binary tournament per offspring slot (SolutionPopulation.java:117)
        a = jax.random.randint(k_t1, (P, 2), 0, P)
        b = jax.random.randint(k_t2, (P, 2), 0, P)
        pa = jnp.where((costs[a[:, 0]] < costs[a[:, 1]])[:, None],
                       pop[a[:, 0]], pop[a[:, 1]])
        pb = jnp.where((costs[b[:, 0]] < costs[b[:, 1]])[:, None],
                       pop[b[:, 0]], pop[b[:, 1]])
        # crossover with probability
        point = jax.random.randint(k_cx, (P, 1), 1, L)
        crossed = jnp.where(jnp.arange(L)[None, :] < point, pa, pb)
        do_cx = jax.random.uniform(k_cxp, (P, 1)) < params.crossover_prob
        child = jnp.where(do_cx, crossed, pa)
        # mutation with probability (independent keys: position and value
        # must not be correlated)
        mpos = jax.random.randint(k_mut, (P,), 0, L)
        mval = jax.random.randint(k_mutv, (P,), 0, domain.n_choices)
        mutated = set_components(child, mpos, mval)
        do_mut = jax.random.uniform(k_mutp, (P, 1)) < params.mutation_prob
        return jnp.where(do_mut, mutated, child)

    def step(carry, _):
        pop, key = carry
        key, *iskeys = jax.random.split(key, I + 1)
        costs = domain.cost_batch(pop.reshape(I * P, L)).reshape(I, P)
        new_pop = jax.vmap(island_generation)(jnp.stack(iskeys), pop, costs)
        # elitism: keep each island's best in slot 0
        best_idx = jnp.argmin(costs, axis=1)
        elite = pop[jnp.arange(I), best_idx]
        new_pop = new_pop.at[:, 0, :].set(elite)
        return (new_pop, key), None

    def build_run():
        def run(pop, key):
            (pop, _), _ = jax.lax.scan(step, (pop, key), None,
                                       length=params.num_generations)
            costs = domain.cost_batch(pop.reshape(I * P, L)).reshape(I, P)
            return pop, costs
        return run

    from dataclasses import astuple
    run = cached_jit_run(domain, "_ga_run", astuple(params), build_run)
    pop, costs = run(pop, key)
    pop = np.asarray(pop)
    costs = np.asarray(costs)
    island_best_idx = costs.argmin(axis=1)
    island_best = pop[np.arange(I), island_best_idx]
    island_best_costs = costs[np.arange(I), island_best_idx]
    gi = int(island_best_costs.argmin())
    return GeneticResult(best_solution=island_best[gi],
                         best_cost=float(island_best_costs[gi]),
                         island_best=island_best,
                         island_best_costs=island_best_costs)
