"""Search-domain protocol for stochastic optimization.

Parity target: optimize/BasicSearchDomain.java (SURVEY.md §2.7) — the
Strategy interface between optimizers and business domains.  In the
reference a solution is a delimited string of components with scalar
callbacks (cost / validity / mutation / crossover).  TPU-first redesign:

  * a solution is an int32 vector ``(n_components,)`` of choice indices;
  * a POPULATION is a matrix ``(k, n_components)`` and every callback is
    batched: ``cost_batch`` maps (k, L) -> (k,) under jit, so thousands of
    SA chains / GA members evaluate in one device pass;
  * mutation = random component resample (createNeighborhoodSolution's
    single-component replacement, BasicSearchDomain.java:175), crossover =
    single point (:328-411) — both implemented here generically as jnp ops.

String serialization round-trips the reference's component format
('taskId:employeeId' items joined by the solution delimiter) so artifacts
stay interchangeable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@dataclass
class StepSize:
    """Neighborhood step-size sampling strategies
    (optimize/StepSize.java:28-101): how many solution components one
    neighborhood move replaces.  constant -> always max; uniform ->
    U[1, max]; gaussian -> round(N(mean, std)) clipped to [1, max].

    Reference bug noted: StepSize.java:93-97 tests ``Strategy.Constant``
    twice, so its Uniform branch is dead and Gaussian falls through to 1;
    we implement the strategies the API names intend."""

    max_step_size: int = 1
    strategy: str = "constant"        # constant | uniform | gaussian
    mean: float = 1.0
    std_dev: float = 1.0

    def sample(self, key, k: int) -> jnp.ndarray:
        """(k,) int32 per-solution step sizes in [1, max_step_size]."""
        if self.strategy == "constant":
            return jnp.full((k,), self.max_step_size, dtype=jnp.int32)
        if self.strategy == "uniform":
            return jax.random.randint(key, (k,), 1, self.max_step_size + 1)
        if self.strategy == "gaussian":
            s = self.mean + self.std_dev * jax.random.normal(key, (k,))
            return jnp.clip(jnp.round(s), 1,
                            self.max_step_size).astype(jnp.int32)
        raise ValueError(f"unknown step-size strategy {self.strategy!r}")


def set_components(solutions: jnp.ndarray, pos: jnp.ndarray,
                   val: jnp.ndarray) -> jnp.ndarray:
    """solutions with solutions[i, pos[i]] = val[i], as a broadcast select —
    same values as the row scatter ``.at[arange, pos].set(val)``, without
    the TPU scatter cost (scatters lower poorly, measured in the SA scan)."""
    L = solutions.shape[1]
    return jnp.where(jnp.arange(L)[None, :] == pos[:, None],
                     val[:, None].astype(solutions.dtype), solutions)


class SearchDomain:
    """Base class: subclasses define n_components, n_choices and cost."""

    #: number of positions in a solution
    n_components: int
    #: number of choices per position (uniform alphabet)
    n_choices: int

    # ---- batched device callbacks ----
    def cost_batch(self, solutions: jnp.ndarray) -> jnp.ndarray:
        """(k, L) int32 -> (k,) float32 cost.  Must be jit-traceable."""
        raise NotImplementedError

    # ---- host helpers ----
    def initial_solutions(self, rng: np.random.Generator, k: int) -> np.ndarray:
        return rng.integers(0, self.n_choices, (k, self.n_components),
                            dtype=np.int32)

    # ---- generic neighborhood / crossover (jit-traceable) ----
    def mutate(self, key, solutions: jnp.ndarray,
               n_mutations: int = 1,
               step_sizes: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Replace random components with random choices per solution
        (createNeighborhoodSolution).  ``n_mutations`` is the static upper
        bound; ``step_sizes`` (k,) optionally varies the count per solution
        (StepSize strategies) — mutation m applies only where
        step_sizes > m."""
        k, L = solutions.shape
        out = solutions
        for m in range(n_mutations):
            key, k1, k2 = jax.random.split(key, 3)
            pos = jax.random.randint(k1, (k,), 0, L)
            val = jax.random.randint(k2, (k,), 0, self.n_choices)
            nxt = set_components(out, pos, val)
            if step_sizes is not None:
                nxt = jnp.where((step_sizes > m)[:, None], nxt, out)
            out = nxt
        return out

    def crossover(self, key, parents_a: jnp.ndarray,
                  parents_b: jnp.ndarray) -> jnp.ndarray:
        """Single-point crossover per pair (BasicSearchDomain:328-411)."""
        k, L = parents_a.shape
        point = jax.random.randint(key, (k, 1), 1, L)
        idx = jnp.arange(L)[None, :]
        return jnp.where(idx < point, parents_a, parents_b)

    # ---- serialization ----
    def component_str(self, position: int, choice: int) -> str:
        return f"{position}:{choice}"

    def parse_component(self, comp: str) -> Tuple[int, int]:
        a, b = comp.split(":")
        return int(a), int(b)

    def to_string(self, solution: np.ndarray, delim: str = ";") -> str:
        return delim.join(self.component_str(i, int(c))
                          for i, c in enumerate(solution))

    def from_string(self, text: str, delim: str = ";") -> np.ndarray:
        out = np.zeros((self.n_components,), dtype=np.int32)
        for comp in text.split(delim):
            pos, choice = self.parse_component(comp)
            out[pos] = choice
        return out


@dataclass
class MatrixCostDomain(SearchDomain):
    """Domain whose cost is sum of per-(position, choice) costs plus an
    optional pairwise penalty — covers assignment-style problems (the
    TaskSchedule example) with one masked-select lookup per evaluation."""

    cost_matrix: np.ndarray                    # (L, n_choices)
    # optional conflicts: conflict[l1, l2] == 1 means positions l1 != l2 may
    # not share a choice (e.g. overlapping tasks, same employee)
    conflict: Optional[np.ndarray] = None
    # cost assigned to an invalid solution (the reference replaces the whole
    # solution cost with inavlidSolutionCost rather than adding a penalty)
    conflict_penalty: float = 0.0
    invalid_replaces_cost: bool = True
    average: bool = True

    def __post_init__(self):
        self.n_components, self.n_choices = self.cost_matrix.shape
        self._cm = jnp.asarray(self.cost_matrix, dtype=jnp.float32)
        self._conf = None if self.conflict is None else \
            jnp.asarray(self.conflict, dtype=jnp.float32)

    def cost_batch(self, solutions: jnp.ndarray) -> jnp.ndarray:
        # masked-select lookup instead of an advanced-index gather: gathers
        # lower to scalar loops on TPU (25x slower measured inside the SA
        # scan).  Semantics match the gather exactly: the clip reproduces
        # jit-gather's index clamping, and where (not multiply) keeps
        # +/-inf cost cells selectable without 0*inf NaN-poisoning every
        # entry; each (k, l) picks exactly one cm value, so trajectories
        # and golden fixtures are unchanged.
        sel = jnp.clip(solutions, 0, self.n_choices - 1)[..., None]
        choice = sel == jnp.arange(self.n_choices)          # (k, L, C) bool
        base = jnp.where(choice, self._cm[None], 0.0).sum(axis=2)  # (k, L)
        total = base.mean(axis=1) if self.average else base.sum(axis=1)
        if self._conf is not None:
            same = (solutions[:, :, None] == solutions[:, None, :])
            pen = (same * self._conf[None]).sum(axis=(1, 2))
            if self.invalid_replaces_cost:
                total = jnp.where(pen > 0, self.conflict_penalty, total)
            else:
                total = total + self.conflict_penalty * pen
        return total


def cached_jit_run(domain: SearchDomain, cache_attr: str, key, builder):
    """Per-domain memo of a jitted optimizer program.  The SA/GA run
    closures capture the domain's cost code plus Python-static knobs, so
    a fresh ``@jax.jit`` inside each call has a new identity and
    retraces/recompiles EVERY invocation (TPU_NOTES.md rule 3, the
    per-call-closure disease).  The compiled program is cached on the
    domain instance under ``cache_attr``, keyed by the static knobs;
    shape changes re-trace inside the cached jit as usual.

    Contract: a SearchDomain must be treated as IMMUTABLE after its first
    optimizer run.  The cached program captures the domain's arrays (e.g.
    MatrixCostDomain.cost_matrix) as compile-time closure constants, so
    mutating them afterwards silently leaves the cached program computing
    against the old values — build a fresh domain instead.  The cache also
    pins those captured device buffers for the domain's lifetime."""
    cached = getattr(domain, cache_attr, None)
    if cached is None or cached[0] != key:
        cached = (key, jax.jit(builder()))
        setattr(domain, cache_attr, cached)
    return cached[1]
