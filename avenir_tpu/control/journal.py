"""The controller's crash journal: one tmp-then-rename JSON state file.

The retrain controller is a state machine whose every transition must
survive a kill -9 at any instant (TPU_NOTES §26).  This journal is the
whole durability story: ONE small JSON file under the controller's state
directory, rewritten atomically (write ``controller.json.tmp.<pid>``,
``os.replace`` into place) BEFORE each stage's work starts — so a crash
mid-stage leaves a journal that names exactly the stage to re-enter —
and again when the stage's durable result lands (candidate saved,
version published, pin written).

What the journal deliberately does NOT hold: model payloads (the
candidate lives in its own tmp-then-renamed ``cycle_<n>/candidate``
directory), serving state (the registry pin file is the serving tier's
source of truth — the journal only records what the controller intended,
and resume re-derives what actually happened from the registry), or
anything a restarted controller could not safely act on.

Stage order (the five chaos-drill fault points map 1:1 onto the five
active stages)::

    idle -> retrain_build -> candidate_validate -> canary_validate
         -> registry_publish -> fleet_swap -> probation -> complete
                                           \\-> rollback -> complete

(``canary_validate`` — a policy-gated live-traffic gate (ISSUE 18):
the candidate serves a deterministic x% canary split on a models=
fleet and must match the champion's accuracy on its own outcome
series before publish; ``RetrainPolicy.canary_outcomes == 0`` records
a journaled skip and the stage is a pass-through.)

Terminal outcomes recorded at ``complete``: ``published`` (candidate
survived probation or probation disabled), ``refused`` (validation said
the candidate is worse — champion untouched), ``rolled_back`` (probation
said the candidate underperforms live — pin back to the champion),
``abandoned`` (resume found the cycle unfinishable, e.g. the candidate
payload is gone — champion untouched).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

# stages
IDLE = "idle"
RETRAIN_BUILD = "retrain_build"
CANDIDATE_VALIDATE = "candidate_validate"
CANARY_VALIDATE = "canary_validate"
REGISTRY_PUBLISH = "registry_publish"
FLEET_SWAP = "fleet_swap"
PROBATION = "probation"
ROLLBACK = "rollback"
COMPLETE = "complete"

STAGES = (IDLE, RETRAIN_BUILD, CANDIDATE_VALIDATE, CANARY_VALIDATE,
          REGISTRY_PUBLISH, FLEET_SWAP, PROBATION, ROLLBACK, COMPLETE)
# the resumable (mid-cycle) stages, in order
ACTIVE_STAGES = (RETRAIN_BUILD, CANDIDATE_VALIDATE, CANARY_VALIDATE,
                 REGISTRY_PUBLISH, FLEET_SWAP, PROBATION, ROLLBACK)

# outcomes
PUBLISHED = "published"
REFUSED = "refused"
ROLLED_BACK = "rolled_back"
ABANDONED = "abandoned"

JOURNAL_FILE = "controller.json"
FORMAT_VERSION = 1
_KEEP_HISTORY = 64


class CycleJournal:
    """Load/advance/persist the controller's one-cycle-at-a-time state."""

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.path = os.path.join(state_dir, JOURNAL_FILE)
        self._state: Dict[str, Any] = self._fresh()
        self._load()

    # ---- persistence ----
    @staticmethod
    def _fresh() -> Dict[str, Any]:
        return {
            "format_version": FORMAT_VERSION,
            "cycle": 0,
            "stage": IDLE,
            "outcome": None,
            "trigger": None,           # AlertRecord dict that opened the cycle
            "mode": None,              # incremental | full
            "champion_version": None,  # serving version at cycle start
            "champion_accuracy": None,
            "candidate_accuracy": None,
            "candidate_sha": None,     # model fingerprint, set BEFORE publish
            "candidate_version": None,  # set AFTER publish commits
            "probation": None,         # {floor, needed, seen, windows}
            "canary": None,            # {needed, percent, opened_unix, ...}
            "history": [],             # bounded completed-cycle summaries
        }

    def _load(self) -> None:
        try:
            with open(self.path) as fh:
                state = json.load(fh)
        except FileNotFoundError:
            return
        except Exception:
            # a torn journal can only be the pre-rename tmp surviving a
            # crash plus a damaged final — never written by this class;
            # treat as fresh rather than wedging the controller forever
            import warnings
            warnings.warn(
                f"controller journal {self.path!r} is unreadable; "
                f"starting from an idle state (the registry pin, not the "
                f"journal, is the serving source of truth)",
                RuntimeWarning)
            return
        if isinstance(state, dict) and state.get("stage") in STAGES:
            base = self._fresh()
            base.update(state)
            self._state = base

    def write(self) -> None:
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=2, sort_keys=True)
        os.replace(tmp, self.path)

    # ---- views ----
    def __getitem__(self, key: str) -> Any:
        return self._state[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self._state.get(key, default)

    @property
    def stage(self) -> str:
        return self._state["stage"]

    @property
    def cycle(self) -> int:
        return int(self._state["cycle"])

    @property
    def pending(self) -> bool:
        """True when a crash (or a stop) left a cycle mid-flight."""
        return self.stage in ACTIVE_STAGES

    def cycle_dir(self, cycle: Optional[int] = None) -> str:
        return os.path.join(self.state_dir,
                            f"cycle_{self.cycle if cycle is None else cycle:06d}")

    @property
    def history(self) -> List[Dict[str, Any]]:
        return list(self._state.get("history") or [])

    # ---- transitions ----
    def open_cycle(self, trigger: Optional[Dict[str, Any]], mode: str,
                   champion_version: Optional[int]) -> int:
        """Start cycle N+1 at retrain_build.  Refuses while a cycle is
        mid-flight — the controller runs ONE cycle at a time (alerts
        arriving meanwhile coalesce)."""
        if self.pending:
            raise RuntimeError(
                f"cycle {self.cycle} is still at stage {self.stage!r}; "
                f"resume or abandon it before opening a new one")
        self._state.update(
            cycle=self.cycle + 1, stage=RETRAIN_BUILD, outcome=None,
            trigger=trigger, mode=mode,
            champion_version=champion_version,
            champion_accuracy=None, candidate_accuracy=None,
            candidate_sha=None, candidate_version=None, probation=None,
            canary=None)
        self.write()
        return self.cycle

    def advance(self, stage: str, **fields: Any) -> None:
        """Record entering ``stage`` (plus any durable result fields) —
        ALWAYS before the stage's side effects, so the crash window of
        every stage re-enters that stage, never skips it."""
        if stage not in STAGES:
            raise ValueError(f"unknown stage {stage!r}")
        self._state["stage"] = stage
        self._state.update(fields)
        self.write()

    def update(self, **fields: Any) -> None:
        self._state.update(fields)
        self.write()

    def close_cycle(self, outcome: str, **fields: Any) -> None:
        """Terminal transition: record the outcome, append the bounded
        history summary, return to a resumable-idle complete state."""
        self._state.update(fields)
        self._state["stage"] = COMPLETE
        self._state["outcome"] = outcome
        summary = {k: self._state[k] for k in
                   ("cycle", "outcome", "mode", "champion_version",
                    "candidate_version", "champion_accuracy",
                    "candidate_accuracy")}
        hist = list(self._state.get("history") or [])
        hist.append(summary)
        self._state["history"] = hist[-_KEEP_HISTORY:]
        self.write()


# ---- the online supervision journal (ISSUE 19) -------------------------
#
# The online learning plane is not a cycle machine — it is ALWAYS in
# probation.  Its journal is the same tmp-then-rename single JSON file,
# but the state machine is a loop, not a ladder::
#
#     idle -> probation <-> snapshot
#                  \\-> rollback -> probation
#
# ``snapshot`` / ``rollback`` are advanced into BEFORE their side
# effects (the CycleJournal rule), so a kill at the ``online_snapshot``
# or ``online_restore`` fault point resumes knowing exactly what was in
# flight; resume itself is uniform — restore device state from the last
# pinned registry snapshot and re-enter probation — because the
# registry pin, not the journal, is the state source of truth.

ONLINE_IDLE = "idle"
ONLINE_PROBATION = "probation"
ONLINE_SNAPSHOT = "snapshot"
ONLINE_ROLLBACK = "rollback"
ONLINE_STAGES = (ONLINE_IDLE, ONLINE_PROBATION, ONLINE_SNAPSHOT,
                 ONLINE_ROLLBACK)
ONLINE_JOURNAL_FILE = "online.json"


class OnlineJournal:
    """Crash journal for the online supervisor: one small JSON file,
    rewritten atomically before every stage's side effects."""

    def __init__(self, state_dir: str):
        self.state_dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self.path = os.path.join(state_dir, ONLINE_JOURNAL_FILE)
        self._state: Dict[str, Any] = self._fresh()
        self._load()

    @staticmethod
    def _fresh() -> Dict[str, Any]:
        return {
            "format_version": FORMAT_VERSION,
            "stage": ONLINE_IDLE,
            "windows": 0,                 # windows supervised, ever
            "snapshots": 0,
            "rollbacks": 0,
            "last_snapshot_version": None,   # the rollback target
            "last_snapshot_window": None,
        }

    def _load(self) -> None:
        try:
            with open(self.path) as fh:
                state = json.load(fh)
        except FileNotFoundError:
            return
        except Exception:
            import warnings
            warnings.warn(
                f"online journal {self.path!r} is unreadable; starting "
                f"idle (the registry pin is the state source of truth)",
                RuntimeWarning)
            return
        if isinstance(state, dict) and state.get("stage") in ONLINE_STAGES:
            base = self._fresh()
            base.update(state)
            self._state = base

    def write(self) -> None:
        tmp = self.path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(self._state, fh, indent=2, sort_keys=True)
        os.replace(tmp, self.path)

    def __getitem__(self, key: str) -> Any:
        return self._state[key]

    def get(self, key: str, default: Any = None) -> Any:
        return self._state.get(key, default)

    @property
    def stage(self) -> str:
        return self._state["stage"]

    @property
    def interrupted(self) -> bool:
        """True when a crash left a snapshot or rollback in flight."""
        return self.stage in (ONLINE_SNAPSHOT, ONLINE_ROLLBACK)

    def advance(self, stage: str, **fields: Any) -> None:
        """Record entering ``stage`` (ALWAYS before side effects)."""
        if stage not in ONLINE_STAGES:
            raise ValueError(f"unknown online stage {stage!r}")
        self._state["stage"] = stage
        self._state.update(fields)
        self.write()

    def update(self, **fields: Any) -> None:
        self._state.update(fields)
        self.write()
