"""Closed-loop model lifecycle control (TPU_NOTES §26).

The control plane above monitor/ and serving/: a drift alert becomes a
retrained, validated, published, hot-swapped model — or a refused one,
or (when it underperforms live) an automatically rolled-back one.  The
controller journals every transition tmp-then-rename so a crash at any
stage resumes without double-publishing, half-swapping, or touching the
data path (serving workers never wait on the controller).

  * :mod:`.journal`    — :class:`CycleJournal`, the one-file atomic
    state machine record (stages, outcomes, bounded history);
  * :mod:`.controller` — :class:`RetrainController` (the loop),
    :class:`RetrainPolicy` (its knobs), :class:`WireFleetLink`
    (addressed-reload swap link for out-of-process fleets), the
    alerts.jsonl / RESP intake helpers, and the shared
    :func:`accuracy_pct` delayed-label scorer.

Wire a live policy with ``monitor.policy.retrain_action(controller)``;
run the batch/ops form with the ``retrainController`` CLI job.
"""

from .controller import (FULL, INCREMENTAL, RetrainController,
                         RetrainPolicy, WireFleetLink, accuracy_pct,
                         alert_from_json, alerts_from_jsonl,
                         alerts_from_resp)
from .journal import (ABANDONED, ACTIVE_STAGES, CANDIDATE_VALIDATE,
                      COMPLETE, CycleJournal, FLEET_SWAP, IDLE, PROBATION,
                      PUBLISHED, REFUSED, REGISTRY_PUBLISH, RETRAIN_BUILD,
                      ROLLBACK, ROLLED_BACK, STAGES)

__all__ = [
    "RetrainController", "RetrainPolicy", "WireFleetLink",
    "CycleJournal", "accuracy_pct", "alert_from_json",
    "alerts_from_jsonl", "alerts_from_resp", "INCREMENTAL", "FULL",
    "IDLE", "RETRAIN_BUILD", "CANDIDATE_VALIDATE", "REGISTRY_PUBLISH",
    "FLEET_SWAP", "PROBATION", "ROLLBACK", "COMPLETE", "STAGES",
    "ACTIVE_STAGES", "PUBLISHED", "REFUSED", "ROLLED_BACK", "ABANDONED",
]
