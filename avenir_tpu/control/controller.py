"""The closed loop: drift alert -> retrain -> validate -> publish -> swap
-> probation -> (rollback).

``RetrainController`` is the control-plane role the reference avenir ran
as its Storm realtime loop (PAPER.md §0), rebuilt as a crash-resumable
state machine over the pieces earlier PRs landed: ``predictDriftScore``/
``DriftPolicy`` fire debounced AlertRecords, streaming builds
checkpoint/resume bit-identically, the registry hot-swaps atomically and
the fleet converges on a generation counter.  The controller closes the
loop — and, per Execution Templates' control-plane/data-plane split
(PAPERS.md), it NEVER sits on the data path: its only side effects are
registry writes (publish, serving pin) and a reload nudge; workers keep
warm compiled state and keep answering through any controller crash.

Cycle shape (journal.py names the stages; each is a fault point)::

  alert -> retrain_build        train the candidate: incremental (resume
                                ``build_forest_from_stream`` from its own
                                checkpoint over the fresh window, served
                                through the ``.avtc`` cache) or a
                                scheduled full rebuild
        -> candidate_validate   champion-vs-candidate on a delayed-label
                                holdout via ``AccuracyTracker`` + a drift
                                re-score; worse candidate -> REFUSED,
                                champion untouched
        -> registry_publish     atomic versioned publish + baseline
                                sidecar; resume dedups by the candidate
                                sha journaled BEFORE publishing, so a
                                crash in the publish window can never
                                double-publish
        -> fleet_swap           pin the serving version + addressed
                                ``reload``; swap-ack = fleet convergence
        -> probation            watch live delayed-label accuracy; a
                                candidate underperforming the journaled
                                floor AUTO-ROLLS-BACK (pin back to the
                                champion, re-converge the fleet)
        -> complete             outcome: published | refused |
                                rolled_back | abandoned

Crash contract: every transition journals tmp-then-rename BEFORE its
side effects.  A controller killed at ANY stage resumes (or safely
abandons) from the journal: builds restart from their checkpoint,
publishes dedup by sha, pins and reloads are idempotent — and serving
never notices beyond the swap it was asked for.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from ..core.faults import fault_point
from ..core.metrics import Counters
from ..monitor.policy import (ALERT, DEFAULT_ALERT, AccuracyTracker,
                              AlertRecord, DriftPolicy)
from ..telemetry import instant, span
from .journal import (ABANDONED, CANARY_VALIDATE, CANDIDATE_VALIDATE,
                      COMPLETE, FLEET_SWAP, PROBATION, PUBLISHED, REFUSED,
                      REGISTRY_PUBLISH, RETRAIN_BUILD, ROLLBACK,
                      ROLLED_BACK, CycleJournal)

CANDIDATE_DIR = "candidate"
CANDIDATE_META = "meta.json"
INCREMENTAL = "incremental"
FULL = "full"


@dataclass
class RetrainPolicy:
    """The controller's knobs (CLI twin: the ``dtb.retrain.*`` keys).

    Validation: the candidate is REFUSED when its holdout accuracy falls
    more than ``accuracy_margin`` integer points below the champion's,
    or when its normalized drift re-score (worst statistic / its alert
    threshold, over the holdout window vs each model's own baseline) is
    worse than the champion's by more than ``drift_margin``.

    Probation: ``probation_outcomes`` delayed-label outcomes per window,
    ``probation_windows`` windows; ANY window below the journaled floor
    (champion holdout accuracy - ``probation_margin``) rolls back.
    ``probation_outcomes=0`` disables probation (complete at swap)."""
    full_rebuild_every: int = 0      # every Nth cycle rebuilds in full; 0=never
    accuracy_margin: int = 2         # integer accuracy points
    drift_margin: float = 0.25       # normalized drift-score slack
    probation_outcomes: int = 0      # outcomes per probation window
    probation_windows: int = 1
    probation_margin: int = 5        # live floor = champion acc - this
    # a probation that never receives outcomes (mis-wired delayed-label
    # lane) must not wedge the controller forever: past the timeout the
    # cycle completes as published-with-a-warning (no evidence AGAINST
    # the candidate ever arrived).  0 = wait indefinitely;
    # resolve_probation() is the operator escape either way.
    probation_timeout_s: float = 24 * 3600.0
    # canary validation (ISSUE 18): with canary_outcomes > 0 and a
    # models= fleet attached, a validated candidate serves a
    # deterministic canary_percent% live split (pre-publish, from the
    # in-memory payload) and must score within accuracy_margin of the
    # journaled champion accuracy over canary_outcomes candidate-arm
    # outcomes before the cycle publishes.  0 = journaled skip (the
    # canary_validate stage records why and passes straight through).
    canary_outcomes: int = 0
    canary_percent: int = 10
    canary_timeout_s: float = 3600.0
    swap_ack_timeout_s: float = 30.0
    cooldown_s: float = 0.0          # min seconds between cycle starts
    chunk_rows: int = 1 << 16        # streaming build block size
    checkpoint_blocks: int = 1       # checkpoint cadence (blocks)
    baseline_bins: int = 32
    cache_policy: str = "use"        # .avtc policy for retrain reads
    retire_keep_last: int = 0        # >0: registry GC after each cycle

    def __post_init__(self):
        if self.probation_outcomes < 0 or self.probation_windows < 1:
            raise ValueError("probation_outcomes must be >= 0 and "
                             "probation_windows >= 1")
        if self.canary_outcomes < 0 \
                or not 0 <= self.canary_percent <= 100:
            raise ValueError("canary_outcomes must be >= 0 and "
                             "canary_percent 0..100")
        if self.checkpoint_blocks < 1 or self.chunk_rows < 1:
            raise ValueError("chunk_rows and checkpoint_blocks must be "
                             ">= 1")


class WireFleetLink:
    """Addressed-reload swap link for OUT-of-process fleets: one
    ``reload,<host_label>`` per host (the PR 12 multi-host convergence
    protocol; a bare ``reload`` when no hosts are named) pushed onto the
    request queue.  No ack surface — the controller counts
    ``SwapAckUnavailable`` and trusts the fleets' own refresh loop."""

    def __init__(self, client, request_queue: str = "requestQueue",
                 hosts: Iterable[str] = ()):
        self.client = client
        self.request_queue = request_queue
        self.hosts = [h for h in hosts if h]

    def refresh(self) -> bool:
        msgs = [f"reload,{h}" for h in self.hosts] or ["reload"]
        for m in msgs:
            self.client.lpush(self.request_queue, m)
        return True


# --------------------------------------------------------------------------
# alert intake helpers (the RESP / alerts.jsonl stream sources)
# --------------------------------------------------------------------------

def alert_from_json(line: str) -> AlertRecord:
    return AlertRecord(**json.loads(line))


def alerts_from_jsonl(path: str) -> List[AlertRecord]:
    """Parse a ``driftMonitor``/``predictDriftScore`` alerts.jsonl file;
    malformed lines are skipped with a warning (a monitoring artifact
    must not wedge the controller)."""
    out: List[AlertRecord] = []
    try:
        with open(path) as fh:
            for ln, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(alert_from_json(line))
                except Exception as exc:
                    warnings.warn(
                        f"alerts stream {path!r} line {ln}: unparseable "
                        f"record skipped ({type(exc).__name__}: {exc})",
                        RuntimeWarning)
    except FileNotFoundError:
        pass
    return out


def alerts_from_resp(client, queue: str, max_batch: int = 256
                     ) -> List[AlertRecord]:
    """Drain whatever alert JSON lines sit on a RESP list queue right
    now (the live-monitor wire lane).  A literal 'stop' drained here is
    RE-PUSHED for whatever consumer the sentinel was aimed at (this
    reader is a tap, not the queue's owner), and the rest of the popped
    batch is still parsed — records already popped must never be
    dropped on the floor."""
    out: List[AlertRecord] = []
    msgs = client.rpop_many(queue, max_batch)
    for m in msgs:
        if m == "stop":
            try:
                client.lpush(queue, "stop")
            except Exception:
                pass
            continue
        try:
            out.append(alert_from_json(m))
        except Exception as exc:
            warnings.warn(f"alert queue {queue!r}: unparseable record "
                          f"skipped ({type(exc).__name__}: {exc})",
                          RuntimeWarning)
    return out


# --------------------------------------------------------------------------
# the controller
# --------------------------------------------------------------------------

class RetrainController:
    """One model's closed retraining loop (see module docstring).

    ``train_source``/``full_source``/``holdout_source`` are CSV paths (or
    zero-arg callables returning one): the fresh drifted window to retrain
    on, the full dataset for scheduled rebuilds (defaults to the fresh
    window), and the delayed-label holdout the validation stage scores
    champion vs candidate on (defaults to the fresh window — in
    production, point it at held-back labeled traffic).

    ``fleet`` is the swap link, duck-typed: anything with ``refresh()``
    (``ServingFleet``, ``PredictionService``, :class:`WireFleetLink`), an
    optional ``converged_version()``/``version`` ack surface.  ``None``
    means pin-only — standalone services converge at their own next
    refresh."""

    def __init__(self, registry, model_name: str, schema, *,
                 state_dir: str,
                 train_source,
                 holdout_source=None,
                 full_source=None,
                 forest_params=None,
                 fleet=None,
                 policy: Optional[RetrainPolicy] = None,
                 counters: Optional[Counters] = None,
                 delim_regex: str = ","):
        self.registry = registry
        self.model_name = model_name
        self.schema = schema
        self.policy = policy or RetrainPolicy()
        self.counters = counters if counters is not None else Counters()
        self.delim_regex = delim_regex
        self.fleet = fleet
        self._train_source = train_source
        self._holdout_source = holdout_source or train_source
        self._full_source = full_source or train_source
        if forest_params is None:
            from ..models.forest import ForestParams
            forest_params = ForestParams()
        self.forest_params = forest_params
        self.journal = CycleJournal(state_dir)
        self._lock = threading.Lock()
        # the pending-alert slot has its OWN tiny lock: submit_alert runs
        # on the monitor/serving thread and must never wait behind the
        # cycle lock (held for a whole retrain by run_pending)
        self._alert_lock = threading.Lock()
        self._pending_alert: Optional[AlertRecord] = None
        self._last_cycle_end = 0.0
        # probation outcome buffers (live delayed labels)
        self._prob_pred: List[str] = []
        self._prob_actual: List[str] = []
        # canary_validate live state: True only while THIS process has
        # the canary installed on the fleet (deliberately not journaled
        # — a restarted controller re-installs on resume; buffered
        # outcomes restart with it)
        self._canary_live = False
        self._can_pred: Dict[str, List[str]] = {}
        self._can_actual: Dict[str, List[str]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- alert intake (control plane; never blocks the caller on a
    # retrain — the serving/monitor thread hands off and returns) ----
    def submit_alert(self, rec: AlertRecord) -> bool:
        """Queue an alert for the next :meth:`run_pending`.  Only
        level=alert records trigger (warnings are counted and ignored);
        while a cycle is active or an alert is already queued, later
        alerts coalesce into one pending trigger."""
        if rec.level != ALERT:
            self.counters.increment("Controller", "AlertsIgnored")
            return False
        with self._alert_lock:
            if self._pending_alert is not None:
                self.counters.increment("Controller", "AlertsCoalesced")
                self._pending_alert = rec
                return False
            self._pending_alert = rec
            self.counters.increment("Controller", "Alerts")
        return True

    def consume(self, records: Iterable[AlertRecord]) -> int:
        """Submit a batch (the alerts.jsonl / RESP stream lane)."""
        return sum(1 for r in records if self.submit_alert(r))

    # ---- the run surface ----
    def run_pending(self) -> Optional[Dict[str, Any]]:
        """One control-loop tick: resume a mid-flight cycle if the
        journal holds one, else start a cycle for the pending alert (if
        any, and the cooldown passed).  Returns the cycle summary dict,
        a probation-waiting marker, or None when there is nothing to
        do."""
        with self._lock:
            if self.journal.pending:
                if self.journal.stage == CANARY_VALIDATE \
                        and self._canary_live:
                    # WAITING on live canary outcomes, not crashed:
                    # record_canary_outcome drives it.  Past the timeout
                    # the candidate proceeds to publish — no evidence
                    # against it ever arrived (the probation-timeout
                    # rationale, one stage earlier).
                    can = self.journal["canary"] or {}
                    opened = float(can.get("opened_unix") or 0)
                    if self.policy.canary_timeout_s > 0 and opened \
                            and time.time() - opened \
                            > self.policy.canary_timeout_s:
                        return self._resolve_canary_locked(timed_out=True)
                    return None
                if self.journal.stage == PROBATION:
                    # not a crash to resume: the cycle is WAITING on live
                    # delayed labels (record_outcome drives it); alerts
                    # arriving meanwhile stay coalesced.  A probation
                    # past its timeout resolves as kept — no evidence
                    # against the candidate ever arrived, and a wedged
                    # controller is worse than an unprobed swap.
                    prob = self.journal["probation"] or {}
                    opened = float(prob.get("opened_unix") or 0)
                    if self.policy.probation_timeout_s > 0 and opened \
                            and time.time() - opened \
                            > self.policy.probation_timeout_s:
                        return self._resolve_probation_locked(keep=True,
                                                              timed_out=True)
                    return None
                return self._resume_locked()
            with self._alert_lock:
                alert = self._pending_alert
                if alert is None:
                    return None
                if time.monotonic() - self._last_cycle_end \
                        < self.policy.cooldown_s:
                    return None
                self._pending_alert = None
            return self._run_cycle_locked(alert)

    def force_cycle(self, mode: Optional[str] = None
                    ) -> Optional[Dict[str, Any]]:
        """Operator override: run one cycle now without an alert (the
        CLI's ``dtb.retrain.trigger=force``).  A CRASHED cycle resumes
        first; a cycle WAITING in probation is left exactly in place
        (returns None, buffered outcomes preserved) — forcing must not
        reset a partially-scored probation window and buy a bad
        candidate a fresh one."""
        with self._lock:
            if self.journal.pending:
                if self.journal.stage == PROBATION or \
                        (self.journal.stage == CANARY_VALIDATE
                         and self._canary_live):
                    return None
                return self._resume_locked()
            return self._run_cycle_locked(None, mode=mode)

    # ---- background loop (the live deployment shape) ----
    def start(self, poll_s: float = 0.5) -> "RetrainController":
        if self._thread is not None:
            if self._thread.is_alive() and not self._stop.is_set():
                return self            # already running
            # a previous loop may still be finishing its cycle after a
            # timed-out stop(): wait for it BEFORE clearing the stop
            # flag, or the old loop would see the cleared flag and keep
            # ticking alongside the new one — two concurrent control
            # loops double-evaluating every resume and timeout
            self._thread.join()
            self._thread = None
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.run_pending()
                except Exception as exc:
                    # the loop must survive a failing cycle: the journal
                    # already holds the resumable state, the next tick
                    # retries — exactly the chaos-drill resume path
                    warnings.warn(
                        f"retrain controller cycle failed "
                        f"({type(exc).__name__}: {exc}); will resume",
                        RuntimeWarning)
                self._stop.wait(poll_s)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="avenir-retrain-controller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
            if t.is_alive():
                # mid-cycle: the loop exits at its next tick (the stop
                # flag is set).  Keep the handle so a later start()
                # joins it instead of racing a second loop against it.
                warnings.warn(
                    "retrain controller loop is still finishing its "
                    "cycle; it exits at the next tick (journal state is "
                    "safe to resume)", RuntimeWarning)
            else:
                self._thread = None

    # ---- cycle machinery ----
    def _decide_mode(self, next_cycle: int) -> str:
        every = self.policy.full_rebuild_every
        if every > 0 and next_cycle % every == 0:
            return FULL
        return INCREMENTAL

    def _source_path(self, source) -> str:
        return source() if callable(source) else source

    def _run_cycle_locked(self, alert: Optional[AlertRecord],
                          mode: Optional[str] = None) -> Dict[str, Any]:
        champion = self.registry.serving_version(self.model_name)
        if champion is None:
            raise FileNotFoundError(
                f"no intact versions of {self.model_name!r} in "
                f"{self.registry.base_dir!r}: the controller retrains an "
                f"existing champion, it does not bootstrap one")
        mode = mode or self._decide_mode(self.journal.cycle + 1)
        self.journal.open_cycle(
            alert.__dict__ if alert is not None else None, mode, champion)
        self.counters.increment("Controller", "Cycles")
        instant("controller.decision", cat="controller",
                action="cycle_start", cycle=self.journal.cycle, mode=mode,
                champion_version=champion,
                trigger=(alert.scope if alert is not None else "operator"))
        return self._advance(RETRAIN_BUILD, resuming=False)

    def _resume_locked(self) -> Dict[str, Any]:
        self.counters.increment("Controller", "Resumes")
        stage = self.journal.stage
        instant("controller.decision", cat="controller", action="resume",
                cycle=self.journal.cycle, stage=stage)
        return self._advance(stage, resuming=True)

    def _advance(self, stage: str, resuming: bool) -> Dict[str, Any]:
        """Run the state machine from ``stage`` to a terminal state (or
        to probation-wait).  Candidate payloads travel in-memory along
        the happy path and reload from the cycle directory on resume."""
        # every stage executes under ONE taxonomy span
        # (``controller.stage``, args naming the stage + cycle): the
        # control plane's decisions become correlatable with the
        # data-plane latencies they cause in the same merged timeline —
        # the stages already journal, so tracing is just this wrapper
        models = baseline = None
        if stage == RETRAIN_BUILD:
            with span("controller.stage", cat="controller",
                      stage=RETRAIN_BUILD, cycle=self.journal.cycle):
                models, baseline = self._stage_build(resuming)
            stage = CANDIDATE_VALIDATE
        if stage in (CANDIDATE_VALIDATE, CANARY_VALIDATE,
                     REGISTRY_PUBLISH) and models is None:
            cand = self._load_candidate()
            if cand is None:
                # resume found no usable candidate payload: published
                # already?  (publish crash after commit, candidate dir
                # lost) — else the cycle is unfinishable; abandon with
                # the champion untouched
                v = self._find_published(self.journal["candidate_sha"])
                if stage == REGISTRY_PUBLISH and v is not None:
                    self.journal.advance(FLEET_SWAP, candidate_version=v)
                    stage = FLEET_SWAP
                else:
                    return self._abandon("candidate payload missing or "
                                         "torn at resume")
            else:
                models, baseline = cand
        if stage == CANDIDATE_VALIDATE:
            with span("controller.stage", cat="controller",
                      stage=CANDIDATE_VALIDATE, cycle=self.journal.cycle):
                verdict = self._stage_validate(models, baseline)
            if verdict is not None:
                return verdict           # refused
            stage = CANARY_VALIDATE
        if stage == CANARY_VALIDATE:
            with span("controller.stage", cat="controller",
                      stage=CANARY_VALIDATE, cycle=self.journal.cycle):
                waiting = self._stage_canary(models)
            if waiting:
                # the cycle now WAITS on live canary outcomes —
                # record_canary_outcome (or the timeout) decides it
                return {"cycle": self.journal.cycle,
                        "stage": CANARY_VALIDATE,
                        "canary": self.journal["canary"]}
            stage = REGISTRY_PUBLISH
        if stage == REGISTRY_PUBLISH:
            with span("controller.stage", cat="controller",
                      stage=REGISTRY_PUBLISH, cycle=self.journal.cycle):
                self._stage_publish(models, baseline)
            stage = FLEET_SWAP
        if stage == FLEET_SWAP:
            with span("controller.stage", cat="controller",
                      stage=FLEET_SWAP, cycle=self.journal.cycle):
                waiting = self._stage_swap()
            if waiting:
                return {"cycle": self.journal.cycle, "stage": PROBATION,
                        "candidate_version":
                            self.journal["candidate_version"]}
            return self._complete(PUBLISHED)
        # no PROBATION branch: a probation-waiting journal never reaches
        # _advance (run_pending/force_cycle return before resuming it —
        # record_outcome and the timeout are its only drivers)
        if stage == ROLLBACK:
            return self._stage_rollback()
        raise RuntimeError(f"unexpected controller stage {stage!r}")

    # ---- stage: retrain_build ----
    def _faulted_blocks(self, blocks):
        for b in blocks:
            fault_point("retrain_build")
            yield b

    def _stage_build(self, resuming: bool):
        from ..core.checkpoint import CheckpointManager
        from ..core.table import (BadRecordPolicy, iter_csv_chunks,
                                  prefetch_chunks)
        from ..models.forest import build_forest_from_stream
        from ..monitor.baseline import BaselineBuilder
        from ..parallel.mesh import runtime_context
        jr = self.journal
        fault_point("retrain_build")
        cycle_dir = jr.cycle_dir()
        os.makedirs(cycle_dir, exist_ok=True)
        src = self._source_path(
            self._full_source if jr["mode"] == FULL else self._train_source)
        mgr = CheckpointManager(os.path.join(cycle_dir, "ckpt"))
        resume_state, start_row = None, 0
        if resuming:
            try:
                step, arrays, meta = mgr.restore()
            except FileNotFoundError:
                pass    # crashed before the first checkpoint: cold build
            else:
                resume_state = (arrays, meta)
                start_row = int(meta.get("source_rows_done") or 0)
                self.counters.increment("Controller", "BuildResumes")
        def cache_policy():
            if self.policy.cache_policy == "off":
                return None
            from ..io.colcache import CachePolicy
            return CachePolicy(policy=self.policy.cache_policy,
                               counters=self.counters)
        baseline_builder = BaselineBuilder(
            self.schema, n_bins=self.policy.baseline_bins)
        if start_row > 0:
            # the checkpoint restores the MODEL's progress but not the
            # baseline's (stream checkpoints carry no baseline counts),
            # and the stream below restarts at start_row — re-profile
            # the already-consumed head first, or the candidate ships a
            # tail-only baseline that silently skews every later drift
            # score.  A warm .avtc sidecar serves the head at memcpy
            # speed (the cached iterator honors stop_row; a bounded
            # read never BUILDS a cache — a head must not masquerade
            # as a full sidecar).
            for head in iter_csv_chunks(
                    src, self.schema, self.delim_regex,
                    chunk_rows=self.policy.chunk_rows,
                    bad_records=BadRecordPolicy("skip", None,
                                                self.counters),
                    cache=cache_policy(), stop_row=start_row):
                baseline_builder.update(head)
        blocks = prefetch_chunks(iter_csv_chunks(
            src, self.schema, self.delim_regex,
            chunk_rows=self.policy.chunk_rows,
            bad_records=BadRecordPolicy("skip", None, self.counters),
            start_row=start_row, cache=cache_policy()),
            consumer_wait_key=None)
        models = build_forest_from_stream(
            self._faulted_blocks(blocks), self.schema, self.forest_params,
            runtime_context(), checkpoint=mgr,
            checkpoint_every=self.policy.checkpoint_blocks,
            resume_state=resume_state, baseline=baseline_builder)
        baseline = baseline_builder.finalize()
        sha = _models_sha(models)
        self._save_candidate(models, baseline, sha)
        jr.advance(CANDIDATE_VALIDATE, candidate_sha=sha)
        return models, baseline

    # ---- candidate persistence (resume survives a post-build crash) ----
    def _candidate_dir(self) -> str:
        return os.path.join(self.journal.cycle_dir(), CANDIDATE_DIR)

    def _save_candidate(self, models, baseline, sha: str) -> None:
        from ..monitor.baseline import BASELINE_JSON, BASELINE_NPZ
        final = self._candidate_dir()
        tmp = final + f".tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for i, m in enumerate(models):
            with open(os.path.join(tmp, f"tree_{i}.json"), "w") as fh:
                fh.write(m.to_json())
        sidecar = baseline.to_sidecar()
        for fname in (BASELINE_JSON, BASELINE_NPZ):
            with open(os.path.join(tmp, fname), "wb") as fh:
                fh.write(sidecar[fname])
        with open(os.path.join(tmp, CANDIDATE_META), "w") as fh:
            json.dump({"sha": sha, "n_trees": len(models)}, fh)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)

    def _load_candidate(self):
        """(models, baseline) from the cycle dir, or None when missing /
        torn / sha-mismatched (a damaged candidate must never be
        published)."""
        from ..models.tree import DecisionPathList
        from ..monitor.baseline import BASELINE_JSON, BASELINE_NPZ, Baseline
        d = self._candidate_dir()
        try:
            with open(os.path.join(d, CANDIDATE_META)) as fh:
                meta = json.load(fh)
            models = []
            for i in range(int(meta["n_trees"])):
                with open(os.path.join(d, f"tree_{i}.json")) as fh:
                    models.append(DecisionPathList.from_json(fh.read()))
            if _models_sha(models) != meta["sha"] \
                    or meta["sha"] != self.journal["candidate_sha"]:
                return None
            with open(os.path.join(d, BASELINE_JSON), "rb") as fh:
                bj = fh.read()
            with open(os.path.join(d, BASELINE_NPZ), "rb") as fh:
                bn = fh.read()
            return models, Baseline.from_sidecar(bj, bn)
        except Exception:
            return None

    # ---- stage: candidate_validate ----
    def _stage_validate(self, models, baseline) -> Optional[Dict[str, Any]]:
        from ..core.table import BadRecordPolicy, load_csv
        from ..monitor.baseline import load_baseline
        jr = self.journal
        fault_point("candidate_validate")
        holdout = load_csv(self._source_path(self._holdout_source),
                           self.schema, self.delim_regex,
                           bad_records=BadRecordPolicy("skip", None,
                                                       self.counters))
        champ = self.registry.load(self.model_name,
                                   jr["champion_version"])
        champ_acc = self._accuracy_table(champ.model, holdout)
        cand_acc = self._accuracy_table(models, holdout)
        cand_norm = _drift_norm(baseline, holdout)
        champ_norm = None
        try:
            champ_baseline = load_baseline(self.registry, self.model_name,
                                           jr["champion_version"])
            champ_norm = _drift_norm(champ_baseline, holdout)
        except FileNotFoundError:
            pass   # pre-baseline champion: accuracy alone decides
        worse_acc = cand_acc < champ_acc - self.policy.accuracy_margin
        worse_drift = champ_norm is not None and \
            cand_norm > champ_norm + self.policy.drift_margin
        jr.update(champion_accuracy=champ_acc,
                  candidate_accuracy=cand_acc)
        instant("controller.decision", cat="controller",
                action="validate", cycle=jr.cycle,
                champion_accuracy=champ_acc, candidate_accuracy=cand_acc,
                candidate_drift=round(cand_norm, 4),
                champion_drift=(round(champ_norm, 4)
                                if champ_norm is not None else None),
                refused=bool(worse_acc or worse_drift))
        if worse_acc or worse_drift:
            self.counters.increment("Controller", "Refused")
            warnings.warn(
                f"retrain cycle {jr.cycle}: candidate refused "
                f"(accuracy {cand_acc} vs champion {champ_acc}, "
                f"margin {self.policy.accuracy_margin}; drift "
                f"{cand_norm:.3g} vs "
                f"{champ_norm if champ_norm is not None else 'n/a'}); "
                f"champion stays", RuntimeWarning)
            return self._complete(REFUSED)
        jr.advance(CANARY_VALIDATE)
        return None

    def _accuracy_table(self, models, table) -> int:
        """Delayed-label holdout accuracy (integer percent) through the
        SAME AccuracyTracker/ConfusionMatrix path the live monitor uses."""
        labels, actual = predict_outcomes(models, self.schema, table)
        card = list(self.schema.class_attr_field.cardinality or [])
        return accuracy_pct(labels, actual,
                            neg_class=card[0], pos_class=card[1])

    # ---- stage: canary_validate (live outcomes drive it) ----
    def _canary_fleet(self):
        """The fleet link, iff it speaks the multi-model canary verbs."""
        f = self.fleet
        if f is not None and hasattr(f, "install_canary") \
                and hasattr(f, "record_canary_outcome"):
            return f
        return None

    def _stage_canary(self, models) -> bool:
        """Install the candidate as a live canary (pre-publish, from the
        in-memory payload) and wait for outcomes.  Returns True when the
        cycle now waits; False when the stage was a journaled skip
        (policy disabled, or no canary-capable fleet attached) and the
        cycle proceeds straight to publish."""
        jr = self.journal
        fault_point("canary_validate")
        fleet = self._canary_fleet()
        if self.policy.canary_outcomes <= 0 or fleet is None:
            reason = ("disabled" if self.policy.canary_outcomes <= 0
                      else "no canary-capable fleet")
            # journaled skip: the durable record says the stage ran and
            # WHY it passed through, so a resumed cycle replays the
            # same decision instead of inventing a canary it never had
            jr.advance(REGISTRY_PUBLISH, canary={"skipped": True,
                                                 "reason": reason})
            self.counters.increment("Controller", "CanarySkipped")
            instant("controller.decision", cat="controller",
                    action="canary_skip", cycle=jr.cycle, reason=reason)
            return False
        from ..serving.predictor import ForestPredictor
        card = list(self.schema.class_attr_field.cardinality or [])
        pred = ForestPredictor(models, self.schema)
        fleet.install_canary(self.model_name, predictor=pred,
                             percent=self.policy.canary_percent,
                             pos_class=card[1], neg_class=card[0],
                             window=max(1, self.policy.canary_outcomes))
        self._can_pred = {"champion": [], "candidate": []}
        self._can_actual = {"champion": [], "candidate": []}
        self._canary_live = True
        jr.advance(CANARY_VALIDATE, canary={
            "needed": self.policy.canary_outcomes,
            "percent": self.policy.canary_percent,
            "opened_unix": time.time()})
        self.counters.increment("Controller", "CanaryInstalled")
        instant("controller.decision", cat="controller",
                action="canary_start", cycle=jr.cycle,
                percent=self.policy.canary_percent,
                needed=self.policy.canary_outcomes)
        return True

    def record_canary_outcome(self, rid, predicted: str, actual: str
                              ) -> Optional[Dict[str, Any]]:
        """Feed one live delayed-label outcome for a canaried request.
        The arm is re-derived from the request id by the SAME
        deterministic split that routed it (no routing journal needed).
        Collecting ``canary_outcomes`` candidate-arm outcomes decides
        the stage: candidate accuracy within ``accuracy_margin`` of the
        journaled champion accuracy proceeds to publish (synchronously,
        on this thread — the control-plane lane, like probation's
        deciding outcome); below it the cycle completes REFUSED and the
        champion keeps 100%.  No-op (None) outside canary-wait."""
        with self._lock:
            if self.journal.stage != CANARY_VALIDATE \
                    or not self._canary_live:
                return None
            fleet = self._canary_fleet()
            arm = None
            if fleet is not None:
                arm = fleet.record_canary_outcome(
                    self.model_name, rid, predicted, actual)
            if arm is None:
                from ..serving.router import canary_split
                arm = "candidate" if canary_split(
                    rid, self.policy.canary_percent) else "champion"
            self._can_pred[arm].append(predicted)
            self._can_actual[arm].append(actual)
            if len(self._can_pred["candidate"]) \
                    < self.policy.canary_outcomes:
                return None
            return self._resolve_canary_locked(timed_out=False)

    def _teardown_canary(self) -> None:
        fleet = self._canary_fleet()
        if fleet is not None and self._canary_live:
            try:
                fleet.clear_canary(self.model_name)
            except Exception as exc:
                warnings.warn(
                    f"retrain cycle {self.journal.cycle}: canary "
                    f"teardown failed ({type(exc).__name__}: {exc})",
                    RuntimeWarning)
        self._canary_live = False

    def _resolve_canary_locked(self, timed_out: bool
                               ) -> Optional[Dict[str, Any]]:
        jr = self.journal
        card = list(self.schema.class_attr_field.cardinality or [])
        cand_n = len(self._can_pred["candidate"])
        cand_acc = accuracy_pct(self._can_pred["candidate"],
                                self._can_actual["candidate"],
                                neg_class=card[0], pos_class=card[1]) \
            if cand_n else None
        champ_n = len(self._can_pred["champion"])
        champ_acc = accuracy_pct(self._can_pred["champion"],
                                 self._can_actual["champion"],
                                 neg_class=card[0], pos_class=card[1]) \
            if champ_n else None
        floor = max(0, (jr["champion_accuracy"] or 0)
                    - self.policy.accuracy_margin)
        refused = not timed_out and cand_acc is not None \
            and cand_acc < floor
        can = dict(jr["canary"] or {})
        can.update(candidate_accuracy=cand_acc,
                   candidate_outcomes=cand_n,
                   champion_accuracy=champ_acc,
                   champion_outcomes=champ_n,
                   floor=floor, timed_out=timed_out)
        jr.update(canary=can)
        self._teardown_canary()
        self.counters.increment(
            "Controller",
            "CanaryTimeouts" if timed_out else "CanaryWindows")
        instant("controller.decision", cat="controller",
                action="canary_verdict", cycle=jr.cycle,
                candidate_accuracy=cand_acc, floor=floor,
                candidate_outcomes=cand_n, champion_outcomes=champ_n,
                refused=refused, timed_out=timed_out)
        if timed_out:
            warnings.warn(
                f"retrain cycle {jr.cycle}: canary received only "
                f"{cand_n}/{self.policy.canary_outcomes} candidate "
                f"outcomes within {self.policy.canary_timeout_s}s; "
                f"proceeding to publish (no evidence against the "
                f"candidate — wire the delayed-label lane)",
                RuntimeWarning)
        if refused:
            self.counters.increment("Controller", "Refused")
            warnings.warn(
                f"retrain cycle {jr.cycle}: candidate refused at canary "
                f"(live accuracy {cand_acc} under floor {floor} over "
                f"{cand_n} outcomes); champion keeps 100%",
                RuntimeWarning)
            return self._complete(REFUSED)
        jr.advance(REGISTRY_PUBLISH)
        return self._advance(REGISTRY_PUBLISH, resuming=False)

    # ---- stage: registry_publish ----
    def _find_published(self, sha: Optional[str]) -> Optional[int]:
        """A committed version already carrying THIS cycle's candidate
        (the no-double-publish probe resume runs before writing).  The
        match is (candidate sha AND this journal cycle number, both
        stamped into the version's params at publish) over versions
        newer than this cycle's champion — only this cycle's own
        crashed publish attempt can satisfy all three, so a
        bit-identical model published by an EARLIER cycle (same window,
        same seed — and possibly already rolled back) is never adopted:
        it gets a fresh version with an honest audit trail."""
        if not sha:
            return None
        champion = self.journal["champion_version"] or 0
        from ..serving.registry import META_FILE
        for v in reversed(self.registry.versions(self.model_name)):
            if v <= champion:
                break
            d = self.registry.version_dir(self.model_name, v)
            try:
                with open(os.path.join(d, META_FILE)) as fh:
                    meta = json.load(fh)
            except Exception:
                continue
            params = meta.get("params") or {}
            if params.get("candidate_sha") == sha \
                    and params.get("controller_cycle") == self.journal.cycle \
                    and self.registry.is_intact(self.model_name, v):
                return v
        return None

    def _stage_publish(self, models, baseline) -> None:
        from ..monitor.baseline import BASELINE_JSON, publish_baseline
        from ..serving.registry import META_FILE
        jr = self.journal
        fault_point("registry_publish")
        sha = jr["candidate_sha"]
        version = self._find_published(sha)
        if version is None:
            params = {"controller_cycle": jr.cycle,
                      "candidate_sha": sha,
                      "retrain_mode": jr["mode"]}
            champion = jr["champion_version"]
            if champion is not None:
                # O(delta) distribution (ISSUE 20): a retrained candidate
                # is the champion's child, so publish it WITH a delta
                # sidecar against the champion — fleet refreshes then
                # patch only the changed trees instead of re-shipping
                # the forest.  publish_delta is a full publish plus a
                # best-effort sidecar: a delta that cannot be built
                # (kind/schema mismatch) warns and the version still
                # commits, so this branch never loses a publish.
                version = self.registry.publish_delta(
                    self.model_name, models, parent_version=champion,
                    schema=self.schema, params=params)
                if self.registry.delta_info(self.model_name,
                                            version) is not None:
                    self.counters.increment("Controller", "DeltaPublished")
            else:
                version = self.registry.publish(
                    self.model_name, models, schema=self.schema,
                    params=params)
            self.counters.increment("Controller", "Published")
        else:
            # a pre-journal crash landed AFTER the commit: adopt it
            self.counters.increment("Controller", "PublishDeduped")
        # the baseline sidecar may be missing when the crash hit between
        # publish and add_sidecar; attaching is idempotent
        d = self.registry.version_dir(self.model_name, version)
        with open(os.path.join(d, META_FILE)) as fh:
            files = json.load(fh).get("files") or []
        if BASELINE_JSON not in files:
            publish_baseline(self.registry, self.model_name, version,
                             baseline)
        # THE double-publish window: committed but not yet journaled — a
        # kill here must dedup by sha on resume, never publish twice
        fault_point("registry_publish")
        jr.advance(FLEET_SWAP, candidate_version=version)

    # ---- stage: fleet_swap ----
    def _reload_fleet(self) -> None:
        if self.fleet is None:
            return
        self.fleet.refresh()

    def _wait_converged(self, version: int) -> bool:
        """Swap-ack: poll the link's convergence surface until every
        worker serves ``version`` (True), or the timeout passes (False —
        serving is unharmed; workers converge at their next poll)."""
        f = self.fleet
        if f is None:
            return True
        probe: Optional[Callable[[], Optional[int]]] = None
        if hasattr(f, "converged_version"):
            probe = f.converged_version
        elif hasattr(f, "version"):
            probe = lambda: f.version      # noqa: E731
        if probe is None:
            self.counters.increment("Controller", "SwapAckUnavailable")
            return True
        deadline = time.monotonic() + self.policy.swap_ack_timeout_s
        while time.monotonic() < deadline:
            if probe() == version:
                return True
            time.sleep(0.01)
        return False

    def _stage_swap(self) -> bool:
        """Pin + reload + ack.  Returns True when the cycle now waits in
        probation, False when it completes immediately."""
        jr = self.journal
        fault_point("fleet_swap")
        version = jr["candidate_version"]
        self.registry.pin_version(self.model_name, version)
        self._reload_fleet()
        if not self._wait_converged(version):
            self.counters.increment("Controller", "SwapAckTimeouts")
            warnings.warn(
                f"retrain cycle {jr.cycle}: fleet did not ack version "
                f"{version} within {self.policy.swap_ack_timeout_s}s; "
                f"workers converge at their next poll", RuntimeWarning)
        self.counters.increment("Controller", "Swaps")
        instant("controller.decision", cat="controller", action="swap",
                cycle=jr.cycle, candidate_version=version,
                champion_version=jr["champion_version"])
        if self.policy.probation_outcomes > 0:
            floor = max(0, (jr["champion_accuracy"] or 0)
                        - self.policy.probation_margin)
            jr.advance(PROBATION, probation={
                "floor": floor,
                "needed": self.policy.probation_outcomes,
                "windows": self.policy.probation_windows,
                "windows_done": 0,
                "opened_unix": time.time()})
            self._prob_pred.clear()
            self._prob_actual.clear()
            return True
        return False

    # ---- stage: probation (live outcomes drive it) ----
    def record_outcome(self, predicted: str, actual: str
                       ) -> Optional[Dict[str, Any]]:
        """Feed one live delayed-label outcome (predicted, actual).
        Outside probation this is a no-op.  Closing a probation window
        below the journaled floor AUTO-ROLLS-BACK; surviving all windows
        completes the cycle as published.  Returns the terminal summary
        when this outcome decided the cycle.

        The deciding outcome executes the rollback (pin + reload + ack
        wait, up to ``swap_ack_timeout_s``) SYNCHRONOUSLY on the
        caller's thread — feed outcomes from the delayed-label lane
        (control plane), never from a request-serving thread.  Alert
        intake stays responsive meanwhile: ``submit_alert`` takes only
        the alert-slot lock, not this cycle lock."""
        with self._lock:
            if self.journal.stage != PROBATION:
                return None
            self._prob_pred.append(predicted)
            self._prob_actual.append(actual)
            prob = dict(self.journal["probation"] or {})
            needed = int(prob.get("needed") or 1)
            if len(self._prob_pred) < needed:
                return None
            card = list(self.schema.class_attr_field.cardinality or [])
            acc = accuracy_pct(self._prob_pred[:needed],
                               self._prob_actual[:needed],
                               neg_class=card[0], pos_class=card[1])
            del self._prob_pred[:needed], self._prob_actual[:needed]
            prob["windows_done"] = int(prob.get("windows_done", 0)) + 1
            prob["last_accuracy"] = acc
            self.counters.increment("Controller", "ProbationWindows")
            self.journal.update(probation=prob)
            instant("controller.decision", cat="controller",
                    action="probation_window", cycle=self.journal.cycle,
                    accuracy=acc, floor=prob["floor"],
                    window=prob["windows_done"])
            if acc < int(prob["floor"]):
                self.journal.advance(ROLLBACK)
                return self._stage_rollback()
            if prob["windows_done"] >= int(prob.get("windows") or 1):
                return self._complete(PUBLISHED)
            return None

    def resolve_probation(self, keep: bool = True
                          ) -> Optional[Dict[str, Any]]:
        """Operator escape hatch for a probation whose outcome stream
        never materialized (or a judgment call): ``keep=True`` completes
        the cycle as published on the candidate; ``keep=False`` rolls
        back to the champion NOW.  No-op (None) outside probation."""
        with self._lock:
            if self.journal.stage != PROBATION:
                return None
            return self._resolve_probation_locked(keep=keep,
                                                  timed_out=False)

    def _resolve_probation_locked(self, keep: bool, timed_out: bool
                                  ) -> Dict[str, Any]:
        self.counters.increment(
            "Controller",
            "ProbationTimeouts" if timed_out else "ProbationResolved")
        instant("controller.decision", cat="controller",
                action="probation_resolved", cycle=self.journal.cycle,
                keep=keep, timed_out=timed_out)
        if timed_out:
            warnings.warn(
                f"retrain cycle {self.journal.cycle}: probation received "
                f"no verdict within {self.policy.probation_timeout_s}s; "
                f"keeping the candidate (wire the delayed-label lane or "
                f"call resolve_probation)", RuntimeWarning)
        if keep:
            return self._complete(PUBLISHED)
        self.journal.advance(ROLLBACK)
        return self._stage_rollback()

    # ---- stage: rollback ----
    def _stage_rollback(self) -> Dict[str, Any]:
        # spanned HERE, not in _advance: probation outcomes trigger
        # rollback from record_outcome/check_probation_timeout too, and
        # every entry path must land on the timeline
        with span("controller.stage", cat="controller", stage=ROLLBACK,
                  cycle=self.journal.cycle):
            return self._rollback_locked()

    def _rollback_locked(self) -> Dict[str, Any]:
        jr = self.journal
        fault_point("rollback")
        champion = jr["champion_version"]
        try:
            self.registry.pin_version(self.model_name, champion)
        except ValueError:
            # the rollback target is GONE (an operator GC retired the
            # journaled champion mid-cycle — retire() only knows the
            # pin/serving versions, not a journal's).  There is nothing
            # to roll back TO; wedging here would re-raise on every
            # resume forever.  Un-pin so serving resolves the newest
            # intact version and close the cycle honestly as abandoned.
            self.counters.increment("Controller", "RollbackTargetMissing")
            self.registry.clear_pin(self.model_name)
            self._reload_fleet()
            warnings.warn(
                f"retrain cycle {jr.cycle}: rollback target v{champion} "
                f"no longer exists in the registry (retired by an "
                f"external GC?); serving stays on the newest intact "
                f"version — run GC between cycles, not during probation",
                RuntimeWarning)
            return self._abandon(f"rollback target v{champion} missing")
        self._reload_fleet()
        if not self._wait_converged(champion):
            self.counters.increment("Controller", "SwapAckTimeouts")
        self.counters.increment("Controller", "Rollbacks")
        instant("controller.decision", cat="controller", action="rollback",
                cycle=jr.cycle, champion_version=champion,
                candidate_version=jr["candidate_version"])
        warnings.warn(
            f"retrain cycle {jr.cycle}: candidate v"
            f"{jr['candidate_version']} rolled back to champion "
            f"v{champion} (live accuracy under the probation floor)",
            RuntimeWarning)
        return self._complete(ROLLED_BACK)

    # ---- terminal ----
    def _abandon(self, reason: str) -> Dict[str, Any]:
        self.counters.increment("Controller", "Abandoned")
        warnings.warn(f"retrain cycle {self.journal.cycle} abandoned: "
                      f"{reason}; champion untouched", RuntimeWarning)
        return self._complete(ABANDONED)

    def _complete(self, outcome: str) -> Dict[str, Any]:
        jr = self.journal
        self._teardown_canary()   # no-op unless a canary is still live
        cycle_dir = jr.cycle_dir()
        jr.close_cycle(outcome)
        self._last_cycle_end = time.monotonic()
        # the cycle's working set (checkpoints + candidate payload) is
        # dead weight once the outcome journaled; dropping it bounds the
        # state dir at one in-flight cycle (the journal keeps the
        # bounded history)
        shutil.rmtree(cycle_dir, ignore_errors=True)
        if self.policy.retire_keep_last > 0:
            retired = self.registry.retire(
                self.model_name, keep_last=self.policy.retire_keep_last)
            if retired:
                self.counters.increment("Controller", "VersionsRetired",
                                        len(retired))
        instant("controller.decision", cat="controller",
                action="cycle_end", cycle=jr.cycle, outcome=outcome,
                candidate_version=jr["candidate_version"],
                champion_version=jr["champion_version"])
        return {"cycle": jr.cycle, "outcome": outcome,
                "champion_version": jr["champion_version"],
                "candidate_version": jr["candidate_version"],
                "champion_accuracy": jr["champion_accuracy"],
                "candidate_accuracy": jr["candidate_accuracy"]}


# --------------------------------------------------------------------------
# shared scoring helpers
# --------------------------------------------------------------------------

def predict_outcomes(models, schema, table):
    """(predicted_labels, actual_labels) for a labeled table — THE one
    ensemble-predict + class-code decode used by validation, and by the
    CLI job's probation replay (one label convention: ambiguous/veto
    predictions and unknown actual codes both become '', which the
    binary ConfusionMatrix scores as not-that-class)."""
    from ..models.forest import EnsembleModel
    from ..models.tree import DecisionTreeModel
    ens = EnsembleModel(
        [DecisionTreeModel(pl, schema) for pl in models],
        require_odd=len(models) % 2 == 1)
    labels = [lab or "" for lab in ens.predict(table)]
    card = list(schema.class_attr_field.cardinality or [])
    actual = [card[c] if c >= 0 else "" for c in table.class_codes()]
    return labels, actual


def accuracy_pct(pred_labels, actual_labels, *, neg_class: str,
                 pos_class: str) -> int:
    """Integer-percent accuracy through the real delayed-label machinery:
    one AccuracyTracker window over a capture policy whose alert bar sits
    above 100, so the quality AlertRecord ALWAYS fires and its ``value``
    IS the ConfusionMatrix accuracy — validation and probation score
    through the identical path the live monitor alerts on."""
    import logging
    if not len(pred_labels):
        return 0
    policy = DriftPolicy(consecutive=1, accuracy_alert=101,
                         counters=Counters())
    # the always-firing capture alert is a measurement, not a finding:
    # route it to a silenced logger so every validation does not print a
    # fake "drift alert" line into the operator log
    probe_log = logging.getLogger("avenir_tpu.control._accuracy_probe")
    if not probe_log.handlers:
        probe_log.addHandler(logging.NullHandler())
        probe_log.propagate = False
    policy._log = probe_log
    tracker = AccuracyTracker(pos_class=pos_class, neg_class=neg_class,
                              policy=policy, window=len(pred_labels))
    recs = tracker.record(list(pred_labels), list(actual_labels))
    return int(recs[-1].value)


def _drift_norm(baseline, table) -> float:
    """Worst normalized drift statistic of one window vs one baseline:
    max over applicable (row, stat) of value / alert threshold — 1.0 ==
    'exactly at the alert bar'.  The validation re-score: a candidate
    whose OWN baseline still alerts on the fresh window did not fix the
    drift it was trained for."""
    from ..monitor.drift import STATS, DriftScorer
    report = DriftScorer(baseline).score_table(table)
    worst = 0.0
    for row in report.rows:
        for stat in STATS:
            if row.applicable(stat):
                worst = max(worst, row.stats[stat] / DEFAULT_ALERT[stat])
    return worst


def _models_sha(models) -> str:
    h = hashlib.sha256()
    for m in models:
        h.update(m.to_json().encode())
    return h.hexdigest()


# ---- the online supervisor (ISSUE 19) ----------------------------------

ONLINE_STATE_FILE = "online_state.bin"


@dataclass
class OnlineSupervisorPolicy:
    """Knobs of the online learning plane's supervisor (CLI twin: the
    ``ps.online.*`` keys)."""
    snapshot_every: int = 32      # windows between registry snapshots
    accuracy_floor: int = 0       # integer percent; 0 disables rollback
    floor_window: int = 256      # labeled outcomes per probation window
    floor_consecutive: int = 2    # breached windows before rollback
    pos_class: str = "1"
    neg_class: str = "0"


class OnlineSupervisor:
    """The RetrainController's role for the online plane: not a
    rebuilder (the plane learns every window) but a guardian.

    Duties, all journaled (``OnlineJournal``) and chaos-drillable at
    the ``online_snapshot`` / ``online_restore`` fault points:

    * **snapshot cadence** — every ``snapshot_every`` supervised
      windows, serialize the plane's device state and publish it to the
      registry as a versioned model (the logistic coefficients are the
      payload, kind ``logistic``) with the FULL state bytes as a
      ``online_state.bin`` sidecar, then pin the version: the pin IS
      the rollback target, exactly the PR 13 machinery.
    * **probation, permanently** — every supervised window's labeled
      outcomes feed an :class:`AccuracyTracker`; ``accuracy_floor``
      breached for ``floor_consecutive`` probation windows triggers
      the rollback actuator.
    * **rollback** — restore the pinned snapshot's sidecar bytes into
      the plane's donated carries, bit-identical, without a process
      restart.
    * **resume** — on attach (service start, or restart after a kill),
      restore from the pinned snapshot if one exists; an interrupted
      snapshot/rollback found in the journal resumes through the SAME
      path, because the registry pin — not the journal — is the state
      source of truth.
    """

    def __init__(self, registry, model_name: str, state_dir: str,
                 policy: Optional[OnlineSupervisorPolicy] = None,
                 counters: Optional[Counters] = None):
        from .journal import (ONLINE_PROBATION, ONLINE_ROLLBACK,
                              ONLINE_SNAPSHOT, OnlineJournal)
        self._stages = (ONLINE_PROBATION, ONLINE_SNAPSHOT,
                        ONLINE_ROLLBACK)
        self.registry = registry
        self.model_name = model_name
        self.policy = policy or OnlineSupervisorPolicy()
        self.counters = counters if counters is not None else Counters()
        self.journal = OnlineJournal(state_dir)
        self.plane = None
        self.windows = int(self.journal.get("windows") or 0)
        self._since_snapshot = 0
        self._tracker = self._fresh_tracker()

    def _fresh_tracker(self) -> Optional[AccuracyTracker]:
        p = self.policy
        if p.accuracy_floor <= 0:
            return None
        dp = DriftPolicy(consecutive=p.floor_consecutive,
                         accuracy_alert=p.accuracy_floor,
                         counters=self.counters)
        return AccuracyTracker(pos_class=p.pos_class,
                               neg_class=p.neg_class, policy=dp,
                               window=p.floor_window)

    # ---- lifecycle -----------------------------------------------------
    def attach(self, plane) -> None:
        """Bind the plane and resume: restore the pinned snapshot (if
        any), complete any interrupted journal stage, and guarantee a
        rollback target exists by taking snapshot #1 on a fresh start."""
        self.plane = plane
        interrupted = self.journal.interrupted
        v = self.registry.pinned_version(self.model_name)
        if v is not None:
            self._restore(v)
            if interrupted:
                # the crash window re-enters probation through the same
                # restore path a rollback uses; the half-done snapshot
                # (published, unpinned) is abandoned to registry gc
                self.counters.increment("Online", "ResumedInterrupted")
        elif self.journal.stage != "idle" and interrupted:
            self.counters.increment("Online", "ResumedInterrupted")
        self.journal.advance(self._stages[0],
                             windows=self.windows)
        if v is None:
            self.snapshot()     # the first rollback target

    def on_window(self, pred_labels, actual_labels) -> Dict[str, Any]:
        """One supervised window: feed the probation tracker, enforce
        the floor, keep the snapshot cadence.  Returns the window's
        events (``snapshot``/``rollback`` -> version)."""
        if self.plane is None:
            raise RuntimeError("supervisor has no attached plane")
        events: Dict[str, Any] = {}
        self.windows += 1
        self._since_snapshot += 1
        if self._tracker is not None and pred_labels:
            fired = self._tracker.record(list(pred_labels),
                                         list(actual_labels))
            if any(r.level == ALERT for r in fired):
                worst = min(r.value for r in fired)
                instant("online.floor_breach", cat="online",
                        model=self.model_name, accuracy=worst,
                        floor=self.policy.accuracy_floor,
                        window=self.windows)
                self.counters.increment("Online", "FloorBreaches")
                events["rollback"] = self.rollback()
                return events
        if self.policy.snapshot_every > 0 \
                and self._since_snapshot >= self.policy.snapshot_every:
            events["snapshot"] = self.snapshot()
        return events

    # ---- actuators -----------------------------------------------------
    def snapshot(self) -> int:
        """Publish the plane's state as the next pinned version."""
        probation, snapshot_stage, _ = self._stages
        self.journal.advance(snapshot_stage, windows=self.windows)
        fault_point("online_snapshot")
        payload = self.plane.state_bytes()
        version = self.registry.publish(
            self.model_name, self.plane.logistic_w(), kind="logistic",
            params={"online": True, "window": self.windows,
                    "algorithm": self.plane.config.algorithm})
        self.registry.add_sidecar(self.model_name, version,
                                  {ONLINE_STATE_FILE: payload})
        self.registry.pin_version(self.model_name, version)
        self.journal.advance(
            probation, windows=self.windows,
            last_snapshot_version=version,
            last_snapshot_window=self.windows,
            snapshots=int(self.journal.get("snapshots") or 0) + 1)
        instant("online.snapshot", cat="online", model=self.model_name,
                version=version, window=self.windows,
                bytes=len(payload))
        self.counters.increment("Online", "Snapshots")
        self._since_snapshot = 0
        return version

    def rollback(self) -> int:
        """Restore the pinned snapshot into the plane, bit-identical."""
        probation, _, rollback_stage = self._stages
        self.journal.advance(rollback_stage, windows=self.windows)
        fault_point("online_restore")
        version = self.journal.get("last_snapshot_version")
        if version is None:
            version = self.registry.pinned_version(self.model_name)
        if version is None:
            raise RuntimeError(
                f"online rollback for {self.model_name!r} has no "
                f"snapshot to restore")
        self._restore(int(version))
        self.journal.advance(
            probation, windows=self.windows,
            rollbacks=int(self.journal.get("rollbacks") or 0) + 1)
        instant("online.rollback", cat="online", model=self.model_name,
                version=int(version), window=self.windows)
        self.counters.increment("Online", "Rollbacks")
        # the restored learner starts a fresh probation record — stale
        # pre-rollback outcomes must not instantly re-breach the floor
        self._tracker = self._fresh_tracker()
        self._since_snapshot = 0
        return int(version)

    def _restore(self, version: int) -> None:
        payload = self.registry.read_sidecar(self.model_name, version,
                                             ONLINE_STATE_FILE)
        self.plane.restore(payload)

    # ---- observability -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "supervised_windows": self.windows,
            "snapshots": int(self.journal.get("snapshots") or 0),
            "rollbacks": int(self.journal.get("rollbacks") or 0),
            "last_snapshot_version":
                self.journal.get("last_snapshot_version") or 0,
        }
