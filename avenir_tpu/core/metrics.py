"""Metrics channel: counters, confusion matrix, cost-based arbitration.

The reference uses Hadoop Counters / Spark accumulators as its metrics channel
(SURVEY.md §5; bayesian/BayesianPredictor.java:170-180,
spark SimulatedAnnealing.scala:88-92).  Here metrics are plain dicts of
integers accumulated host-side (or psum'd scalars fetched from jitted steps via
avenir_tpu.parallel.collectives.counter_sum) and rendered the same way Hadoop
prints counter groups.

ConfusionMatrix and CostBasedArbitrator keep the exact integer-percent
semantics of util/ConfusionMatrix.java and util/CostBasedArbitrator.java so
validation counters match the reference run for run.
"""

from __future__ import annotations

import json
import threading
from collections import defaultdict
from typing import Dict, Optional, Tuple

import numpy as np


class Counters:
    """Hadoop-counter-style metrics: (group, name) -> int.

    Updates are atomic under one internal lock: serving loops mutate
    counters from several threads while the metrics snapshot thread reads
    them mid-flight, so read-modify-write races (lost increments, a
    high-water mark going DOWN) must be impossible by construction."""

    def __init__(self):
        self._c: Dict[Tuple[str, str], int] = defaultdict(int)
        self._lock = threading.Lock()

    def __getstate__(self):
        # the lock is process-local; counters cross process boundaries
        # (shard allgather, subprocess result plumbing) as plain data —
        # snapshot UNDER the lock so pickling a live Counters cannot race
        # a first-seen key insert mid-copy
        with self._lock:
            return dict(self._c)

    def __setstate__(self, state):
        self._c = defaultdict(int, state)
        self._lock = threading.Lock()

    def increment(self, group: str, name: str, amount: int = 1) -> None:
        with self._lock:
            self._c[(group, name)] += int(amount)

    def set(self, group: str, name: str, value: int) -> None:
        with self._lock:
            self._c[(group, name)] = int(value)

    def max(self, group: str, name: str, value: int) -> int:
        """Atomically raise the counter to ``value`` if it is larger;
        returns the resulting value.  The high-water-mark update (e.g.
        Serving/MaxBatchObserved) as ONE operation — a get-then-set from
        two threads could publish the smaller of two observations."""
        with self._lock:
            key = (group, name)
            cur = self._c.get(key, 0)
            if int(value) > cur:
                cur = int(value)
                self._c[key] = cur
            return cur

    def get(self, group: str, name: str) -> int:
        return self._c.get((group, name), 0)

    def update_group(self, group: str, values: Dict[str, int]) -> None:
        """Set a whole group at once (e.g. a TransferLedger export)."""
        for name, v in values.items():
            self.set(group, name, v)

    def group(self, group: str) -> Dict[str, int]:
        """All (name, value) pairs of one group."""
        with self._lock:
            items = sorted(self._c.items())
        return {n: v for (g, n), v in items if g == group}

    def as_dict(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            items = sorted(self._c.items())
        out: Dict[str, Dict[str, int]] = defaultdict(dict)
        for (g, n), v in items:
            out[g][n] = v
        return dict(out)

    def render(self) -> str:
        """Render like Hadoop's end-of-job counter dump."""
        lines = []
        for g, names in self.as_dict().items():
            lines.append(f"{g}")
            for n, v in names.items():
                lines.append(f"\t{n}={v}")
        return "\n".join(lines)

    # ---- machine-readable export (stable key order) ----
    def to_json(self) -> str:
        """One compact JSON object {group: {name: value}} with groups and
        names sorted — jobs and the bench harness consume this instead of
        parsing render() text, and identical counters always serialize to
        identical bytes (diffable artifacts)."""
        return json.dumps(self.as_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, text: str) -> "Counters":
        """Inverse of :meth:`to_json`: ``from_json(c.to_json())`` holds
        every (group, name, value) of ``c``."""
        out = cls()
        for g, names in json.loads(text).items():
            for n, v in names.items():
                out.set(g, n, int(v))
        return out

    def append_jsonl(self, path: str,
                     tag: Optional[str] = None) -> None:
        """Append one ``{"tag":..., "counters": {...}}`` line to a JSONL
        file (key order stable) — the per-window/per-run export stream."""
        record: Dict = {}
        if tag is not None:
            record["tag"] = tag
        record["counters"] = self.as_dict()
        with open(path, "a") as fh:
            fh.write(json.dumps(record, sort_keys=True,
                                separators=(",", ":")) + "\n")


class ConfusionMatrix:
    """Binary confusion matrix with the reference's integer-percent metrics
    (util/ConfusionMatrix.java:30-75).  Constructor arg order is
    (negClass, posClass), as in the reference."""

    def __init__(self, neg_class: str, pos_class: str):
        self.neg_class = neg_class
        self.pos_class = pos_class
        self.true_pos = 0
        self.false_pos = 0
        self.true_neg = 0
        self.false_neg = 0

    def report(self, pred_class: str, actual_class: str) -> None:
        if pred_class == self.pos_class:
            if actual_class == self.pos_class:
                self.true_pos += 1
            else:
                self.false_pos += 1
        else:
            if actual_class == self.neg_class:
                self.true_neg += 1
            else:
                self.false_neg += 1

    def report_batch(self, pred_is_pos: np.ndarray, actual_is_pos: np.ndarray,
                     actual_is_neg: np.ndarray) -> None:
        """Vectorized report: boolean arrays per record.  actual_is_neg is
        passed separately because the reference treats 'not neg' (e.g. unknown
        label) as a false negative when prediction is negative."""
        pp = np.asarray(pred_is_pos, dtype=bool)
        ap = np.asarray(actual_is_pos, dtype=bool)
        an = np.asarray(actual_is_neg, dtype=bool)
        self.true_pos += int(np.sum(pp & ap))
        self.false_pos += int(np.sum(pp & ~ap))
        self.true_neg += int(np.sum(~pp & an))
        self.false_neg += int(np.sum(~pp & ~an))

    # integer-percent metrics, matching reference integer division (plus a
    # zero-denominator guard the reference lacks — it would throw
    # ArithmeticException and kill the job)
    def recall(self) -> int:
        denom = self.true_pos + self.false_neg
        return (100 * self.true_pos) // denom if denom else 0

    def precision(self) -> int:
        denom = self.true_pos + self.false_pos
        return (100 * self.true_pos) // denom if denom else 0

    def accuracy(self) -> int:
        total = self.true_pos + self.true_neg + self.false_pos + self.false_neg
        return (100 * (self.true_pos + self.true_neg)) // total if total else 0

    def export(self, counters: Counters, group: str = "Validation") -> None:
        """Export with the reference's counter names (including its
        'TrueNagative' typo, bayesian/BayesianPredictor.java:174)."""
        counters.increment(group, "TruePositive", self.true_pos)
        counters.increment(group, "FalseNegative", self.false_neg)
        counters.increment(group, "TrueNagative", self.true_neg)
        counters.increment(group, "FalsePositive", self.false_pos)
        counters.increment(group, "Accuracy", self.accuracy())
        counters.increment(group, "Recall", self.recall())
        counters.increment(group, "Precision", self.precision())


class CostBasedArbitrator:
    """Misclassification-cost arbitration (util/CostBasedArbitrator.java:25-65).
    Probabilities are integer percents, as in the reference."""

    def __init__(self, neg_class: str, pos_class: str,
                 false_neg_cost: int, false_pos_cost: int):
        self.neg_class = neg_class
        self.pos_class = pos_class
        self.false_neg_cost = false_neg_cost
        self.false_pos_cost = false_pos_cost

    def arbitrate(self, pos_prob: int, neg_prob: int) -> str:
        neg_cost = self.false_neg_cost * pos_prob + neg_prob
        pos_cost = self.false_pos_cost * neg_prob + pos_prob
        return self.pos_class if pos_cost < neg_cost else self.neg_class

    def classify(self, pos_prob: int) -> str:
        threshold = (self.false_pos_cost * 100) // (self.false_pos_cost + self.false_neg_cost)
        return self.pos_class if pos_prob > threshold else self.neg_class

    def classify_batch(self, pos_prob: np.ndarray) -> np.ndarray:
        """Vectorized classify(): boolean array 'is positive'."""
        threshold = (self.false_pos_cost * 100) // (self.false_pos_cost + self.false_neg_cost)
        return np.asarray(pos_prob) > threshold
