"""Columnar dataset: CSV text -> encoded numpy/JAX arrays.

This replaces the record-at-a-time layer of the reference (chombo ``Tuple``
Writables + per-mapper ``value.toString().split(fieldDelimRegex)``, e.g.
bayesian/BayesianDistribution.java:140).  There is no record object in the new
design: a dataset is a struct of columns, each encoded once on load:

  * categorical columns  -> int32 vocabulary codes (schema cardinality order;
    unknown values -> -1)
  * numeric columns      -> float64 values
  * binned-numeric view  -> int32 bin codes, ``value // bucketWidth - offset``
    (reference binning: bayesian/BayesianDistribution.java:152)
  * id/string columns    -> kept host-side as python lists (never on device)

A table can be padded to a multiple of the mesh size; ``valid_mask`` marks real
rows so padded rows never contribute to reductions.

A fast native CSV tokenizer (avenir_tpu.io.native_csv, C++) is used when the
shared library is available; the numpy path is the fallback and the oracle.
"""

from __future__ import annotations

import io
import os
import re
import warnings
from collections.abc import Sequence
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Union

import numpy as np

from .faults import fault_point, with_retry
from .metrics import Counters
from .schema import FeatureField, FeatureSchema
from ..telemetry import span


# --------------------------------------------------------------------------
# bad-record policy (Hadoop skip-bad-records, rebuilt natively)
# --------------------------------------------------------------------------

@dataclass
class BadRecordPolicy:
    """What to do with a malformed CSV record (short row, or a numeric
    field that fails to parse — the native parser's ``bad`` contract;
    unknown categorical values encode as -1 and are NOT malformed):

      * ``fail``        — raise, killing the job (the historic behavior)
      * ``skip``        — drop the record, count it
      * ``quarantine``  — drop the record, count it, AND append its raw
        line to ``<quarantine_path>/part-q-00000`` for offline triage
        (the reference substrate's skipped-records output)

    Counters land in the Hadoop-style ``BadRecords`` group: ``Malformed``
    (total seen), ``Skipped``, ``Quarantined``.  Reporting is at-least-
    once across crash+resume: records between the last checkpoint and a
    crash are re-reported when the stream re-reads them.
    """

    policy: str = "fail"
    quarantine_path: Optional[str] = None
    counters: Optional[Counters] = None
    n_bad: int = 0
    # quarantine dir existence is checked once, not per appended record
    # (os.makedirs on every record() measured as pure syscall overhead on
    # heavily-corrupt streams)
    _qdir_ready: bool = dc_field(default=False, repr=False, compare=False)

    POLICIES = ("fail", "skip", "quarantine")

    def __post_init__(self):
        if self.policy not in self.POLICIES:
            raise ValueError(f"badrecords.policy must be one of "
                             f"{self.POLICIES}, got {self.policy!r}")
        if self.policy == "quarantine" and not self.quarantine_path:
            raise ValueError("badrecords.policy=quarantine needs a "
                             "quarantine path")

    @property
    def skips(self) -> bool:
        return self.policy in ("skip", "quarantine")

    def quarantine_file(self) -> str:
        if not self._qdir_ready:
            os.makedirs(self.quarantine_path, exist_ok=True)
            self._qdir_ready = True
        return os.path.join(self.quarantine_path, "part-q-00000")

    def record(self, lines: Sequence[str],
               src_rows: Optional[Sequence[int]] = None) -> None:
        """Report (and for quarantine, persist) a batch of malformed raw
        lines.  Appends, so resumed runs accumulate into one part file.
        The quarantine write happens FIRST (one buffered write call) and
        counters bump only after it succeeds: a write that fails and gets
        the whole chunk retried must not have already inflated the
        tallies (the file itself stays at-least-once — a mid-append fault
        can duplicate lines on retry, exactly like a re-run Hadoop
        task).

        ``src_rows`` (parallel to ``lines``) carries each record's
        absolute SOURCE row index (non-blank line count, the
        checkpoint/resume axis).  This policy ignores it; the columnar
        cache's recording wrapper (io.colcache) persists it so a cached
        replay can honor a mid-cache ``start_row`` cut exactly."""
        n = len(lines)
        if n == 0:
            return
        if self.policy == "quarantine":
            path = self.quarantine_file()
            payload = "".join(line + "\n" for line in lines)

            def write():
                fault_point("artifact_write")
                with open(path, "a") as fh:
                    fh.write(payload)
            with_retry(write, what=f"quarantine append to {path}")
            if self.counters is not None:
                self.counters.increment("BadRecords", "Quarantined", n)
        self.n_bad += n
        if self.counters is not None:
            self.counters.increment("BadRecords", "Malformed", n)
            self.counters.increment("BadRecords", "Skipped", n)


class LazyStringColumn(Sequence):
    """An id/string column decoded on access: joined UTF-8 bytes plus int64
    row offsets, as handed over by the native ingest path.

    Materializing ``n`` python strings costs ~100 ns each — at the 100M-row
    north-star scale that is ~10 s and ~6 GB before training starts, paid
    even when nothing ever reads the ids (NB/RF training does not).  This
    wrapper keeps the column as two flat buffers and decodes per access;
    consumers index, iterate, or compare it exactly like the list the
    python oracle path produces."""

    __slots__ = ("_blob", "_offsets")

    def __init__(self, blob: bytes, offsets: np.ndarray):
        if len(offsets) == 0:
            raise ValueError("offsets must have n+1 entries")
        self._blob = blob
        self._offsets = offsets

    def __len__(self) -> int:
        return len(self._offsets) - 1

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._blob[self._offsets[i]:self._offsets[i + 1]].decode()

    def __iter__(self):
        blob, offs = self._blob, self._offsets
        for i in range(len(self)):
            yield blob[offs[i]:offs[i + 1]].decode()

    def __eq__(self, other):
        if isinstance(other, LazyStringColumn):
            return (len(self) == len(other)
                    and all(a == b for a, b in zip(self, other)))
        if isinstance(other, (list, tuple)):
            return len(self) == len(other) and all(
                a == b for a, b in zip(self, other))
        return NotImplemented

    def __repr__(self):
        return f"LazyStringColumn(n={len(self)})"

    def tolist(self) -> List[str]:
        return list(self)


@dataclass
class ColumnarTable:
    schema: FeatureSchema
    n_rows: int
    # ordinal -> encoded column; int32 codes for categorical, float64 for numeric
    columns: Dict[int, np.ndarray]
    # ordinal -> raw string column for id/string/text fields (host side)
    str_columns: Dict[int, List[str]] = dc_field(default_factory=dict)
    # raw tokenized rows, kept only when the caller needs record echo in outputs
    raw_rows: Optional[List[List[str]]] = None
    # ordinal -> precomputed int32 bin codes for bucketWidth-binned numeric
    # fields (the native ingest emits them during the parse pass; the host
    # floor-divide re-walk costs ~0.2 s/column per 10M rows otherwise)
    binned_cache: Dict[int, np.ndarray] = dc_field(default_factory=dict)

    # ---- views ----
    def column(self, ordinal: int) -> np.ndarray:
        return self.columns[ordinal]

    def class_codes(self) -> np.ndarray:
        return self.columns[self.schema.class_attr_field.ordinal]

    def binned_codes(self, ordinal: int) -> np.ndarray:
        """int32 bin codes in [0, num_bins) for a binned field (categorical code
        or value // bucketWidth - bin_offset)."""
        cached = self.binned_cache.get(ordinal)
        if cached is not None:  # before the O(fields) schema scan
            return cached
        f = self.schema.find_field_by_ordinal(ordinal)
        col = self.columns[ordinal]
        if f.is_categorical:
            return col.astype(np.int32)
        if f.bucket_width is None:
            raise ValueError(f"field {ordinal} has no finite bin alphabet")
        return (col // f.bucket_width).astype(np.int32) - f.bin_offset

    def feature_matrix(self, fields: Optional[Sequence[FeatureField]] = None,
                       dtype=np.float64) -> np.ndarray:
        """(n_rows, F) dense matrix of feature values (categorical as codes)."""
        fields = list(fields if fields is not None else self.schema.feature_fields)
        if not fields:
            return np.zeros((self.n_rows, 0), dtype=dtype)
        return np.stack([self.columns[f.ordinal].astype(dtype) for f in fields], axis=1)

    def binned_feature_matrix(self, fields: Optional[Sequence[FeatureField]] = None
                              ) -> np.ndarray:
        """(n_rows, F) int32 matrix of bin codes for binned feature fields."""
        fields = list(fields if fields is not None else
                      [f for f in self.schema.feature_fields if f.is_binned])
        if not fields:
            return np.zeros((self.n_rows, 0), dtype=np.int32)
        return np.stack([self.binned_codes(f.ordinal) for f in fields], axis=1)

    def take_rows(self, lo: int, hi: int) -> "ColumnarTable":
        """Contiguous row slice [lo, hi) as a new table — the work_slice
        axis of partition-mode jobs (each process keeps its share of the
        test rows).  Encoded columns are numpy views; string columns
        materialize the slice (the consumers of a slice read the ids)."""
        return ColumnarTable(
            schema=self.schema, n_rows=hi - lo,
            columns={k: v[lo:hi] for k, v in self.columns.items()},
            str_columns={k: v[lo:hi] for k, v in self.str_columns.items()},
            raw_rows=self.raw_rows[lo:hi] if self.raw_rows is not None
            else None,
            binned_cache={k: v[lo:hi]
                          for k, v in self.binned_cache.items()})

    @classmethod
    def from_chunks(cls, chunks: Sequence["ColumnarTable"]) -> "ColumnarTable":
        """Assemble contiguous row blocks (same schema, in row order) into
        one table — the inverse of chunked ingest.  Encoded columns and bin
        caches concatenate; string columns concatenate as one joined
        blob+offsets when every block carries the LazyStringColumn form
        (the native chunk reader's output), else as plain lists.  The
        result is byte-identical to loading the whole file at once
        (tests/test_native_csv_fuzz.py proves it on fuzzed schemas)."""
        chunks = list(chunks)
        if not chunks:
            raise ValueError("from_chunks needs at least one chunk")
        schema = chunks[0].schema
        n = sum(c.n_rows for c in chunks)
        columns = {o: np.concatenate([c.columns[o] for c in chunks])
                   for o in chunks[0].columns}
        binned: Dict[int, np.ndarray] = {}
        for o in chunks[0].binned_cache:
            if all(o in c.binned_cache for c in chunks):
                arr = np.concatenate([c.binned_cache[o] for c in chunks])
                # keep the native path's freeze-by-reference contract
                arr.flags.writeable = False
                binned[o] = arr
        str_columns: Dict[int, List[str]] = {}
        for o in chunks[0].str_columns:
            cols = [c.str_columns[o] for c in chunks]
            if all(isinstance(c, LazyStringColumn) for c in cols):
                str_columns[o] = _concat_lazy_strings(cols)
            else:
                merged: List[str] = []
                for c in cols:
                    merged.extend(c)
                str_columns[o] = merged
        raw = None
        if all(c.raw_rows is not None for c in chunks):
            raw = [r for c in chunks for r in c.raw_rows]
        return cls(schema=schema, n_rows=n, columns=columns,
                   str_columns=str_columns, raw_rows=raw,
                   binned_cache=binned)

    def pad_to_multiple(self, multiple: int) -> "PaddedTable":
        """Pad all encoded columns with zeros to a row count divisible by
        ``multiple`` (the mesh data-axis size) and return the padded view with
        its validity mask."""
        n = self.n_rows
        n_pad = (-n) % multiple
        total = n + n_pad
        if n_pad == 0:
            # already aligned: share the arrays — concatenating with an
            # empty tail still deep-copies every column, which measured
            # 21 s of the 86 s 100M-row NB train (single-device mesh
            # always lands here)
            mask = np.ones((n,), dtype=bool)
            return PaddedTable(schema=self.schema, n_rows=n,
                               columns=dict(self.columns),
                               str_columns=self.str_columns,
                               raw_rows=self.raw_rows,
                               binned_cache=dict(self.binned_cache),
                               valid_mask=mask, n_valid=n)
        cols = {}
        for k, v in self.columns.items():
            pad_val = 0
            cols[k] = np.concatenate([v, np.full((n_pad,), pad_val, dtype=v.dtype)])
        mask = np.zeros((total,), dtype=bool)
        mask[:n] = True
        binned = {}
        for k, v in self.binned_cache.items():
            # parity with computing codes on the zero-padded column:
            # bin code of 0.0 is -bin_offset (masked out downstream anyway)
            off = self.schema.find_field_by_ordinal(k).bin_offset
            binned[k] = np.concatenate(
                [v, np.full((n_pad,), -off, dtype=v.dtype)])
        return PaddedTable(schema=self.schema, n_rows=total, columns=cols,
                           str_columns=self.str_columns, raw_rows=self.raw_rows,
                           binned_cache=binned,
                           valid_mask=mask, n_valid=n)


@dataclass
class PaddedTable(ColumnarTable):
    valid_mask: np.ndarray = None  # type: ignore[assignment]
    n_valid: int = 0


def _concat_lazy_strings(cols: Sequence[LazyStringColumn]
                         ) -> LazyStringColumn:
    """Join per-chunk blob+offset string columns into one without decoding
    a single row: blobs concatenate, each chunk's offsets shift by the
    bytes before it."""
    blobs = [c._blob for c in cols]
    parts = [np.asarray(cols[0]._offsets, dtype=np.int64)]
    base = len(blobs[0])
    for c in cols[1:]:
        offs = np.asarray(c._offsets, dtype=np.int64)
        parts.append(offs[1:] + base)
        base += len(c._blob)
    return LazyStringColumn(b"".join(blobs), np.concatenate(parts))


def _filter_lazy_strings(col, keep: np.ndarray):
    """Drop the rows where ``keep`` is False from a blob+offsets string
    column (the native chunk reader's form) without decoding kept rows;
    plain lists filter by mask.  Bad rows are sparse, so the blob is
    rebuilt from the contiguous runs BETWEEN dropped rows — O(n_bad)
    slices, not one slice per kept row (a multi-million-row block with
    one bad record must not pay millions of tiny allocations)."""
    if not isinstance(col, LazyStringColumn):
        return [v for v, k in zip(col, keep) if k]
    offs = np.asarray(col._offsets, dtype=np.int64)
    n = len(keep)
    parts = []
    lo = 0
    for b in np.nonzero(~keep)[0]:
        if b > lo:
            parts.append(col._blob[offs[lo]:offs[b]])
        lo = int(b) + 1
    if lo < n:
        parts.append(col._blob[offs[lo]:offs[n]])
    idx = np.nonzero(keep)[0]
    lens = offs[1:] - offs[:-1]
    new_offs = np.zeros(len(idx) + 1, dtype=np.int64)
    np.cumsum(lens[idx], out=new_offs[1:])
    return LazyStringColumn(b"".join(parts), new_offs)


def _bad_row_checker(schema: FeatureSchema):
    """Per-row malformedness test matching the native parser's ``bad``
    contract: short row (any schema field's ordinal missing) or a numeric
    field that fails ``float()``.  (The python oracle's float grammar is
    slightly laxer than the C one — '1_0', unicode digits — exactly as on
    the fail path; genuinely corrupt fields fail both.)"""
    need = max((f.ordinal for f in schema.fields), default=-1)
    numeric_ords = [f.ordinal for f in schema.fields if f.is_numeric]

    def bad(r: List[str]) -> bool:
        if len(r) <= need:
            return True
        for o in numeric_ords:
            try:
                float(r[o])
            except (TypeError, ValueError):
                return True
        return False
    return bad


def _make_splitter(delim_regex: str):
    """ONE line-splitter for every parse path (tokenize, policy filter,
    chunk iterators): literal fast path when the regex is a plain string,
    compiled re.split otherwise."""
    if re.escape(delim_regex) == delim_regex:
        return lambda line: line.split(delim_regex)
    return re.compile(delim_regex).split


def _tokenize(text: str, delim_regex: str) -> List[List[str]]:
    """Split lines on the reference's field.delim.regex (usually a plain ',')."""
    split = _make_splitter(delim_regex)
    return [split(line) for line in text.splitlines() if line.strip()]


# Contract: categorical values are trimmed of exactly these six ASCII
# whitespace bytes (not unicode whitespace) before vocab lookup, so the
# native C++ encoders — the CSV ingest (io/csv_native.cpp) and the
# serving wire assembler (io/serve_native.cpp), both alternate producers
# of ColumnarTable columns — are bit-identical to this python oracle.
CATEGORICAL_TRIM = " \t\r\n\v\f"


def encode_rows(rows: List[List[str]], schema: FeatureSchema,
                keep_raw: bool = False) -> ColumnarTable:
    """Encode tokenized rows into a ColumnarTable per the schema.

    This is the encode CONTRACT every producer matches: categorical ->
    ``vocab.get(value.strip(CATEGORICAL_TRIM), -1)`` int32, numeric ->
    ``float(value)`` float64, everything else a host string column; a
    short row (any schema ordinal missing) raises.  The native serving
    wire codec (io/native_wire.WireCodec) assembles the same columns
    straight from socket bytes and FALLS BACK here whenever it is not
    bit-certain (tests/test_native_wire_fuzz.py holds the two equal)."""
    n = len(rows)
    columns: Dict[int, np.ndarray] = {}
    str_columns: Dict[int, List[str]] = {}
    for f in schema.fields:
        o = f.ordinal
        if f.is_categorical:
            vocab = {v: i for i, v in enumerate(f.cardinality or [])}
            col = np.fromiter(
                (vocab.get(r[o].strip(CATEGORICAL_TRIM), -1) for r in rows),
                dtype=np.int32, count=n)
            columns[o] = col
        elif f.is_numeric:
            col = np.fromiter((float(r[o]) for r in rows), dtype=np.float64, count=n)
            columns[o] = col
        else:  # id / string / text: host side only
            str_columns[o] = [r[o] for r in rows]
    return ColumnarTable(schema=schema, n_rows=n, columns=columns,
                         str_columns=str_columns,
                         raw_rows=rows if keep_raw else None)


def load_csv(source: Union[str, io.TextIOBase], schema: FeatureSchema,
             delim_regex: str = ",", keep_raw: bool = False,
             use_native: bool = True,
             bad_records: Optional[BadRecordPolicy] = None,
             cache=None) -> ColumnarTable:
    """Load a CSV file (path or file object) into a ColumnarTable.

    Uses the native C++ tokenizer/encoder when available and the delimiter is a
    literal single character; otherwise the pure-python path.

    ``bad_records`` with a skipping policy (skip/quarantine) drops
    malformed records instead of raising; the monolithic load runs the
    python oracle path for it (per-record filtering needs the raw lines —
    the streaming path, ``iter_csv_chunks``, keeps the native fast path
    under the same policy).

    ``cache`` (an ``io.colcache.CachePolicy``) routes the load through
    the chunked stream so the binary columnar sidecar is used/built; the
    assembled table is byte-identical to the direct load
    (``ColumnarTable.from_chunks`` contract).  Only path sources without
    ``keep_raw`` can be cached: ``require`` refuses anything else, the
    softer policies fall through to the plain load.
    """
    skipping = bad_records is not None and bad_records.skips
    if cache is not None and getattr(cache, "enabled", False):
        cacheable = isinstance(source, str) and not keep_raw
        if not cacheable and cache.policy == "require":
            raise ValueError(
                "cache.policy=require needs a path source without "
                "keep_raw (raw-row echo and text streams are not cached)")
        if cacheable:
            chunks = list(iter_csv_chunks(
                source, schema, delim_regex, use_native=use_native,
                bad_records=bad_records, cache=cache))
            if not chunks:
                return encode_rows([], schema)
            return ColumnarTable.from_chunks(chunks)
    if isinstance(source, str):
        if use_native and len(delim_regex) == 1 and not skipping:
            try:
                from ..io.native_csv import native_load_csv
                t = native_load_csv(source, schema, delim_regex, keep_raw=keep_raw)
                if t is not None:
                    return t
            except Exception:
                # Includes ValueError: the C++ float grammar is stricter than
                # python's (no '1_0', no unicode digits), so re-parse with the
                # python oracle — behavior must not depend on whether the .so
                # built.  Genuinely malformed fields then raise from the
                # python path below; infra failures just take the slow path.
                pass
        with open(source, "r") as fh:
            text = fh.read()
    else:
        text = source.read()
    return load_csv_text(text, schema, delim_regex, keep_raw=keep_raw,
                         bad_records=bad_records)


def load_csv_text(text: str, schema: FeatureSchema, delim_regex: str = ",",
                  keep_raw: bool = False,
                  bad_records: Optional[BadRecordPolicy] = None
                  ) -> ColumnarTable:
    if bad_records is None or not bad_records.skips:
        return encode_rows(_tokenize(text, delim_regex), schema,
                           keep_raw=keep_raw)
    split = _make_splitter(delim_regex)
    is_bad = _bad_row_checker(schema)
    rows: List[List[str]] = []
    bad_lines: List[str] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        r = split(line)
        if is_bad(r):
            bad_lines.append(line)
        else:
            rows.append(r)
    table = encode_rows(rows, schema, keep_raw=keep_raw)
    bad_records.record(bad_lines)  # side effects after the fallible encode
    return table


# --------------------------------------------------------------------------
# chunked / streaming ingest (the CSV->device pipeline's parse stage)
# --------------------------------------------------------------------------

def count_source_rows(path: str) -> int:
    """Total SOURCE rows (non-blank lines) of a CSV — the denominator of
    the sharded-ingest split arithmetic when the native reader (which
    indexes the file and knows its row count up front) is unavailable.
    One streaming text pass, no tokenization."""
    n = 0
    with open(path, "r") as fh:
        for line in fh:
            if line.strip():
                n += 1
    return n


def _iter_csv_chunks_python(path: str, schema: FeatureSchema,
                            delim_regex: str, chunk_rows: int,
                            skip_rows: int = 0,
                            bad_records: Optional[BadRecordPolicy] = None,
                            stop_row: Optional[int] = None):
    """Oracle-equivalent streamed parse: read the file line by line (never
    the whole text in memory), encode every ``chunk_rows`` non-blank rows.
    ``skip_rows`` resumes after a partially-consumed native stream (or a
    checkpoint): it counts SOURCE rows (non-blank lines), the same axis
    every yielded chunk reports via ``source_row_end``.  ``stop_row``
    (exclusive, same axis) ends the stream early — the sharded-ingest
    upper bound."""
    split = _make_splitter(delim_regex)
    skipping = bad_records is not None and bad_records.skips
    is_bad = _bad_row_checker(schema) if skipping else None
    rows: List[List[str]] = []
    bad_lines: List[str] = []
    bad_srcs: List[int] = []   # absolute 0-based source row per bad line
    consumed = 0   # non-blank source lines consumed, absolute
    block_idx = 0
    with open(path, "r") as fh:
        for line in fh:
            line = line.rstrip("\r\n")  # same record set as str.splitlines
            if not line.strip():        # for \n / \r\n terminated CSVs
                continue
            if stop_row is not None and consumed >= stop_row:
                break  # this line's 0-based source index == consumed
            consumed += 1
            if consumed <= skip_rows:
                continue
            r = split(line)
            if skipping and is_bad(r):
                bad_lines.append(line)
                bad_srcs.append(consumed - 1)
                continue
            rows.append(r)
            if len(rows) >= chunk_rows:
                fault_point("chunk_encode", block_idx)
                with span("parse.chunk", cat="parse", block=block_idx,
                          rows=len(rows), parser="python"):
                    chunk = encode_rows(rows, schema)
                if bad_lines:
                    bad_records.record(bad_lines, src_rows=bad_srcs)
                    bad_lines, bad_srcs = [], []
                chunk.source_row_end = consumed
                yield chunk
                rows = []
                block_idx += 1
    if rows or bad_lines:
        fault_point("chunk_encode", block_idx)
        if rows:
            with span("parse.chunk", cat="parse", block=block_idx,
                      rows=len(rows), parser="python"):
                chunk = encode_rows(rows, schema)
        else:
            chunk = None
        if bad_lines:
            bad_records.record(bad_lines, src_rows=bad_srcs)
        if chunk is not None:
            chunk.source_row_end = consumed
            yield chunk


def iter_csv_chunks(path: str, schema: FeatureSchema,
                    delim_regex: str = ",", chunk_rows: int = 1 << 22,
                    use_native: bool = True,
                    bad_records: Optional[BadRecordPolicy] = None,
                    start_row: int = 0, cache=None,
                    shard=None, stop_row: Optional[int] = None):
    """Yield a CSV as ColumnarTable row blocks of up to ``chunk_rows`` rows
    — the parse stage of the streaming CSV->device ingest pipeline.  Host
    memory holds one encoded block at a time instead of the whole dataset
    (what caps the monolithic path well short of the 100M-row north star).

    Uses the native chunk reader (io.native_csv.NativeCsvReader) when
    available; per the load_csv contract, behavior must not depend on
    whether the .so built, so any native failure — including a mid-stream
    ValueError from the C float grammar being stricter than python's —
    resumes the stream from the python oracle at the exact row already
    reached (with a degradation warning).  Blocks concatenate
    (ColumnarTable.from_chunks) to the same table load_csv produces.

    Fault tolerance: each native chunk read passes through
    ``core.faults.with_retry`` (transient OSError/MemoryError retries
    with backoff before the python fallback engages), ``bad_records``
    applies the skip/quarantine policy per block, and ``start_row``
    restarts the stream at a SOURCE row index (non-blank line count) —
    the checkpoint/resume contract; every yielded chunk reports its own
    ``source_row_end`` on that axis.

    ``cache`` (an ``io.colcache.CachePolicy``) slots the write-once
    binary columnar sidecar under this stream: ``use``/``build``/
    ``require`` serve an intact fresh sidecar at memcpy speed (parse
    skipped entirely), ``build`` additionally emits the sidecar during a
    cold full pass; bad-record policy, quarantine bytes, counters, and
    ``start_row`` resume behave bit-identically either way (the sidecar
    persists the per-chunk bad-record manifest), and a torn sidecar
    degrades to this CSV parse with a warning.

    ``shard=(index, count)`` is the multi-host ingest mode: this stream
    yields ONLY the row-range shard ``index`` of ``count`` — split points
    from ``parallel.distributed.shard_rows`` over the total source-row
    count (the native reader knows it up front; the python path pays one
    cheap line-count pass), aligned to the ``chunk_rows`` grid so every
    shard consumes whole ingest blocks and the per-shard streams union to
    exactly the single-host stream (rows, ``source_row_end`` accounting,
    and bad-record tallies all partition — pinned by
    tests/test_sharded_stream.py).  Composes with ``start_row``: a
    resumed shard restarts at max(its own range start, start_row).  A
    cache hit shards too, by source-row arithmetic over the sidecar's own
    chunk grid.  ``stop_row`` (exclusive source-row bound) is the
    lower-level knob shard mode is built on; passing both is refused."""
    if chunk_rows <= 0:
        raise ValueError(f"chunk_rows must be positive, got {chunk_rows}")
    if start_row < 0:
        raise ValueError(f"start_row must be >= 0, got {start_row}")
    if shard is not None and stop_row is not None:
        raise ValueError("pass shard= or stop_row=, not both (shard "
                         "computes its own bounds)")
    if cache is not None and getattr(cache, "enabled", False):
        from ..io.colcache import iter_csv_chunks_cached
        yield from iter_csv_chunks_cached(
            path, schema, delim_regex, chunk_rows, use_native,
            bad_records, int(start_row), cache, shard=shard,
            stop_row=stop_row)
        return
    done_rows = int(start_row)
    stop: Optional[int] = int(stop_row) if stop_row is not None else None
    reader = None
    if use_native and len(delim_regex) == 1:
        try:
            from ..io.native_csv import native_open_csv
            reader = native_open_csv(path, schema, delim_regex)
        except Exception:
            reader = None
    if shard is not None:
        from ..parallel.distributed import shard_rows as _split_rows
        total = reader.n_rows if reader is not None \
            else count_source_rows(path)
        lo, hi = _split_rows(total, int(shard[0]), int(shard[1]),
                             chunk_rows)
        done_rows = max(done_rows, lo)
        stop = hi
    if reader is not None:
        native_done = False
        with reader:  # closed on EVERY exit path, incl. GeneratorExit
            n = reader.n_rows if stop is None else min(reader.n_rows, stop)
            block_idx = 0
            try:
                while done_rows < n:
                    take = min(chunk_rows, n - done_rows)

                    def read_block(lo=done_rows, m=take, i=block_idx):
                        fault_point("chunk_read", i)
                        return reader.parse_chunk(
                            lo, m, bad_records=bad_records)

                    with span("parse.chunk", cat="parse", block=block_idx,
                              rows=take, parser="native"):
                        chunk = with_retry(
                            read_block,
                            what=f"chunk read [{done_rows}, "
                                 f"{done_rows + take}) of {path!r}")
                    chunk.source_row_end = done_rows + take
                    yield chunk
                    done_rows += take
                    block_idx += 1
                native_done = True
            except (ValueError, MemoryError, OSError) as exc:
                # python oracle resumes at done_rows below
                warnings.warn(
                    f"native CSV reader failed mid-stream at row "
                    f"{done_rows} of {path!r} ({type(exc).__name__}: "
                    f"{exc}); degrading to the python parser",
                    RuntimeWarning)
        if native_done:
            return
    yield from _iter_csv_chunks_python(path, schema, delim_regex,
                                       chunk_rows, skip_rows=done_rows,
                                       bad_records=bad_records,
                                       stop_row=stop)


def prefetch_chunks(chunks, depth: int = 1, stats: Optional[dict] = None,
                    stage_fn=None, wait_key: str = "parse_s",
                    stage_key: str = "transfer_s",
                    consumer_wait_key: Optional[str] = "queue_wait_s",
                    thread_name: str = "avenir-ingest-prefetch"):
    """Run a chunk iterator in a background thread with a bounded queue:
    the producer parses block i+1 while the consumer transfers/computes
    block i — the double-buffering that overlaps the ingest pipeline's
    stages.  ``depth`` bounds in-flight blocks (memory = depth + 1 blocks).

    ``stage_fn`` (optional) runs on every block IN THE PRODUCER THREAD
    after it is pulled from the source — the device-staging hook: it
    ``device_put``s block i+1 onto its own committed buffers while the
    consumer computes on block i (see :func:`stage_chunks`).

    Phase accounting (``stats``, all keys initialized to 0.0 so the
    overlap decomposition downstream never KeyErrors):
      * ``stats[wait_key]``   (default ``parse_s``)    — time pulling from
        the source iterator (the parse, when the source is a raw reader;
        upstream-queue wait when chained behind another prefetch layer);
      * ``stats[stage_key]``  (default ``transfer_s``) — time inside
        ``stage_fn`` (0.0 when no stage_fn);
      * ``stats[consumer_wait_key]`` (default ``queue_wait_s``) —
        CONSUMER-side blocking time on the queue: >0 means the consumer
        outran the producer (the pipeline is parse/transfer-bound), ~0
        means blocks were always ready (compute-bound).  Together with
        the consumer's own compute timing this decomposes
        ``overlap_fraction`` into parse vs transfer vs compute.  When
        this layer feeds ANOTHER prefetch/stage layer (parse -> stage
        chains), pass ``consumer_wait_key=None`` here: the downstream
        layer's producer already times this layer's q.get as its own
        upstream wait, and booking the same wall time twice would
        misattribute parse starvation as final-consumer starvation.

    Producer failures are re-raised on the consumer side in stream order
    (exactly once), and the moment one happens the producer ALSO records
    ``stats['producer_error']`` = ``"ExcType: message"`` and
    ``stats['producer_error_thread']`` = the producer thread's name —
    so anything watching the stats dict (an operator polling a stuck
    job, the queue_wait decomposition) can tell a CRASHED producer from
    a merely slow one without waiting for the consumer to drain the
    queue and hit the raise."""
    import queue
    import threading
    import time as _time

    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if stats is not None:
        for key in (wait_key, stage_key, consumer_wait_key or "queue_wait_s"):
            stats.setdefault(key, 0.0)
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    end = object()
    failure: List[BaseException] = []
    # set when the consumer abandons the generator mid-stream (an exception
    # downstream, e.g. device OOM): a producer blocked on a full queue must
    # not hang forever holding parsed blocks and the open mmap
    stop = threading.Event()

    def put_until_stopped(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def produce():
        it = None
        try:
            # inside the try: a raising __iter__ must surface on the
            # consumer side like any mid-stream failure, not kill the
            # thread before `end` is queued (which would hang the consumer
            # forever on q.get())
            it = iter(chunks)
            while not stop.is_set():
                t0 = _time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    break
                finally:
                    if stats is not None:
                        stats[wait_key] = (stats.get(wait_key, 0.0)
                                           + _time.perf_counter() - t0)
                if stage_fn is not None:
                    t0 = _time.perf_counter()
                    try:
                        item = stage_fn(item)
                    finally:
                        if stats is not None:
                            stats[stage_key] = (stats.get(stage_key, 0.0)
                                                + _time.perf_counter() - t0)
                if not put_until_stopped(item):
                    break
        except BaseException as exc:  # surfaced on the consumer side
            failure.append(exc)
            if stats is not None:
                # visible IMMEDIATELY (not at join): a crashed producer
                # and a slow one otherwise look identical from the
                # consumer's queue_wait accounting until the raise lands
                stats["producer_error"] = f"{type(exc).__name__}: {exc}"
                stats["producer_error_thread"] = thread_name
        finally:
            close = getattr(it, "close", None)
            if close is not None:  # release the source NOW (native reader
                try:               # mmap), not at some later GC pass
                    close()
                except Exception:
                    pass
            put_until_stopped(end)

    threading.Thread(target=produce, daemon=True,
                     name=thread_name).start()
    try:
        while True:
            t0 = _time.perf_counter()
            item = q.get()
            if stats is not None and consumer_wait_key is not None:
                stats[consumer_wait_key] = (stats.get(consumer_wait_key, 0.0)
                                            + _time.perf_counter() - t0)
            if item is end:
                if failure:
                    raise failure[0]
                return
            yield item
    finally:
        stop.set()
        try:  # unblock a producer mid-put; it exits via its stop check
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def stage_chunks(blocks, stage_fn, depth: int = 2,
                 stats: Optional[dict] = None):
    """Two-deep device staging pipeline (TPU_NOTES §18): a staging thread
    runs ``stage_fn(block)`` — host encode + ``device_put`` — for block
    i+1 onto its own committed buffers while the consumer computes on
    block i.  ``depth=2`` is classic double buffering (up to two staged
    blocks queued plus one in flight inside stage_fn).

    Chain behind :func:`prefetch_chunks` for the full three-stage
    pipeline: parse (prefetch thread) || transfer (staging thread) ||
    compute (consumer).  Stage time lands in ``stats['transfer_s']``,
    upstream wait (which INCLUDES the parse layer's queue) in
    ``stats['stage_wait_s']``, and final-consumer queue blocking in
    ``stats['queue_wait_s']``.  Construct the upstream parse layer with
    ``consumer_wait_key=None`` so the stage thread's wait on it is not
    double-booked as consumer starvation.

    Exactly-once failure propagation, thread shutdown on consumer
    abandonment, and upstream ``close()`` follow prefetch_chunks.

    Each staged block records an ``h2d.stage`` telemetry span (no-op with
    no tracer installed), so the staging thread shows up as its own lane
    on the Chrome timeline next to parse and compute."""
    def staged(block, _fn=stage_fn):
        with span("h2d.stage", cat="transfer"):
            return _fn(block)

    return prefetch_chunks(blocks, depth=depth, stats=stats,
                           stage_fn=staged, wait_key="stage_wait_s",
                           stage_key="transfer_s",
                           thread_name="avenir-ingest-stage")
