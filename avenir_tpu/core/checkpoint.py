"""Step-indexed checkpoint/resume for iterative jobs.

The reference's checkpointing is structural: every iteration writes a durable
HDFS artifact and any job resumes from the last one (SURVEY.md §5 —
decision-path JSON per tree level, LR coefficient history, k-means centroid
files, bandit model state).  This manager gives the rebuilt iterative drivers
one uniform version of that contract: numbered step directories holding an
npz of array state plus a JSON sidecar for metadata, atomic via
write-then-rename, with retention and latest-step discovery.

Crash safety: ``save`` is atomic (tmp dir + rename), and discovery is
corruption-tolerant — a step dir whose ``state.npz`` or ``meta.json`` is
missing or unreadable (torn write, disk fault) is never selected as
latest; ``latest_step``/``restore`` fall back to the newest INTACT step
with a warning, so a fault at checkpoint time costs at most one step of
progress, never the whole resume.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .faults import fault_point


class CheckpointManager:
    def __init__(self, base_dir: str, keep: int = 3):
        """keep: retain at most this many newest steps (0 = keep all)."""
        self.base_dir = base_dir
        self.keep = keep
        os.makedirs(base_dir, exist_ok=True)

    # ---- paths ----
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.base_dir, f"step_{step:08d}")

    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.base_dir):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def _read_step(self, step: int
                   ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        """Fully read one step (arrays decompressed — a corrupt member
        fails here, not later mid-restore)."""
        d = self._step_dir(step)
        with np.load(os.path.join(d, "state.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        with open(os.path.join(d, "meta.json")) as fh:
            meta = json.load(fh)
        return step, arrays, meta

    def is_intact(self, step: int) -> bool:
        """True when the step's state.npz AND meta.json open and parse — a
        header-level probe (npz zip directory + JSON), NOT a full array
        decompress, so the common latest_step-then-restore(step) pattern
        reads the state once, not twice.  Torn writes corrupt the zip
        directory (it trails the file) and fail here; the pathological
        valid-directory/corrupt-member case still raises at restore."""
        d = self._step_dir(step)
        try:
            with np.load(os.path.join(d, "state.npz")) as z:
                z.files
            with open(os.path.join(d, "meta.json")) as fh:
                json.load(fh)
            return True
        except Exception:
            return False

    def latest_step(self) -> Optional[int]:
        """Newest INTACT step — a torn or corrupt newest dir is skipped
        with a warning instead of being handed to restore."""
        for s in reversed(self.steps()):
            if self.is_intact(s):
                return s
            warnings.warn(
                f"checkpoint step {s} in {self.base_dir!r} is missing or "
                f"unreadable (torn write?); falling back to an older step",
                RuntimeWarning)
        return None

    # ---- save/restore ----
    def save(self, step: int, arrays: Dict[str, np.ndarray],
             meta: Optional[Dict[str, Any]] = None) -> str:
        """Atomically write arrays (+ JSON-serializable meta) as ``step``."""
        fault_point("checkpoint_save", step)
        final = self._step_dir(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, "state.npz"),
                 **{k: np.asarray(v) for k, v in arrays.items()})
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta or {}, fh)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._retain()
        return final

    def restore(self, step: Optional[int] = None
                ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        """(step, arrays, meta) for ``step`` or the newest intact step;
        raises FileNotFoundError when nothing (intact) is saved."""
        if step is not None:
            return self._read_step(step)
        candidates = self.steps()
        if not candidates:
            raise FileNotFoundError(f"no checkpoints in {self.base_dir!r}")
        last_exc: Optional[Exception] = None
        for s in reversed(candidates):
            try:
                return self._read_step(s)
            except Exception as exc:
                warnings.warn(
                    f"checkpoint step {s} in {self.base_dir!r} failed to "
                    f"restore ({type(exc).__name__}: {exc}); trying the "
                    f"previous step", RuntimeWarning)
                last_exc = exc
        raise FileNotFoundError(
            f"no intact checkpoints in {self.base_dir!r} "
            f"({len(candidates)} corrupt)") from last_exc

    def _retain(self) -> None:
        if self.keep <= 0:
            return
        steps = self.steps()
        for s in steps[:-self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
