"""Step-indexed checkpoint/resume for iterative jobs.

The reference's checkpointing is structural: every iteration writes a durable
HDFS artifact and any job resumes from the last one (SURVEY.md §5 —
decision-path JSON per tree level, LR coefficient history, k-means centroid
files, bandit model state).  This manager gives the rebuilt iterative drivers
one uniform version of that contract: numbered step directories holding an
npz of array state plus a JSON sidecar for metadata, atomic via
write-then-rename, with retention and latest-step discovery.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class CheckpointManager:
    def __init__(self, base_dir: str, keep: int = 3):
        """keep: retain at most this many newest steps (0 = keep all)."""
        self.base_dir = base_dir
        self.keep = keep
        os.makedirs(base_dir, exist_ok=True)

    # ---- paths ----
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.base_dir, f"step_{step:08d}")

    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.base_dir):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    # ---- save/restore ----
    def save(self, step: int, arrays: Dict[str, np.ndarray],
             meta: Optional[Dict[str, Any]] = None) -> str:
        """Atomically write arrays (+ JSON-serializable meta) as ``step``."""
        final = self._step_dir(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, "state.npz"),
                 **{k: np.asarray(v) for k, v in arrays.items()})
        with open(os.path.join(tmp, "meta.json"), "w") as fh:
            json.dump(meta or {}, fh)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._retain()
        return final

    def restore(self, step: Optional[int] = None
                ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, Any]]:
        """(step, arrays, meta) for ``step`` or the latest; raises
        FileNotFoundError when nothing is saved."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.base_dir!r}")
        d = self._step_dir(step)
        with np.load(os.path.join(d, "state.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        with open(os.path.join(d, "meta.json")) as fh:
            meta = json.load(fh)
        return step, arrays, meta

    def _retain(self) -> None:
        if self.keep <= 0:
            return
        steps = self.steps()
        for s in steps[:-self.keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
