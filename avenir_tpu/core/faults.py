"""Fault tolerance primitives: retry/backoff + deterministic fault injection.

The reference lineage (Hadoop) gets skip-bad-records, task retry, and
durable per-iteration artifacts from the substrate; the rebuilt native
pipeline needs the same guarantees in-process.  This module provides the
two substrate pieces everything else composes:

  * :func:`with_retry` — bounded exponential-backoff retry of a callable,
    for transient ``OSError``/``MemoryError`` on chunk reads
    (core/table.iter_csv_chunks) and artifact writes (core/artifacts).
    The Hadoop analogue is ``mapreduce.map.maxattempts``.
  * :class:`FaultInjector` — a deterministic, spec-driven injector used
    by the robustness tests (and by operators, via the
    ``AVENIR_TPU_FAULTS`` env hook) to prove the retry/skip/resume story
    end to end.  Instrumented sites call :func:`fault_point`; with no
    injector installed that is one module-global ``is None`` check.

Fault spec grammar (comma/semicolon separated entries)::

    <op>@<index|*>=<action>[x<times>]

    chunk_read@2=raise:OSError        one OSError on native chunk read #2
    chunk_read@3=raise:RuntimeErrorx9 a "crash" (not retried, not absorbed)
    chunk_read@*=delay:0.01x5         50 ms stall on the first 5 reads
    artifact_write@0=raise:OSError    transient write failure

``index`` counts calls to the op's fault point (0-based, one count per
call, retries included).  ``times`` bounds how often the spec fires
(default 1 — "fail once, then heal", the classic transient fault).

Instrumented ops: ``chunk_read`` (native chunk parse), ``chunk_encode``
(python-oracle chunk parse), ``artifact_write`` (part-file/JSON writes),
``checkpoint_save`` (CheckpointManager.save), ``registry_publish``
(serving ModelRegistry.publish array payload write), ``cache_write``
(columnar-cache chunk emit — a fault abandons the build with a warning,
never the training pass), ``cache_read`` (columnar-cache chunk load — a
fault degrades the stream to CSV parse with a warning), and the broker
write-ahead journal trio (io/qjournal, TPU_NOTES §29): ``journal_write``
(segment append + checkpoint write — a fault degrades the shard to
in-memory with a warning, availability over durability),
``journal_fsync`` (the fsync-mode flush), ``journal_replay`` (restart
recovery entry — a fault/torn tail recovers the intact prefix with a
warning, never a corrupt record).

The retrain controller (control/controller.py, TPU_NOTES §26) names its
five stages as fault points for the chaos-drill lane: ``retrain_build``
(stage entry + once per training block), ``candidate_validate``,
``registry_publish`` (stage entry, the registry's own payload-write
point, and post-publish/pre-journal — the double-publish window),
``fleet_swap``, ``rollback``.  A ``raise:RuntimeError`` at any of them is
the "controller crashed here" drill; the journal contract says a new
controller resumes the cycle without double-publishing or half-swapping.
"""

from __future__ import annotations

import os
import random
import threading
import time
import warnings
from dataclasses import dataclass, field as dc_field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# exception classes an injected spec may raise (a whitelist: the spec
# string is operator input, never eval'd)
_RAISABLE = {
    "OSError": OSError,
    "IOError": OSError,
    "MemoryError": MemoryError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
    "TimeoutError": TimeoutError,
}


class InjectedFault(RuntimeError):
    """Default exception for ``raise:`` specs without a recognized class."""


@dataclass
class FaultSpec:
    op: str
    index: Optional[int]          # None == '*' (every call)
    action: str                   # 'raise' | 'delay'
    exc: type = InjectedFault
    delay_s: float = 0.0
    times: int = 1                # how many firings remain
    fired: int = 0

    @classmethod
    def parse(cls, entry: str) -> "FaultSpec":
        head, _, action = entry.strip().partition("=")
        op, _, idx = head.partition("@")
        if not op or not action:
            raise ValueError(f"bad fault spec {entry!r} "
                             "(want op@index=action[xN])")
        times = 1
        if "x" in action:
            base, _, n = action.rpartition("x")
            if n.isdigit():
                action, times = base, int(n)
        index = None if idx in ("", "*") else int(idx)
        kind, _, arg = action.partition(":")
        if kind == "raise":
            return cls(op=op, index=index, action="raise",
                       exc=_RAISABLE.get(arg, InjectedFault), times=times)
        if kind == "delay":
            return cls(op=op, index=index, action="delay",
                       delay_s=float(arg or 0.01), times=times)
        raise ValueError(f"bad fault action {action!r} in {entry!r} "
                         "(want raise:<Exc> or delay:<seconds>)")


class FaultInjector:
    """Deterministic spec-driven fault source.  Each op keeps a call
    counter; a spec fires when its index matches the op's current call
    number (or is '*'), at most ``times`` times.  Thread-safe: fault
    points run inside the prefetch producer thread."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.log: List[Tuple[str, int, str]] = []  # (op, call, action)

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultInjector":
        entries = [e for part in spec.replace(";", ",").split(",")
                   if (e := part.strip())]
        return cls([FaultSpec.parse(e) for e in entries], seed=seed)

    def fire(self, op: str, index: Optional[int] = None) -> None:
        with self._lock:
            call = self._counts.get(op, 0)
            self._counts[op] = call + 1
            at = call if index is None else index
            due = []
            for s in self.specs:
                if (s.op == op and s.fired < s.times
                        and (s.index is None or s.index == at)):
                    s.fired += 1
                    due.append(s)
                    self.log.append((op, at, s.action))
        for s in due:  # act outside the lock (sleep/raise)
            if s.action == "delay":
                time.sleep(s.delay_s)
            elif s.action == "raise":
                raise s.exc(f"injected fault: {op}@{at}")


_injector: Optional[FaultInjector] = None


def install(injector: Optional[FaultInjector]) -> None:
    global _injector
    _injector = injector


def uninstall() -> None:
    install(None)


def current() -> Optional[FaultInjector]:
    return _injector


def fault_point(op: str, index: Optional[int] = None) -> None:
    """Instrumentation hook: no-op unless an injector is installed."""
    if _injector is not None:
        _injector.fire(op, index)


# env hook: AVENIR_TPU_FAULTS installs an injector at import time, so CLI
# runs can be fault-tested without code changes (documented TPU_NOTES §15)
if os.environ.get("AVENIR_TPU_FAULTS"):
    install(FaultInjector.parse(os.environ["AVENIR_TPU_FAULTS"]))


# --------------------------------------------------------------------------
# retry/backoff
# --------------------------------------------------------------------------

RETRY_ATTEMPTS = int(os.environ.get("AVENIR_TPU_RETRY_ATTEMPTS", "3"))
RETRY_BASE_S = float(os.environ.get("AVENIR_TPU_RETRY_BASE_S", "0.05"))

# transient by default: a chunk read hit by an IO hiccup or an allocation
# spike should be re-attempted before the job gives up on the fast path
TRANSIENT = (OSError, MemoryError)

# full-jitter backoff RNG, one stream per process: seeded from the pid so
# P sharded processes whose chunk reads fail together (one NFS hiccup, one
# broker stall) do NOT retry in lockstep and re-hammer the same file or
# broker at the exact same instants.  AVENIR_TPU_RETRY_SEED pins the
# stream for deterministic tests; with_retry(jitter_seed=) pins one call.
_JITTER_RNG = random.Random(
    int(os.environ["AVENIR_TPU_RETRY_SEED"])
    if os.environ.get("AVENIR_TPU_RETRY_SEED") else os.getpid())
_JITTER_LOCK = threading.Lock()


def _jitter_delay(base_cap: float, rng: Optional[random.Random]) -> float:
    """Full-jitter draw: uniform over (0, cap] where cap is this
    attempt's exponential ceiling (AWS's 'full jitter' rule — the whole
    interval is randomized, not just a fringe, so colliding retriers
    spread across the entire window).  The draw is floored at cap/100 so
    a pathological 0 draw still yields a real backoff."""
    r = rng if rng is not None else _JITTER_RNG
    if rng is None:
        with _JITTER_LOCK:
            u = r.random()
    else:
        u = r.random()
    return base_cap * max(u, 0.01)


def with_retry(fn: Callable, *, attempts: Optional[int] = None,
               base_delay: Optional[float] = None,
               retry_on: Tuple[type, ...] = TRANSIENT,
               what: str = "operation",
               jitter_seed: Optional[int] = None):
    """Call ``fn()``; on a ``retry_on`` exception retry up to ``attempts``
    total tries with full-jitter exponential backoff: attempt i sleeps a
    uniform draw from (0, base * 2**i] (deterministic under a fixed
    ``jitter_seed`` or AVENIR_TPU_RETRY_SEED; per-process pid-seeded
    otherwise, so sharded processes never back off in lockstep).
    Anything else — including the classes an injected "crash" uses —
    propagates immediately.  The final failure re-raises the last
    exception unchanged so callers' except clauses keep working."""
    attempts = RETRY_ATTEMPTS if attempts is None else attempts
    base_delay = RETRY_BASE_S if base_delay is None else base_delay
    rng = random.Random(jitter_seed) if jitter_seed is not None else None
    last: Optional[BaseException] = None
    for i in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as exc:
            last = exc
            if i + 1 >= max(1, attempts):
                break
            delay = _jitter_delay(base_delay * (1 << i), rng)
            warnings.warn(
                f"{what} failed ({type(exc).__name__}: {exc}); "
                f"retry {i + 1}/{attempts - 1} after "
                f"{delay:.3g}s", RuntimeWarning,
                stacklevel=2)
            time.sleep(delay)
    raise last


# --------------------------------------------------------------------------
# deterministic corruption helper (the tests' "corrupt a record" fault)
# --------------------------------------------------------------------------

def corrupt_csv_rows(path: str, rows: Sequence[int], seed: int = 0,
                     mode: str = "garble",
                     field: Optional[int] = None) -> List[str]:
    """Deterministically corrupt the given 0-based non-blank-row indices of
    a CSV file in place, returning the corrupted line texts (what a
    quarantine pass should capture).  ``mode``: 'garble' replaces one
    field (``field``, default last — pick a NUMERIC ordinal: unknown
    categorical values encode as -1 rather than counting as malformed)
    with a non-numeric token; 'truncate' drops fields so the row is
    short."""
    import random as _random
    rng = _random.Random(seed)
    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    targets = set(rows)
    out: List[str] = []
    corrupted: List[str] = []
    nonblank = 0
    for line in lines:
        if line.strip():
            if nonblank in targets:
                parts = line.split(",")
                if mode == "truncate" and len(parts) > 1:
                    parts = parts[:max(1, len(parts) // 2)]
                else:
                    at = len(parts) - 1 if field is None else field
                    parts[at] = f"@bad{rng.randrange(10 ** 6)}"
                line = ",".join(parts)
                corrupted.append(line)
            nonblank += 1
        out.append(line)
    with open(path, "w") as fh:
        fh.write("\n".join(out) + "\n")
    return corrupted
