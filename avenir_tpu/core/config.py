"""Configuration layer: .properties and HOCON-subset readers with typed getters.

The reference has a two-tier config system (SURVEY.md §5):
  (a) Hadoop jobs: flat ``.properties`` passed via ``-Dconf.path=``, loaded by
      chombo ``Utility.setConfiguration`` (bayesian/BayesianDistribution.java:67),
      keys namespaced by per-job prefixes (``dtb.*``, ``bap.*``, ``nen.*`` ...)
      plus globals ``field.delim.regex``, ``num.reducer``, ``debug.on``.
  (b) Spark jobs: Typesafe-config HOCON with a top-level app block
      (spark/.../SimulatedAnnealing.scala:56-59, resource/opt.conf).

This module reads both formats into one ``Config`` object so that existing
reference config files drive the new framework without modification.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence


class ConfigError(KeyError):
    pass


class Config:
    """Flat key->string map with typed getters and mandatory-param assertions
    (the surface of chombo's Utility.get*ConfigParam / assert*ConfigParam)."""

    def __init__(self, data: Optional[Dict[str, str]] = None):
        self._data: Dict[str, str] = dict(data or {})

    # ---- raw access ----
    def __contains__(self, key: str) -> bool:
        return key in self._data and self._data[key] != ""

    def raw(self) -> Dict[str, str]:
        return dict(self._data)

    def set(self, key: str, value: Any) -> None:
        self._data[key] = str(value)

    def update(self, other: Dict[str, str]) -> None:
        self._data.update(other)

    # ---- typed getters with defaults ----
    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._data.get(key)
        if v is None or v == "":
            return default
        return v

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self.get(key)
        return int(v) if v is not None else default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self.get(key)
        return float(v) if v is not None else default

    def get_boolean(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        return v.strip().lower() == "true" if v is not None else default

    def get_list(self, key: str, default: Optional[Sequence[str]] = None,
                 delim: str = ",") -> Optional[List[str]]:
        v = self.get(key)
        if v is None:
            return list(default) if default is not None else None
        return [t.strip() for t in v.split(delim)]

    def get_int_list(self, key: str, default: Optional[Sequence[int]] = None,
                     delim: str = ",") -> Optional[List[int]]:
        v = self.get_list(key, None, delim)
        if v is None:
            return list(default) if default is not None else None
        return [int(t) for t in v]

    def get_float_list(self, key: str, default: Optional[Sequence[float]] = None,
                       delim: str = ",") -> Optional[List[float]]:
        v = self.get_list(key, None, delim)
        if v is None:
            return list(default) if default is not None else None
        return [float(t) for t in v]

    # ---- mandatory getters (assertXConfigParam equivalents) ----
    def _must(self, key: str, msg: Optional[str]) -> str:
        v = self.get(key)
        if v is None:
            raise ConfigError(msg or f"missing mandatory configuration parameter {key!r}")
        return v

    def must_get(self, key: str, msg: Optional[str] = None) -> str:
        return self._must(key, msg)

    def must_get_int(self, key: str, msg: Optional[str] = None) -> int:
        return int(self._must(key, msg))

    def must_get_float(self, key: str, msg: Optional[str] = None) -> float:
        return float(self._must(key, msg))

    def must_get_list(self, key: str, msg: Optional[str] = None,
                      delim: str = ",") -> List[str]:
        return [t.strip() for t in self._must(key, msg).split(delim)]

    # ---- namespacing ----
    def scoped(self, prefix: str) -> "ScopedConfig":
        return ScopedConfig(self, prefix)

    # ---- common globals of the reference ----
    @property
    def field_delim_regex(self) -> str:
        return self.get("field.delim.regex", ",")

    @property
    def field_delim_out(self) -> str:
        return self.get("field.delim.out", self.get("field.delim", ","))

    @property
    def debug_on(self) -> bool:
        return self.get_boolean("debug.on", False)


class ScopedConfig(Config):
    """View of a Config under a job prefix: ``get('max.depth')`` looks up
    ``<prefix>.max.depth`` first, then the bare key (so globals like
    ``field.delim.regex`` resolve through the same object)."""

    def __init__(self, base: Config, prefix: str):
        super().__init__()
        self._base = base
        self._prefix = prefix.rstrip(".")

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._base.get(f"{self._prefix}.{key}")
        if v is not None:
            return v
        return self._base.get(key, default)

    def set(self, key: str, value: Any) -> None:
        self._base.set(f"{self._prefix}.{key}", value)

    def update(self, other: Dict[str, str]) -> None:
        for k, v in other.items():
            self.set(k, v)

    def raw(self) -> Dict[str, str]:
        prefix = self._prefix + "."
        return {k[len(prefix):]: v for k, v in self._base.raw().items()
                if k.startswith(prefix)}

    def __contains__(self, key: str) -> bool:
        return f"{self._prefix}.{key}" in self._base or key in self._base


# --------------------------------------------------------------------------
# .properties parsing
# --------------------------------------------------------------------------

def parse_properties(text: str) -> Dict[str, str]:
    """java.util.Properties-flavoured parsing: ``key=value`` lines, ``#``/``!``
    comments, later keys override earlier ones, values may be empty."""
    out: Dict[str, str] = {}
    for rawline in text.splitlines():
        line = rawline.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        if "=" in line:
            key, _, value = line.partition("=")
        elif ":" in line:
            key, _, value = line.partition(":")
        else:
            key, value = line, ""
        out[key.strip()] = value.strip()
    return out


def load_properties(path: str) -> Config:
    with open(path, "r") as fh:
        return Config(parse_properties(fh.read()))


# --------------------------------------------------------------------------
# HOCON-subset parsing (enough for the reference's .conf files: one level of
# named blocks with key = value pairs; nested blocks flatten with dots)
# --------------------------------------------------------------------------

_HOCON_KV = re.compile(r"^\s*([^=:{}\s][^=:{}]*?)\s*[=:]\s*(.*?)\s*,?\s*$")


def parse_hocon(text: str) -> Dict[str, str]:
    """Parse the HOCON subset used by resource/*.conf: named blocks containing
    ``key = value`` lines.  Returns flat keys ``block.key``; list values are
    rendered as comma-joined strings; quoted strings are unquoted."""
    out: Dict[str, str] = {}
    stack: List[str] = []
    for rawline in text.splitlines():
        # strip '//' comments only at start of line or after whitespace, so
        # values like "file:///path" (resource/atmTrans.conf) survive
        line = re.split(r"(?:^|\s)//", rawline, maxsplit=1)[0].strip()
        if not line or line.startswith("#"):
            continue
        # block open:  name {          (possibly 'name { key = v }' is not supported)
        m = re.match(r"^([^={}\s][^={}]*?)\s*\{\s*$", line)
        if m:
            stack.append(m.group(1).strip())
            continue
        if line == "}":
            if stack:
                stack.pop()
            continue
        m = _HOCON_KV.match(line)
        if m:
            key, val = m.group(1).strip(), m.group(2).strip()
            if val.startswith("[") and val.endswith("]"):
                items = [v.strip().strip('"') for v in val[1:-1].split(",") if v.strip()]
                val = ",".join(items)
            elif len(val) >= 2 and val[0] == '"' and val[-1] == '"':
                val = val[1:-1]
            full = ".".join(stack + [key]) if stack else key
            out[full] = val
    return out


def load_hocon(path: str, app: Optional[str] = None) -> Config:
    """Load a HOCON .conf file.  If ``app`` is given, keys under that block are
    exposed without the block prefix (mirrors JobConfiguration's
    ``config.getConfig(appName)`` in the Spark jobs)."""
    with open(path, "r") as fh:
        flat = parse_hocon(fh.read())
    if app is not None:
        prefix = app + "."
        flat = {k[len(prefix):]: v for k, v in flat.items() if k.startswith(prefix)}
    return Config(flat)


def load_config(path: str, app: Optional[str] = None) -> Config:
    """Dispatch on extension: .properties / .props -> properties, .conf -> HOCON."""
    if path.endswith(".conf"):
        return load_hocon(path, app)
    return load_properties(path)
