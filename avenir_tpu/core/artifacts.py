"""Durable artifacts: every model/iteration state is a file, as in the reference.

The reference's checkpoint/resume story is structural (SURVEY.md §5): each
iteration writes a durable HDFS artifact (decision-path JSON per tree level,
LR coefficient history, k-means centroid files, bandit model state) and any job
can resume from its last artifact.  This module keeps that contract on a local
or shared filesystem:

  * text outputs are written Hadoop-style as ``<dir>/part-r-00000`` so driver
    scripts that expect that layout keep working
    (cf. resource/cust_churn_bayesian_prediction.txt:60 model path)
  * JSON models round-trip through plain files
  * an ``ArtifactStore`` wraps a base directory with namespaced read/write
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Iterable, List, Optional

from .faults import fault_point, with_retry


def write_text_output(dir_path: str, lines: Iterable[str],
                      part: Optional[int] = None, role: str = "r",
                      local_shard: Optional[bool] = None) -> str:
    """Write lines as ``<dir>/part-{role}-{part:05d}`` (Hadoop output layout).

    ``local_shard=True`` marks per-record outputs computed over THIS
    process's input shard (prediction lines etc.): under multi-process the
    part number defaults to the process index, so every process contributes
    its own part file — the Hadoop one-part-per-task layout — instead of
    all processes clobbering part 0.  Default: map-only outputs
    (role "m", the reference's per-record predictor jobs) are shard-local;
    reducer-style artifacts (role "r": model files, which every process
    computes identically from the sharded global arrays) keep part 0."""
    if part is None:
        if local_shard is None:
            local_shard = role == "m"
        part = 0
        if local_shard:
            import jax
            from ..parallel.distributed import is_multiprocess
            if is_multiprocess():
                part = jax.process_index()
    os.makedirs(dir_path, exist_ok=True)
    path = os.path.join(dir_path, f"part-{role}-{part:05d}")
    # materialize once so a retried write re-emits identical content even
    # when the caller passed a one-shot generator
    lines = list(lines)

    def write():
        fault_point("artifact_write")
        with open(path, "w") as fh:
            for line in lines:
                fh.write(line)
                fh.write("\n")
    with_retry(write, what=f"artifact write {path}")
    return path


def read_text_input(path: str) -> List[str]:
    """Read lines from a file, or from every ``part-*`` file of a directory
    (Hadoop input semantics: a job input path may be a dir of part files)."""
    paths: List[str]
    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, "part-*")))
        if not paths:
            paths = sorted(p for p in glob.glob(os.path.join(path, "*"))
                           if os.path.isfile(p) and not os.path.basename(p).startswith(("_", ".")))
    else:
        paths = [path]
    lines: List[str] = []
    for p in paths:
        with open(p, "r") as fh:
            for line in fh.read().splitlines():
                if line:
                    lines.append(line)
    return lines


def write_json(path: str, obj: Any, indent: int = 2) -> str:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)

    def write():
        fault_point("artifact_write")
        with open(path, "w") as fh:
            json.dump(obj, fh, indent=indent)
    with_retry(write, what=f"artifact write {path}")
    return path


def read_json(path: str) -> Any:
    with open(path, "r") as fh:
        return json.load(fh)


class ArtifactStore:
    """Namespaced artifact directory: the replacement for the HDFS paths wired
    through the reference's shell scripts (resource/detr.sh:35-41 rotation)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def path(self, *parts: str) -> str:
        return os.path.join(self.base_dir, *parts)

    def write_lines(self, name: str, lines: Iterable[str]) -> str:
        return write_text_output(self.path(name), lines)

    def read_lines(self, name: str) -> List[str]:
        return read_text_input(self.path(name))

    def write_json(self, name: str, obj: Any) -> str:
        return write_json(self.path(name), obj)

    def read_json(self, name: str) -> Any:
        return read_json(self.path(name))

    def exists(self, name: str) -> bool:
        return os.path.exists(self.path(name))

    def rotate(self, src: str, dst: str) -> None:
        """Move an output artifact into the input slot for the next iteration
        (detr.sh 'mvDecFiles': decPathOut -> decPathIn)."""
        os.replace(self.path(src), self.path(dst))
