"""Backend/platform selection for processes whose interpreter pre-imports jax.

The dev container's sitecustomize imports jax at interpreter start pinned to
the tunneled TPU ("axon"); when that tunnel is wedged, every device call hangs
forever.  Because jax is already imported, setting JAX_PLATFORMS in the
environment is not enough — ``jax.config.update`` must run before any backend
initializes.  This is the single shared escape hatch for the CLI
(``AVENIR_TPU_PLATFORM=cpu`` / ``-Dplatform=cpu``), the benchmark harness, and
tests (conftest applies the same recipe).
"""

from __future__ import annotations

import os
from typing import Optional


def force_platform(name: Optional[str] = None) -> Optional[str]:
    """Pin jax to ``name`` (or $AVENIR_TPU_PLATFORM / $JAX_PLATFORMS when
    ``name`` is None).  No-op when nothing is requested or jax already agrees.
    Returns the platform applied, if any."""
    name = name or os.environ.get("AVENIR_TPU_PLATFORM") \
        or os.environ.get("JAX_PLATFORMS")
    if not name:
        return None
    os.environ["JAX_PLATFORMS"] = name
    import jax
    if jax.config.jax_platforms != name:
        jax.config.update("jax_platforms", name)
    return name
