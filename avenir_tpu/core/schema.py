"""Feature schema: JSON metadata describing a CSV dataset.

Equivalent surface of chombo's ``FeatureSchema`` / ``FeatureField`` as used by the
reference (SURVEY.md §2.9; e.g. /root/reference resource/call_hangup.json,
bayesian/BayesianDistribution.java:117-123).  The JSON format is preserved
bit-for-bit so existing schema files drive the new framework unchanged:

    {"fields": [
        {"name": "id", "ordinal": 0, "id": true, "dataType": "string"},
        {"name": "issue", "ordinal": 3, "dataType": "categorical", "feature": true,
         "maxSplit": 2, "cardinality": ["internet", "cable", "billing", "other"]},
        {"name": "hold time", "ordinal": 5, "dataType": "int", "feature": true,
         "bucketWidth": 60, "min": 0, "max": 600, "splitScanInterval": 60},
        {"name": "hungup", "ordinal": 6, "dataType": "categorical"}]}

Semantics (matching the reference):
  * ``feature: true``  -> predictor attribute
  * ``id: true``       -> record identifier (kept host-side, never on device)
  * the class attribute is the field that is neither feature nor id and is
    categorical (chombo FeatureSchema.findClassAttrField behaviour).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import Any, Dict, List, Optional


NUMERIC_TYPES = ("int", "long", "double", "float")


@dataclass
class FeatureField:
    """One column of the dataset, as declared in the schema JSON."""

    name: str
    ordinal: int
    data_type: str = "string"
    feature: bool = False
    id_field: bool = False
    class_field: bool = False
    cardinality: Optional[List[str]] = None
    min: Optional[float] = None
    max: Optional[float] = None
    bucket_width: Optional[float] = None
    max_split: Optional[int] = None
    split_scan_interval: Optional[float] = None
    # free-form extras kept for forward compatibility with reference schemas
    extras: Dict[str, Any] = dc_field(default_factory=dict)

    # ---- type predicates (FeatureField.isCategorical etc. in chombo) ----
    @property
    def is_categorical(self) -> bool:
        return self.data_type == "categorical"

    @property
    def is_numeric(self) -> bool:
        return self.data_type in NUMERIC_TYPES

    @property
    def is_integer(self) -> bool:
        return self.data_type in ("int", "long")

    @property
    def is_double(self) -> bool:
        return self.data_type in ("double", "float")

    @property
    def is_text(self) -> bool:
        return self.data_type == "text"

    @property
    def is_binned(self) -> bool:
        """Categorical, or numeric with a bucketWidth: has a finite bin alphabet."""
        return self.is_categorical or self.bucket_width is not None

    @property
    def num_bins(self) -> int:
        """Size of the bin alphabet for a binned field.

        For categorical: len(cardinality).  For bucketed numeric: number of
        ``value // bucketWidth`` bins covering [min, max] (reference binning:
        bayesian/BayesianDistribution.java:152 ``bin = value / bucketWidth``).
        """
        if self.is_categorical:
            if not self.cardinality:
                raise ValueError(f"field {self.name!r}: categorical without cardinality")
            return len(self.cardinality)
        if self.bucket_width is None:
            raise ValueError(f"field {self.name!r} is not binned")
        if self.min is None or self.max is None:
            raise ValueError(f"field {self.name!r}: bucketWidth without min/max")
        return int(self.max // self.bucket_width) - int(self.min // self.bucket_width) + 1

    @property
    def bin_offset(self) -> int:
        """First bin id = min // bucketWidth (so codes start at 0 after subtracting)."""
        if self.bucket_width is None or self.min is None:
            return 0
        return int(self.min // self.bucket_width)

    def cat_code(self, value: str) -> int:
        """Vocabulary code of a categorical value (-1 if unknown)."""
        try:
            return self.cardinality.index(value)  # type: ignore[union-attr]
        except (ValueError, AttributeError):
            return -1

    def must_cat_code(self, value: str) -> int:
        """Vocabulary code of a categorical value; raises on unknown — for
        config-supplied values (e.g. positive.class.value) where a typo must
        not silently become an impossible code of -1."""
        code = self.cat_code(value)
        if code < 0:
            raise ValueError(
                f"value {value!r} not in cardinality {self.cardinality!r} "
                f"of field {self.name!r}")
        return code

    def bin_label(self, code: int) -> str:
        """Inverse of encoding: the bin string the reference would emit."""
        if self.is_categorical:
            return self.cardinality[code]  # type: ignore[index]
        return str(code + self.bin_offset)


@dataclass
class FeatureSchema:
    """The parsed schema file: ordered fields plus convenience accessors."""

    fields: List[FeatureField]

    # ---- constructors ----
    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FeatureSchema":
        fields = []
        for fd in d.get("fields", []):
            known = {
                "name": fd.get("name", ""),
                "ordinal": int(fd["ordinal"]),
                "data_type": fd.get("dataType", "string"),
                "feature": bool(fd.get("feature", False)),
                "id_field": bool(fd.get("id", False)),
                "class_field": bool(fd.get("classAttr", False)),
                "cardinality": fd.get("cardinality"),
                "min": fd.get("min"),
                "max": fd.get("max"),
                "bucket_width": fd.get("bucketWidth"),
                "max_split": fd.get("maxSplit"),
                "split_scan_interval": fd.get("splitScanInterval"),
            }
            consumed = {"name", "ordinal", "dataType", "feature", "id", "classAttr",
                        "cardinality", "min", "max", "bucketWidth", "maxSplit",
                        "splitScanInterval"}
            extras = {k: v for k, v in fd.items() if k not in consumed}
            if known["cardinality"] is not None:
                known["cardinality"] = [str(c) for c in known["cardinality"]]
            fields.append(FeatureField(extras=extras, **known))
        fields.sort(key=lambda f: f.ordinal)
        return cls(fields=fields)

    def to_dict(self) -> Dict[str, Any]:
        """Inverse of :meth:`from_dict` (reference JSON key names), so a
        schema can travel inside a model artifact (serving registry) and
        reconstruct identically: ``from_dict(s.to_dict()) == s``."""
        out = []
        for f in self.fields:
            d: Dict[str, Any] = {"name": f.name, "ordinal": f.ordinal,
                                 "dataType": f.data_type}
            if f.feature:
                d["feature"] = True
            if f.id_field:
                d["id"] = True
            if f.class_field:
                d["classAttr"] = True
            for key, v in (("cardinality", f.cardinality), ("min", f.min),
                           ("max", f.max), ("bucketWidth", f.bucket_width),
                           ("maxSplit", f.max_split),
                           ("splitScanInterval", f.split_scan_interval)):
                if v is not None:
                    d[key] = v
            d.update(f.extras)
            out.append(d)
        return {"fields": out}

    @classmethod
    def from_json(cls, text: str) -> "FeatureSchema":
        return cls.from_dict(json.loads(text))

    @classmethod
    def load(cls, path: str) -> "FeatureSchema":
        with open(path, "r") as fh:
            return cls.from_json(fh.read())

    # ---- accessors (mirroring chombo FeatureSchema methods) ----
    def find_field_by_ordinal(self, ordinal: int) -> FeatureField:
        for f in self.fields:
            if f.ordinal == ordinal:
                return f
        raise KeyError(f"no field with ordinal {ordinal}")

    @property
    def feature_fields(self) -> List[FeatureField]:
        """getFeatureAttrFields(): fields flagged feature=true, ordinal order."""
        return [f for f in self.fields if f.feature]

    @property
    def id_fields(self) -> List[FeatureField]:
        return [f for f in self.fields if f.id_field]

    @property
    def class_attr_field(self) -> FeatureField:
        """findClassAttrField(): explicitly flagged, else the categorical field
        that is neither a feature nor an id (reference schemas rely on this,
        e.g. 'hungup' in call_hangup.json / 'status' in churn.json)."""
        for f in self.fields:
            if f.class_field:
                return f
        for f in self.fields:
            if f.is_categorical and not f.feature and not f.id_field:
                return f
        raise ValueError("schema has no class attribute field")

    @property
    def num_columns(self) -> int:
        return max(f.ordinal for f in self.fields) + 1

    def feature_ordinals(self) -> List[int]:
        return [f.ordinal for f in self.feature_fields]
