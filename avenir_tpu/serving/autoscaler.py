"""SLO-driven fleet autoscaler: the control loop the telemetry was for.

Execution Templates' control-plane/data-plane split (PAPERS.md) applied
to serving: the data plane — :class:`~avenir_tpu.serving.fleet
.ServingFleet` workers with their warm shape-bucket executables — keeps
all compiled state; this module is the thin control plane that only
repoints traffic, by starting/parking workers through the fleet's
PR 10 admission + parking machinery (``ServingFleet.scale_to``).

Three pieces, deliberately separable so each is testable alone:

  * **sensor** (:meth:`FleetAutoscaler._sense`) — reads the live
    sources every tick: broker queue depth (``llen`` over the shard
    ring, no popping — the INFO/LLEN path) and its DERIVATIVE over the
    tick interval, plus the fleet's recent request p99 from the
    workers' live ``StepTimer`` sample windows (the same windows the
    ``/metrics`` gauges render — the autoscaler watches what the
    operator's dashboard watches).
  * **policy** (:class:`AutoscalePolicy` + :meth:`FleetAutoscaler
    .decide`) — pure, side-effect-free: (depth, derivative, p99,
    active) -> ``"up" | "down" | "hold"``.  Hysteresis on three axes so
    the loop NEVER flaps: distinct pressure/calm bands (a reading
    between them holds), consecutive-tick debounce (one noisy scrape
    cannot trigger an action), and a post-action cooldown (the system
    gets time to absorb the last decision before the next).  Scale-down
    additionally requires the queue near-empty AND p99 comfortably
    under the SLO — pressure evidence scales up fast, calm evidence
    scales down slowly (the asymmetry every production autoscaler
    converges on: a late scale-up costs SLO, a late scale-down costs
    only footprint).
  * **actuator** — ``fleet.scale_to(active ± 1)``: unpark-first warm
    scale-up, park-the-tail scale-down, never below ``min_workers``.

Every decision — including holds — is emitted as a traced instant
(``autoscaler.decision``) and tallied under ``Autoscaler/*`` counters,
so ``tracetool summarize`` can replay WHY the fleet scaled after the
fact (the decision log prints next to the serving-lane breakdown).
"""

from __future__ import annotations

import itertools
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import instant


@dataclass
class AutoscalePolicy:
    """The policy knobs.  Defaults are tuned for the repo's bench host
    (sub-second ticks, single-digit worker counts); the hysteresis
    SHAPE, not the exact numbers, is the contract (TPU_NOTES §25).

    Pressure (any one axis): queue depth ≥ ``depth_high``; depth rising
    faster than ``derivative_high``/s while non-trivial; or — with an
    SLO budget set — recent p99 ≥ ``p99_high_fraction`` of it.

    Calm (ALL axes): depth ≤ ``depth_low``, derivative ≤ 0, and p99 ≤
    ``p99_low_fraction`` of the budget (p99 always passes with no SLO
    set).  Between the bands: hold."""
    min_workers: int = 1
    max_workers: int = 4
    slo_p99_ms: float = 0.0          # 0 = depth/derivative-only policy
    depth_high: int = 64             # queued requests = real backlog
    depth_low: int = 4               # near-drained
    derivative_high: float = 50.0    # req/s of queue GROWTH = a spike
    p99_high_fraction: float = 0.8   # p99 at 80% of budget = pressure
    p99_low_fraction: float = 0.5    # p99 under half budget = calm
    up_consecutive: int = 2          # ticks of pressure before +1
    down_consecutive: int = 6        # ticks of calm before -1 (slower)
    cooldown_ticks: int = 3          # no action this soon after one

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(f"min_workers must be >= 1, got "
                             f"{self.min_workers}")
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) < min_workers "
                f"({self.min_workers})")
        if self.depth_low >= self.depth_high:
            raise ValueError(
                f"hysteresis band inverted: depth_low "
                f"({self.depth_low}) must sit under depth_high "
                f"({self.depth_high})")
        if self.slo_p99_ms and not (0.0 < self.p99_low_fraction
                                    < self.p99_high_fraction <= 1.0):
            raise ValueError(
                f"p99 fractions must satisfy 0 < low < high <= 1, got "
                f"low={self.p99_low_fraction} "
                f"high={self.p99_high_fraction}")


class FleetAutoscaler:
    """Sensor→policy→actuator loop over one :class:`ServingFleet`.

    ``broker`` is anything with ``llen(queue)`` (a :class:`RespClient`
    or :class:`ShardedRespClient` — the sharded form sums the ring);
    ``depth_fn``/``p99_fn`` override the sensors outright (unit tests
    drive :meth:`tick` with synthetic traffic; production leaves them
    None).  ``start()`` runs :meth:`tick` every ``interval_s`` on a
    daemon thread; a failing tick warns and keeps ticking — a flaky
    scrape must not kill the control loop (and with it the scale-down
    path, pinning the fleet at peak footprint forever)."""

    # how many of the newest serve.request samples per worker feed the
    # p99 sensor — same recency rationale as PredictionService's
    # adaptive-window _ADAPT_SAMPLES
    _P99_SAMPLES = 256

    def __init__(self, fleet, broker=None, *,
                 queue: Optional[str] = None,
                 policy: Optional[AutoscalePolicy] = None,
                 interval_s: float = 0.25,
                 counters=None,
                 depth_fn=None, p99_fn=None):
        self.fleet = fleet
        self.broker = broker
        self.queue = queue if queue is not None \
            else getattr(fleet, "request_q", "requestQueue")
        self.policy = policy or AutoscalePolicy()
        self.interval_s = float(interval_s)
        self.counters = counters
        self._depth_fn = depth_fn
        self._p99_fn = p99_fn
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # controller state: the hysteresis memory
        self._pressure_ticks = 0
        self._calm_ticks = 0
        self._cooldown = 0
        self._last_depth: Optional[int] = None
        self._last_t: Optional[float] = None
        # per-worker serve.request call totals at the last tick: the
        # staleness detector for the p99 sensor (see _sense_p99_ms)
        self._last_calls: Dict[str, int] = {}
        self.decisions: List[Dict] = []   # bounded in tick()
        self._count("Ticks", 0)   # group visible from tick zero

    # ---- counters ----
    def _count(self, name: str, n: int = 1) -> None:
        if self.counters is not None:
            self.counters.increment("Autoscaler", name, n)

    # ---- sensor ----
    def _sense_depth(self) -> int:
        if self._depth_fn is not None:
            return int(self._depth_fn())
        depth = 0
        if self.broker is not None:
            depth += int(self.broker.llen(self.queue))
        # requests already pulled off the broker but still coalescing
        # inside worker queues are backlog too — without them a fleet
        # that drains the broker into deep service queues reads "calm"
        # while requests age
        for w in list(self.fleet.workers):
            depth += w.service.stats()["queue_depth"]
        return depth

    def _sense_p99_ms(self) -> float:
        if self._p99_fn is not None:
            return float(self._p99_fn())
        recent: List[float] = []
        fresh = False
        for w in list(self.fleet.workers):
            # staleness guard: the sample window remembers the last N
            # requests FOREVER — after a spike drains and traffic goes
            # quiet, those samples would read as permanent pressure and
            # pin the fleet at peak footprint.  No new serve.request
            # completions anywhere since the last tick = no live
            # latency = no pressure.
            calls = w.service.timer.calls.get("serve.request", 0)
            if calls != self._last_calls.get(w.name, 0):
                fresh = True
            self._last_calls[w.name] = calls
            s = w.service.timer.samples.get("serve.request")
            if not s:
                continue
            for _ in range(3):   # deque may be appended to concurrently
                try:
                    # newest N via reversed islice — copying the whole
                    # 8k-sample deque per worker per tick to keep 256
                    # would be real steady-state overhead on the very
                    # host serving the traffic (order is irrelevant to
                    # the percentile)
                    recent.extend(itertools.islice(
                        reversed(s), self._P99_SAMPLES))
                    break
                except RuntimeError:
                    continue
        if not recent or not fresh:
            return 0.0
        return float(np.percentile(np.asarray(recent), 99)) * 1000.0

    def _sense_model_depths(self) -> Dict[str, int]:
        # per-tenant pressure (ISSUE 18): a models= fleet exposes
        # model_queue_depths() — each resident model's own queued
        # backlog, summed across workers.  The aggregate policy still
        # decides up/down; the per-model split rides every decision
        # record and instant so an operator (and the noisy-tenant
        # bench) can see WHICH tenant's backlog drove the action.
        probe = getattr(self.fleet, "model_queue_depths", None)
        if probe is None:
            return {}
        try:
            return dict(probe())
        except Exception:
            return {}

    def _sense(self) -> Dict:
        now = time.monotonic()
        depth = self._sense_depth()
        if self._last_depth is None or self._last_t is None \
                or now <= self._last_t:
            deriv = 0.0
        else:
            deriv = (depth - self._last_depth) / (now - self._last_t)
        self._last_depth, self._last_t = depth, now
        sensed = {"depth": depth, "derivative_per_s": round(deriv, 2),
                  "p99_ms": round(self._sense_p99_ms(), 3)}
        by_model = self._sense_model_depths()
        if by_model:
            sensed["depth_by_model"] = by_model
        return sensed

    # ---- policy (pure: no clocks, no actuation) ----
    def decide(self, depth: int, deriv: float, p99_ms: float,
               active: int) -> str:
        """One policy step over one sensed sample; mutates only the
        hysteresis counters.  Returns ``"up" | "down" | "hold"`` — the
        caller actuates."""
        pol = self.policy
        pressure = depth >= pol.depth_high \
            or (deriv >= pol.derivative_high and depth > pol.depth_low) \
            or (pol.slo_p99_ms > 0
                and p99_ms >= pol.p99_high_fraction * pol.slo_p99_ms)
        calm = depth <= pol.depth_low and deriv <= 0.0 \
            and (pol.slo_p99_ms <= 0
                 or p99_ms <= pol.p99_low_fraction * pol.slo_p99_ms)
        if pressure:
            self._pressure_ticks += 1
            self._calm_ticks = 0
        elif calm:
            self._calm_ticks += 1
            self._pressure_ticks = 0
        else:
            # between the bands: hysteresis hold — decay both memories
            # so a long ambiguous spell cannot bank ticks toward either
            # action
            self._pressure_ticks = 0
            self._calm_ticks = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return "hold"
        if pressure and self._pressure_ticks >= pol.up_consecutive \
                and active < pol.max_workers:
            self._pressure_ticks = 0
            self._cooldown = pol.cooldown_ticks
            return "up"
        if calm and self._calm_ticks >= pol.down_consecutive \
                and active > pol.min_workers:
            self._calm_ticks = 0
            self._cooldown = pol.cooldown_ticks
            return "down"
        return "hold"

    # ---- one full sensor→policy→actuator pass ----
    def tick(self) -> Dict:
        """Sense, decide, actuate, emit.  Returns the decision record
        (also appended to :attr:`decisions`, bounded to the last 4096,
        and emitted as an ``autoscaler.decision`` trace instant)."""
        sensed = self._sense()
        active = self.fleet.active_workers()
        if active < self.policy.min_workers:
            # the floor is the actuator's job, not the pressure rule's:
            # a fleet started (or externally scaled) below min_workers
            # must be brought up even under perfect calm — decide()
            # only ever scales up on pressure
            action = "up"
        else:
            action = self.decide(sensed["depth"],
                                 sensed["derivative_per_s"],
                                 sensed["p99_ms"], active)
        new_active = active
        if action == "up":
            new_active = self.fleet.scale_to(
                max(active + 1, self.policy.min_workers))
            self._count("ScaleUps")
        elif action == "down":
            new_active = self.fleet.scale_to(active - 1)
            self._count("ScaleDowns")
        else:
            self._count("Holds")
        self._count("Ticks")
        if self.counters is not None:
            self.counters.set("Autoscaler", "ActiveWorkers", new_active)
        rec = {"action": action, "active": active,
               "new_active": new_active, **sensed,
               "slo_p99_ms": self.policy.slo_p99_ms,
               "pressure_ticks": self._pressure_ticks,
               "calm_ticks": self._calm_ticks,
               "cooldown": self._cooldown}
        # the host label rides the instant (not the decision record) so
        # a multi-host incident report can attribute scale actions
        instant("autoscaler.decision", cat="serving",
                host=getattr(self.fleet, "host_label", None), **rec)
        self.decisions.append(rec)
        if len(self.decisions) > 4096:
            del self.decisions[:2048]
        return rec

    # ---- lifecycle ----
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception as exc:
                    # the control loop must outlive a flaky scrape: a
                    # dead autoscaler after a spike would pin the fleet
                    # at max footprint forever
                    warnings.warn(
                        f"autoscaler tick failed ({type(exc).__name__}: "
                        f"{exc}); continuing", RuntimeWarning)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="avenir-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=max(5.0, 4 * self.interval_s))
        self._thread = None
