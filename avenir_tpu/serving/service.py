"""Micro-batched prediction serving: the request loop over warm predictors.

Single-row requests are coalesced into device batches under a
max-latency/max-batch policy: the first queued request opens a batch window
of ``max_wait_ms``; the batch closes when ``max_batch`` requests are
queued or the window expires, whichever is first.  One bucketed predict
then answers the whole batch — the device does per-request work at batch
throughput while the slowest request waits at most one window plus one
predict.

Transports (same split as reinforce/serving.py, the bandit loop):

  * in-process — ``submit()`` returns a future; a daemon worker thread
    runs the coalescing loop.  Unit tests and embedded serving.
  * the wire (:class:`RespPredictionLoop`) — RESP-list queues polled like
    the reference's Redis spout (requests ``rpop``ed from the request
    queue, predictions ``lpush``ed to the prediction queue), against
    io/respq.RespServer or a real Redis, with the same config key style
    (redis.server.host/port, redis.request.queue, redis.prediction.queue).

Message formats (delim-joined, like the bandit loop's ``round,<n>``):
  request:    'predict,<requestId>,<field0>,<field1>,...'  (a full record)
  response:   '<requestId>,<predictedClass>'
  control:    'reload' -> hot-swap to the registry's newest intact model
              'stop'   -> end the wire loop (transport-level, like the
                          bandit loop's stop)

Operational hooks: per-request and per-batch latency recorded through
utils/tracing.StepTimer percentile samples, request/batch counters in the
core/metrics.Counters channel, transient predict errors retried via
core/faults.with_retry.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..core.faults import with_retry
from ..core.metrics import Counters
from ..utils.tracing import StepTimer
from .predictor import AMBIGUOUS, DEFAULT_BUCKETS, Predictor, make_predictor
from .registry import ModelRegistry


@dataclass
class BatchPolicy:
    """Coalescing knobs: a batch closes at ``max_batch`` requests or
    ``max_wait_ms`` after its first request, whichever comes first."""
    max_batch: int = 64
    max_wait_ms: float = 2.0


class _Request:
    __slots__ = ("row", "t_submit", "future")

    def __init__(self, row: List[str]):
        self.row = row
        self.t_submit = time.perf_counter()
        self.future: "Future[Optional[str]]" = Future()


class PredictionService:
    """The serving bolt: coalesce, predict, respond.

    Construct either around a ready ``predictor`` or around a
    ``registry`` + ``model_name`` (which enables :meth:`refresh` hot-swap:
    publish a new version, send 'reload' or call refresh(), and the next
    batch runs on it — torn versions are skipped by the registry)."""

    def __init__(self, predictor: Optional[Predictor] = None, *,
                 registry: Optional[ModelRegistry] = None,
                 model_name: Optional[str] = None,
                 schema=None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 policy: Optional[BatchPolicy] = None,
                 counters: Optional[Counters] = None,
                 timer: Optional[StepTimer] = None,
                 warm: bool = True,
                 delim: str = ",",
                 ambiguous_label: str = AMBIGUOUS,
                 error_label: str = "error",
                 monitor=None):
        if predictor is None and (registry is None or model_name is None):
            raise ValueError("need a predictor, or registry= + model_name=")
        self.registry = registry
        self.model_name = model_name
        self._schema = schema
        self._buckets = tuple(buckets)
        self.policy = policy or BatchPolicy()
        self.counters = counters if counters is not None else Counters()
        self.timer = timer if timer is not None else \
            StepTimer(keep_samples=8192)
        self._warm = warm
        self.delim = delim
        self.ambiguous_label = ambiguous_label
        self.error_label = error_label
        self.version: Optional[int] = None
        # drift/quality hook (monitor.accumulator.ServingMonitor): every
        # served micro-batch records through it; None = unmonitored
        self.monitor = monitor
        # set by mark_degraded (e.g. a drift policy's degrade_action):
        # serving continues, operators see the reason + counter
        self.degraded: Optional[str] = None
        self._swap_lock = threading.Lock()
        if predictor is None:
            predictor = self._load(must=True)
        elif warm:
            predictor.warm()
        if monitor is not None and warm and hasattr(monitor, "warm"):
            monitor.warm()   # monitor compiles must not race live traffic
        self.predictor = predictor
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- model lifecycle ----
    def _load(self, must: bool = False) -> Optional[Predictor]:
        latest = self.registry.latest_version(self.model_name)
        if latest is None:
            if must:
                raise FileNotFoundError(
                    f"no intact versions of {self.model_name!r} in "
                    f"{self.registry.base_dir!r}")
            return None
        loaded = self.registry.load(self.model_name, latest)
        pred = make_predictor(loaded, schema=self._schema,
                              buckets=self._buckets, delim=self.delim)
        if self._warm:
            pred.warm()
        self.version = latest
        return pred

    def refresh(self) -> bool:
        """Hot-swap reload: if the registry holds a newer INTACT version,
        build + warm its predictor off the request path and swap it in
        atomically (in-flight batches finish on the old one).  Returns
        whether a swap happened.  A half-written newest version is skipped
        by the registry with a warning — serving stays on the current
        model."""
        if self.registry is None:
            return False
        latest = self.registry.latest_version(self.model_name)
        if latest is None or latest == self.version:
            return False
        loaded = self.registry.load(self.model_name, latest)
        pred = make_predictor(loaded, schema=self._schema,
                              buckets=self._buckets, delim=self.delim)
        if self._warm:
            pred.warm()
        with self._swap_lock:
            self.predictor = pred
            self.version = latest
        self.degraded = None   # a fresh model clears the degraded flag
        self.counters.increment("Serving", "HotSwaps")
        return True

    def mark_degraded(self, reason: str) -> None:
        """Flag the served model as degraded (drift policy guardrail).
        Serving continues — the flag and counter are the operator
        signal; a successful :meth:`refresh` hot-swap clears it."""
        self.degraded = reason
        self.counters.increment("Serving", "Degraded")

    # ---- prediction ----
    def _label(self, pred: Optional[str]) -> str:
        return pred if pred is not None else self.ambiguous_label

    def predict_rows(self, rows: List[List[str]]) -> List[str]:
        """One coalesced device batch for ``rows``, with transient-error
        retry (a recoverable allocator/IO hiccup re-runs the batch rather
        than failing every request in it)."""
        with self._swap_lock:
            pred = self.predictor
        t0 = time.perf_counter()
        out = with_retry(lambda: pred.predict_rows(rows),
                         what="serving predict batch")
        self.timer.record("serve.batch", time.perf_counter() - t0)
        self.counters.increment("Serving", "Requests", len(rows))
        self.counters.increment("Serving", "Batches")
        return [self._label(p) for p in out]

    def _predict_isolating(self, rows: List[List[str]]):
        """('ok', label) | ('err', exc) per row.  The whole batch runs as
        one launch when it is clean; if anything in it fails (e.g. a short
        record or a non-numeric token blowing up encode_rows), fall back
        to per-row isolation so one malformed request cannot take down the
        batchmates drained off the queue alongside it.  The fallback
        accounts as ONE isolated batch — per-row launches must not flood
        the Batches count or the serve.batch samples operators tune
        BatchPolicy with."""
        import warnings
        try:
            results = [("ok", lab) for lab in self.predict_rows(rows)]
            self._record_monitor(rows, results)
            return results
        except Exception as exc:
            warnings.warn(
                f"serving: batch predict failed ({type(exc).__name__}: "
                f"{exc}); isolating per row", RuntimeWarning)
        with self._swap_lock:
            pred = self.predictor
        t0 = time.perf_counter()
        out = []
        for row in rows:
            try:
                lab = with_retry(lambda r=row: pred.predict_rows([r]),
                                 what="serving predict row")[0]
                out.append(("ok", self._label(lab)))
            except Exception as exc:
                self.counters.increment("Serving", "BadRequests")
                out.append(("err", exc))
        self.timer.record("serve.batch", time.perf_counter() - t0)
        self.counters.increment("Serving", "Requests", len(rows))
        self.counters.increment("Serving", "Batches")
        self.counters.increment("Serving", "IsolatedBatches")
        self._record_monitor(rows, out)
        return out

    def _record_monitor(self, rows, results) -> None:
        """Feed successfully answered (row, label) pairs to the drift
        monitor hook.  Cheap on the request path (the hook only
        buffers); monitoring failures are warned, never propagated —
        observability must not take serving down."""
        if self.monitor is None:
            return
        import warnings
        try:
            ok_rows = [r for r, (st, _) in zip(rows, results) if st == "ok"]
            ok_labels = [v for st, v in results if st == "ok"]
            if ok_rows:
                self.monitor.record_batch(ok_rows, ok_labels)
        except Exception as exc:
            warnings.warn(f"serving: monitor hook failed "
                          f"({type(exc).__name__}: {exc}); continuing "
                          f"unmonitored for this batch", RuntimeWarning)

    # ---- message contract (shared by both transports) ----
    def process(self, message: str) -> Optional[str]:
        """Bolt-execute for ONE message (the bandit loop's synchronous
        contract); micro-batching callers use process_batch."""
        return (self.process_batch([message]) or [None])[0]

    def process_batch(self, messages: List[str]) -> List[str]:
        """Coalesce a drained message batch: all predict messages run as
        one device batch, response lines returned in arrival order.  A
        malformed or unknown message is counted + warned and skipped — it
        must not take down the valid requests already drained off the
        queue alongside it (they cannot be re-queued).  A 'reload' in the
        drain applies AFTER the batch is answered: the swap (and its
        multi-bucket warm-up compiles) must not stall requests already
        accepted, so the new model takes effect from the next batch."""
        import warnings
        ids: List[str] = []
        rows: List[List[str]] = []
        reload_requested = False
        for message in messages:
            parts = message.split(self.delim)
            if parts[0] == "predict" and len(parts) >= 3:
                ids.append(parts[1])
                rows.append(parts[2:])
            elif parts[0] == "reload":
                reload_requested = True
            else:
                self.counters.increment("Serving", "BadRequests")
                warnings.warn(f"serving: dropping malformed message "
                              f"{message!r}", RuntimeWarning)
        if reload_requested and not rows:
            self.refresh()
            return []
        if not rows:
            return []
        t0 = time.perf_counter()
        results = self._predict_isolating(rows)
        dt = time.perf_counter() - t0
        out = []
        for rid, (status, val) in zip(ids, results):
            self.timer.record("serve.request", dt)
            lab = val if status == "ok" else self.error_label
            out.append(f"{rid}{self.delim}{lab}")
        if reload_requested:
            self.refresh()
        return out

    # ---- in-process micro-batch loop ----
    def submit(self, row) -> "Future[str]":
        """Queue one record (tokenized row or delim-joined line); the
        worker thread answers the future with the class label."""
        if isinstance(row, str):
            row = row.split(self.delim)
        req = _Request(list(row))
        self._queue.put(req)
        return req.future

    def start(self) -> "PredictionService":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Stop the worker; queued requests are still served (bounded by
        ``drain_s``) so no accepted request is dropped on shutdown."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=max(drain_s, 0.1) + 5.0)
        self._thread = None
        deadline = time.monotonic() + drain_s
        batch = []
        while time.monotonic() < deadline:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if batch:
            self._serve(batch)

    def _loop(self) -> None:
        pol = self.policy
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.02)
            except queue.Empty:
                continue
            batch = [first]
            # free coalescing first: whatever queued while the previous
            # batch was on device joins this one with zero added wait
            while len(batch) < pol.max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            # then hold the window open for stragglers — bounded by the
            # FIRST request's age, so the policy's latency promise holds
            # even when the window was already spent in the backlog
            deadline = first.t_submit + pol.max_wait_ms / 1000.0
            while len(batch) < pol.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            self._serve(batch)

    def _serve(self, batch: List[_Request]) -> None:
        results = self._predict_isolating([r.row for r in batch])
        now = time.perf_counter()
        for r, (status, val) in zip(batch, results):
            if r.future.set_running_or_notify_cancel():
                if status == "ok":
                    self.timer.record("serve.request", now - r.t_submit)
                    r.future.set_result(val)
                else:  # answer with the error, don't wedge the waiter
                    r.future.set_exception(val)
        self.counters.set("Serving", "MaxBatchObserved",
                          max(len(batch),
                              self.counters.get("Serving",
                                                "MaxBatchObserved")))


class RespPredictionLoop:
    """The serving loop over the wire: drain up to ``policy.max_batch``
    requests from the request queue per poll (pipelined RPOPs — the wire
    half of micro-batching), answer them as one device batch, ``lpush``
    each response to the prediction queue.  Config keys mirror
    reinforce/serving.RedisServingLoop: redis.server.host,
    redis.server.port, redis.request.queue, redis.prediction.queue.  A
    literal 'stop' on the request queue ends :meth:`run` after the
    requests drained alongside it are answered (no accepted request is
    dropped, like the bandit loop's reward drain on stop)."""

    def __init__(self, service: PredictionService,
                 config: Optional[Dict] = None):
        from ..io.respq import RespClient
        cfg = dict(config or {})
        self.service = service
        self.client = RespClient(cfg.get("redis.server.host", "127.0.0.1"),
                                 int(cfg.get("redis.server.port", 6379)))
        self.request_q = cfg.get("redis.request.queue", "requestQueue")
        self.prediction_q = cfg.get("redis.prediction.queue",
                                    "predictionQueue")
        self.stopped = False

    def poll_once(self) -> int:
        """One spout pass; returns how many messages were consumed."""
        msgs = self.client.rpop_many(self.request_q,
                                     self.service.policy.max_batch)
        if not msgs:
            return 0
        batch: List[str] = []
        for m in msgs:
            if m == "stop":
                # requests drained in the same pipelined pop as the stop
                # are already off the queue — they are still answered
                # below (the bandit loop's drain-before-stop rule)
                self.stopped = True
            else:
                batch.append(m)
        if batch:
            for resp in self.service.process_batch(batch):
                self.client.lpush(self.prediction_q, resp)
        return len(msgs)

    def run(self, max_idle_s: float = 30.0,
            idle_sleep_s: float = 0.002) -> None:
        """Poll until a 'stop' message or ``max_idle_s`` without traffic."""
        idle_since = time.monotonic()
        while not self.stopped:
            if self.poll_once():
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > max_idle_s:
                break
            else:
                time.sleep(idle_sleep_s)

    def close(self) -> None:
        self.client.close()
