"""Micro-batched prediction serving: the request loop over warm predictors.

Single-row requests are coalesced into device batches under a
max-latency/max-batch policy: the first queued request opens a batch window
of ``max_wait_ms``; the batch closes when ``max_batch`` requests are
queued or the window expires, whichever is first.  One bucketed predict
then answers the whole batch — the device does per-request work at batch
throughput while the slowest request waits at most one window plus one
predict.

Batching modes (``BatchPolicy.batching``):

  * ``continuous`` (default) — double-buffered over ASYNC device
    dispatch: the loop launches batch N without forcing its result (jax
    computes on XLA's own pool), gathers + encodes + dispatches batch
    N+1 while N is in flight, then reads N back — the serving twin of
    ``stage_chunks``' parse ‖ transfer ‖ compute split, with no second
    python thread contending for the GIL.  Device idle between batches
    goes to ~0 under load; ``Serving/OverlappedBatches`` counts batches
    whose assembly genuinely overlapped a predict in flight.  With a
    batch in flight the coalescing window is skipped — the in-flight
    predict IS the window (arrivals during it join the next greedy
    drain).  Predictors without the dispatch/readback split degrade to
    drain-first behavior.
  * ``drain`` — the original drain-first loop: assemble, predict, repeat,
    each batch forced before the next gather.  Kept for comparison (the
    bench sweeps both).

SLO-adaptive coalescing: with ``BatchPolicy.slo_p99_ms`` set, the
effective window shrinks (×0.5, floored at ``min_wait_ms``) whenever the
recent request-latency p99 climbs past ``_SLO_SHRINK_FRACTION`` (60%) of
the budget AND the window's own measured hold is a real part of that
latency, and grows back (×1.5, capped at ``max_wait_ms``) while p99 sits
under ``_SLO_GROW_FRACTION`` (35%) of it — the window fills buckets when
latency is cheap and gets out of the way when the budget is under
pressure (see :meth:`PredictionService._effective_wait_ms` for the full
rule).

Admission control: with ``BatchPolicy.max_queue_depth`` set, a submit
against a full queue is answered immediately with ``busy_label`` (wire
reply ``<id>,busy``) instead of queueing unboundedly —
``Serving/Rejected`` counts them and ``serve.admit``/``serve.reject``
instants mark the decisions in the trace.  Nothing accepted is ever
dropped.

Transports (same split as reinforce/serving.py, the bandit loop):

  * in-process — ``submit()`` returns a future; a daemon worker thread
    runs the coalescing loop.  Unit tests and embedded serving.
  * the wire (:class:`RespPredictionLoop`) — RESP-list queues polled like
    the reference's Redis spout (requests ``rpop``ed from the request
    queue, predictions ``lpush``ed to the prediction queue), against
    io/respq.RespServer or a real Redis, with the same config key style
    (redis.server.host/port, redis.request.queue, redis.prediction.queue).

Message formats (delim-joined, like the bandit loop's ``round,<n>``):
  request:    'predict,<requestId>,<field0>,<field1>,...'  (a full record)
              — optionally carrying the request-trace field as the third
              token: 'predict,<id>,t=<enqueue_us>:<sampled>,<fields...>'
              (head-sampled at the pushing client, ``ps.trace.sample``;
              absent = old behavior — see telemetry/reqtrace.py)
  response:   '<requestId>,<predictedClass>'
  control:    'reload' -> hot-swap to the registry's newest intact model
              'stop'   -> end the wire loop (transport-level, like the
                          bandit loop's stop)

Operational hooks: per-request and per-batch latency recorded through
utils/tracing.StepTimer percentile samples, request/batch counters in the
core/metrics.Counters channel, transient predict errors retried via
core/faults.with_retry.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.faults import fault_point, with_retry
from ..core.metrics import Counters
from ..io import native_wire
from ..telemetry import get_default_registry, instant, span
from ..telemetry import reqtrace
from ..utils.tracing import StepTimer
from .predictor import AMBIGUOUS, DEFAULT_BUCKETS, Predictor, make_predictor
from .quantized import QUANTIZED_VERB, wire_decode_tokens
from .registry import ModelRegistry

# adaptive-window hysteresis band: shrink above SHRINK*slo, grow back
# below GROW*slo, hold in between (so the window does not oscillate on
# every batch when p99 hovers near one edge).  SHRINK sits well under
# 1.0 deliberately: the controller's equilibrium lands near SHRINK*slo,
# and the gap up to the budget is the headroom that absorbs tail noise
# the window cannot control (scheduler stalls, allocator hiccups)
_SLO_SHRINK_FRACTION = 0.6
_SLO_GROW_FRACTION = 0.35

# one warning per affected batch, identical text on both data planes —
# the differential fuzz compares recorded warnings too
_NO_PREBINNED_WARNING = (
    "serving: predictq message(s) but the served model has no quantized "
    "sidecar (ps.quantized); replying error")


@dataclass
class BatchPolicy:
    """Coalescing knobs: a batch closes at ``max_batch`` requests or
    ``max_wait_ms`` after its first request, whichever comes first.

    ``batching`` selects the loop shape (``continuous`` double-buffered
    assembly, or the original ``drain``-first).  ``slo_p99_ms > 0``
    enables the adaptive window (``min_wait_ms`` is its floor; the
    configured ``max_wait_ms`` its ceiling).  ``max_queue_depth > 0``
    bounds the request queue: submits past it are answered ``busy``."""
    max_batch: int = 64
    max_wait_ms: float = 2.0
    batching: str = "continuous"       # "continuous" | "drain"
    slo_p99_ms: float = 0.0            # 0 = fixed window
    min_wait_ms: float = 0.05          # adaptive-window floor
    max_queue_depth: int = 0           # 0 = unbounded (no admission control)

    def __post_init__(self):
        if self.batching not in ("continuous", "drain"):
            raise ValueError(f"BatchPolicy.batching must be 'continuous' "
                             f"or 'drain', got {self.batching!r}")


def _stamp_dispatch(ctxs, rows: int) -> None:
    """Stamp dispatch time + emit the flow ``t`` step for every sampled
    context entering a device batch (shared by the submit path and
    ``process_batch``).  Lazy timestamp: an untraced batch costs one
    None-check per member, no clock, no allocation."""
    t = None
    for tr in ctxs:
        if tr is not None and tr.t_dispatch_us is None:
            if t is None:
                t = reqtrace.now_us()
            tr.t_dispatch_us = t
            reqtrace.emit_flow("t", tr.rid, "dispatch", ts_us=t,
                               rows=rows)


def _stamp_done(ctxs) -> None:
    """Stamp readback-complete time for every sampled context in a
    finished batch (same lazy-clock discipline)."""
    t = None
    for tr in ctxs:
        if tr is not None:
            if t is None:
                t = reqtrace.now_us()
            tr.t_done_us = t


def _mark_dispatch(batch, rows: int) -> None:
    _stamp_dispatch((r.trace for r in batch), rows)


def _mark_done(batch) -> None:
    _stamp_done(r.trace for r in batch)


def _mark_popped(req) -> None:
    """Stamp queue-pop time for a sampled request the batch loop just
    dequeued.  Wire contexts already carry their worker-pop stamp (the
    fleet sets it at RESP drain) — without this, an in-process request's
    queue backlog would masquerade as coalesce time in the
    decomposition."""
    tr = req.trace
    if tr is not None and tr.t_pop_us is None:
        tr.t_pop_us = reqtrace.now_us()
        reqtrace.emit_flow("t", tr.rid, "pop", ts_us=tr.t_pop_us)


class _Request:
    __slots__ = ("row", "t_submit", "future", "trace")

    def __init__(self, row: List[str], trace=None):
        self.row = row
        self.t_submit = time.perf_counter()
        self.future: "Future[Optional[str]]" = Future()
        # reqtrace.RequestTrace for a head-sampled request, else None
        self.trace = trace


class PredictionService:
    """The serving bolt: coalesce, predict, respond.

    Construct either around a ready ``predictor`` or around a
    ``registry`` + ``model_name`` (which enables :meth:`refresh` hot-swap:
    publish a new version, send 'reload' or call refresh(), and the next
    batch runs on it — torn versions are skipped by the registry)."""

    def __init__(self, predictor: Optional[Predictor] = None, *,
                 registry: Optional[ModelRegistry] = None,
                 model_name: Optional[str] = None,
                 schema=None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 policy: Optional[BatchPolicy] = None,
                 counters: Optional[Counters] = None,
                 timer: Optional[StepTimer] = None,
                 warm: bool = True,
                 delim: str = ",",
                 ambiguous_label: str = AMBIGUOUS,
                 error_label: str = "error",
                 busy_label: str = "busy",
                 late_label: str = "late",
                 name: Optional[str] = None,
                 host_label: Optional[str] = None,
                 model_label: Optional[str] = None,
                 monitor=None,
                 metrics=None,
                 quantized: bool = False,
                 wire_native: str = "auto",
                 shared_cores: bool = False,
                 device=None,
                 serve_mesh=None,
                 reward_sink=None):
        if predictor is None and (registry is None or model_name is None):
            raise ValueError("need a predictor, or registry= + model_name=")
        if wire_native not in native_wire.MODES:
            raise ValueError(
                f"wire_native must be one of {native_wire.MODES}, "
                f"got {wire_native!r}")
        self.registry = registry
        self.model_name = model_name
        self._schema = schema
        self._buckets = tuple(buckets)
        # ps.quantized: registry loads (initial + hot-swap refresh) build
        # the int8 predictor from the version's sidecar; a version
        # without one warns and serves float (serving/quantized.py)
        self._quantized = bool(quantized)
        # cross-model executable sharing (ISSUE 18): registry loads build
        # predictors whose jitted cores are memoized on the ProgramCache
        # axes (variant, schema fp, buckets, mesh fp, arg shapes) instead
        # of model identity — N residents with structurally identical
        # programs compile once (serving/predictor.py _SHARED_CORES)
        self._shared_cores = bool(shared_cores)
        # device placement (ISSUE 20): ``device=`` pins registry-built
        # forest predictors onto one chip (fleet round-robin spread);
        # ``serve_mesh=`` shards the vote over a tree-axis mesh instead
        # (model-parallel serving).  Mutually exclusive, both None = the
        # old default-device single-chip shape.
        self._device = device
        self._serve_mesh = serve_mesh
        self.policy = policy or BatchPolicy()
        self.counters = counters if counters is not None else Counters()
        self.timer = timer if timer is not None else \
            StepTimer(keep_samples=8192)
        self._warm = warm
        self.delim = delim
        self.ambiguous_label = ambiguous_label
        self.error_label = error_label
        self.busy_label = busy_label
        # deadline-aware admission (ISSUE 17): a request whose wire
        # deadline field has passed answers this label BEFORE any
        # device dispatch — a replayed/redelivered backlog sheds its
        # stale tail cheaply instead of browning out fresh traffic
        self.late_label = late_label
        # identity for metrics/health series (fleet workers get w0/w1/...);
        # defaults to the model name in bind_metrics
        self.name = name
        # multi-host identity: every bound gauge series carries a `host`
        # label (empty when unset) so N fleets on N hosts scraped into
        # one Prometheus land as DISJOINT series — the same fix shape as
        # the PR 8 `service` label, one level up.  ServingFleet threads
        # its host_label through here.
        self.host_label = host_label
        # multi-model identity (ISSUE 18): every bound series also
        # carries a `model` label (empty when unset — the single-model
        # shape) so N resident models behind one ModelRouter land as
        # disjoint per-tenant series in one scrape.  Same fix shape as
        # host, one level down.
        self.model_label = model_label
        self.version: Optional[int] = None
        # drift/quality hook (monitor.accumulator.ServingMonitor): every
        # served micro-batch records through it; None = unmonitored
        self.monitor = monitor
        # set by mark_degraded (e.g. a drift policy's degrade_action):
        # serving continues, operators see the reason + counter
        self.degraded: Optional[str] = None
        # online-learning reward intake (ISSUE 19): when a sink is
        # configured, ``reward,<id>,<value>`` rows drained alongside
        # predicts are handed to it (a callable taking the message
        # list) instead of counting as BadRequests; the native codec
        # declines any batch containing the verb, so the sink only
        # ever fires from the python path — one judged parse
        self.reward_sink = reward_sink
        self._swap_lock = threading.Lock()
        if predictor is None:
            predictor = self._load(must=True)
        elif warm:
            predictor.warm()
        if monitor is not None and warm and hasattr(monitor, "warm"):
            monitor.warm()   # monitor compiles must not race live traffic
        self.predictor = predictor
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # adaptive coalescing state (only moves when slo_p99_ms is set):
        # the current window, plus an EMA of how long recent batches
        # actually HELD the window open for stragglers — the window's own
        # latency contribution, which decides shrink vs grow
        self._adaptive_wait_ms = self.policy.max_wait_ms
        self._hold_ema_ms = 0.0
        # rows currently inside a device predict (for the in-flight gauge
        # and stats(); the lock is a few adds per multi-row batch)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # metrics integration: bind queue-depth/in-flight gauges + the
        # /healthz provider onto the given MetricsRegistry, defaulting to
        # the process registry cli.run installs when the job opened a
        # telemetry.metrics.port endpoint (None = unmetered)
        self._metrics_binding = None
        # request-component histogram binding (ISSUE 15): ONE attribute
        # holding (family, ident), read/cleared under _comp_lock so a
        # sampled request closing concurrently with stop() can neither
        # see a half-applied unbind nor observe into a series
        # drop_series already swept (which would resurrect the retired
        # service's series in every later scrape).  None = sampled
        # requests still trace, just no histogram/exemplar landing spot
        self._comp_binding = None
        self._comp_lock = threading.Lock()
        # ps.wire.native: the native data-plane switch for THIS service
        # ("auto" defers to the process-wide native_wire.set_mode knob).
        # The codec is built lazily per predictor (schema/buckets/
        # pre-binned width are the predictor's) and rebuilt on hot-swap.
        self.wire_native = wire_native
        self._wire_codec = None
        self._wire_codec_pred = None   # weakref to the codec's predictor
        reg = metrics if metrics is not None else get_default_registry()
        if reg is not None:
            self.bind_metrics(reg)

    # ---- model lifecycle ----
    def _load(self, must: bool = False) -> Optional[Predictor]:
        # serving_version, not latest_version: a controller rollback pin
        # (registry.pin_version) must repoint a cold-started worker too
        latest = self.registry.serving_version(self.model_name)
        if latest is None:
            if must:
                raise FileNotFoundError(
                    f"no intact versions of {self.model_name!r} in "
                    f"{self.registry.base_dir!r}")
            return None
        loaded = self.registry.load(self.model_name, latest)
        pred = make_predictor(loaded, schema=self._schema,
                              buckets=self._buckets, delim=self.delim,
                              quantized=self._quantized,
                              shared_cores=self._shared_cores,
                              device=self._device,
                              serve_mesh=self._serve_mesh)
        if self._warm:
            pred.warm()
        self.version = latest
        return pred

    def refresh(self) -> bool:
        """Hot-swap reload: converge onto the registry's SERVING version —
        the newest intact one, or the pinned one when a controller
        pin/rollback is in force (so a refresh can swap DOWN to the prior
        version, the rollback contract).  The replacement predictor is
        built + warmed off the request path and swapped in atomically
        (in-flight batches finish on the old one).  Returns whether a
        swap happened.  A half-written target is skipped by the registry
        with a warning — serving stays on the current model.

        O(delta) path (ISSUE 20): when the new version carries a delta
        sidecar whose parent is the CURRENTLY served version, the resident
        predictor's device arrays are patched in place (H2D proportional
        to the changed trees, not the forest) instead of rebuilding from
        the full artifact.  Any mismatch in the sha chain — or a failure
        mid-patch — falls back to the full-artifact load below, so a torn
        delta can never leave wrong weights serving."""
        if self.registry is None:
            return False
        latest = self.registry.serving_version(self.model_name)
        if latest is None or latest == self.version:
            return False
        if self._try_delta(latest):
            return True
        loaded = self.registry.load(self.model_name, latest)
        pred = make_predictor(loaded, schema=self._schema,
                              buckets=self._buckets, delim=self.delim,
                              quantized=self._quantized,
                              shared_cores=self._shared_cores,
                              device=self._device,
                              serve_mesh=self._serve_mesh)
        if self._warm:
            pred.warm()
        with self._swap_lock:
            self.predictor = pred
            self.version = latest
        self.degraded = None   # a fresh model clears the degraded flag
        self.counters.increment("Serving", "HotSwaps")
        return True

    def _try_delta(self, latest: int) -> bool:
        """In-place delta patch onto the resident predictor.  True only
        when the patch fully applied and ``latest`` is now serving; False
        means "take the full-load path" (no delta sidecar, wrong parent,
        predictor without patch support, or a failure mid-apply — the
        predictor's functional update leaves the old arrays serving in
        every failure case, so falling through is always safe)."""
        pred = self.predictor
        if (self._quantized or pred is None
                or not hasattr(pred, "apply_delta")):
            return False
        dmeta = self.registry.delta_info(self.model_name, latest)
        if dmeta is None or dmeta.get("parent_version") != self.version:
            return False
        try:
            with self._swap_lock:
                fault_point("swap_patch")
                dmeta, arrays = self.registry.load_delta(
                    self.model_name, latest)
                moved = pred.apply_delta(dmeta, arrays)
                self.version = latest
        except Exception as exc:   # noqa: BLE001 — any tear -> full load
            self.counters.increment("Serving", "DeltaSwapTorn")
            import warnings
            warnings.warn(
                f"serving: delta patch onto v{self.version} failed "
                f"({exc}); falling back to full artifact load",
                RuntimeWarning, stacklevel=2)
            return False
        self.degraded = None
        self.counters.increment("Serving", "HotSwaps")
        self.counters.increment("Serving", "DeltaSwaps")
        instant("swap.patch", cat="serving", model=self.model_name or "",
                version=int(latest),
                parent=int(dmeta["parent_version"]),
                changed=len(dmeta.get("changed", ())),
                h2d_bytes=int(moved))
        return True

    def mark_degraded(self, reason: str) -> None:
        """Flag the served model as degraded (drift policy guardrail).
        Serving continues — the flag and counter are the operator
        signal; a successful :meth:`refresh` hot-swap clears it.  The
        flip is also an instant trace event and turns ``/healthz``
        non-OK, so the load balancer sees it too."""
        self.degraded = reason
        self.counters.increment("Serving", "Degraded")
        from ..telemetry import instant
        instant("serving.degraded", cat="serving", reason=reason,
                model_version=self.version)

    # ---- observability snapshot (the /healthz + /metrics source) ----
    def stats(self) -> Dict:
        """One consistent-enough snapshot of the serving loop's state:
        queue depth (requests accepted, not yet drained), in-flight rows
        (inside a device predict right now), served/error/batch counts,
        hot-swaps, the degraded reason (None = healthy) and the model
        version.  Cheap — counter reads and a qsize — so probes and the
        ``/healthz`` handler can call it on every scrape."""
        with self._inflight_lock:
            inflight = self._inflight
        return {
            "queue_depth": self._queue.qsize(),
            "in_flight": inflight,
            "served": self.counters.get("Serving", "Requests"),
            "errors": self.counters.get("Serving", "BadRequests"),
            "batches": self.counters.get("Serving", "Batches"),
            "hot_swaps": self.counters.get("Serving", "HotSwaps"),
            "rejected": self.counters.get("Serving", "Rejected"),
            "window_ms": self._adaptive_wait_ms,
            "degraded": self.degraded,
            "model_version": self.version,
            "host": self.host_label or "",
            "model": self.model_label or "",
        }

    def health(self):
        """Health-provider contract (``telemetry.MetricsRegistry
        .add_health``): (ok, payload).  OK == not degraded; the payload
        is :meth:`stats`, so the 503 body tells the operator WHY."""
        st = self.stats()
        st["degraded"] = st["degraded"] or ""
        return self.degraded is None, st

    def bind_metrics(self, registry) -> None:
        """Register this service's gauges + health on a
        ``telemetry.MetricsRegistry``: queue depth, in-flight rows,
        served/error totals, degraded flag, model version, and latency
        percentiles from the request timer — everything the acceptance
        load balancer and autoscaler read."""
        # every series carries the service identity (same key the health
        # provider uses): two services bound to one registry — several
        # models in one process — write DISJOINT labeled series instead
        # of last-probe-wins clobbering each other's numbers
        # one binding at a time: an explicit bind on a service that
        # already auto-bound (constructed under cli.run's default
        # registry) must release the old probe/health first, or stop()
        # would only ever unbind the LAST one and the first probe would
        # pin this service in the registry forever
        self._unbind_metrics()
        # two services must not share one identity on one registry (two
        # UNNAMED ones would both be 'predictor'): add_health would
        # silently overwrite one's health provider, their probes would
        # clobber one label series, and either stop() would drop the
        # survivor's gauges.  Uniquify against the registry's live
        # health keys — own key was just unbound above, so rebinding
        # the SAME service reclaims its label.
        base = self.name or self.model_name or "predictor"
        # the host label makes multi-HOST series disjoint; unset renders
        # as host="" (single-process serving, the pre-fleet shape).  A
        # host-labeled service's health key is host-qualified too, so
        # two fleets with identical worker names on one registry (two
        # hosts scraped centrally) keep both providers — /healthz/<name>
        # still reaches them by bare worker name or by <host>:<name>
        # (telemetry.MetricsRegistry.health_one's suffix match).
        host = self.host_label or ""

        def _health_key(label: str) -> str:
            return f"serving:{host}:{label}" if host \
                else f"serving:{label}"
        svc_label, n = base, 1
        while registry.has_health(_health_key(svc_label)):
            svc_label = f"{base}-{n}"
            n += 1
        # the model label makes multi-MODEL series disjoint (ISSUE 18);
        # unset renders as model="" — the single-model serving shape
        mlabel = self.model_label or ""
        g = registry.gauge("avenir_serving", "prediction service state",
                           labels=("host", "service", "model", "key"))
        gl = registry.gauge("avenir_serving_latency_ms",
                            "serving latency percentiles",
                            labels=("host", "service", "model", "step",
                                    "quantile"))

        def probe():
            st = self.stats()
            g.set(st["queue_depth"], host=host, service=svc_label,
                  model=mlabel, key="queue_depth")
            g.set(st["in_flight"], host=host, service=svc_label,
                  model=mlabel, key="in_flight")
            g.set(st["served"], host=host, service=svc_label,
                  model=mlabel, key="served")
            g.set(st["errors"], host=host, service=svc_label,
                  model=mlabel, key="errors")
            g.set(st["batches"], host=host, service=svc_label,
                  model=mlabel, key="batches")
            g.set(st["hot_swaps"], host=host, service=svc_label,
                  model=mlabel, key="hot_swaps")
            g.set(st["rejected"], host=host, service=svc_label,
                  model=mlabel, key="rejected")
            g.set(st["window_ms"], host=host, service=svc_label,
                  model=mlabel, key="window_ms")
            g.set(0 if st["degraded"] is None else 1,
                  host=host, service=svc_label, model=mlabel,
                  key="degraded")
            g.set(st["model_version"] or 0,
                  host=host, service=svc_label, model=mlabel,
                  key="model_version")
            for step in ("serve.request", "serve.batch"):
                if self.timer.samples.get(step):
                    for q in (50, 95, 99):
                        gl.set(self.timer.percentile_ms(step, q),
                               host=host, service=svc_label,
                               model=mlabel, step=step,
                               quantile=f"p{q}")
        registry.register_probe(probe)
        health_key = _health_key(svc_label)
        registry.add_health(health_key, self.health)
        # per-sampled-request latency decomposition with request-id
        # exemplars (ISSUE 15): observed only for traced requests, so
        # the family costs nothing with sampling off
        ch = registry.histogram(
            "avenir_request_component_seconds",
            "sampled-request latency decomposition (queue_wait/"
            "coalesce/device/reply/total), exemplar = request id",
            labels=("host", "service", "model", "component"))
        self._comp_binding = (ch, {"host": host, "service": svc_label,
                                   "model": mlabel})
        # remembered so stop() can unbind: a retired service must not be
        # probed (and thereby pinned in memory, predictor and all) by
        # every scrape for the rest of the process
        self._metrics_binding = (registry, probe, health_key,
                                 (g, gl, ch), {"host": host,
                                               "service": svc_label,
                                               "model": mlabel})

    def _unbind_metrics(self) -> None:
        if self._metrics_binding is not None:
            reg, probe, health_key, families, ident = \
                self._metrics_binding
            self._metrics_binding = None
            # clear under the observe lock BEFORE sweeping the series:
            # an in-flight record_request_trace either finished its
            # observe (drop_series below sweeps it) or will re-read
            # None and skip — never observe-after-drop
            with self._comp_lock:
                self._comp_binding = None
            reg.unregister_probe(probe)
            reg.remove_health(health_key)
            # drop the bound label series too: without this, the dead
            # service's last-written gauges (degraded=1, queue_depth, …)
            # keep rendering in every later scrape as if they were live.
            # Matched on (host, service): another host's same-named
            # worker on a shared registry must keep its series.
            for fam in families:
                fam.drop_series(**ident)

    # ---- per-request trace closure ----
    def record_request_trace(self, ctx) -> None:
        """Close one sampled request's trace: stamp the reply time if
        the transport has not, emit the flow ``f`` finish carrying the
        component decomposition, observe the component histograms with
        the request id as exemplar.  Called by :meth:`_reply` for
        in-process requests and by the wire transports (fleet flush /
        ``process_batch``) AFTER the reply actually pushed."""
        if ctx.t_reply_us is None:
            ctx.t_reply_us = reqtrace.now_us()
        self.counters.increment("Serving", "TracedRequests")
        # unlocked peek: with no metrics binding and no tracer installed
        # the decomposition has no consumer — skip building it.  A stale
        # non-None read just falls through to the locked re-check below;
        # a None read after unbind is exactly the skip the unbind wants.
        if self._comp_binding is None \
                and reqtrace.current_tracer() is None:
            return
        comps = ctx.components_ms()
        # observe under _comp_lock: once _unbind_metrics cleared the
        # binding (same lock) and swept the series, no straggler may
        # observe the dead series back into existence
        with self._comp_lock:
            binding = self._comp_binding
            if binding is not None:
                hist, ident = binding
                for comp, ms in comps.items():
                    # clamp at 0: queue_wait bridges the client->worker
                    # clock boundary, and a skewed-negative value would
                    # land in EVERY bucket and walk _sum backwards
                    hist.observe(max(ms, 0.0) / 1e3, exemplar=ctx.rid,
                                 component=comp, **ident)
        reqtrace.emit_flow("f", ctx.rid, "reply", ts_us=ctx.t_reply_us,
                           **{f"{k}_ms": round(v, 3)
                              for k, v in comps.items()})

    # ---- prediction ----
    def _label(self, pred: Optional[str]) -> str:
        return pred if pred is not None else self.ambiguous_label

    def predict_rows(self, rows: List[List[str]], *,
                     _pred=None, _prepared=None) -> List[str]:
        """One coalesced device batch for ``rows``, with transient-error
        retry (a recoverable allocator/IO hiccup re-runs the batch rather
        than failing every request in it).  ``_pred``/``_prepared`` carry
        a predictor snapshot + its pre-encoded tables from the continuous
        assembler (the encode already overlapped the previous predict);
        without them the whole predict runs here."""
        if _pred is None:
            with self._swap_lock:
                _pred = self.predictor
        t0 = time.perf_counter()
        with span("serve.predict", cat="serving", rows=len(rows),
                  model=self.model_label or ""):
            if _prepared is not None:
                out = with_retry(lambda: _pred.predict_prepared(_prepared),
                                 what="serving predict batch")
            else:
                out = with_retry(lambda: _pred.predict_rows(rows),
                                 what="serving predict batch")
        self.timer.record("serve.batch", time.perf_counter() - t0)
        self.counters.increment("Serving", "Requests", len(rows))
        self.counters.increment("Serving", "Batches")
        return [self._label(p) for p in out]

    def _predict_isolating(self, rows: List[List[str]],
                           pred=None, prepared=None):
        """('ok', label) | ('err', exc) per row.  The whole batch runs as
        one launch when it is clean; if anything in it fails (e.g. a short
        record or a non-numeric token blowing up encode_rows), fall back
        to per-row isolation so one malformed request cannot take down the
        batchmates drained off the queue alongside it.  The fallback
        accounts as ONE isolated batch — per-row launches must not flood
        the Batches count or the serve.batch samples operators tune
        BatchPolicy with."""
        import warnings
        with self._inflight_lock:
            self._inflight += len(rows)
        try:
            try:
                results = [("ok", lab) for lab in
                           self.predict_rows(rows, _pred=pred,
                                             _prepared=prepared)]
                self._record_monitor(rows, results)
                return results
            except Exception as exc:
                warnings.warn(
                    f"serving: batch predict failed ({type(exc).__name__}: "
                    f"{exc}); isolating per row", RuntimeWarning)
            if pred is None:
                with self._swap_lock:
                    pred = self.predictor
            return self._isolated_pass(pred, rows)
        finally:
            with self._inflight_lock:
                self._inflight -= len(rows)

    def _isolated_pass(self, pred, rows: List[List[str]]):
        """Per-row isolation after a whole-batch failure: one launch per
        row so one malformed request cannot take down its batchmates.
        Accounts as ONE isolated batch (see _predict_isolating)."""
        t0 = time.perf_counter()
        out = []
        for row in rows:
            try:
                lab = with_retry(lambda r=row: pred.predict_rows([r]),
                                 what="serving predict row")[0]
                out.append(("ok", self._label(lab)))
            except Exception as exc:
                self.counters.increment("Serving", "BadRequests")
                out.append(("err", exc))
        self.timer.record("serve.batch", time.perf_counter() - t0)
        self.counters.increment("Serving", "Requests", len(rows))
        self.counters.increment("Serving", "Batches")
        self.counters.increment("Serving", "IsolatedBatches")
        self._record_monitor(rows, out)
        return out

    def _record_monitor(self, rows, results) -> None:
        """Feed successfully answered (row, label) pairs to the drift
        monitor hook.  Cheap on the request path (the hook only
        buffers); monitoring failures are warned, never propagated —
        observability must not take serving down."""
        if self.monitor is None:
            return
        import warnings
        try:
            ok_rows = [r for r, (st, _) in zip(rows, results) if st == "ok"]
            ok_labels = [v for st, v in results if st == "ok"]
            if ok_rows:
                self.monitor.record_batch(ok_rows, ok_labels)
        except Exception as exc:
            warnings.warn(f"serving: monitor hook failed "
                          f"({type(exc).__name__}: {exc}); continuing "
                          f"unmonitored for this batch", RuntimeWarning)

    # ---- message contract (shared by both transports) ----
    def process(self, message: str) -> Optional[str]:
        """Bolt-execute for ONE message (the bandit loop's synchronous
        contract); micro-batching callers use process_batch."""
        return (self.process_batch([message]) or [None])[0]

    def process_batch(self, messages: List[str]) -> List[str]:
        """Coalesce a drained message batch: all predict messages run as
        one device batch, response lines returned in arrival order.  A
        malformed or unknown message is counted + warned and skipped — it
        must not take down the valid requests already drained off the
        queue alongside it (they cannot be re-queued).  A 'reload' in the
        drain applies AFTER the batch is answered: the swap (and its
        multi-bucket warm-up compiles) must not stall requests already
        accepted, so the new model takes effect from the next batch.

        Two message forms are served: the float ``predict`` form and the
        int8 pre-binned ``predictq`` form (serving/quantized.py wire
        codec), the latter only when the served model carries a
        quantized sidecar — without one the request is answered
        ``error`` and counted (the grid lives with the model; there is
        nothing to decode against).

        The batch runs through the native wire codec
        (io/native_wire.WireCodec) when available and enabled
        (``ps.wire.native``): one C pass classifies + assembles the
        whole drain straight into reusable host buffers, no per-request
        python tokenize/float().  Any input the native pass is not
        bit-certain about re-runs the WHOLE batch through the python
        path below, so replies and BadRequests counts are identical by
        construction (tests/test_native_wire_fuzz.py)."""
        if not messages:
            return []
        with self._swap_lock:
            pred = self.predictor
        codec = self._wire_codec_for(pred)
        if codec is not None:
            out = self._process_batch_native(pred, codec, messages)
            if out is not None:
                return out
        return self._process_batch_python(pred, messages)

    def _process_batch_python(self, pred, messages: List[str]) -> List[str]:
        """The retained pure-python data plane — the semantics oracle
        the native codec defers to, and the serving path when the
        toolchain is unavailable or a drift monitor needs token rows."""
        import warnings
        # (form, rid, slot): "f" float row, "q" decoded pre-binned row,
        # "e" error reply (unservable/malformed predictq) — arrival order
        entries: List[tuple] = []
        rows: List[List[str]] = []
        q_rows: List[tuple] = []
        traced = None
        reload_requested = False
        reward_msgs: List[str] = []
        q_width = pred.prebinned_width \
            if getattr(pred, "supports_prebinned", False) else 0
        warned_no_prebinned = False
        with span("serve.assemble", cat="serving", rows=len(messages)):
            for message in messages:
                parts = message.split(self.delim)
                is_predict = parts[0] == "predict"
                if (is_predict or parts[0] == QUANTIZED_VERB) \
                        and len(parts) >= 3:
                    # the optional wire trace + deadline fields (ISSUE
                    # 15/17) are stripped whether acted on or not;
                    # absent = the old message layout, byte for byte
                    rid, row, ctx, deadline_us = \
                        reqtrace.split_predict_deadline(parts)
                    if ctx is not None:
                        ctx.t_pop_us = reqtrace.now_us()
                        reqtrace.emit_flow("t", rid, "pop",
                                           ts_us=ctx.t_pop_us)
                        if traced is None:
                            traced = []
                        traced.append(ctx)
                    if deadline_us is not None \
                            and reqtrace.now_us() > deadline_us:
                        # past deadline: answer late, never dispatch
                        self.counters.increment("Broker", "LateShed")
                        entries.append(("l", rid, -1))
                        continue
                    if is_predict:
                        entries.append(("f", rid, len(rows)))
                        rows.append(row)
                    elif q_width <= 0:
                        self.counters.increment("Serving", "BadRequests")
                        if not warned_no_prebinned:
                            warned_no_prebinned = True
                            warnings.warn(_NO_PREBINNED_WARNING,
                                          RuntimeWarning)
                        entries.append(("e", rid, -1))
                    else:
                        decoded = wire_decode_tokens(row, q_width)
                        if decoded is None:
                            self.counters.increment("Serving",
                                                    "BadRequests")
                            warnings.warn(
                                f"serving: malformed predictq payload "
                                f"{message!r}", RuntimeWarning)
                            entries.append(("e", rid, -1))
                        else:
                            entries.append(("q", rid, len(q_rows)))
                            q_rows.append(decoded)
                elif parts[0] == "reload":
                    reload_requested = True
                elif parts[0] == "reward" and self.reward_sink is not None:
                    # online reward intake: hand the raw message to the
                    # sink (it owns arity/value judgement + the join);
                    # rewards produce no reply line
                    reward_msgs.append(message)
                else:
                    self.counters.increment("Serving", "BadRequests")
                    warnings.warn(f"serving: dropping malformed message "
                                  f"{message!r}", RuntimeWarning)
        if reward_msgs:
            self.counters.increment("Serving", "RewardsRouted",
                                    len(reward_msgs))
            self.reward_sink(reward_msgs)
        if reload_requested and not entries:
            self.refresh()
            return []
        if not entries:
            return []
        if traced:
            _stamp_dispatch(traced, len(rows) + len(q_rows))
        t0 = time.perf_counter()
        results_f = self._predict_isolating(rows, pred=pred) if rows \
            else []
        if q_rows:
            results_q = self._serve_prebinned(
                pred, np.stack([v for v, _ in q_rows]),
                np.stack([c for _, c in q_rows]))
        else:
            results_q = []
        dt = time.perf_counter() - t0
        if traced:
            _stamp_done(traced)
        with span("serve.reply", cat="serving", rows=len(entries)):
            self._record_request_times(traced, dt)
            out = []
            for form, rid, slot in entries:
                if form == "f":
                    status, val = results_f[slot]
                elif form == "q":
                    status, val = results_q[slot]
                elif form == "l":
                    out.append(f"{rid}{self.delim}{self.late_label}")
                    continue
                else:
                    status, val = "err", None
                lab = val if status == "ok" else self.error_label
                out.append(f"{rid}{self.delim}{lab}")
        if traced:
            # the reply lines are about to push (RespPredictionLoop
            # lpushes right after this returns): close the flows here,
            # where the service identity (histograms, exemplars) lives
            for ctx in traced:
                self.record_request_trace(ctx)
        if reload_requested:
            self.refresh()
        return out

    def _process_batch_native(self, pred, codec,
                              messages: List[str]) -> Optional[List[str]]:
        """The native data plane: the batch was already classified and
        assembled by ONE C pass (``codec.parse``) — what remains in
        python is per-message bookkeeping (counters, trace contexts) and
        the reply join.  Returns None when the codec declined the batch
        (its fallback verdict): the caller re-runs the python path on
        the SAME messages, which is where all only-python-can-judge
        inputs (lexotic numerics, malformed payloads) are decided."""
        import warnings
        pb = codec.parse(messages)
        if pb is None:
            return None
        traced = None
        n_replies = pb.n_float + pb.n_q
        with span("serve.assemble", cat="serving", rows=len(messages),
                  native=1):
            # per-message python work only where the batch actually has
            # exceptions: the all-clean saturation case (every message a
            # decoded predict/predictq, nothing traced) skips the scans
            # the C pass already did
            if n_replies + pb.n_reload != pb.n_msgs:
                for i in np.nonzero(pb.kind == native_wire.MSG_BAD)[0]:
                    self.counters.increment("Serving", "BadRequests")
                    warnings.warn(f"serving: dropping malformed message "
                                  f"{messages[i]!r}", RuntimeWarning)
                unsup = np.nonzero((pb.kind == native_wire.MSG_PREDICTQ)
                                   & (pb.slot < 0))[0]
                if len(unsup):
                    # no quantized sidecar on the served model: answered
                    # error, never decoded — same as the python path
                    n_replies += len(unsup)
                    self.counters.increment("Serving", "BadRequests",
                                            len(unsup))
                    warnings.warn(_NO_PREBINNED_WARNING, RuntimeWarning)
            if pb.trace_sampled.any():
                traced = []
                for i in np.nonzero(pb.trace_sampled)[0]:
                    ctx = reqtrace.RequestTrace(pb.rids[i],
                                                float(pb.trace_us[i]),
                                                wire=True)
                    ctx.t_pop_us = reqtrace.now_us()
                    reqtrace.emit_flow("t", ctx.rid, "pop",
                                       ts_us=ctx.t_pop_us)
                    traced.append(ctx)
        if pb.n_reload and n_replies == 0:
            self.refresh()
            return []
        if n_replies == 0:
            return []
        if traced:
            _stamp_dispatch(traced, pb.n_float + pb.n_q)
        t0 = time.perf_counter()
        results_f = self._serve_prepared_native(
            pred, pb.prepared, pb.n_float,
            lambda: self._retokenize_float_rows(messages, pb)) \
            if pb.n_float else []
        results_q = self._serve_prebinned(pred, pb.qv, pb.qc) \
            if pb.n_q else []
        dt = time.perf_counter() - t0
        if traced:
            _stamp_done(traced)
        with span("serve.reply", cat="serving", rows=n_replies, native=1):
            self._record_request_times(traced, dt)
            delim = self.delim
            err = self.error_label
            labs_f = [v if s == "ok" else err for s, v in results_f]
            if pb.n_float == pb.n_msgs:
                # saturation fast path: all-float batch, slots ARE the
                # arrival order — one join, no per-message dispatch
                out = [f"{r}{delim}{lab}"
                       for r, lab in zip(pb.rids, labs_f)]
            else:
                labs_q = [v if s == "ok" else err for s, v in results_q]
                out = []
                for i in range(pb.n_msgs):
                    k = pb.kind[i]
                    if k == native_wire.MSG_PREDICT:
                        lab = labs_f[pb.slot[i]]
                    elif k == native_wire.MSG_PREDICTQ:
                        s = pb.slot[i]
                        lab = labs_q[s] if s >= 0 else err
                    else:
                        continue
                    out.append(f"{pb.rids[i]}{delim}{lab}")
        if traced:
            for ctx in traced:
                self.record_request_trace(ctx)
        if pb.n_reload:
            self.refresh()
        return out

    def _retokenize_float_rows(self, messages: List[str], pb):
        """Token rows (slot order) for the native path's per-row
        isolation — built ONLY when a whole-batch predict failed, so
        the common path never pays a python tokenize."""
        rows = []
        for i in range(pb.n_msgs):
            if pb.kind[i] == native_wire.MSG_PREDICT:
                _, row, _ = reqtrace.split_predict(
                    messages[i].split(self.delim))
                rows.append(row)
        return rows

    def _serve_prepared_native(self, pred, prepared, n_rows: int,
                               row_thunk):
        """``_predict_isolating`` for natively-assembled float batches:
        same counters/timer/span accounting, but the tokenized rows are
        materialized (``row_thunk``) only if the whole-batch predict
        fails and per-row isolation must run — parse validity was
        already proven by the codec, so a failure here is device-side."""
        import warnings
        with self._inflight_lock:
            self._inflight += n_rows
        try:
            t0 = time.perf_counter()
            try:
                with span("serve.predict", cat="serving", rows=n_rows,
                          model=self.model_label or ""):
                    out = with_retry(
                        lambda: pred.predict_prepared(prepared),
                        what="serving predict batch")
                self.timer.record("serve.batch", time.perf_counter() - t0)
                self.counters.increment("Serving", "Requests", n_rows)
                self.counters.increment("Serving", "Batches")
                amb = self.ambiguous_label
                return [("ok", p if p is not None else amb) for p in out]
            except Exception as exc:
                warnings.warn(
                    f"serving: batch predict failed "
                    f"({type(exc).__name__}: {exc}); isolating per row",
                    RuntimeWarning)
                return self._isolated_pass(pred, row_thunk())
        finally:
            with self._inflight_lock:
                self._inflight -= n_rows

    def _serve_prebinned(self, pred, qv, qc):
        """('ok', label) | ('err', exc) per pre-binned int8 row — BOTH
        data planes land predictq rows here, so their replies and
        counters cannot diverge.  No per-row isolation: a decoded int8
        row has no per-row failure mode (arity and range were validated
        at decode), so a predict failure is device-side and fails the
        whole q-batch."""
        import warnings
        n = len(qv)
        with self._inflight_lock:
            self._inflight += n
        try:
            t0 = time.perf_counter()
            try:
                with span("serve.predict", cat="serving", rows=n,
                          model=self.model_label or ""):
                    out = with_retry(
                        lambda: pred.predict_prebinned(qv, qc),
                        what="serving predictq batch")
                self.timer.record("serve.batch", time.perf_counter() - t0)
                self.counters.increment("Serving", "Requests", n)
                self.counters.increment("Serving", "Batches")
                return [("ok", self._label(p)) for p in out]
            except Exception as exc:
                warnings.warn(
                    f"serving: pre-binned batch predict failed "
                    f"({type(exc).__name__}: {exc}); failing the q-batch",
                    RuntimeWarning)
                self.counters.increment("Serving", "BadRequests", n)
                return [("err", exc)] * n
        finally:
            with self._inflight_lock:
                self._inflight -= n

    def _record_request_times(self, traced, dt: float) -> None:
        """``serve.request`` histogram feed: traced requests record
        their true wire-derived latency (reply time minus the client
        enqueue stamp), one sample each; an untraced batch records ONE
        ``dt`` sample.  The old loop recorded the same batch ``dt``
        once PER request, over-weighting large batches in the very
        histogram BatchPolicy is tuned against."""
        if traced:
            t_now = reqtrace.now_us()
            for ctx in traced:
                self.timer.record(
                    "serve.request",
                    max(t_now - ctx.enqueue_us, 0.0) / 1e6)
        else:
            self.timer.record("serve.request", dt)

    def _wire_codec_for(self, pred):
        """The native batch assembler bound to the CURRENT predictor
        (schema/buckets/pre-binned width are its), rebuilt on hot-swap.
        None = python path: mode off, toolchain unavailable (one
        process-wide warning), a drift monitor attached (it needs the
        token rows the native path never materializes), or no usable
        schema/delimiter."""
        mode = self.wire_native if self.wire_native != "auto" \
            else native_wire.get_mode()
        if mode == "off":
            return None
        if self.monitor is not None:
            return None
        schema = getattr(pred, "schema", None)
        if schema is None or not getattr(schema, "fields", None):
            return None
        if native_wire.get_lib() is None:
            native_wire.warn_fallback_once(
                "no toolchain or AVENIR_TPU_NO_NATIVE set")
            return None
        if self._wire_codec is not None \
                and self._wire_codec_pred is not None \
                and self._wire_codec_pred() is pred:
            return self._wire_codec
        q_width = pred.prebinned_width \
            if getattr(pred, "supports_prebinned", False) else 0
        codec = native_wire.WireCodec(schema, delim=self.delim,
                                      buckets=tuple(pred.buckets),
                                      q_width=q_width)
        if not codec.usable:
            return None
        self._wire_codec = codec
        self._wire_codec_pred = weakref.ref(pred)
        return codec

    # ---- in-process micro-batch loop ----
    def submit(self, row, trace=None,
               sample_local: bool = True) -> "Future[str]":
        """Queue one record (tokenized row or delim-joined line); the
        worker thread answers the future with the class label.  Past the
        admission threshold (``policy.max_queue_depth``) the future is
        answered immediately with ``busy_label`` — backpressure the
        caller can see, never a silently dropped request.  ``trace``
        carries a wire request's :class:`~avenir_tpu.telemetry.reqtrace
        .RequestTrace`; without one, in-process head sampling applies
        (one global read when ``ps.trace.sample`` is off).  Wire
        transports pass ``sample_local=False``: sampling is a HEAD
        decision — a request the pushing client left unstamped must not
        be re-sampled mid-path (its queue-wait leg is already lost)."""
        if isinstance(row, str):
            row = row.split(self.delim)
        if trace is None and sample_local:
            trace = reqtrace.maybe_sample_local()
        req = _Request(list(row), trace=trace)
        dmax = self.policy.max_queue_depth
        if dmax and self._queue.qsize() >= dmax:
            self.counters.increment("Serving", "Rejected")
            instant("serve.reject", cat="serving",
                    queue_depth=self._queue.qsize())
            req.future.set_result(self.busy_label)
            # a rejected sampled request still closes its flow (busy IS
            # the reply) — for wire contexts the transport closes it
            # when the busy reply pushes
            if trace is not None and not trace.wire:
                self.record_request_trace(trace)
            return req.future
        # admit instants only for SAMPLED requests: an every-submit
        # instant runs >1k/s at saturation — past the §21 granularity
        # rule — and measurably taxes the traced closed loop; rejects
        # stay always-on (rare, and exactly the event operators hunt)
        if trace is not None:
            instant("serve.admit", cat="serving", rid=trace.rid)
        self._queue.put(req)
        return req.future

    def start(self) -> "PredictionService":
        if self._thread is not None:
            return self
        self._stop.clear()
        target = self._loop_continuous \
            if self.policy.batching == "continuous" else self._loop
        self._thread = threading.Thread(target=target, daemon=True,
                                        name="avenir-serve-loop")
        self._thread.start()
        return self

    def stop(self, drain_s: float = 5.0) -> None:
        """Stop the worker; queued requests are still served (bounded by
        ``drain_s``) so no accepted request is dropped on shutdown.  The
        leftover drain is CHUNKED into ``policy.max_batch`` batches —
        a deep backlog at shutdown must run through the same compiled
        bucket sizes as live traffic, never one unbounded batch.  Also
        runs when the worker never started: accepted futures are
        answered regardless."""
        # unbind from the registry whether or not the worker ran: a
        # stopped service must not be probed by every later scrape
        self._unbind_metrics()
        self._stop.set()
        join_s = max(drain_s, 0.1) + 5.0
        if self._thread is not None:
            self._thread.join(timeout=join_s)
            self._thread = None
        deadline = time.monotonic() + drain_s
        max_b = max(1, self.policy.max_batch)
        batch: List[_Request] = []
        while time.monotonic() < deadline:
            try:
                leftover = self._queue.get_nowait()
            except queue.Empty:
                break
            _mark_popped(leftover)
            batch.append(leftover)
            if len(batch) >= max_b:
                self._serve(batch)
                batch = []
        if batch:
            self._serve(batch)

    # how many of the newest serve.request samples steer the adaptive
    # window: small enough to react within ~a quarter second of traffic,
    # large enough that one straggler is not "the p99"
    _ADAPT_SAMPLES = 256

    def _recent_p99_ms(self) -> float:
        s = self.timer.samples.get("serve.request")
        if not s:
            return 0.0
        import numpy as np
        # the predict thread appends to this bounded deque concurrently
        # (a full-deque append also pops): list() can raise "deque
        # mutated during iteration".  Retry, and on persistent contention
        # report "no pressure" (0.0) — one adaptive step on stale info is
        # noise; an exception here would kill the assembler thread and
        # silently stop the service
        for _ in range(3):
            try:
                recent = list(s)[-self._ADAPT_SAMPLES:]
                break
            except RuntimeError:
                continue
        else:
            return 0.0
        if not recent:
            return 0.0
        return float(np.percentile(np.asarray(recent), 99)) * 1000.0

    def _effective_wait_ms(self) -> float:
        """The coalescing window for the NEXT batch.  Fixed at
        ``policy.max_wait_ms`` unless an SLO budget is set; under one:

        * recent p99 past ``_SLO_SHRINK_FRACTION`` of the budget AND the
          window's own measured latency contribution (the straggler-hold
          EMA) above 10% of the budget -> SHRINK ×0.5 (floored at
          ``min_wait_ms``): the window is demonstrably where the latency
          comes from.
        * recent p99 past the shrink fraction but the hold EMA is NOT
          the cost -> GROW ×1.5: latency is coming from queueing/predict
          pressure, and cutting the window further would only shrink
          batch fill and collapse throughput (making p99 worse) — fill
          the buckets instead.
        * recent p99 under ``_SLO_GROW_FRACTION`` of the budget -> GROW
          ×1.5 (capped at ``max_wait_ms``): latency is cheap, refill the
          buckets.

        Between the two fractions the window holds (hysteresis).
        "Recent" is
        the last ``_ADAPT_SAMPLES`` request samples — the full timer
        window would remember a bad spell for thousands of requests and
        keep the window pinned long after recovery."""
        pol = self.policy
        if not pol.slo_p99_ms:
            return pol.max_wait_ms
        w = self._adaptive_wait_ms
        try:
            p99 = self._recent_p99_ms()
            if p99 >= _SLO_SHRINK_FRACTION * pol.slo_p99_ms:
                if self._hold_ema_ms >= 0.1 * pol.slo_p99_ms:
                    w = max(pol.min_wait_ms, w * 0.5)
                else:
                    w = min(pol.max_wait_ms, max(w * 1.5, pol.min_wait_ms))
            elif p99 and p99 < _SLO_GROW_FRACTION * pol.slo_p99_ms:
                w = min(pol.max_wait_ms, max(w * 1.5, pol.min_wait_ms))
        except Exception:
            # the adaptive controller is advisory: any failure keeps the
            # current window rather than killing the assembler (whose
            # death would wedge every future the loop still owes)
            return w
        self._adaptive_wait_ms = w
        return w

    def _gather(self, first: _Request,
                skip_hold: bool = False) -> List[_Request]:
        """Assemble one batch starting from ``first`` under the policy:
        free coalescing of everything already queued, then hold the
        window open for stragglers — bounded by the FIRST request's age,
        so the latency promise holds even when the window was already
        spent in the backlog.  ``skip_hold`` (continuous mode with a
        batch already in flight) takes only the free coalescing: the
        in-flight predict IS the window — everything arriving during it
        joins the next greedy drain, and holding longer would only delay
        the pending batch's readback."""
        pol = self.policy
        batch = [first]
        with span("serve.assemble", cat="serving") as sp:
            while len(batch) < pol.max_batch:
                try:
                    batch.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            # pop stamps BEFORE the straggler hold: in-process sampled
            # requests' queue backlog must read as queue_wait, and the
            # hold as coalesce — not all lumped into one component
            for r in batch:
                _mark_popped(r)
            hold_ms = 0.0
            if not skip_hold:
                deadline = first.t_submit + \
                    self._effective_wait_ms() / 1000.0
                t_hold = time.perf_counter()
                while len(batch) < pol.max_batch:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    try:
                        straggler = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                    _mark_popped(straggler)
                    batch.append(straggler)
                hold_ms = (time.perf_counter() - t_hold) * 1000.0
            # the window's own latency contribution, fed to the adaptive
            # rule: how long THIS batch held open for stragglers
            self._hold_ema_ms += 0.1 * (hold_ms - self._hold_ema_ms)
            sp.add(rows=len(batch))
        return batch

    def _loop(self) -> None:
        """Drain-first: assemble, predict, repeat — one thread, device
        idle while assembling, assembly idle while predicting."""
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.02)
            except queue.Empty:
                continue
            self._serve(self._gather(first))

    def _loop_continuous(self) -> None:
        """Continuous batching, single-threaded over ASYNC device
        dispatch (the §18 discipline): stage batch N (host encode +
        launch, no forcing — XLA computes on its own pool, GIL free),
        then gather+encode+dispatch batch N+1 while N is in flight, THEN
        read N back.  Device idle between batches goes to ~0 under load
        with no extra python thread contending for the GIL.  Predictors
        without the dispatch/readback split stage pre-resolved and the
        loop degrades to drain-first for them."""
        staged = None
        try:
            while not self._stop.is_set():
                try:
                    # with a batch in flight, only peek for new work —
                    # its readback must not wait an idle-poll period
                    first = self._queue.get(
                        timeout=0.0005 if staged is not None else 0.02)
                except queue.Empty:
                    if staged is not None:
                        item, staged = staged, None
                        self._complete(item)
                    continue
                batch = self._gather(first, skip_hold=staged is not None)
                nxt = self._stage(batch)
                if staged is not None:
                    if staged[2] is not None:
                        # this batch's assembly/encode/dispatch genuinely
                        # overlapped the previous batch's DEVICE time —
                        # a sync-staged predecessor (handle None) never
                        # had anything in flight to overlap
                        self.counters.increment("Serving",
                                                "OverlappedBatches")
                    self._complete(staged)
                staged = nxt
        finally:
            if staged is not None:
                self._complete(staged)

    def _stage(self, batch: List[_Request]):
        """The launch half of a continuous-mode batch: snapshot the
        predictor (a hot-swap mid-flight must finish this batch on the
        model that encoded it), encode, and dispatch asynchronously.
        Returns ``(batch, pred, staged_handle)``; a predictor without
        the async split — or a prepare/dispatch failure (malformed
        row) — stages ``None`` and completes via the sync isolating
        path."""
        with self._swap_lock:
            pred = self.predictor
        dispatch = getattr(pred, "dispatch_prepared", None)
        if dispatch is not None:
            try:
                with span("serve.dispatch", cat="serving",
                          rows=len(batch)):
                    handle = dispatch(
                        pred.prepare_rows([r.row for r in batch]))
            except Exception:
                pass   # fall through to the sync isolating completion
            else:
                _mark_dispatch(batch, len(batch))
                with self._inflight_lock:
                    self._inflight += len(batch)
                return (batch, pred, handle, time.perf_counter())
        return (batch, pred, None, time.perf_counter())

    def _complete(self, item) -> None:
        """The readback half: force the staged device result, account,
        reply.  A readback failure isolates per row (same contract as
        the sync path); sync-staged batches run the full _serve."""
        batch, pred, handle, t0 = item
        if handle is None:
            self._serve(batch, pred=pred)
            return
        rows = [r.row for r in batch]
        try:
            try:
                with span("serve.predict", cat="serving", rows=len(rows),
                          model=self.model_label or ""):
                    out = pred.readback_dispatched(handle)
                results = [("ok", self._label(p)) for p in out]
                # serve.batch spans dispatch->readback: the batch's real
                # device residency including the overlapped window
                self.timer.record("serve.batch",
                                  time.perf_counter() - t0)
                self.counters.increment("Serving", "Requests", len(rows))
                self.counters.increment("Serving", "Batches")
                self._record_monitor(rows, results)
            except Exception as exc:
                import warnings
                warnings.warn(
                    f"serving: dispatched batch readback failed "
                    f"({type(exc).__name__}: {exc}); isolating per row",
                    RuntimeWarning)
                results = self._isolated_pass(pred, rows)
        finally:
            with self._inflight_lock:
                self._inflight -= len(batch)
        _mark_done(batch)
        self._reply(batch, results)

    def _serve(self, batch: List[_Request], pred=None,
               prepared=None) -> None:
        # sync path: the whole predict runs here, so dispatch == entry
        _mark_dispatch(batch, len(batch))
        results = self._predict_isolating([r.row for r in batch],
                                          pred=pred, prepared=prepared)
        _mark_done(batch)
        self._reply(batch, results)

    def _reply(self, batch: List[_Request], results) -> None:
        now = time.perf_counter()
        with span("serve.reply", cat="serving", rows=len(batch)):
            for r, (status, val) in zip(batch, results):
                if r.future.set_running_or_notify_cancel():
                    if status == "ok":
                        self.timer.record("serve.request", now - r.t_submit)
                        r.future.set_result(val)
                    else:  # answer with the error, don't wedge the waiter
                        r.future.set_exception(val)
                # in-process sampled requests close here (the future IS
                # the reply); wire contexts close at the transport's
                # reply push, which owns the t_reply stamp
                tr = r.trace
                if tr is not None and not tr.wire:
                    self.record_request_trace(tr)
        self.counters.max("Serving", "MaxBatchObserved", len(batch))


class RespPredictionLoop:
    """The serving loop over the wire: drain up to ``policy.max_batch``
    requests from the request queue per poll (pipelined RPOPs — the wire
    half of micro-batching), answer them as one device batch, ``lpush``
    each response to the prediction queue.  Config keys mirror
    reinforce/serving.RedisServingLoop: redis.server.host,
    redis.server.port, redis.request.queue, redis.prediction.queue.  A
    literal 'stop' on the request queue ends :meth:`run` after the
    requests drained alongside it are answered (no accepted request is
    dropped, like the bandit loop's reward drain on stop)."""

    def __init__(self, service: PredictionService,
                 config: Optional[Dict] = None):
        from ..io.respq import RespClient
        cfg = dict(config or {})
        self.service = service
        # the service's counters ride in so this client's reconnects
        # land as Broker/Reconnects in the job dump, same as the fleet's
        self.client = RespClient(cfg.get("redis.server.host", "127.0.0.1"),
                                 int(cfg.get("redis.server.port", 6379)),
                                 delim=service.delim,
                                 counters=service.counters)
        self.request_q = cfg.get("redis.request.queue", "requestQueue")
        self.prediction_q = cfg.get("redis.prediction.queue",
                                    "predictionQueue")
        # ps.broker.lease.timeout.s (ISSUE 17): > 0 drains under
        # visibility-timeout leases and acks via the reply push — a
        # loop killed mid-batch gets its requests redelivered.  0
        # (default) keeps the classic destructive path byte for byte.
        self.lease_timeout_s = float(
            cfg.get("redis.lease.timeout.s", 0.0) or 0.0)
        self.stopped = False

    def poll_once(self) -> int:
        """One spout pass; returns how many messages were consumed."""
        if self.lease_timeout_s > 0:
            msgs = self.client.lease_many(self.request_q,
                                          self.service.policy.max_batch,
                                          self.lease_timeout_s)
        else:
            msgs = self.client.rpop_many(self.request_q,
                                         self.service.policy.max_batch)
        if not msgs:
            return 0
        batch: List[str] = []
        for m in msgs:
            if m == "stop":
                # requests drained in the same pipelined pop as the stop
                # are already off the queue — they are still answered
                # below (the bandit loop's drain-before-stop rule)
                self.stopped = True
            else:
                batch.append(m)
        if batch:
            out = self.service.process_batch(batch)
            if out:
                # ONE variadic LPUSH for the whole batch of replies —
                # with the native codec the buffer is built by one C
                # pass and hits the socket as a single sendall.  In
                # lease mode the push doubles as the lease ack
                # (ACKPUSH), closing the crash window in the same trip.
                if self.lease_timeout_s > 0:
                    self.client.ackpush(self.prediction_q,
                                        self.request_q, out)
                else:
                    self.client.lpush_many(self.prediction_q, out)
        return len(msgs)

    def run(self, max_idle_s: float = 30.0,
            idle_sleep_s: float = 0.002,
            max_idle_sleep_s: float = 0.05) -> None:
        """Poll until a 'stop' message or ``max_idle_s`` without traffic.

        While the queue stays empty the sleep backs off exponentially
        (doubling from ``idle_sleep_s`` up to ``max_idle_sleep_s``) and
        resets on the first drained message — an idle fleet of N workers
        must not burn N cores spin-polling.  ``Serving/Polls`` and
        ``Serving/EmptyPolls`` make the polling economy observable."""
        counters = self.service.counters
        idle_since = time.monotonic()
        sleep_s = idle_sleep_s
        while not self.stopped:
            counters.increment("Serving", "Polls")
            if self.poll_once():
                idle_since = time.monotonic()
                sleep_s = idle_sleep_s
            elif time.monotonic() - idle_since > max_idle_s:
                break
            else:
                counters.increment("Serving", "EmptyPolls")
                time.sleep(sleep_s)
                sleep_s = min(sleep_s * 2.0, max_idle_sleep_s)

    def close(self) -> None:
        self.client.close()
